"""blit benchmark — the driver-tracked metric (BASELINE.json).

Measures sustained single-chip GUPPI RAW → hi-res filterbank reduction:
int8 dual-pol complex voltages through dequant → 4-tap PFB → 1M-point
matmul-DFT channelization → Stokes-I detect (blit.ops.channelize, the
rawspec-equivalent hi-res "0000" product).

Prints ONE JSON line:
  {"metric": ..., "value": GB/s/chip of net RAW input, "unit": "GB/s",
   "vs_baseline": real-time factor vs one bank's 0.75 GB/s recording rate}

The north-star target is >= 4x real-time for a full bank (BASELINE.json:
>= 3 GB/s/chip).  "Net" input counts each voltage sample once (the PFB
overlap re-processing is not credited).

Methodology: data device-resident, K dispatches enqueued back-to-back, one
final sync — steady-state streaming with dispatch latency amortized, matching
how blit.pipeline overlaps host IO with device work.  On non-TPU backends
(dev machines) a small config keeps runtime sane; the reported config is in
the JSON's "config" field either way.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Per-bank recording rate: 187.5 Msamp/s x 2 pol x 2 bytes (SURVEY.md §6).
REALTIME_BANK_GBPS = 0.750


def main() -> None:
    import jax
    import jax.numpy as jnp

    from blit.ops.channelize import channelize, pfb_coeffs

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    if on_tpu:
        # Hi-res product, sized to HBM: 32 coarse channels x 5 frames of
        # 2^20-point channelization per dispatch (671 MB net per call;
        # measured 4.4 GB/s = 5.8x real-time on a v5e chip).
        nfft, ntap, nint, nchan, frames, cb, K = 1 << 20, 4, 1, 32, 5, 0, 8
    else:
        nfft, ntap, nint, nchan, frames, cb, K = 1 << 14, 4, 1, 4, 4, 0, 4

    ntime = (ntap - 1 + frames) * nfft
    rng = np.random.default_rng(0)
    v = rng.integers(-40, 40, size=(nchan, ntime, 2, 2), dtype=np.int8)
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft))
    vj = jax.block_until_ready(jnp.asarray(v))

    def step(x):
        out = channelize(
            x, coeffs, nfft=nfft, ntap=ntap, nint=nint, stokes="I",
            channel_block=cb,
        )
        # Tiny on-device reduction: forces execution while keeping the
        # sync payload scalar (the tunnel's host readback is not the DUT).
        return jnp.sum(out)

    # Warmup / compile.
    float(step(vj))

    t0 = time.perf_counter()
    acc = [step(vj) for _ in range(K)]
    total = sum(float(a) for a in acc)
    elapsed = time.perf_counter() - t0

    net_bytes_per_call = frames * nfft * nchan * 2 * 2  # int8 re/im, 2 pol
    gbps = net_bytes_per_call * K / elapsed / 1e9
    result = {
        "metric": "guppi_raw_to_hires_filterbank_GBps_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / REALTIME_BANK_GBPS, 2),
        "config": {
            "backend": backend,
            "nfft": nfft,
            "ntap": ntap,
            "nint": nint,
            "nchan": nchan,
            "frames_per_call": frames,
            "channel_block": cb,
            "calls": K,
            "stokes": "I",
            "checksum": total,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
