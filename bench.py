"""blit benchmark — the driver-tracked metric (BASELINE.json).

Measures sustained single-chip GUPPI RAW → hi-res filterbank reduction:
int8 dual-pol complex voltages through dequant → 4-tap PFB → 1M-point
matmul-DFT channelization → Stokes-I detect (blit.ops.channelize, the
rawspec-equivalent hi-res "0000" product).

Prints ONE JSON line:
  {"metric": ..., "value": GB/s/chip of net RAW input, "unit": "GB/s",
   "vs_baseline": real-time factor vs one bank's 0.75 GB/s recording rate}

The north-star target is >= 4x real-time for a full bank (BASELINE.json:
>= 3 GB/s/chip).  "Net" input counts each voltage sample once (the PFB
overlap re-processing is not credited).

Methodology: data device-resident, K dispatches enqueued back-to-back, one
final sync — steady-state streaming with dispatch latency amortized, matching
how blit.pipeline overlaps host IO with device work.  On non-TPU backends
(dev machines) a small config keeps runtime sane; the reported config is in
the JSON's "config" field either way.

Robustness: the remote-compile tunnel can hiccup transiently, and a failed
op can poison the whole JAX process — so each measurement attempt runs in a
fresh subprocess (``--single <config>``), and the orchestrator retries with
backoff, falling back to a smaller config if the primary keeps failing.  A
JSON line is always printed (round 1 lost its official perf number to a
single un-retried warmup error).
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

import numpy as np

# Per-bank recording rate: 187.5 Msamp/s x 2 pol x 2 bytes (SURVEY.md §6).
REALTIME_BANK_GBPS = 0.750

# Ingest-inclusive leg: (nfft, nchan, chunk_frames, nblocks, ntime_per_block)
# — synthetic RAW file -> streamed filterbank product via RawReducer, i.e.
# file read + host->device + channelize + host readback, the reference's
# whole worker-side data path (src/gbtworkerfunctions.jl:171-189 analog).
# Shapes are chosen so (a) the chunk shape equals the primary leg's already-
# compiled shape (chunk_frames == its frames_per_call, same nchan → jit
# cache hit, steady-state timing) and (b) the file length leaves exactly the
# (ntap-1)*nfft filter tail after the last chunk, so no flush-shape compile
# triggers (total samples = n_chunks*frames*nfft + 3*nfft).
_INGEST_CONFIGS = {
    # 48 channels — the SAME shape as the primary leg, so the streamed
    # chunks hit the already-compiled 48x8 program.  (Round-3 note: this
    # OOM'd before the fused tail+detect kernel removed the bf16 tail
    # spectra and separate power plane from HBM; re-tested post-fusion —
    # the drain holds two chunk inputs in flight and now fits.)
    "tpu_bf16": (1 << 20, 48, 8, 4, 19 * (1 << 18)),
    "tpu": (1 << 20, 32, 5, 4, 13 * (1 << 18)),
    "tpu_small": (1 << 20, 16, 3, 4, 3 * (1 << 20)),
    "cpu": (1 << 14, 4, 4, 4, 11 * (1 << 12)),
}

# (nfft, ntap, nint, nchan, frames, K calls, dtype).  K follows the
# rep-count rule (DESIGN.md §9 round-4): K x call-time >> the ~100 ms
# closing fetch, or the number measures the tunnel.  At 86-90 ms/call,
# K=24 pins the fetch share under 5% (K=8 cost ~5% and doubled variance:
# interleaved sweep measured 16.8-17.8 vs a stable 18.73-18.75 GB/s).
_CONFIGS = {
    # Hi-res product, bf16 stages + fused pallas dequant+PFB: the gross
    # dequant planes never hit HBM, so 48 coarse channels x 8 frames fit
    # per dispatch (interleaved A/B: 48ch 6.2-6.4 vs 32ch 5.8-6.0 GB/s;
    # 64ch OOMs).  Accuracy bound: DESIGN.md §8.
    "tpu_bf16": (1 << 20, 4, 1, 48, 8, 24, "bfloat16"),
    # f32 flat-layout config: 32 coarse channels x 5 frames of 2^20-point
    # channelization per dispatch (671 MB net per call; measured 4.4 GB/s
    # = 5.8x real-time on a v5e chip in round 2).
    "tpu": (1 << 20, 4, 1, 32, 5, 24, "float32"),
    # Fallback under repeated failures: same hi-res metric, half the
    # working set per dispatch.
    "tpu_small": (1 << 20, 4, 1, 16, 3, 24, "float32"),
    # Dev machines (CPU): keep runtime sane.
    "cpu": (1 << 14, 4, 1, 4, 4, 4, "float32"),
}

_ATTEMPTS_PER_CONFIG = 3
_BACKOFF_S = (5.0, 20.0)
# Budget for ONE subprocess attempt.  Must absorb a fully cold .jax_cache:
# primary 2^20 compile (~250-500 s through the tunnel) + the fqav leg's
# second 2^20 compile + the secondary legs' smaller compiles — a 1500 s
# budget lost the headline number to exactly this in a cold-cache dry run
# (the measurement had already succeeded when the SIGKILL landed).
_ATTEMPT_TIMEOUT_S = 2400.0


def run_single(config_name: str) -> None:
    """One measurement in this process; prints the JSON line on success."""
    import os

    import jax
    import jax.numpy as jnp

    # Persistent compilation cache: the 1M-point channelizer takes minutes
    # to compile through the remote-compile tunnel; retries and re-runs (the
    # orchestrator's fallback ladder, the driver's end-of-round run) hit the
    # cache instead.  Verified effective on this backend.
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        pass

    # Live monitoring (ISSUE 11): with BLIT_MONITOR_SPOOL / _PORT set,
    # the bench publishes its stage/hist telemetry while it measures —
    # `blit top` watches a long TPU bench exactly like a production run.
    try:
        from blit import monitor

        monitor.ensure_publisher()
    except Exception:  # noqa: BLE001 — monitoring must not kill the bench
        pass

    from blit.ops.channelize import (
        channelize,
        last_kernel_plan as _last_kernel_plan,
        pfb_coeffs,
    )

    backend = jax.default_backend()
    nfft, ntap, nint, nchan, frames, K, dtype = _CONFIGS[config_name]

    ntime = (ntap - 1 + frames) * nfft
    rng = np.random.default_rng(0)
    v = rng.integers(-40, 40, size=(nchan, ntime, 2, 2), dtype=np.int8)
    coeffs = jnp.asarray(pfb_coeffs(ntap, nfft))
    vj = jax.block_until_ready(jnp.asarray(v))

    # NOTE: the kwarg set here matches RawReducer._channelize_kw EXACTLY
    # (jax.jit caches per call signature, so an extra/missing kwarg — even
    # at its default value — forces a recompile and would poison the ingest
    # leg's warm-cache assumption).  RawReducer adds dtype= only when not
    # float32; mirror that.
    kw = dict(nfft=nfft, ntap=ntap, nint=nint, stokes="I", fft_method="auto")
    if dtype != "float32":
        kw["dtype"] = dtype

    def step(x):
        out = channelize(x, coeffs, **kw)
        # Tiny on-device reduction: forces execution while keeping the
        # sync payload scalar (the tunnel's host readback is not the DUT).
        return jnp.sum(out)

    # Warmup / compile.
    float(step(vj))

    # Methodology: enqueue all K dispatches, then ONE final sync — the
    # device queue is in-order, so the last scalar materializing implies
    # every dispatch executed.  Each float() is a separate fetch RPC that
    # costs the rig's full ~100 ms tunnel round trip EVEN when the result
    # is already computed, so fetching the K checksums happens outside the
    # timed window (the compute being timed is genuinely done).
    t0 = time.perf_counter()
    acc = [step(vj) for _ in range(K)]
    float(acc[-1])
    elapsed = time.perf_counter() - t0
    # Checksum: one on-device sum + one fetch (K separate float()s would
    # each pay the ~100 ms round trip).
    total = float(jnp.sum(jnp.stack(acc)))
    del acc
    net_bytes_per_call = frames * nfft * nchan * 2 * 2  # int8 re/im, 2 pol

    # fqav epilogue leg (VERDICT r3 item 7): the same reduction with the
    # on-device reduce-before-the-wire fold active.  Interleaved A/B on
    # the chip measured parity (ratio 0.998 at this config — XLA fuses
    # the 1/16-size fold into the product epilogue; DESIGN.md §9), and
    # this leg keeps that claim continuously measured.
    fqav_extra = {}
    try:
        kwf = dict(kw, fqav_by=16)

        def stepf(x):
            return jnp.sum(channelize(x, coeffs, **kwf))

        float(stepf(vj))  # compile (persistent-cached)
        t0 = time.perf_counter()
        accf = [stepf(vj) for _ in range(K)]
        float(accf[-1])
        elf = time.perf_counter() - t0
        del accf
        fqav_extra = {
            "fqav16_gbps": round(net_bytes_per_call * K / elf / 1e9, 3)
        }
    except Exception as e:  # noqa: BLE001 — secondary leg must not kill the line
        fqav_extra = {"fqav16_error": f"{type(e).__name__}: {e}"}

    # Full-Stokes leg (VERDICT r4 item 5): the SAME config with
    # stokes="IQUV" — nif=4, 4x the product bytes through the fused
    # detect path.  Interleaved A/B measured 0.853x vs Stokes I at this
    # config (17.8 vs 20.9 GB/s — DESIGN.md §9 r5 addendum); this leg
    # keeps "every Stokes product" carrying a number.
    try:
        kwq = dict(kw, stokes="IQUV")

        def stepq(x):
            return jnp.sum(channelize(x, coeffs, **kwq))

        float(stepq(vj))  # compile (persistent-cached)
        t0 = time.perf_counter()
        accq = [stepq(vj) for _ in range(K)]
        float(accq[-1])
        elq = time.perf_counter() - t0
        del accq
        fqav_extra["stokes_iquv_gbps"] = round(
            net_bytes_per_call * K / elq / 1e9, 3
        )
    except Exception as e:  # noqa: BLE001 — secondary leg must not kill the line
        fqav_extra["stokes_iquv_error"] = f"{type(e).__name__}: {e}"

    # Free the primary leg's device residents (up to GBs) before the
    # secondary legs — they have their own working sets and OOM otherwise.
    del vj

    gbps = net_bytes_per_call * K / elapsed / 1e9

    try:
        ingest = _run_ingest(config_name)
    except Exception as e:  # noqa: BLE001 — secondary metric must not kill the line
        ingest = {"ingest_error": f"{type(e).__name__}: {e}"}

    result = {
        "metric": "guppi_raw_to_hires_filterbank_GBps_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / REALTIME_BANK_GBPS, 2),
        "config": {
            "backend": backend,
            "name": config_name,
            "nfft": nfft,
            "ntap": ntap,
            "nint": nint,
            "nchan": nchan,
            "frames_per_call": frames,
            "calls": K,
            "stokes": "I",
            "dtype": dtype,
            "checksum": total,
            # What 'auto' dispatch resolved to (ADVICE r3: silent default
            # changes must be attributable in the recorded numbers).
            "kernel_plan": _last_kernel_plan(),
        },
    }
    result.update(fqav_extra)
    result.update(ingest)
    try:
        result.update(_run_config1())
    except Exception as e:  # noqa: BLE001 — secondary metric must not kill the line
        result["config1_error"] = f"{type(e).__name__}: {e}"
    try:
        result.update(_run_dedoppler(config_name))
    except Exception as e:  # noqa: BLE001 — secondary metric must not kill the line
        result["dedoppler_error"] = f"{type(e).__name__}: {e}"
    try:
        result.update(_run_collectives())
    except Exception as e:  # noqa: BLE001 — secondary metric must not kill the line
        result["collectives_error"] = f"{type(e).__name__}: {e}"
    try:
        result.update(_run_mesh_collectives())
    except Exception as e:  # noqa: BLE001 — secondary metric must not kill the line
        result["mesh_collectives_error"] = f"{type(e).__name__}: {e}"
    # Telemetry surfacing (ISSUE 5): span/flight-event counts plus any
    # process-timeline histograms ride the bench line, and the full fleet
    # report lands wherever BLIT_TELEMETRY_OUT points (the CI-artifact
    # hook; no-op when unset).
    try:
        from blit import observability

        result["telemetry"] = {
            "spans": len(observability.tracer().spans()),
            "flight_events": len(observability.flight_recorder().events()),
            "hists": observability.process_timeline().report().get(
                "hists", {}),
        }
        observability.maybe_write_report()
    except Exception as e:  # noqa: BLE001 — telemetry must not kill the line
        result["telemetry_error"] = f"{type(e).__name__}: {e}"
    # Perf-regression self-check (ISSUE 11): with BLIT_BENCH_BASELINE_DIR
    # pointing at the checked-in BENCH_*.json trajectory, this run diffs
    # itself against the noise bands and records the verdict in its own
    # line — the bench-diff gate with zero extra invocations.  Advisory
    # here (the line must always print); CI runs `blit bench-diff` as
    # the gating step.
    try:
        import glob
        import os as _os

        bdir = _os.environ.get("BLIT_BENCH_BASELINE_DIR")
        if bdir:
            from blit import monitor

            baselines = []
            for p in sorted(glob.glob(
                    _os.path.join(bdir, "BENCH_*.json"))):
                try:
                    baselines.append(monitor.load_bench_json(p))
                except ValueError:
                    # A failed round with no record line thins the
                    # trajectory; it must not break the self-check.
                    continue
            if baselines:
                diff = monitor.bench_diff(result, baselines)
                result["bench_diff"] = {
                    "verdict": diff["verdict"],
                    "regressed": diff["regressed"],
                    "baselines": diff["baselines"],
                }
    except Exception as e:  # noqa: BLE001 — the gate must not kill the line
        result["bench_diff_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result))


def _staging_stats() -> dict:
    """The process staging pool's hit counters (blit/hostmem.py) — a
    reuse rate near zero on a long run means the pool budget is too
    small for the product shape."""
    try:
        from blit import hostmem

        return hostmem.slab_pool().stats()
    except Exception as e:  # noqa: BLE001 — provenance must not kill the line
        return {"error": f"{type(e).__name__}: {e}"}


def _run_ingest(config_name: str) -> dict:
    """File→product throughput: synthetic RAW on a ram-backed dir, streamed
    through :class:`blit.pipeline.RawReducer` (native threaded reads + ring
    buffer + jitted channelize + full host readback of the product)."""
    import os
    import shutil
    import tempfile

    from blit.io.guppi import GuppiRaw, write_raw
    from blit.outplane import INGEST_HISTS
    from blit.pipeline import RawReducer
    from blit.testing import make_raw_header

    nfft, nchan, chunk_frames, nblocks, ntime = _INGEST_CONFIGS[config_name]
    # Same working dtype as the primary leg (keeps the jit cache shared).
    *_, dtype = _CONFIGS[config_name]
    rng = np.random.default_rng(1)
    tmp = tempfile.mkdtemp(
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None
    )
    try:
        path = os.path.join(tmp, "bench.raw")
        hdr = make_raw_header(obsnchan=nchan, npol=2)
        blocks = [
            rng.integers(-40, 40, (nchan, ntime, 2, 2)).astype(np.int8)
            for _ in range(nblocks)
        ]
        write_raw(path, hdr, blocks)
        file_bytes = sum(b.nbytes for b in blocks)

        # BLIT_BENCH_TRACE=<logdir> wraps the streaming run in a JAX
        # profiler trace (TensorBoard/Perfetto) without touching the metric.
        red = RawReducer(nfft=nfft, nint=1, stokes="I",
                         chunk_frames=chunk_frames, dtype=dtype,
                         trace_logdir=os.environ.get("BLIT_BENCH_TRACE") or None)
        raw = GuppiRaw(path)
        # Producer-only read pass FIRST: measures the host read leg clean of
        # device/tunnel interference (best of 2 — the shared single-vCPU rig
        # has noisy-neighbor variance), and doubles as steady-state warmup
        # (page cache + buffer first-touch faults) for the timed run below,
        # matching the compute leg's compile warmup.
        host_read_gbps = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            for c in red._chunks(raw):
                c.release()
            host_read_gbps = max(
                host_read_gbps,
                file_bytes / (time.perf_counter() - t0) / 1e9,
            )
        # Discard warmup passes IN PLACE (Timeline.reset) — NOT
        # stages.clear(): clear() orphans any StageStats object a thread
        # or captured local still holds, so later byte/second updates
        # land in objects the report never sees.  That identity bug is
        # how the seed-era rig reported BENCH_r05's
        # "stream": {"s": 350.3, "bytes": 0} (ISSUE 4 satellite;
        # tests/test_outplane.py pins this exact warmup→reset→drain
        # sequence).
        red.timeline.reset()
        t0 = time.perf_counter()
        checksum = red.drain(raw)
        elapsed = time.perf_counter() - t0

        # Rig characterization: device→host bandwidth (NOT part of the
        # metric — the dev tunnel reads back at ~10 MB/s where a TPU host's
        # PCIe does GB/s; the drain keeps the product device-side and the
        # framework's own write path is bounded by this link, so the honest
        # per-rig number is reported alongside).
        import jax
        import jax.numpy as jnp

        y = jax.block_until_ready(jnp.zeros((1 << 21,), jnp.float32))  # 8 MB
        t1 = time.perf_counter()
        np.asarray(y)
        readback_gbps = y.nbytes / (time.perf_counter() - t1) / 1e9

        # Product leg (ISSUE 4): the SAME recording reduced to an actual
        # on-disk product through the asynchronous output plane — host
        # read → H2D → compute → D2H readback → write-behind .fil append
        # all overlapped (blit/outplane.py).  fqav_by=16 is the paper's
        # reduce-before-the-wire lever: the product (hence the slow-link
        # readback) shrinks 16x, and the fqav compile is already warm
        # from the primary leg's fqav16 pass.  The stage table carries
        # the new readback/write stages and the overlap gauge
        # (sum of device+readback+write seconds per stream-wall second;
        # ~1 = serialized — the BENCH_r05 collapse — higher = hidden).
        product = {}
        try:
            def product_leg(async_output: bool, name: str) -> dict:
                # tune_online=False: with BLIT_TUNE_ONLINE=1 the async
                # leg could persist a profile mid-bench that the sync
                # leg then loads — the A/B must compare ONE knob set
                # (same reason ingest-bench pins it).
                redp = RawReducer(nfft=nfft, nint=1, stokes="I",
                                  chunk_frames=chunk_frames, dtype=dtype,
                                  fqav_by=16, async_output=async_output,
                                  tune_online=False)
                t2 = time.perf_counter()
                redp.reduce_to_file(raw, os.path.join(tmp, name))
                elp = time.perf_counter() - t2
                return {
                    "async_output": async_output,
                    "wall_s": round(elp, 3),
                    "gbps": round(file_bytes / elp / 1e9, 3),
                    "overlap_efficiency": round(
                        redp.timeline.overlap_efficiency(), 3
                    ),
                    "stages": {
                        k: {"s": round(v.seconds, 3), "bytes": v.bytes}
                        for k, v in redp.timeline.stages.items()
                    },
                    # Stage TAILS from the telemetry hists (ISSUE 8):
                    # p50/p99 readback lag / write / chunk service.
                    "stage_quantiles": redp.timeline.hist_quantiles(
                        INGEST_HISTS),
                }

            # Before/after --sync-compare table ON the bench artifact
            # (ISSUE 8 acceptance): the same recording through the async
            # plane and the serialized path, byte-identity checked.
            pa = product_leg(True, "bench.0000.fil")
            ps = product_leg(False, "bench.sync.0000.fil")
            from blit.testing import sync_compare_verdict

            product = {
                "rig_product_gbps": pa["gbps"],
                "product_config": {
                    "fqav_by": 16,
                    "sink": ".fil (async output plane)",
                    "overlap_efficiency": pa["overlap_efficiency"],
                    "stages": pa["stages"],
                    "stage_quantiles": pa["stage_quantiles"],
                    "sync_compare": ps,
                    **sync_compare_verdict(
                        os.path.join(tmp, "bench.0000.fil"),
                        os.path.join(tmp, "bench.sync.0000.fil"),
                        async_wall_s=pa["wall_s"],
                        sync_wall_s=ps["wall_s"]),
                },
            }
        except Exception as e:  # noqa: BLE001 — secondary leg must not kill the line
            product = {"rig_product_error": f"{type(e).__name__}: {e}"}

        return {
            **product,
            # "rig_" prefix: this end-to-end figure is dominated by the dev
            # rig's tunneled host->device link (see the stage table and
            # rig_readback_gbps), NOT by the framework — host_read_gbps and
            # the primary chip metric are the framework numbers.
            "rig_ingest_gbps": round(file_bytes / elapsed / 1e9, 3),
            "ingest_config": {
                "nfft": nfft,
                "nchan": nchan,
                "chunk_frames": chunk_frames,
                "dtype": dtype,
                "prefetch_depth": red.prefetch_depth,
                "host_read_gbps": round(host_read_gbps, 3),
                "file_bytes": file_bytes,
                "out_frames": red.stats.output_frames,
                "checksum": checksum,
                "native_reader": raw.native,
                "sink": "device (see DESIGN.md §8)",
                "rig_readback_gbps": round(readback_gbps, 4),
                # Which ingest knobs ran and where they came from
                # (explicit bench pin / per-rig tuning profile / default
                # — blit/tune.py; ISSUE 8 satellite).
                "tuning": red.tuning_provenance(),
                "staging_pool": _staging_stats(),
                "stages": {
                    k: {"s": round(v.seconds, 3), "bytes": v.bytes}
                    for k, v in red.timeline.stages.items()
                },
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _run_collectives() -> dict:
    """BASELINE configs 4-5: coherent beamform and FX correlator throughput
    on the real chip (1x1 mesh — the per-chip math plus the collective code
    path; ICI scaling is validated separately on the virtual mesh).
    Reported as GB/s of planar antenna voltages consumed.

    The inputs are REAL per-antenna GUPPI RAW files on a ram-backed dir,
    loaded through the WINDOWED antenna data plane
    (blit/parallel/antenna.py streams — the collective legs consume the
    same bytes a recording would provide, not rng arrays; VERDICT r3
    item 4).  Device residents for the K-dispatch chip numbers come from
    a one-window feed; the ``*_stream_*`` legs then run genuinely
    multi-window (ingest/pack/transfer overlapping compute at
    ``prefetch_depth`` windows of host memory — recording length no
    longer bounds host RSS) and report per-window stage timings with
    byte counts (``rig_*_feed`` — "rig_" because on this 1-core tunneled
    rig the host+transfer legs are environment-bound); the chip numbers
    are the headline.
    """
    import os
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from blit.observability import Timeline
    from blit.ops.channelize import pfb_coeffs
    from blit.parallel import antenna as A
    from blit.parallel import beamform as B
    from blit.parallel import correlator as C
    from blit.parallel import mesh as M
    from blit.testing import synth_raw

    mesh = M.make_mesh(1, 1)
    rng = np.random.default_rng(3)
    out = {}

    def stage_table(tl: Timeline) -> dict:
        """ONE serializer for every collective stage table (s/bytes per
        stage + the byte_free marker, so each report can be checked
        against the nonzero-seconds ⇒ nonzero-bytes-or-byte-free
        invariant).  list(): feed producer threads may still be
        inserting stage keys."""
        return {
            k: {"s": round(v.seconds, 3), "bytes": v.bytes,
                **({"byte_free": True} if v.byte_free else {})}
            for k, v in sorted(list(tl.stages.items()))
        }

    def feed_report(tl: Timeline, seconds: float) -> dict:
        """A feed Timeline as the JSON report block."""
        return {"seconds": round(seconds, 3), "stages": stage_table(tl)}

    tmp = tempfile.mkdtemp(
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None
    )
    try:

        def ant_files(tag, nant, nchan, ntime):
            paths = []
            for a in range(nant):
                p = os.path.join(tmp, f"{tag}{a}.raw")
                synth_raw(p, nblocks=2, obsnchan=nchan,
                          ntime_per_block=ntime // 2, seed=300 + a,
                          tone_chan=a % nchan)
                paths.append(p)
            return paths

        # Beamform: 64 antennas -> 64 beams, detect+integrate.
        nant, nbeam, nchan, ntime, npol, nint = 64, 64, 64, 8192, 2, 8
        # Fixture synthesis happens OUTSIDE the timed load window — the
        # feed legs measure the antenna data plane (file read + dequant +
        # device_put), not rng writes a real recording never incurs.
        paths = ant_files("bf", nant, nchan, ntime)
        # Device residents via a ONE-WINDOW feed (the windowed data plane
        # is the only load path now); the window stays unreleased for the
        # whole K-loop — its arrays may alias the slot's host buffers.
        tl_bf = Timeline()
        t0 = time.perf_counter()
        bf_wins = list(A.AntennaStream(
            paths, mesh=mesh, window_samples=ntime, max_samples=ntime,
            timeline=tl_bf,
        ))
        jax.block_until_ready(bf_wins[0].arrays)
        out["rig_beamform_feed"] = feed_report(
            tl_bf, time.perf_counter() - t0
        )
        vp = bf_wins[0].arrays
        wr, wi = B.delay_weights_planar(
            jnp.asarray(rng.uniform(0, 1e-9, (nbeam, nant))),
            jnp.asarray(np.linspace(1e9, 1.1e9, nchan)),
        )
        wp = jax.device_put((np.asarray(wr), np.asarray(wi)),
                            B.weight_sharding(mesh))
        jax.block_until_ready(wp)

        # bf16-resident planes: lossless for 8-bit RAW voltages, half the
        # HBM reads (measured +26%, DESIGN.md §9 r5; ~1e-2 max rel err on
        # detected power from weight rounding + bf16 partial sums).
        vp16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), vp)
        jax.block_until_ready(vp16)

        def bstep():
            return jnp.sum(B.beamform(vp, wp, mesh=mesh, nint=nint))

        def bstep16():
            return jnp.sum(B.beamform(vp16, wp, mesh=mesh, nint=nint))

        float(bstep())  # compile
        float(bstep16())
        # These calls run ~10 ms each — far below the tunnel's ~100 ms
        # closing-fetch latency, which K=4 buried the measurement under
        # (round 3 reported 6.5 GB/s for a ~22 GB/s correlator; the
        # round-4 roofline caught it, tools/roofline_fx.py).  48 reps
        # make the amortized fetch share a few percent.
        K = 48
        # In-order queue: sync the last dispatch only (see run_single).
        t0 = time.perf_counter()
        acc = [bstep() for _ in range(K)]
        float(acc[-1])
        el = time.perf_counter() - t0
        nbytes = vp[0].nbytes + vp[1].nbytes
        out["beamform_gbps"] = round(nbytes * K / el / 1e9, 3)
        out["beamform_config"] = {
            "nant": nant, "nbeam": nbeam, "nchan": nchan, "ntime": ntime,
            "npol": npol, "nint": nint, "input_bytes": nbytes,
            "source": "raw_files",
        }
        # Same voltages, bf16-resident: GB/s in f32-equivalent bytes so
        # the two legs compare like-for-like (the bf16 planes MOVE half).
        t0 = time.perf_counter()
        acc = [bstep16() for _ in range(K)]
        float(acc[-1])
        el = time.perf_counter() - t0
        out["beamform_bf16_gbps"] = round(nbytes * K / el / 1e9, 3)
        del vp16

        # Fused beamform+detect (round 5): packed chan-major bf16 planes
        # from the SAME recordings through the VMEM-resident kernel
        # (beamform(layout="chan") — beam planes never touch HBM;
        # measured 2.1x the einsum path, DESIGN.md §9 r5 addendum).
        from jax.sharding import NamedSharding, PartitionSpec as P

        from blit.ops.pallas_beamform import pack_weights

        chan_wins = list(A.AntennaStream(
            paths, mesh=mesh, window_samples=ntime, max_samples=ntime,
            dtype="bfloat16", layout="chan",
        ))
        vpc = chan_wins[0].arrays
        kwr, kwi = pack_weights(jnp.asarray(np.asarray(wr)),
                                jnp.asarray(np.asarray(wi)))
        kwp = jax.device_put(
            (np.asarray(kwr), np.asarray(kwi)),
            NamedSharding(mesh, P(None, None, "bank")),
        )
        jax.block_until_ready((vpc, kwp))

        def bstep_fused():
            return jnp.sum(B.beamform(vpc, kwp, mesh=mesh, nint=nint,
                                      layout="chan"))

        float(bstep_fused())
        # The number is only honest if the pallas path dispatched: a
        # silent einsum fallback must not masquerade as "fused" — record
        # the fallback as an explicit error field and skip the number
        # (NOT a raise: that used to kill every later collective leg on
        # rigs whose backend can't fuse, exactly where the windowed
        # stream legs below still carry signal).
        if B.last_beamform_plan().get("fused"):
            float(bstep_fused())  # absorb the rig's one-off first-call alloc
            t0 = time.perf_counter()
            acc = [bstep_fused() for _ in range(K)]
            float(acc[-1])
            el = time.perf_counter() - t0
            out["beamform_fused_gbps"] = round(nbytes * K / el / 1e9, 3)
        else:
            out["beamform_fused_error"] = (
                f"fell back to einsums: {B.last_beamform_plan()}"
            )
        del vpc
        for w_ in chan_wins:
            w_.release()
        del chan_wins

        # WINDOWED streaming beamform leg: the same recordings through a
        # genuinely multi-window feed + beamform_stream — end-to-end
        # file→beam-power at prefetch_depth-bounded host memory, with
        # per-window stage timings (the mesh analog of rig_ingest_gbps;
        # acceptance: ingest/transfer/compute each carry bytes or are
        # declared byte-free).
        tl_s = Timeline()
        wsamp = ntime // 4
        feed = A.AntennaStream(
            paths, mesh=mesh, window_samples=wsamp, max_samples=ntime,
            timeline=tl_s,
        )
        per_window = []
        snap = tl_s.snapshot()
        t0 = time.perf_counter()
        for _slab in B.beamform_stream(feed, wp, mesh=mesh, nint=nint,
                                       timeline=tl_s):
            if len(per_window) < 3:
                per_window.append(tl_s.since(snap))
            snap = tl_s.snapshot()
        el = time.perf_counter() - t0
        fed = nant * nchan * ntime * npol * 2  # int8 RAW bytes consumed
        out["rig_beamform_stream_gbps"] = round(fed / el / 1e9, 3)
        out["rig_beamform_stream"] = {
            "windows": feed.nwindows,
            "window_samples": wsamp,
            "prefetch_depth": feed.prefetch_depth,
            "seconds": round(el, 3),
            "stages": stage_table(tl_s),
            "per_window": per_window,
        }
        del vp
        for w_ in bf_wins:
            w_.release()
        del bf_wins

        # FX correlator: 8 antennas, PFB+DFT F-engine + full visibility matrix.
        nant, nchan, nfft, ntap, npol = 8, 64, 512, 4, 2
        ntime = 64 * nfft
        paths = ant_files("fx", nant, nchan, ntime)
        tl_fx = Timeline()
        t0 = time.perf_counter()
        fx_wins = list(A.CorrelatorStream(
            paths, mesh=mesh, nfft=nfft, ntap=ntap,
            window_frames=ntime // nfft - ntap + 1, max_samples=ntime,
            timeline=tl_fx,
        ))
        jax.block_until_ready(fx_wins[0].arrays)
        out["rig_correlator_feed"] = feed_report(
            tl_fx, time.perf_counter() - t0
        )
        cvp = fx_wins[0].arrays
        h = jnp.asarray(pfb_coeffs(ntap, nfft))

        def cstep():
            visr, visi = C.correlate(cvp, h, mesh=mesh, nfft=nfft, ntap=ntap)
            return jnp.sum(visr) + jnp.sum(visi)

        float(cstep())
        t0 = time.perf_counter()
        acc = [cstep() for _ in range(K)]
        float(acc[-1])
        el = time.perf_counter() - t0
        nbytes = cvp[0].nbytes + cvp[1].nbytes
        out["correlator_gbps"] = round(nbytes * K / el / 1e9, 3)
        out["correlator_config"] = {
            "nant": nant, "nchan": nchan, "nfft": nfft, "ntap": ntap,
            "ntime": ntime, "npol": npol, "input_bytes": nbytes,
            "source": "raw_files",
        }
        del cvp
        for w_ in fx_wins:
            w_.release()
        del fx_wins

        # FX correlator at ARRAY SCALE (VERDICT r4 item 1): 64 antennas —
        # (nant*npol)^2 = 128^2 baseline tiles, exactly MXU-sized — through
        # the packed-layout pallas X-engine (correlate(vis_layout="packed"),
        # blit/ops/pallas_xengine.py; measured +19% over the einsum
        # X-engine at this shape, DESIGN.md §9 r5 addendum).  nchan=16
        # keeps visibilities + spectra + inputs comfortably inside HBM.
        nant, nchan, nfft, ntap, npol = 64, 16, 512, 4, 2
        ntime = 64 * nfft
        h = jnp.asarray(pfb_coeffs(ntap, nfft))  # local: don't lean on the
        # nant=8 section happening to share (ntap, nfft)
        paths = ant_files("fx64", nant, nchan, ntime)
        tl_fx64 = Timeline()
        t0 = time.perf_counter()
        fx64_wins = list(A.CorrelatorStream(
            paths, mesh=mesh, nfft=nfft, ntap=ntap,
            window_frames=ntime // nfft - ntap + 1, max_samples=ntime,
            timeline=tl_fx64,
        ))
        jax.block_until_ready(fx64_wins[0].arrays)
        out["rig_correlator64_feed"] = feed_report(
            tl_fx64, time.perf_counter() - t0
        )
        cvp = fx64_wins[0].arrays

        cvp16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), cvp)
        jax.block_until_ready(cvp16)

        def c64step():
            visr, visi = C.correlate(cvp, h, mesh=mesh, nfft=nfft,
                                     ntap=ntap, vis_layout="packed")
            return jnp.sum(visr) + jnp.sum(visi)

        def c64step16():
            visr, visi = C.correlate(cvp16, h, mesh=mesh, nfft=nfft,
                                     ntap=ntap, vis_layout="packed")
            return jnp.sum(visr) + jnp.sum(visi)

        float(c64step())
        # Provenance follows the ACTUAL dispatch: _xengine_packed records
        # its trace-time gate decision (last_xengine_plan, the
        # last_beamform_plan convention) — the gate runs on per-shard
        # LOCAL shapes, so re-deriving it here from global shapes would
        # drift (ADVICE r5 low).  Read it right after the f32 warmup
        # trace (the bf16 warmup below re-traces with itemsize 2).
        plan = C.last_xengine_plan()
        xe = (
            "pallas" if plan.get("engine") == "pallas" else "einsum-packed"
        )
        float(c64step16())
        K64 = 24  # ~21 ms/call: K*c >= 400 ms amortizes the closing fetch
        t0 = time.perf_counter()
        acc = [c64step() for _ in range(K64)]
        float(acc[-1])
        el = time.perf_counter() - t0
        nbytes = cvp[0].nbytes + cvp[1].nbytes
        out["correlator64_gbps"] = round(nbytes * K64 / el / 1e9, 3)
        out["correlator64_config"] = {
            "nant": nant, "nchan": nchan, "nfft": nfft, "ntap": ntap,
            "ntime": ntime, "npol": npol, "input_bytes": nbytes,
            "vis_layout": "packed", "x_engine": xe,
            "source": "raw_files",
        }
        # bf16-staged (f32-equivalent bytes; measured +25% in the
        # controlled A/B — DESIGN.md §9 r5 addendum).
        t0 = time.perf_counter()
        acc = [c64step16() for _ in range(K64)]
        float(acc[-1])
        el = time.perf_counter() - t0
        out["correlator64_bf16_gbps"] = round(nbytes * K64 / el / 1e9, 3)
        del cvp, cvp16
        for w_ in fx64_wins:
            w_.release()
        del fx64_wins

        # WINDOWED streaming correlator leg: the nant=8 recordings through
        # a multi-window CorrelatorStream + correlate_stream — file→
        # integrated visibilities with the PFB tail carried between
        # windows and the accumulator folded on-device, at
        # prefetch_depth-bounded host memory.
        nant, nchan, nfft, ntap, npol = 8, 64, 512, 4, 2
        ntime = 64 * nfft
        h = jnp.asarray(pfb_coeffs(ntap, nfft))
        paths = ant_files("fxs", nant, nchan, ntime)
        tl_cs = Timeline()
        wf = (ntime // nfft - ntap + 1) // 4  # 4 windows + remainder
        feed = A.CorrelatorStream(
            paths, mesh=mesh, nfft=nfft, ntap=ntap, window_frames=wf,
            max_samples=ntime, timeline=tl_cs,
        )
        per_window = []
        snap = tl_cs.snapshot()
        t0 = time.perf_counter()

        def _fx_windows():
            nonlocal snap
            for win in feed:
                if len(per_window) < 3:
                    per_window.append(tl_cs.since(snap))
                snap = tl_cs.snapshot()
                yield win

        visr, visi = C.correlate_stream(
            _fx_windows(), h, mesh=mesh, nfft=nfft, ntap=ntap,
            timeline=tl_cs,
        )
        checksum = float(jnp.sum(visr) + jnp.sum(visi))
        el = time.perf_counter() - t0
        fed = nant * nchan * feed.seg * feed.nband * npol * 2
        out["rig_correlator_stream_gbps"] = round(fed / el / 1e9, 3)
        out["rig_correlator_stream"] = {
            "windows": feed.nwindows,
            "window_frames": wf,
            "prefetch_depth": feed.prefetch_depth,
            "seconds": round(el, 3),
            "checksum": checksum,
            "stages": stage_table(tl_cs),
            "per_window": per_window,
        }
        return out
    finally:
        # RAM-backed fixtures must not outlive the run, success or
        # not — repeated failed attempts would exhaust /dev/shm.
        shutil.rmtree(tmp, ignore_errors=True)

def _run_mesh_collectives() -> dict:
    """The sharded plane's collective probe (ISSUE 9): pure all_gather
    and psum programs over whatever mesh THIS rig's devices form,
    reporting per-chip vs aggregate ICI GB/s and the ``mesh.gather_s`` /
    ``mesh.psum_s`` p50/p99 quantiles through the PR 5 histogram
    machinery — the same hists the sharded scan's probe windows feed, so
    a bench artifact and a production scan report read alike.

    On a 1-chip rig the gather leg degenerates (no ICI; recorded as
    such) — the multi-device numbers come from pods and from the CI
    virtual mesh.  The provenance block also records the (2, n/2)
    band-axis dryrun parity result (``__graft_entry__.dryrun_multichip``
    run on a virtual CPU pod in a SUBPROCESS, so the real backend held
    by this process is never clobbered)."""
    import os
    import subprocess

    import jax

    from blit.observability import Timeline
    from blit.parallel import mesh as M

    devs = jax.devices()
    n = len(devs)
    nbank = max(k for k in (1, 2, 4, 8) if k <= n)
    mesh = M.make_mesh(1, nbank, devices=devs)
    tl = Timeline()
    rng = np.random.default_rng(7)
    K = 24
    out = {"mesh_collectives": {}}
    cfg = out["mesh_collectives"]

    # all_gather leg: a bank-sharded filterbank block through the scan
    # plane's own stitch program (blit/parallel/mesh.stitch_despike).
    t, F = 16, nbank * 4096
    x = jax.device_put(
        rng.standard_normal((1, t, 1, F)).astype(np.float32),
        M.sharding_for(mesh, "filterbank_sharded"),
    )
    jax.block_until_ready(x)
    shard_bytes = x.nbytes // nbank
    ici = M.gather_ici_bytes(shard_bytes, nbank)
    y = M.stitch_despike(x, mesh=mesh, despike_nfpc=0)  # compile
    jax.block_until_ready(y)
    for _ in range(K):
        t0 = time.perf_counter()
        y = M.stitch_despike(x, mesh=mesh, despike_nfpc=0)
        jax.block_until_ready(y)
        M.record_ici(tl, "gather", ici, time.perf_counter() - t0)
    g = tl.hists["mesh.gather_s"]
    p50 = g.percentile(50) or float("inf")
    cfg["gather"] = {
        "mesh": [1, nbank],
        "operand_bytes": x.nbytes,
        "ici_bytes_per_chip": ici,
        "per_chip_gbps": round(ici / p50 / 1e9, 3),
        "aggregate_gbps": round(ici * nbank / p50 / 1e9, 3),
    }

    # psum leg: the correlator's closing collective — a band-axis psum
    # over a (2, n/2) mesh when the rig has one.
    if n >= 4 and n % 2 == 0:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from blit.compat import shard_map

        mesh2 = M.make_mesh(2, n // 2, devices=devs)
        rows = 64
        v = jax.device_put(
            rng.standard_normal((2 * rows, 4096)).astype(np.float32),
            NamedSharding(mesh2, P("band", None)),
        )
        jax.block_until_ready(v)

        @jax.jit
        def pfn(v):
            return shard_map(
                lambda b: jax.lax.psum(b, "band"), mesh=mesh2,
                in_specs=P("band", None), out_specs=P(None, None),
                check_vma=False,
            )(v)

        w = pfn(v)
        jax.block_until_ready(w)
        per_chip = v.nbytes // 2  # the per-chip band block
        ici_p = M.psum_ici_bytes(per_chip, 2)
        for _ in range(K):
            t0 = time.perf_counter()
            w = pfn(v)
            jax.block_until_ready(w)
            M.record_ici(tl, "psum", ici_p, time.perf_counter() - t0)
        p = tl.hists["mesh.psum_s"]
        p50p = p.percentile(50) or float("inf")
        cfg["psum"] = {
            "mesh": [2, n // 2],
            "operand_bytes": per_chip,
            "ici_bytes_per_chip": ici_p,
            "per_chip_gbps": round(ici_p / p50p / 1e9, 3),
            "aggregate_gbps": round(ici_p * n / p50p / 1e9, 3),
        }
    else:
        cfg["psum"] = {"skipped": f"{n} device(s): no (2, n/2) band axis"}

    # The p50/p99 tails (MESH_HISTS) + per-collective ICI byte hists —
    # the acceptance's provenance block.
    cfg["quantiles"] = tl.hist_quantiles()
    cfg["ici_stage"] = {
        "calls": tl.stages["mesh.ici"].calls,
        "bytes": tl.stages["mesh.ici"].bytes,
    }

    # Band-axis dryrun parity (the (2, n/2) pass of dryrun_multichip,
    # incl. the sharded-vs-per-chip byte-identity assertion) on a
    # subprocess virtual CPU pod.
    try:
        entry = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "__graft_entry__.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8")
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, sys.argv[1]); "
             "from __graft_entry__ import dryrun_multichip; "
             "import json; print(json.dumps(dryrun_multichip(8)))",
             os.path.dirname(entry)],
            env=env, capture_output=True, text=True, timeout=900,
        )
        lines = proc.stdout.strip().splitlines()
        if proc.returncode == 0 and lines:
            cfg["band_axis_dryrun"] = json.loads(lines[-1])
        else:
            tail = proc.stderr.strip().splitlines()
            cfg["band_axis_dryrun"] = {
                "ok": False, "error": (tail[-1] if tail else
                                       f"rc={proc.returncode}"),
            }
    except Exception as e:  # noqa: BLE001 — provenance must not kill the leg
        cfg["band_axis_dryrun"] = {"ok": False,
                                   "error": f"{type(e).__name__}: {e}"}
    return out


def _run_config1() -> dict:
    """BASELINE config 1: single-bank ``0002.h5`` read → integrated power
    spectrum — the reference's core read path (worker ``getdata`` +
    post-read ``fqav``, src/gbtworkerfunctions.jl:179-189) over a
    bitshuffle-compressed FBH5 file on a ram-backed dir.  Host-side only;
    reported as GB/s of decompressed filterbank payload."""
    import os
    import shutil
    import tempfile

    from blit import workers
    from blit.io.bshuf import available as bshuf_available
    from blit.io.fbh5 import write_fbh5
    from blit.testing import make_fil_header, make_spectra

    nsamps, nifs, nchans, fqav_by = 256, 1, 1 << 20, 16
    compression = "bitshuffle" if bshuf_available() else None
    tmp = tempfile.mkdtemp(
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None
    )
    try:
        path = os.path.join(tmp, "bench.rawspec.0002.h5")
        hdr = make_fil_header(nchans=nchans, nifs=nifs, foff=-187.5 / nchans)
        hdr["nfpc"] = nchans // 64
        data = make_spectra(nsamps, nifs, nchans, seed=2)
        write_fbh5(path, hdr, data, compression=compression,
                   chunks=(nsamps, nifs, nchans // 64))
        payload = data.nbytes

        # Warm the reader once (h5py/libhdf5 init), then time the measured
        # read: full-file hyperslab read + worker-side fqav to the
        # integrated spectrum (the bytes that would otherwise cross the
        # wire shrink by fqav_by).  Best of 2 — the shared single-vCPU rig
        # carries noisy-neighbor contention from the preceding device legs
        # (same rule as the ingest leg's host_read).
        workers.get_data(path, (slice(0, 1), slice(None), slice(None)))
        elapsed = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            spec = workers.get_data(path, fqav_by=fqav_by)
            elapsed = min(elapsed, time.perf_counter() - t0)
        assert spec.shape == (nsamps, nifs, nchans // fqav_by)
        return {
            "config1_gbps": round(payload / elapsed / 1e9, 3),
            "config1_config": {
                "nsamps": nsamps,
                "nifs": nifs,
                "nchans": nchans,
                "fqav_by": fqav_by,
                "payload_bytes": payload,
                "compression": compression or "none",
                "checksum": float(spec.sum()),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# Search-plane science leg shapes: (window_spectra T, channels F, reps K).
# The metric is drift-rate trials/s/chip — (2T-1) drift rows × F channels
# × K windows scored per second by the on-device tree + SNR + per-band
# top-k step (blit/ops/pallas_dedoppler), device-resident with a single
# closing fetch like the primary leg.
_DEDOPPLER_CONFIGS = {
    "tpu_bf16": (64, 1 << 20, 8),
    "tpu": (64, 1 << 20, 8),
    "tpu_small": (32, 1 << 19, 8),
    "cpu": (16, 1 << 14, 4),
}


def _run_dedoppler(config_name: str) -> dict:
    """The search plane's science metric (ISSUE 6 / ROADMAP item 4):
    sustained drift-rate trials per second of the jitted on-device
    dedoppler step over synthetic windows with an injected drifting
    tone (which doubles as a liveness check: the tone must surface as
    the strongest hit)."""
    import functools

    import jax
    import jax.numpy as jnp

    from blit.ops.pallas_dedoppler import dedoppler_hits, unpack_hits

    T, F, K = _DEDOPPLER_CONFIGS[config_name]
    nbands = max(1, F >> 14)  # ~one band per 16k channels
    rng = np.random.default_rng(3)
    x = rng.normal(100.0, 10.0, size=(T, F)).astype(np.float32)
    # A clean drifting tone along the tree's own drift-7 path.
    from blit.ops.pallas_dedoppler import tree_path_shift

    f0, db = F // 3, min(7, T - 1)
    for t in range(T):
        x[t, f0 + tree_path_shift(db, t, T)] += 400.0
    # dedoppler_hits is module-level jitted (knobs static); binding the
    # knobs is enough.
    fn = functools.partial(dedoppler_hits, top_k=4, nbands=nbands,
                           kernel="auto")
    xj = jax.block_until_ready(jnp.asarray(x))
    thr = jnp.float32(8.0)
    packed = jax.block_until_ready(fn(xj, thr))  # warmup / compile
    snr, _, drift, chan, _ = unpack_hits(np.asarray(packed))
    top = int(np.argmax(snr)) if len(snr) else -1
    t0 = time.perf_counter()
    acc = [fn(xj, thr) for _ in range(K)]
    jax.block_until_ready(acc[-1])
    elapsed = time.perf_counter() - t0
    trials = (2 * T - 1) * F * K
    return {
        "dedoppler_drift_rates_per_s": round(trials / elapsed, 1),
        "dedoppler_config": {
            "window_spectra": T,
            "nchans": F,
            "nbands": nbands,
            "calls": K,
            "seconds": round(elapsed, 3),
            "tone_recovered": bool(
                top >= 0 and int(drift[top]) == db and int(chan[top]) == f0
            ),
        },
    }


def _probe_backend() -> str:
    """Backend name, probed in a SUBPROCESS — the orchestrator must never
    initialize JAX itself, or it would hold the chip for its whole lifetime
    and starve every ``--single`` child of the device."""
    proc = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.default_backend())"],
        capture_output=True, text=True, timeout=180,
    )
    lines = proc.stdout.strip().splitlines()
    if proc.returncode != 0 or not lines:
        tail = proc.stderr.strip().splitlines()
        raise RuntimeError(tail[-1] if tail else "probe failed")
    return lines[-1]


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--single":
        run_single(sys.argv[2])
        return 0

    try:
        backend = _probe_backend()
    except Exception:
        backend = ""  # probe hiccup: try the chip, but keep the cpu fallback
    if backend == "cpu":
        config_names = ["cpu"]
    elif backend in ("tpu", "axon"):
        config_names = ["tpu_bf16", "tpu", "tpu_small"]
    else:
        config_names = ["tpu_bf16", "tpu", "tpu_small", "cpu"]

    last_err = "no attempts ran"
    for config_name in config_names:
        for attempt in range(_ATTEMPTS_PER_CONFIG):
            try:
                proc = subprocess.run(
                    [sys.executable, __file__, "--single", config_name],
                    capture_output=True, text=True,
                    timeout=_ATTEMPT_TIMEOUT_S,
                )
            except subprocess.TimeoutExpired:
                last_err = f"{config_name}#{attempt}: timeout"
                continue
            for line in reversed(proc.stdout.strip().splitlines()):
                try:
                    result = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue
                print(line)
                return 0
            last_err = (
                f"{config_name}#{attempt} rc={proc.returncode}: "
                + (proc.stderr.strip().splitlines() or ["no stderr"])[-1]
            )
            if attempt + 1 < _ATTEMPTS_PER_CONFIG:
                time.sleep(_BACKOFF_S[min(attempt, len(_BACKOFF_S) - 1)])

    # Every attempt failed: still emit a parseable record, but exit nonzero
    # so CI / calling scripts can detect the failure without parsing it.
    print(json.dumps({
        "metric": "guppi_raw_to_hires_filterbank_GBps_per_chip",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
        "error": last_err,
    }))
    return 1


if __name__ == "__main__":
    sys.exit(main())
