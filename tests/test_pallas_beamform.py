"""Fused beamform+detect kernel (blit/ops/pallas_beamform.py), interpret
mode, plus the packed chan-major ``beamform(layout="chan")`` path on the
virtual mesh (einsum fallback there — the fused kernel needs the real
backend AND a chip-local antenna axis; measured 2.1x, DESIGN.md §9 r5)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops.pallas_beamform import (  # noqa: E402
    fused_beamform_detect,
    pack_voltages,
    pack_weights,
    pick_tile,
)
from blit.parallel import beamform as B  # noqa: E402
from blit.parallel.mesh import make_mesh  # noqa: E402


def make_case(nant=4, nbeam=3, nchan=2, ntime=256, seed=0):
    rng = np.random.default_rng(seed)
    v = (rng.integers(-40, 41, (nant, nchan, ntime, 2))
         + 1j * rng.integers(-40, 41, (nant, nchan, ntime, 2))
         ).astype(np.complex64)
    w = (rng.standard_normal((nbeam, nant, nchan))
         + 1j * rng.standard_normal((nbeam, nant, nchan))
         ).astype(np.complex64)
    return v, w


class TestFusedKernel:
    @pytest.mark.parametrize("nint,tile", [(2, 64), (8, 128), (1, 32)])
    def test_matches_numpy(self, nint, tile):
        v, w = make_case(ntime=256)
        kvr, kvi = pack_voltages(jnp.asarray(v.real), jnp.asarray(v.imag))
        kwr, kwi = pack_weights(jnp.asarray(w.real), jnp.asarray(w.imag))
        got = np.asarray(fused_beamform_detect(
            kvr, kvi, kwr, kwi, nint=nint, tile=tile, interpret=True,
        ))
        want = B.beamform_np(v, w, nint=nint)  # (b, c, t_out, p)
        np.testing.assert_allclose(
            np.transpose(got, (1, 0, 3, 2)), want, rtol=1e-4,
            atol=1e-3 * np.abs(want).max(),
        )

    def test_bf16_operands_accumulate_f32(self):
        # The bench's fused leg feeds bf16-resident planes: the kernel's
        # dots must accumulate f32 (preferred_element_type) and the
        # integer-valued voltages stay exact through bf16.
        v, w = make_case(ntime=256, seed=3)
        kvr, kvi = pack_voltages(jnp.asarray(v.real), jnp.asarray(v.imag))
        kwr, kwi = pack_weights(jnp.asarray(w.real), jnp.asarray(w.imag))
        got = np.asarray(fused_beamform_detect(
            kvr.astype(jnp.bfloat16), kvi.astype(jnp.bfloat16),
            kwr.astype(jnp.bfloat16), kwi.astype(jnp.bfloat16),
            nint=2, tile=64, interpret=True,
        ))
        assert got.dtype == np.float32
        want = B.beamform_np(v, w, nint=2)
        # bf16 weights round (voltages are int-exact): ~1e-2 relative.
        np.testing.assert_allclose(
            np.transpose(got, (1, 0, 3, 2)), want, rtol=3e-2,
            atol=3e-2 * np.abs(want).max(),
        )

    def test_ineligible_shape_raises(self):
        z = jnp.zeros((1, 4, 2, 100), jnp.float32)
        w = jnp.zeros((1, 8, 4), jnp.float32)
        with pytest.raises(ValueError, match="eligible"):
            fused_beamform_detect(z, z, w, w, nint=8, interpret=True)

    def test_explicit_tile_validated(self):
        # An explicit tile that does not divide ntime would leave output
        # tail blocks UNWRITTEN (silent garbage) — the guard must fire
        # for caller-supplied tiles too, not just picked ones.
        z = jnp.zeros((1, 4, 2, 300), jnp.float32)
        w = jnp.zeros((1, 8, 4), jnp.float32)
        with pytest.raises(ValueError, match="tile"):
            fused_beamform_detect(z, z, w, w, nint=2, tile=256,
                                  interpret=True)
        with pytest.raises(ValueError, match="tile"):
            fused_beamform_detect(z, z, w, w, nint=4, tile=150,
                                  interpret=True)  # nint does not divide


class TestPickTile:
    def test_gate(self):
        # Bench shape: tile = nint*128 divides ntime and fits.
        assert pick_tile(64, 64, 2, 8192, 8) == 1024
        assert pick_tile(64, 64, 2, 8192, 8, itemsize=2) == 1024
        # ntime not divisible by nint*128 -> einsum path.
        assert pick_tile(64, 64, 2, 1000, 8) is None
        # nbeam must tile sublanes.
        assert pick_tile(64, 63, 2, 8192, 8) is None


class TestChanLayoutPath:
    def test_matches_antenna_layout(self):
        # The packed opt-in must compute the SAME product as the standard
        # layout (einsum fallback on this CPU mesh), axes permuted.
        v, w = make_case(nant=8, nbeam=5, nchan=4, ntime=64)
        m = make_mesh(1, 8)
        vp = jax.device_put(
            (v.real.copy(), v.imag.copy()), B.antenna_sharding(m)
        )
        wp = jax.device_put((w.real.copy(), w.imag.copy()),
                            B.weight_sharding(m))
        std = np.asarray(B.beamform(vp, wp, mesh=m, nint=4))

        from jax.sharding import NamedSharding, PartitionSpec as P

        kv = pack_voltages(jnp.asarray(v.real), jnp.asarray(v.imag))
        kw = pack_weights(jnp.asarray(w.real), jnp.asarray(w.imag))
        kvp = jax.device_put((np.asarray(kv[0]), np.asarray(kv[1])),
                             NamedSharding(m, P(None, "bank")))
        kwp = jax.device_put((np.asarray(kw[0]), np.asarray(kw[1])),
                             NamedSharding(m, P(None, None, "bank")))
        packed = np.asarray(B.beamform(kvp, kwp, mesh=m, nint=4,
                                       layout="chan"))
        assert packed.shape == (4, 5, 2, 16)  # (c, b, p, t_out)
        np.testing.assert_allclose(
            np.transpose(packed, (1, 0, 3, 2)), std, rtol=1e-4,
            atol=1e-3 * np.abs(std).max(),
        )

    def test_loader_chan_layout(self, tmp_path):
        from blit.parallel.antenna import load_antennas_mesh
        from blit.testing import synth_raw

        paths = []
        for a in range(8):
            p = str(tmp_path / f"a{a}.raw")
            synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=64, seed=a)
            paths.append(p)
        m = make_mesh(1, 8)
        hdr, (cr, ci) = load_antennas_mesh(paths, mesh=m, layout="chan")
        _, (ar, ai) = load_antennas_mesh(paths, mesh=m)
        assert cr.shape == (2, 8, 2, hdr["_ntime"])  # (c, a, p, t)
        np.testing.assert_array_equal(
            np.asarray(cr), np.transpose(np.asarray(ar), (1, 0, 3, 2))
        )
        np.testing.assert_array_equal(
            np.asarray(ci), np.transpose(np.asarray(ai), (1, 0, 3, 2))
        )
        with pytest.raises(ValueError, match="layout"):
            load_antennas_mesh(paths, mesh=m, layout="packed")

    def test_detect_false_complex_contract(self):
        # Same contract as the antenna layout: complex64 out when BOTH
        # inputs were complex, planar pair otherwise.
        v, w = make_case(nant=8, nbeam=5, nchan=4, ntime=64)
        m = make_mesh(1, 8)
        from jax.sharding import NamedSharding, PartitionSpec as P

        kv = np.transpose(v, (1, 0, 3, 2)).copy()
        kw = np.transpose(w, (2, 0, 1)).copy()
        kvp = jax.device_put(kv, NamedSharding(m, P(None, "bank")))
        kwp = jax.device_put(kw, NamedSharding(m, P(None, None, "bank")))
        beams = B.beamform(kvp, kwp, mesh=m, detect=False, layout="chan")
        assert beams.dtype == np.complex64
        br, bi = B.beamform(
            jax.device_put((kv.real.copy(), kv.imag.copy()),
                           NamedSharding(m, P(None, "bank"))),
            kwp, mesh=m, detect=False, layout="chan",
        )
        np.testing.assert_allclose(np.asarray(br), np.asarray(beams).real,
                                   rtol=1e-4, atol=1e-2)
        np.testing.assert_allclose(np.asarray(bi), np.asarray(beams).imag,
                                   rtol=1e-4, atol=1e-2)

    def test_nint_divisibility_checked(self):
        v, w = make_case(nant=8, nbeam=5, nchan=4, ntime=64)
        m = make_mesh(1, 8)
        from jax.sharding import NamedSharding, PartitionSpec as P

        kvp = jax.device_put(np.transpose(v, (1, 0, 3, 2)).copy(),
                             NamedSharding(m, P(None, "bank")))
        kwp = jax.device_put(np.transpose(w, (2, 0, 1)).copy(),
                             NamedSharding(m, P(None, None, "bank")))
        with pytest.raises(ValueError, match="does not divide"):
            B.beamform(kvp, kwp, mesh=m, nint=7, layout="chan")

    def test_dispatch_plan_recorded(self):
        # The fuse/fallback decision is attributable (the channelize
        # _LAST_PLAN convention); on this CPU mesh it must say fused=False.
        v, w = make_case(nant=8, nbeam=5, nchan=4, ntime=64)
        m = make_mesh(1, 8)
        from jax.sharding import NamedSharding, PartitionSpec as P

        kvp = jax.device_put(np.transpose(v, (1, 0, 3, 2)).copy(),
                             NamedSharding(m, P(None, "bank")))
        kwp = jax.device_put(np.transpose(w, (2, 0, 1)).copy(),
                             NamedSharding(m, P(None, None, "bank")))
        B.beamform(kvp, kwp, mesh=m, nint=4, layout="chan")
        assert B.last_beamform_plan() == {"layout": "chan", "fused": False}

    def test_bad_layout_rejected(self):
        v, w = make_case(nant=8)
        m = make_mesh(1, 8)
        with pytest.raises(ValueError, match="layout"):
            B.beamform(
                jax.device_put(v, B.antenna_sharding(m)),
                jax.device_put(w, B.weight_sharding(m)),
                mesh=m, layout="fast",
            )
