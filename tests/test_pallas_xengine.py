"""VMEM-resident packed X-engine (blit/ops/pallas_xengine.py), interpret
mode — the kernel behind ``correlate(vis_layout="packed")`` at MXU-sized
baseline counts (nant·npol >= 128; measured +19% whole-call at nant=64,
DESIGN.md §9 round-5 addendum)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops.pallas_xengine import (  # noqa: E402
    eligible,
    pick_ft,
    xengine_packed,
)


def golden_packed(sr, si):
    s = sr + 1j * si
    nchan, nfft = s.shape[1], s.shape[4]
    nap = s.shape[0] * s.shape[2]
    vis = np.einsum("acptf,bcqtf->cfapbq", s, np.conj(s))
    return vis.reshape(nchan, nfft, nap, nap)


class TestKernel:
    @pytest.mark.parametrize("nant,nchan,nfft,nframes,ft", [
        (4, 2, 16, 13, 8),     # several grid steps both axes
        (4, 1, 8, 5, 8),       # single chan, one fine tile
        (8, 3, 32, 6, 16),     # wider tile, odd chan count
    ])
    def test_matches_einsum(self, nant, nchan, nfft, nframes, ft):
        rng = np.random.default_rng(nant + nfft)
        shape = (nant, nchan, 2, nframes, nfft)
        sr = rng.standard_normal(shape).astype(np.float32)
        si = rng.standard_normal(shape).astype(np.float32)
        vr, vi = xengine_packed(jnp.asarray(sr), jnp.asarray(si), ft=ft,
                                interpret=True)
        want = golden_packed(sr, si)
        np.testing.assert_allclose(np.asarray(vr), want.real, rtol=1e-4,
                                   atol=1e-3)
        np.testing.assert_allclose(np.asarray(vi), want.imag, rtol=1e-4,
                                   atol=1e-3)

    def test_indivisible_nfft_rejected(self):
        s = jnp.zeros((2, 1, 2, 5, 12), jnp.float32)
        with pytest.raises(ValueError, match="fine tiles"):
            xengine_packed(s, s, ft=8, interpret=True)


class TestEligibility:
    def test_mxu_sized_gate(self):
        # The production gate: pallas only where it measured faster
        # (nap >= 128); the nant=8 shape stays on the einsum path.
        assert eligible(128, 512, 61)
        assert not eligible(16, 512, 61)       # nant=8 bench shape
        assert not eligible(128, 500, 61)      # fine tiles must divide

    def test_pick_ft_adapts(self):
        # The dispatcher shrinks the fine tile instead of falling off
        # the kernel: nap=256's output blocks exceed the budget at ft=8.
        assert pick_ft(128, 512, 61) == 8      # measured-best default
        assert pick_ft(256, 512, 61) == 4      # shrinks, stays on kernel
        assert pick_ft(128, 500, 61) == 4      # 500 = 4*125: ft=8 no, 4 yes
        assert pick_ft(16, 512, 61) is None    # einsum path (nap small)
        assert pick_ft(128, 509, 61) is None   # prime nfft: no tile divides

    def test_vmem_bound(self):
        # Long time segments grow the input blocks with nframes: those
        # must fall back to the einsum path, not compile-fail.  The
        # budget applies the measured ~1.6x scoped-allocation factor
        # WITH margin, so admitted shapes sit clearly inside the 16 MB
        # limit (the naive-budget version admitted boundary shapes the
        # factor pushes over).
        assert eligible(128, 512, 256)
        assert not eligible(128, 512, 512)
        assert not eligible(128, 512, 2045)
        # bf16 spectra halve the input blocks: longer segments stay on
        # the kernel exactly where the bf16-staged path runs.
        assert eligible(128, 512, 512, itemsize=2)
        assert not eligible(128, 512, 2045, itemsize=2)
