"""Fused dequant+PFB pallas kernel (blit/ops/pallas_pfb.py) vs the jnp
path — interpreter mode on CPU, same harness pattern as test_pallas_dft."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops import channelize as ch  # noqa: E402
from blit.ops.pallas_pfb import pfb_dequant  # noqa: E402


def jnp_reference(v, coeffs, work_dtype):
    re, im = ch.dequantize(jnp.asarray(v), dtype=work_dtype)
    re = jnp.moveaxis(re, -1, 1)
    im = jnp.moveaxis(im, -1, 1)
    h = jnp.asarray(coeffs).astype(work_dtype)
    return ch.pfb_frontend(re, h), ch.pfb_frontend(im, h)


class TestPfbDequant:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_matches_jnp_path(self, dtype):
        rng = np.random.default_rng(0)
        nchan, nfft, ntap, nblk = 3, 256, 4, 6
        v = rng.integers(-128, 128, (nchan, nblk * nfft, 2, 2), np.int8)
        coeffs = ch.pfb_coeffs(ntap, nfft)
        fr, fi = pfb_dequant(jnp.asarray(v), jnp.asarray(coeffs),
                             dtype=dtype, interpret=True)
        wr, wi = jnp_reference(
            v, coeffs, jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        )
        assert fr.shape == wr.shape == (nchan, 2, nblk - ntap + 1, nfft)
        assert fr.dtype == jnp.dtype(dtype)
        # pallas accumulates taps in f32 (more accurate than the bf16 jnp
        # accumulation) — compare at bf16 grain.
        tol = 3e-2 if dtype == "bfloat16" else 1e-6
        scale = max(np.abs(np.asarray(wr, np.float32)).max(), 1.0)
        for a, b in zip((fr, fi), (wr, wi)):
            err = np.abs(np.asarray(a, np.float32) - np.asarray(b, np.float32))
            assert err.max() / scale < tol

    def test_full_byte_range_sign_extension(self):
        # Every int8 value decodes exactly (the in-kernel byte unpack).
        v = np.arange(-128, 128, dtype=np.int8)
        v = np.tile(v, 8)  # 2048 samples
        block = np.stack([v, -v - 1], axis=-1)  # re, im
        block = np.stack([block, block[::-1]], axis=-2)  # 2 pols
        block = block[None]  # (1, 2048, 2, 2)
        coeffs = np.zeros((4, 256), np.float32)
        coeffs[0] = 1.0  # tap-0 passthrough: frames = raw blocks
        fr, fi = pfb_dequant(jnp.asarray(block), jnp.asarray(coeffs),
                             interpret=True)
        want = block.reshape(1, 8, 256, 2, 2).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(fr)[0, 0], want[0, :5, :, 0, 0])
        np.testing.assert_array_equal(
            np.asarray(fi)[0, 1], want[0, :5, :, 1, 1])

    def test_channelize_pallas_pfb_matches_xla(self):
        rng = np.random.default_rng(2)
        nfft, ntap = 128, 4
        v = rng.integers(-40, 40, (2, 7 * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        a = np.asarray(ch.channelize(jnp.asarray(v), h, nfft=nfft, nint=2,
                                     stokes="XXYY", pfb_kernel="pallas"))
        b = np.asarray(ch.channelize(jnp.asarray(v), h, nfft=nfft, nint=2,
                                     stokes="XXYY"))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-2)

    def test_single_pol_explicit_rejected_auto_falls_back(self):
        rng = np.random.default_rng(3)
        nfft = 64
        v = rng.integers(-40, 40, (2, 5 * nfft, 1, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, nfft))
        # Explicit opt-in that cannot run must error, not silently degrade.
        with pytest.raises(ValueError, match="npol=2"):
            ch.channelize(jnp.asarray(v), h, nfft=nfft, pfb_kernel="pallas")
        # "auto" quietly takes the XLA path for unsupported shapes.
        a = np.asarray(ch.channelize(jnp.asarray(v), h, nfft=nfft))
        b = np.asarray(ch.channelize(jnp.asarray(v), h, nfft=nfft,
                                     pfb_kernel="xla"))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-4)

    def test_bad_kernel_name_rejected(self):
        v = jnp.zeros((1, 256, 2, 2), jnp.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, 64))
        with pytest.raises(ValueError, match="pfb_kernel"):
            ch.channelize(v, h, nfft=64, pfb_kernel="cuda")

    def test_fused1_matches_xla_end_to_end(self):
        # dequant+PFB+stage1 fused: whole channelize parity on a
        # multi-factor nfft (8192 -> factors (128, 64)).
        rng = np.random.default_rng(5)
        nfft, ntap = 8192, 4
        v = rng.integers(-40, 40, (2, 6 * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        a = np.asarray(ch.channelize(jnp.asarray(v), h, nfft=nfft,
                                     stokes="IQUV", fft_method="matmul",
                                     pfb_kernel="fused1"))
        b = np.asarray(ch.channelize(jnp.asarray(v), h, nfft=nfft,
                                     stokes="IQUV", fft_method="matmul",
                                     pfb_kernel="xla"))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-2 * np.abs(b).max())

    def test_fused1_guards(self):
        v = jnp.zeros((1, 6 * 256, 2, 2), jnp.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, 256))
        with pytest.raises(ValueError, match="multi-factor"):
            ch.channelize(v, h, nfft=256, fft_method="matmul",
                          pfb_kernel="fused1")
        v2 = jnp.zeros((1, 6 * 8192, 2, 2), jnp.int8)
        h2 = jnp.asarray(ch.pfb_coeffs(4, 8192))
        with pytest.raises(ValueError, match="twisted"):
            ch.channelize(v2, h2, nfft=8192, fft_method="matmul",
                          pfb_kernel="fused1", dft_order="twisted")

    def test_vmem_gate(self):
        from blit.ops import pallas_pfb as pp

        # Bench shape fits; the '0002' preset's 2048-frame chunks do not.
        assert pp.fits(1 << 20, 11, 4, "bfloat16")
        assert not pp.fits(1 << 10, 2051, 4, "float32")
        # And pfb_dequant refuses outright rather than failing in mosaic.
        v = jnp.zeros((1, 2051 * 1024, 2, 2), jnp.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, 1024))
        with pytest.raises(ValueError, match="VMEM"):
            pp.pfb_dequant(v, h, interpret=True)
