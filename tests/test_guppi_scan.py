"""Multi-file GUPPI RAW scan sequences (blit/io/guppi.GuppiScan).

A GBT scan is recorded as ``<stem>.0000.raw, .0001.raw, ...`` — the NNNN
field of the reference's filename grammar (src/gbtworkerfunctions.jl:35-47;
README.md:25-27) — and rawspec consumes the whole sequence as one gap-free
stream.  These tests pin that contract: reducing the sequence must equal
reducing the concatenated recording, including across OVERLAP-carrying file
boundaries, and a resumable reduction must restart cleanly mid-sequence.
"""

import numpy as np
import pytest

from blit.io.guppi import (
    GuppiRaw,
    GuppiScan,
    open_raw,
    scan_files,
    write_raw,
)
from blit.testing import make_raw_header, synth_raw_sequence


class TestScanFiles:
    def test_expands_member_and_stem(self, tmp_path):
        stem = str(tmp_path / "guppi_59897_21221_HD_84406_0011")
        paths, _ = synth_raw_sequence(stem, nfiles=3, obsnchan=2,
                                      ntime_per_block=64)
        assert scan_files(stem) == paths
        assert scan_files(paths[1]) == paths

    def test_sorted_numerically(self, tmp_path):
        # NNNN is zero-padded: lexical sort == numeric sort even past 9.
        stem = str(tmp_path / "x")
        hdr = make_raw_header(obsnchan=2)
        blk = np.zeros((2, 64, 2, 2), np.int8)
        for i in (11, 2, 0):
            write_raw(f"{stem}.{i:04d}.raw", hdr, [blk])
        assert [p[-8:-4] for p in scan_files(stem)] == ["0000", "0002", "0011"]

    def test_no_match_empty(self, tmp_path):
        assert scan_files(str(tmp_path / "nothing")) == []


class TestGuppiScan:
    @pytest.mark.parametrize("overlap", [0, 32])
    def test_kept_stream_equals_recording(self, tmp_path, overlap):
        # The sequence's overlap-trimmed block stream must reproduce the
        # original contiguous recording exactly — including the trim of the
        # *last block of each non-final file* (its OVERLAP tail repeats at
        # the start of the next file).
        stem = str(tmp_path / "y")
        paths, stream = synth_raw_sequence(
            stem, nfiles=2, blocks_per_file=2, obsnchan=3,
            ntime_per_block=128 + overlap, overlap=overlap,
        )
        scan = GuppiScan(paths)
        assert scan.nblocks == 4
        got = np.concatenate(
            [blk for _, blk in scan.iter_blocks(drop_overlap=True)], axis=1
        )
        np.testing.assert_array_equal(got, stream)
        # read_block_into path (what the streaming ring uses):
        total = sum(scan.block_ntime_kept(i) for i in range(scan.nblocks))
        assert total == stream.shape[1]
        out = np.empty((3, total, 2, 2), np.int8)
        filled = 0
        for i in range(scan.nblocks):
            nt = scan.block_ntime_kept(i)
            scan.read_block_into(i, out[:, filled:], t0=0, ntime_keep=nt)
            filled += nt
        np.testing.assert_array_equal(out, stream)

    def test_single_file_scan_matches_guppiraw(self, tmp_path):
        stem = str(tmp_path / "z")
        paths, stream = synth_raw_sequence(stem, nfiles=1, blocks_per_file=3,
                                           obsnchan=2, ntime_per_block=64)
        scan = GuppiScan(paths)
        raw = GuppiRaw(paths[0])
        assert scan.nblocks == raw.nblocks
        for i in range(scan.nblocks):
            assert scan.block_ntime_kept(i) == raw.block_ntime_kept(i)
            np.testing.assert_array_equal(scan.read_block(i), raw.read_block(i))

    def test_pktidx_gap_warns_and_strict_raises(self, tmp_path, caplog):
        stem = str(tmp_path / "g")
        paths, _ = synth_raw_sequence(stem, nfiles=2, blocks_per_file=2,
                                      obsnchan=2, ntime_per_block=64)
        # Rewrite file 1 with a bogus PKTIDX origin: a dropped-block gap.
        raw1 = GuppiRaw(paths[1])
        hdr = dict(raw1.header(0))
        hdr["PKTIDX"] = hdr["PKTIDX"] + 640
        # Materialize (read_block may memmap the file being rewritten).
        blocks = [np.array(raw1.read_block(i)) for i in range(raw1.nblocks)]
        del raw1
        write_raw(paths[1], hdr, blocks)
        with caplog.at_level("WARNING", logger="blit.guppi"):
            GuppiScan(paths)
        assert any("PKTIDX gap" in r.message for r in caplog.records)
        with pytest.raises(ValueError, match="PKTIDX gap"):
            GuppiScan(paths, strict=True)

    def test_missing_member_warns(self, tmp_path, caplog):
        stem = str(tmp_path / "m")
        paths, _ = synth_raw_sequence(stem, nfiles=3, blocks_per_file=1,
                                      obsnchan=2, ntime_per_block=64)
        import os

        os.unlink(paths[1])
        with caplog.at_level("WARNING", logger="blit.guppi"):
            GuppiScan(scan_files(stem))
        assert any("missing sequence numbers" in r.message for r in caplog.records)

    def test_geometry_mismatch_rejected(self, tmp_path):
        hdr = make_raw_header(obsnchan=2)
        write_raw(str(tmp_path / "a.0000.raw"), hdr,
                  [np.zeros((2, 64, 2, 2), np.int8)])
        hdr4 = make_raw_header(obsnchan=4)
        write_raw(str(tmp_path / "a.0001.raw"), hdr4,
                  [np.zeros((4, 64, 2, 2), np.int8)])
        with pytest.raises(ValueError, match="disagrees"):
            GuppiScan(scan_files(str(tmp_path / "a")))


class TestOpenRaw:
    def test_dispatch(self, tmp_path):
        stem = str(tmp_path / "d")
        paths, _ = synth_raw_sequence(stem, nfiles=2, blocks_per_file=1,
                                      obsnchan=2, ntime_per_block=64)
        assert isinstance(open_raw(paths[0]), GuppiRaw)  # explicit file
        assert isinstance(open_raw(stem), GuppiScan)  # stem expands
        assert isinstance(open_raw(paths), GuppiScan)  # list
        assert isinstance(open_raw([paths[0]]), GuppiRaw)  # 1-list
        scan = GuppiScan(paths)
        assert open_raw(scan) is scan  # passthrough
        with pytest.raises(FileNotFoundError):
            open_raw(str(tmp_path / "absent"))


class TestSequenceReduction:
    @pytest.mark.parametrize("overlap", [0, 32])
    def test_sequence_reduction_equals_concatenation(self, tmp_path, overlap):
        # THE golden test: reducing a 2-file sequence == reducing the single
        # file holding the same blocks (PFB state carried across the file
        # boundary; boundary invisible in the product).
        pytest.importorskip("jax")
        from blit.pipeline import RawReducer

        stem = str(tmp_path / "seq")
        paths, stream = synth_raw_sequence(
            stem, nfiles=2, blocks_per_file=2, obsnchan=2,
            ntime_per_block=512 + overlap, overlap=overlap, tone_chan=1,
        )
        # One file holding the identical gap-free recording:
        mono = str(tmp_path / "mono.raw")
        hdr = make_raw_header(obsnchan=2, overlap=0)
        write_raw(mono, hdr, [stream])

        red = RawReducer(nfft=64, nint=2, chunk_frames=4)
        hdr_seq, data_seq = red.reduce(paths)
        _, data_mono = RawReducer(nfft=64, nint=2, chunk_frames=4).reduce(mono)
        np.testing.assert_array_equal(data_seq, data_mono)
        # Stem form drives the same reduction.
        _, data_stem = RawReducer(nfft=64, nint=2, chunk_frames=4).reduce(stem)
        np.testing.assert_array_equal(data_stem, data_seq)

    def test_resume_across_file_boundary(self, tmp_path):
        # Crash mid-sequence, resume, compare against an uninterrupted run.
        pytest.importorskip("jax")
        from blit.io.sigproc import read_fil_data
        from blit.pipeline import RawReducer, ReductionCursor

        stem = str(tmp_path / "r")
        paths, _ = synth_raw_sequence(
            stem, nfiles=2, blocks_per_file=2, obsnchan=2,
            ntime_per_block=512, tone_chan=1,
        )
        out = str(tmp_path / "r.fil")

        # Crash after the fifth slab landed: fail the write-behind sink's
        # sixth append (ISSUE 4 — the async output plane's crash seam).
        from blit import faults
        from blit.faults import FaultRule

        class Boom(Exception):
            pass

        red = RawReducer(nfft=64, nint=1, chunk_frames=4)
        faults.install(FaultRule(point="sink.write", mode="fail",
                                 after=5, times=-1, exc=Boom))
        try:
            with pytest.raises(Boom):
                red.reduce_resumable(stem, out)
        finally:
            faults.clear()
            faults.reset_counters()

        cur = ReductionCursor.load(out)
        # 20 frames done -> the resume skip (20*64 = 1280 samples) lands
        # INSIDE file 1 (files split at sample 1024): the restart must seek
        # through the boundary correctly.
        assert cur is not None and cur.frames_done == 20
        assert cur.raw_path == paths  # per-member identity recorded

        RawReducer(nfft=64, nint=1, chunk_frames=4).reduce_resumable(stem, out)
        _, data = read_fil_data(out)
        _, want = RawReducer(nfft=64, nint=1, chunk_frames=4).reduce(paths)
        np.testing.assert_array_equal(np.asarray(data), want)

    def test_resume_rejects_modified_member(self, tmp_path):
        pytest.importorskip("jax")
        from blit.pipeline import RawReducer, ReductionCursor

        stem = str(tmp_path / "t")
        paths, _ = synth_raw_sequence(stem, nfiles=2, blocks_per_file=1,
                                      obsnchan=2, ntime_per_block=512)
        red = RawReducer(nfft=64, nint=1, chunk_frames=4)
        size, mtime = ReductionCursor.stat_raw(paths)
        cur = ReductionCursor(paths, nfft=64, ntap=4, nint=1, stokes="I",
                              frames_done=4, window=red.window,
                              raw_size=size, raw_mtime_ns=mtime)
        assert cur.matches(red, paths)
        with open(paths[1], "ab") as f:
            f.write(b"\0")
        assert not cur.matches(red, paths)


class TestDuplicateMembers:
    def test_duplicate_member_flagged(self, tmp_path):
        # The same member listed twice would splice its voltages into the
        # stream twice; strict mode refuses, default warns.
        paths, _ = synth_raw_sequence(
            str(tmp_path / "s"), nfiles=2, blocks_per_file=1, obsnchan=2,
            ntime_per_block=256,
        )
        with pytest.raises(ValueError, match="duplicate"):
            GuppiScan([paths[0], paths[0], paths[1]], strict=True)
        # Alias spellings of one file must not dodge the check.
        import os
        rel = os.path.join(os.path.dirname(paths[0]), ".",
                           os.path.basename(paths[0]))
        with pytest.raises(ValueError, match="duplicate"):
            GuppiScan([paths[0], rel, paths[1]], strict=True)

    def test_duplicate_warns_by_default(self, tmp_path, caplog):
        import logging

        paths, _ = synth_raw_sequence(
            str(tmp_path / "s"), nfiles=2, blocks_per_file=1, obsnchan=2,
            ntime_per_block=256,
        )
        with caplog.at_level(logging.WARNING, logger="blit.guppi"):
            GuppiScan([paths[0], paths[0], paths[1]])
        assert any("duplicate" in r.message for r in caplog.records)
