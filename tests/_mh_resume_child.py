"""Child process for the 2-process RESUMABLE mesh-writer pod test
(tests/test_multiprocess.py): the pod-wide restart-offset agreement of
``reduce_scan_mesh_to_files(resume=True)`` executed for real under
``jax.distributed``.

Run as: ``python tests/_mh_resume_child.py <pid> <nproc> <port> <outdir>``.

Phases:

1. clean run → golden per-band products;
2. run with band_reduce crashing on its 3rd call → both processes leave
   per-band cursor sidecars (symmetric: same call count on every
   process);
3. resume → must complete, drop the sidecars, and byte-match the golden;
4. run where the two processes crash in the SAME window's writer flush
   but on OPPOSITE sides of the append — rank 0 before writing, rank 1
   after — leaving cursors that genuinely DISAGREE (the scenario the
   pod-wide MIN agreement exists for; VERDICT r4 weak item 5).  The
   crash site is the host-side writer, after the iteration's collectives
   have been dispatched on both ranks, so no process is left blocked in
   a collective the other never joins;
5. resume → every rank must restart at the window-aligned MIN of BOTH
   cursors (asserted via the writer's start_rows on each rank: rank 1
   truncates its extra window), complete, and byte-match the golden.
"""

import os
import sys


def main() -> None:
    pid, nproc, port, outdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from blit.parallel.multihost import init_multihost, local_players

    active = init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cpu_collectives="gloo",
    )
    assert active and jax.process_count() == nproc

    # Bring-up barrier marker (tests/test_multiprocess.py).
    from blit.testing import signal_ready

    signal_ready(outdir, pid)

    from blit.parallel import mesh as M
    from blit.parallel.scan import reduce_scan_mesh_to_files
    from blit.testing import synth_raw

    NBAND, NBANK, NFFT, NINT, NCHAN = 2, 4, 32, 2, 2
    mesh = M.make_mesh(NBAND, NBANK)
    local = sorted(local_players(mesh))

    priv = os.path.join(outdir, f"proc{pid}")
    os.makedirs(priv, exist_ok=True)
    bank_bw = -187.5 / NBANK
    paths = [
        [os.path.join(priv, f"blc{b}{k}.raw") for k in range(NBANK)]
        for b in range(NBAND)
    ]
    for b, k in local:
        synth_raw(
            paths[b][k], nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
            seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
            obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw,
        )

    def run(tag, resume):
        d = os.path.join(priv, tag)
        os.makedirs(d, exist_ok=True)
        return d, reduce_scan_mesh_to_files(
            paths, out_dir=d, nfft=NFFT, nint=NINT, despike=False,
            window_frames=4, resume=resume, mesh=mesh,
        )

    # 1. Clean golden.
    gdir, gwritten = run("golden", resume=False)

    # 2. Symmetric crash on the 3rd window (same call count on every
    #    process — the loop is lockstep).
    real = M.band_reduce
    calls = []

    def flaky(*a, **kw):
        calls.append(1)
        if len(calls) == 3:
            raise RuntimeError("synthetic pod crash")
        return real(*a, **kw)

    M.band_reduce = flaky
    crashed = False
    try:
        run("res", resume=True)
    except RuntimeError:
        crashed = True
    M.band_reduce = real
    assert crashed and len(calls) == 3, (
        "the injected 3rd-window crash did not fire (calls=%d) — the test "
        "would otherwise degrade to resume-from-zero" % len(calls)
    )
    rdir = os.path.join(priv, "res")
    cursors = [p for p in os.listdir(rdir) if p.endswith(".cursor")]
    assert cursors, "no cursor sidecars after the crash"

    # 3. Resume: completes, cleans up, matches golden byte-for-byte.
    _, written = run("res", resume=True)
    assert not any(p.endswith(".cursor") for p in os.listdir(rdir))
    for band, (path, hdr) in written.items():
        assert open(path, "rb").read() == open(gwritten[band][0], "rb").read(), (
            f"resumed band {band} != golden"
        )

    # 4. ASYMMETRIC crash: both ranks raise in the 3rd writer flush, but
    #    rank 0 before the append and rank 1 after it — cursors end up
    #    claiming different window counts.
    import json
    import time

    import blit.pipeline as P

    real_append = P.ResumableFilWriter.append
    flushes = []

    def skewed_append(self, slab):
        flushes.append(1)
        if len(flushes) == 3:
            if pid == 0:
                raise RuntimeError("asym crash before append")
            real_append(self, slab)
            raise RuntimeError("asym crash after append")
        return real_append(self, slab)

    P.ResumableFilWriter.append = skewed_append
    crashed = False
    try:
        run("asym", resume=True)
    except RuntimeError:
        crashed = True
    P.ResumableFilWriter.append = real_append
    assert crashed and len(flushes) == 3

    # Host-side barrier (both ranks are mid-failure; no collectives):
    # sentinel files signal "my cursor is on disk".
    adir = os.path.join(priv, "asym")
    open(os.path.join(outdir, f"crashed{pid}"), "w").close()
    other = os.path.join(outdir, f"crashed{1 - pid}")
    deadline = time.time() + 60
    while not os.path.exists(other):
        assert time.time() < deadline, "peer never crashed"
        time.sleep(0.05)

    def cursor_frames(rank, band):
        p = os.path.join(outdir, f"proc{rank}", "asym",
                         f"band{band}.fil.cursor")
        return json.load(open(p))["frames_done"]

    mine_frames = cursor_frames(pid, pid)  # rank r owns band r here
    peer_frames = cursor_frames(1 - pid, 1 - pid)
    rank0_frames = mine_frames if pid == 0 else peer_frames
    rank1_frames = peer_frames if pid == 0 else mine_frames
    assert rank0_frames < rank1_frames, (
        f"cursors must disagree: rank0 crashed pre-append, rank1 post-"
        f"append (got rank0={rank0_frames} rank1={rank1_frames})"
    )

    # 5. Resume: every rank restarts at the window-aligned MIN of both
    #    cursors — rank 1 must truncate its extra window.
    WF = 4  # window_frames in run()
    expected_rows = (min(mine_frames, peer_frames) // WF) * WF // NINT
    starts = []
    real_init = P.ResumableFilWriter.__init__

    def spying_init(self, path, header, nif, nchans, start_rows, nint,
                    cursor):
        starts.append(start_rows)
        real_init(self, path, header, nif, nchans, start_rows, nint, cursor)

    P.ResumableFilWriter.__init__ = spying_init
    try:
        _, awritten = run("asym", resume=True)
    finally:
        P.ResumableFilWriter.__init__ = real_init
    assert starts == [expected_rows], (
        f"rank {pid} restarted at {starts}, pod MIN demands "
        f"{expected_rows} rows"
    )
    assert not any(p.endswith(".cursor") for p in os.listdir(adir))
    for band, (path, hdr) in awritten.items():
        assert open(path, "rb").read() == open(gwritten[band][0], "rb").read(), (
            f"asym-resumed band {band} != golden"
        )
    print("CHILD-RESUME-OK", flush=True)


if __name__ == "__main__":
    main()
