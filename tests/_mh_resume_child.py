"""Child process for the 2-process RESUMABLE mesh-writer pod test
(tests/test_multiprocess.py): the pod-wide restart-offset agreement of
``reduce_scan_mesh_to_files(resume=True)`` executed for real under
``jax.distributed``.

Run as: ``python tests/_mh_resume_child.py <pid> <nproc> <port> <outdir>``.

Phases (both processes execute the SAME deterministic sequence, so the
injected crash is symmetric — mid-collective asymmetric failure is the
runtime's domain, not this test's):

1. clean run → golden per-band products;
2. run with band_reduce crashing on its 3rd call → both processes leave
   per-band cursor sidecars;
3. resume → must complete, drop the sidecars, and byte-match the golden.
"""

import os
import sys


def main() -> None:
    pid, nproc, port, outdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from blit.parallel.multihost import init_multihost, local_players

    active = init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cpu_collectives="gloo",
    )
    assert active and jax.process_count() == nproc

    from blit.parallel import mesh as M
    from blit.parallel.scan import reduce_scan_mesh_to_files
    from blit.testing import synth_raw

    NBAND, NBANK, NFFT, NINT, NCHAN = 2, 4, 32, 2, 2
    mesh = M.make_mesh(NBAND, NBANK)
    local = sorted(local_players(mesh))

    priv = os.path.join(outdir, f"proc{pid}")
    os.makedirs(priv, exist_ok=True)
    bank_bw = -187.5 / NBANK
    paths = [
        [os.path.join(priv, f"blc{b}{k}.raw") for k in range(NBANK)]
        for b in range(NBAND)
    ]
    for b, k in local:
        synth_raw(
            paths[b][k], nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
            seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
            obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw,
        )

    def run(tag, resume):
        d = os.path.join(priv, tag)
        os.makedirs(d, exist_ok=True)
        return d, reduce_scan_mesh_to_files(
            paths, out_dir=d, nfft=NFFT, nint=NINT, despike=False,
            window_frames=4, resume=resume, mesh=mesh,
        )

    # 1. Clean golden.
    gdir, gwritten = run("golden", resume=False)

    # 2. Symmetric crash on the 3rd window (same call count on every
    #    process — the loop is lockstep).
    real = M.band_reduce
    calls = []

    def flaky(*a, **kw):
        calls.append(1)
        if len(calls) == 3:
            raise RuntimeError("synthetic pod crash")
        return real(*a, **kw)

    M.band_reduce = flaky
    crashed = False
    try:
        run("res", resume=True)
    except RuntimeError:
        crashed = True
    M.band_reduce = real
    assert crashed and len(calls) == 3, (
        "the injected 3rd-window crash did not fire (calls=%d) — the test "
        "would otherwise degrade to resume-from-zero" % len(calls)
    )
    rdir = os.path.join(priv, "res")
    cursors = [p for p in os.listdir(rdir) if p.endswith(".cursor")]
    assert cursors, "no cursor sidecars after the crash"

    # 3. Resume: completes, cleans up, matches golden byte-for-byte.
    _, written = run("res", resume=True)
    assert not any(p.endswith(".cursor") for p in os.listdir(rdir))
    for band, (path, hdr) in written.items():
        assert open(path, "rb").read() == open(gwritten[band][0], "rb").read(), (
            f"resumed band {band} != golden"
        )
    print("CHILD-RESUME-OK", flush=True)


if __name__ == "__main__":
    main()
