"""Golden-value tests for the RAW → filterbank reduction core
(blit/ops/channelize.py) against NumPy references, per SURVEY.md §4."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


@pytest.fixture(autouse=True)
def nan_guard():
    """SURVEY.md §5 sanitizer plan: every golden run in this module executes
    under jax_debug_nans, so a NaN produced anywhere in the reduction
    (relevant with reduced-precision MXU paths) fails loudly here rather
    than silently polluting products."""
    jax.config.update("jax_debug_nans", True)
    yield
    jax.config.update("jax_debug_nans", False)


from blit.ops import channelize as ch  # noqa: E402


def make_voltages(nchan=4, ntime=8 * 256, npol=2, seed=0, tone=None, nfft=256):
    rng = np.random.default_rng(seed)
    v = rng.integers(-32, 32, size=(nchan, ntime, npol, 2), dtype=np.int8)
    if tone is not None:
        chan, fine = tone
        t = np.arange(ntime)
        # complex tone at fine-channel offset `fine` (fftshifted index)
        f = (fine - nfft // 2) / nfft
        z = 30 * np.exp(2j * np.pi * f * t)
        v[chan, :, :, 0] += z.real.astype(np.int8)[:, None]
        v[chan, :, :, 1] += z.imag.astype(np.int8)[:, None]
    return v


class TestFFT:
    def test_four_step_matches_direct(self):
        rng = np.random.default_rng(1)
        z = (rng.standard_normal((3, 1024)) + 1j * rng.standard_normal((3, 1024))).astype(
            np.complex64
        )
        a = ch.fft(jnp.asarray(z), method="four_step")
        b = np.fft.fft(z)
        np.testing.assert_allclose(np.asarray(a), b, rtol=2e-4, atol=2e-3)

    def test_four_step_large_pow2(self):
        rng = np.random.default_rng(2)
        n = 1 << 16
        z = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(np.complex64)
        a = np.asarray(ch.fft(jnp.asarray(z), method="four_step"))
        b = np.fft.fft(z)
        assert np.max(np.abs(a - b)) / np.max(np.abs(b)) < 1e-4

    def test_four_step_non_pow2(self):
        rng = np.random.default_rng(3)
        n = 12 * 25
        z = (rng.standard_normal((2, n)) + 1j * rng.standard_normal((2, n))).astype(
            np.complex64
        )
        a = np.asarray(ch.fft(jnp.asarray(z), method="four_step"))
        np.testing.assert_allclose(a, np.fft.fft(z), rtol=1e-3, atol=1e-3)

    def test_factors(self):
        assert ch._four_step_factors(1 << 20) == (1 << 10, 1 << 10)
        n1, n2 = ch._four_step_factors(300)
        assert n1 * n2 == 300


class TestPFB:
    def test_coeffs_shape_and_dc_gain(self):
        h = ch.pfb_coeffs(4, 64)
        assert h.shape == (4, 64)
        assert abs(h.sum() - 1.0) < 1e-6

    def test_frontend_frame_count(self):
        x = jnp.ones((2, 8 * 32))
        h = jnp.asarray(ch.pfb_coeffs(4, 32))
        y = ch.pfb_frontend(x, h)
        assert y.shape == (2, 5, 32)

    def test_rect_window_single_tap_is_framing(self):
        # ntap=1 rect window = plain framing (scaled by 1/nfft via DC norm).
        x = np.arange(64, dtype=np.float32)
        h = ch.pfb_coeffs(1, 16, window="rect")
        y = np.asarray(ch.pfb_frontend(jnp.asarray(x), jnp.asarray(h)))
        np.testing.assert_allclose(y, x.reshape(4, 16) * h[0], rtol=1e-6)


class TestChannelize:
    @pytest.mark.parametrize("stokes", ["I", "XXYY", "full", "IQUV"])
    def test_matches_numpy_reference(self, stokes):
        nfft, ntap, nint = 64, 4, 2
        v = make_voltages(nchan=3, ntime=(ntap - 1 + 2 * nint) * nfft)
        h = ch.pfb_coeffs(ntap, nfft)
        got = np.asarray(
            ch.channelize(
                jnp.asarray(v), jnp.asarray(h), nfft=nfft, ntap=ntap, nint=nint,
                stokes=stokes,
            )
        )
        want = ch.channelize_np(v, h, nfft=nfft, ntap=ntap, nint=nint, stokes=stokes)
        assert got.shape == want.shape == (2, ch.STOKES_NIF[stokes], 3 * nfft)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-2)

    def test_fqav_epilogue_matches_host_fqav(self):
        # On-device frequency averaging == host fqav of the full product
        # (the reduce-before-the-wire lever moved into the jitted kernel).
        from blit.ops.fqav import fqav

        nfft, ntap, nint, by = 64, 4, 1, 8
        v = make_voltages(nchan=2, ntime=(ntap - 1 + 3) * nfft)
        h = ch.pfb_coeffs(ntap, nfft)
        got = np.asarray(
            ch.channelize(
                jnp.asarray(v), jnp.asarray(h), nfft=nfft, ntap=ntap,
                nint=nint, fqav_by=by,
            )
        )
        full = np.asarray(
            ch.channelize(
                jnp.asarray(v), jnp.asarray(h), nfft=nfft, ntap=ntap, nint=nint
            )
        )
        assert got.shape == (3, 1, 2 * nfft // by)
        np.testing.assert_allclose(got, fqav(full, by), rtol=1e-5, atol=1e-2)

    def test_fqav_epilogue_through_reducer(self, tmp_path):
        # RawReducer(fqav_by=): product + header shrink together.
        from blit.ops.fqav import fqav
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024, tone_chan=1)
        hdr, data = RawReducer(nfft=64, nint=2, fqav_by=4).reduce(p)
        fhdr, full = RawReducer(nfft=64, nint=2).reduce(p)
        assert hdr["nchans"] == fhdr["nchans"] // 4 == data.shape[-1]
        assert hdr["foff"] == pytest.approx(fhdr["foff"] * 4)
        assert hdr["nfpc"] == 64 // 4
        np.testing.assert_allclose(data, fqav(full, 4), rtol=1e-5, atol=1e-2)

    def test_channelize_blocked_matches_flat(self):
        # Host-looped channel blocking == flat single dispatch.
        nfft, ntap = 64, 4
        v = make_voltages(nchan=8, ntime=6 * nfft)
        h = ch.pfb_coeffs(ntap, nfft)
        flat = np.asarray(
            ch.channelize(jnp.asarray(v), jnp.asarray(h), nfft=nfft, ntap=ntap)
        )
        blocked = np.asarray(
            ch.channelize_blocked(
                jnp.asarray(v), jnp.asarray(h), channel_block=2,
                nfft=nfft, ntap=ntap,
            )
        )
        np.testing.assert_array_equal(blocked, flat)
        # Degenerate block sizes fall through to the flat path.
        whole = np.asarray(
            ch.channelize_blocked(
                jnp.asarray(v), jnp.asarray(h), channel_block=8,
                nfft=nfft, ntap=ntap,
            )
        )
        np.testing.assert_array_equal(whole, flat)
        with pytest.raises(ValueError, match="divide nchan"):
            ch.channelize_blocked(jnp.asarray(v), jnp.asarray(h),
                                  channel_block=3, nfft=nfft, ntap=ntap)

    def test_fqav_must_divide_nfft(self, tmp_path):
        # Averaging groups must not straddle coarse-channel boundaries.
        from blit.pipeline import RawReducer

        with pytest.raises(ValueError, match="divide nfft"):
            RawReducer(nfft=64, fqav_by=48)
        v = make_voltages(nchan=3, ntime=4 * 64)  # 3*64 divisible by 48
        h = ch.pfb_coeffs(4, 64)
        with pytest.raises(ValueError, match="divide nfft"):
            ch.channelize(jnp.asarray(v), jnp.asarray(h), nfft=64, fqav_by=48)

    def test_tone_lands_in_right_fine_channel(self):
        nfft = 128
        v = make_voltages(nchan=2, ntime=8 * nfft, tone=(1, 96), nfft=nfft, seed=5)
        h = ch.pfb_coeffs(4, nfft)
        out = np.asarray(
            ch.channelize(jnp.asarray(v), jnp.asarray(h), nfft=nfft, nint=5)
        )
        spectrum = out[0, 0]
        # global fine index = coarse*nfft + fine
        assert spectrum.argmax() == 1 * nfft + 96

    def test_dc_tone_lands_at_despike_index(self):
        # A DC offset concentrates at fftshifted index nfft//2 — the exact
        # fine channel blit.ops.despike repairs (src/gbt.jl:101-111 parity).
        nfft = 64
        v = np.zeros((1, 8 * nfft, 2, 2), dtype=np.int8)
        v[..., 0] = 20
        h = ch.pfb_coeffs(4, nfft)
        out = np.asarray(
            ch.channelize(jnp.asarray(v), jnp.asarray(h), nfft=nfft, nint=5)
        )
        assert out[0, 0].argmax() == nfft // 2

    def test_four_step_equals_direct_end_to_end(self):
        nfft = 1024
        v = make_voltages(nchan=1, ntime=5 * nfft)
        h = ch.pfb_coeffs(4, nfft)
        a = np.asarray(
            ch.channelize(
                jnp.asarray(v), jnp.asarray(h), nfft=nfft, nint=2, fft_method="direct"
            )
        )
        b = np.asarray(
            ch.channelize(
                jnp.asarray(v), jnp.asarray(h), nfft=nfft, nint=2,
                fft_method="four_step",
            )
        )
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=10.0)
        rel = np.abs(a - b).max() / np.abs(a).max()
        assert rel < 1e-4

    def test_bfloat16_stage_dtype_close_to_golden(self):
        # dtype="bfloat16" halves the DFT intermediates' HBM (the
        # frames-per-dispatch lever, DESIGN.md §8); detected powers stay
        # within bf16-grade accuracy of the f64 NumPy golden.
        nfft, ntap, nint = 256, 4, 2
        v = make_voltages(
            ntime=(ntap - 1 + 2 * nint) * nfft, nfft=nfft, tone=(1, 70)
        )
        h = ch.pfb_coeffs(ntap, nfft)
        want = ch.channelize_np(v, h, nfft=nfft, ntap=ntap, nint=nint)
        got = np.asarray(ch.channelize(
            jnp.asarray(v), jnp.asarray(h), nfft=nfft, ntap=ntap, nint=nint,
            fft_method="matmul", dtype="bfloat16",
        ))
        assert got.dtype == np.float32  # detect/integrate accumulate in f32
        scale = want.max()
        np.testing.assert_allclose(got / scale, want / scale, atol=2e-2)
        # The tone must land in the same fine channel at full amplitude.
        assert got[0, 0].argmax() == want[0, 0].argmax()
        np.testing.assert_allclose(
            got[0, 0].max(), want[0, 0].max(), rtol=1e-2
        )

    def test_single_pol(self):
        v = make_voltages(nchan=2, ntime=5 * 32, npol=1)
        h = ch.pfb_coeffs(4, 32)
        out = np.asarray(ch.channelize(jnp.asarray(v), jnp.asarray(h), nfft=32))
        assert out.shape == (2, 1, 64)
        with pytest.raises(ValueError):
            ch.detect_stokes(jnp.zeros((1, 1, 2, 4), dtype=jnp.complex64), "IQUV")


class TestOutputHeader:
    RAW = {
        "OBSNCHAN": 64,
        "OBSFREQ": 1500.0,
        "OBSBW": -187.5,
        "TBIN": 64 / 187.5e6,
        "SRC_NAME": "J1234+56",
        "STT_IMJD": 59000,
        "STT_SMJD": 43200,
        "STT_OFFS": 0.0,
    }

    def test_header_fields(self):
        hdr = ch.output_header(self.RAW, nfft=1024, nint=8, stokes="full")
        assert hdr["nchans"] == 64 * 1024
        assert hdr["nifs"] == 4
        assert hdr["nfpc"] == 1024
        assert hdr["foff"] == pytest.approx(-187.5 / 64 / 1024)
        assert hdr["tsamp"] == pytest.approx(64 / 187.5e6 * 1024 * 8)
        assert hdr["tstart"] == pytest.approx(59000.5)

    def test_band_edges(self):
        # The nchans fine channels must span exactly OBSBW centered on OBSFREQ.
        nfft = 256
        hdr = ch.output_header(self.RAW, nfft=nfft, nint=1)
        freqs = hdr["fch1"] + hdr["foff"] * np.arange(hdr["nchans"])
        assert freqs.mean() == pytest.approx(1500.0, abs=abs(hdr["foff"]))
        span = abs(freqs[-1] - freqs[0]) + abs(hdr["foff"])
        assert span == pytest.approx(187.5)


class TestKernelPlan:
    def test_last_kernel_plan_records_trace_resolution(self):
        # ADVICE r3: 'auto' dispatch must be attributable.  On CPU the
        # auto path resolves to XLA kernels; the record reflects the most
        # recent TRACE (unique shape to force one).
        from blit.ops.channelize import (
            channelize, last_kernel_plan, pfb_coeffs,
        )

        rng = np.random.default_rng(0)
        v = rng.integers(-8, 8, (3, 7 * 16, 2, 2), dtype=np.int8)
        channelize(
            jnp.asarray(v), jnp.asarray(pfb_coeffs(4, 16)), nfft=16,
        ).block_until_ready()
        plan = last_kernel_plan()
        assert plan["pfb_kernel"] == "xla"
        assert plan["detect_kernel"] == "xla"
        assert plan["dft_order"] == "natural"
        assert plan["dtype"] == "float32"
