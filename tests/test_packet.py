"""Recorder packet front end (ISSUE 18): GUPPI packet framing
round-trips, the assembler's gap discipline (seeded drop/reorder/dup
replays byte-identical to the zero-filled batch oracle), the UDP
loopback capture path, the ``packet.recv`` fault point (reorder/drop
drills), whole-session orchestration (SessionSupervisor + rejoin under
a packet source), the tail-idle liveness satellite, and the ``blit
session`` CLI leg."""

import contextlib
import glob
import io
import json
import os
import threading

import pytest

from blit import faults
from blit.config import DEFAULT, packet_defaults, slo_defaults
from blit.faults import FaultRule
from blit.io.guppi import open_raw, write_raw
from blit.observability import Timeline
from blit.pipeline import RawReducer
from blit.stream import (
    FileTailSource,
    PacketAssembler,
    PacketReplaySource,
    PacketSource,
    packets_of,
    source_from_spec,
    stream_reduce,
)
from blit.stream.packet import (
    MAGIC,
    PKT_DATA,
    PKT_FIN,
    PKT_HEADER,
    PacketFramer,
    decode_packet,
    encode_packet,
)
from blit.testing import synth_raw

NFFT = 256
NINT = 2
CHUNK_FRAMES = 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


@pytest.fixture(autouse=True)
def _flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path / "flight"))
    os.makedirs(str(tmp_path / "flight"), exist_ok=True)


def _synth(path, nblocks=4, overlap=NFFT, seed=1, **kw):
    return synth_raw(str(path), nblocks=nblocks, obsnchan=2,
                     ntime_per_block=(8 + 3) * NFFT, overlap=overlap,
                     seed=seed, tone_chan=1, **kw)


def _reducer(**kw):
    kw.setdefault("timeline", Timeline())
    return RawReducer(nfft=NFFT, nint=NINT, chunk_frames=CHUNK_FRAMES,
                      **kw)


def _batch(raw, out):
    _reducer().reduce_to_file(str(raw), str(out))
    with open(out, "rb") as f:
        return f.read()


def _read(path):
    with open(path, "rb") as f:
        return f.read()


def _zero_masked_ref(tmp_path, hdr0, blocks, masked):
    """Batch comparator: the recording with the masked blocks' samples
    zeroed — exactly what zero-weight masking must yield."""
    zb = [b.copy() for b in blocks]
    for i in masked:
        zb[i][:] = 0
    zraw = tmp_path / "zeroed.raw"
    write_raw(str(zraw), hdr0, zb)
    return _batch(zraw, tmp_path / "zref.fil")


class TestFraming:
    def test_encode_decode_roundtrip(self):
        pkt = encode_packet(PKT_DATA, 42, block=3, chan0=1, time0=512,
                            nchan=1, ntime=64, payload=b"\x01\x02")
        f, payload = decode_packet(pkt)
        assert f["ptype"] == PKT_DATA
        assert f["pktidx"] == 42
        assert f["block"] == 3
        assert f["chan0"] == 1
        assert f["time0"] == 512
        assert f["nchan"] == 1
        assert f["ntime"] == 64
        assert payload == b"\x01\x02"

    def test_decode_rejects_garbage(self):
        with pytest.raises(ValueError):
            decode_packet(b"short")
        bad_magic = b"XXXX" + encode_packet(PKT_FIN, 0)[4:]
        with pytest.raises(ValueError):
            decode_packet(bad_magic)
        good = bytearray(encode_packet(PKT_FIN, 0))
        good[4] = 99  # unknown version
        with pytest.raises(ValueError):
            decode_packet(bytes(good))

    def test_packets_of_covers_every_block(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=3)
        pkts = list(packets_of(str(raw), packet_ntime=64))
        fr = PacketFramer(open_raw(str(raw)).header(0), 64)
        assert len(pkts) == 2 + 3 * fr.packets_per_block()
        first, _ = decode_packet(pkts[0])
        last, _ = decode_packet(pkts[-1])
        assert pkts[0][:4] == MAGIC
        assert first["ptype"] == PKT_HEADER
        assert last["ptype"] == PKT_FIN
        assert last["block"] == 3  # FIN carries the session total

    def test_assembler_rebuilds_blocks_byte_identical(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=3)
        src = open_raw(str(raw))
        asm = PacketAssembler(timeline=Timeline())
        for pkt in packets_of(src, packet_ntime=64):
            asm.feed(pkt)
        got = []
        while True:
            c = asm.pop()
            if c is None:
                break
            got.append(c)
        assert [c.seq for c in got] == [0, 1, 2]
        for c in got:
            assert c.data.tobytes() == src.read_block(c.seq).tobytes()
        rep = asm.report()
        assert rep["gaps"] == 0 and rep["reorders"] == 0
        assert rep["assembly_p99_s"] is not None


class TestReplayIdentity:
    """The cap drill: seeded packet chaos ≡ batch with gapped blocks
    zero-filled, byte for byte."""

    def test_clean_replay_identical_to_batch(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        out = tmp_path / "s.fil"
        src = PacketReplaySource(str(raw), rate=1e6, packet_ntime=64)
        hdr = stream_reduce(src, str(out), reducer=_reducer())
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 0
        rep = src.packet_report()
        assert rep["gaps"] == 0 and rep["dups"] == 0

    def test_dropped_block_matches_zero_filled_oracle(self, tmp_path):
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        ref = _zero_masked_ref(tmp_path, hdr0, blocks, [2])
        out = tmp_path / "s.fil"
        tl = Timeline()  # the plane counts on the reducer's timeline
        src = PacketReplaySource(str(raw), rate=1e6, packet_ntime=64,
                                 drop_blocks=[2], timeline=tl)
        hdr = stream_reduce(src, str(out),
                            reducer=_reducer(timeline=tl),
                            lateness_s=5.0)
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 1
        assert hdr["_masked_chunks"] == [2]
        rep = src.packet_report()
        assert rep["gaps"] == 1 and rep["gapped_blocks"] == [2]
        # The plane masked off the assembler's gap PROOF, not the
        # watermark timeout.
        assert tl.stages["stream.chunk.gap_fastpath"].calls >= 1
        assert faults.counters().get("mask.chunk", 0) == 1

    def test_seeded_reorder_and_dup_do_not_mask(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        out = tmp_path / "s.fil"
        src = PacketReplaySource(str(raw), rate=1e6, packet_ntime=64,
                                 reorder=0.2, dup=0.1, seed=7)
        hdr = stream_reduce(src, str(out), reducer=_reducer(),
                            lateness_s=5.0)
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 0
        rep = src.packet_report()
        assert rep["reorders"] > 0 and rep["dups"] > 0
        assert rep["gaps"] == 0

    def test_fractional_drop_gaps_match_oracle(self, tmp_path):
        # A seeded per-packet loss rate: whichever blocks lost a tile
        # must mask, and the product must equal the oracle built from
        # the assembler's OWN gap ledger.
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        out = tmp_path / "s.fil"
        src = PacketReplaySource(str(raw), rate=1e6, packet_ntime=64,
                                 drop=0.01, seed=1)
        hdr = stream_reduce(src, str(out), reducer=_reducer(),
                            lateness_s=5.0)
        rep = src.packet_report()
        assert rep["gaps"] >= 1  # seeded: some block loses a tile
        assert hdr["_masked_chunks"] == rep["gapped_blocks"]
        ref = _zero_masked_ref(tmp_path, hdr0, blocks,
                               rep["gapped_blocks"])
        assert _read(out) == ref


class TestUdpCapture:
    def test_loopback_session_identical_to_batch(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=3)
        ref = _batch(raw, tmp_path / "ref.fil")
        src = PacketSource("127.0.0.1", 0)
        import socket

        def send():
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            for pkt in packets_of(str(raw), packet_ntime=64):
                s.sendto(pkt, ("127.0.0.1", src.port))
            s.close()

        t = threading.Thread(target=send)
        t.start()
        out = tmp_path / "s.fil"
        hdr = stream_reduce(src, str(out), reducer=_reducer())
        t.join()
        src.close()
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 0
        assert src.packet_report()["packets"] > 0

    def test_packet_defaults_env_overrides(self, monkeypatch):
        monkeypatch.setenv("BLIT_PACKET_PORT", "61234")
        monkeypatch.setenv("BLIT_PACKET_NTIME", "32")
        monkeypatch.setenv("BLIT_PACKET_HORIZON", "5")
        d = packet_defaults(DEFAULT)
        assert d["port"] == 61234
        assert d["ntime"] == 32
        assert d["horizon_blocks"] == 5

    def test_packet_assembly_slo_template(self, monkeypatch):
        names = [o["name"] for o in slo_defaults(DEFAULT)]
        assert "packet-assembly" not in names  # off until configured
        monkeypatch.setenv("BLIT_SLO_PACKET_P99", "0.25")
        objs = {o["name"]: o for o in slo_defaults(DEFAULT)}
        slo = objs["packet-assembly"]
        assert slo["metric"] == "packet.assembly_s"
        assert slo["threshold"] == 0.25


class TestPacketFaultDrills:
    """The ``packet.recv`` injection point: datagram-level chaos on a
    live capture, without touching the sender."""

    def test_reorder_fault_holds_then_releases(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        faults.install(FaultRule("packet.recv", "reorder", times=1,
                                 after=3, amount=3))
        out = tmp_path / "s.fil"
        src = PacketReplaySource(str(raw), rate=1e6, packet_ntime=64)
        stream_reduce(src, str(out), reducer=_reducer(),
                      lateness_s=5.0)
        rep = src.packet_report()
        assert rep["reorders"] >= 1
        assert rep["gaps"] == 0  # held packets land before FIN resolves
        assert _read(out) == ref

    def test_drop_fault_becomes_gap_not_garbage(self, tmp_path):
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        faults.install(FaultRule("packet.recv", "drop", times=1, after=6))
        out = tmp_path / "s.fil"
        src = PacketReplaySource(str(raw), rate=1e6, packet_ntime=64)
        hdr = stream_reduce(src, str(out), reducer=_reducer(),
                            lateness_s=5.0)
        rep = src.packet_report()
        assert rep["gaps"] == 1
        assert hdr["_masked_chunks"] == rep["gapped_blocks"]
        ref = _zero_masked_ref(tmp_path, hdr0, blocks,
                               rep["gapped_blocks"])
        assert _read(out) == ref
        assert faults.counters().get("packet.gap", 0) == 1

    def test_reorder_spec_parses(self):
        rules = faults.parse_spec("packet.recv:reorder:after=3")
        assert rules[0].point == "packet.recv"
        assert rules[0].mode == "reorder"


class TestTailIdleLiveness:
    """Satellite: the tailer publishes its idle age and dumps the
    flight recorder when the idle timeout ends a session."""

    def test_idle_gauge_and_flight_dump(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=2)
        tl = Timeline()
        src = FileTailSource(str(raw), poll_s=0.01, idle_timeout_s=0.05,
                             timeline=tl)
        got = 0
        while True:
            c = src.get(timeout=2.0)
            if c is not None:
                got += 1
                continue
            if src.finished:
                break
        assert got == 2
        g = tl.gauges["stream.tail.idle_s"]
        assert g.n >= 1 and g.hi >= 0.05
        dumps = glob.glob(os.path.join(
            os.environ["BLIT_FLIGHT_DIR"], "*.json"))
        assert any("tail idle" in _read(p).decode("utf-8", "replace")
                   for p in dumps)


class TestSessionOrchestration:
    def _seat_spec(self, raw, out, **src_kw):
        return {
            "name": os.path.basename(str(out)).split(".")[0],
            "out": str(out),
            "source": dict({"kind": "packet-replay", "raw": str(raw),
                            "rate": 1e6, "packet_ntime": 64}, **src_kw),
            "knobs": dict(nfft=NFFT, nint=NINT,
                          chunk_frames=CHUNK_FRAMES, tune_online=False),
        }

    def test_source_from_spec_dispatch(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=2)
        src = source_from_spec({"kind": "packet-replay",
                                "raw": str(raw), "rate": 1e6})
        assert isinstance(src, PacketReplaySource)
        src = source_from_spec({"kind": "tail", "raw": str(raw)})
        assert isinstance(src, FileTailSource)
        with pytest.raises(ValueError):
            source_from_spec({"kind": "carrier-pigeon"})

    def test_two_seat_session_folds_reports(self, tmp_path):
        from blit.stream import SessionSupervisor

        raw_a, raw_b = tmp_path / "a.raw", tmp_path / "b.raw"
        _synth(raw_a, seed=1)
        _synth(raw_b, seed=2)
        ref_a = _batch(raw_a, tmp_path / "ref_a.fil")
        ref_b = _batch(raw_b, tmp_path / "ref_b.fil")
        seats = [
            self._seat_spec(raw_a, tmp_path / "blc00.fil"),
            self._seat_spec(raw_b, tmp_path / "blc01.fil",
                            drop_blocks=[1]),
        ]
        sup = SessionSupervisor(seats,
                                work_dir=str(tmp_path / "work"),
                                lease_ttl_s=3.0, poll_s=0.05)
        rep = sup.run()
        assert rep["ok"]
        assert set(rep["seats"]) == {"blc00", "blc01"}
        assert all(s["ok"] for s in rep["seats"].values())
        assert rep["masked_total"] == 1
        assert _read(tmp_path / "blc00.fil") == ref_a
        # Seat blc01 lost block 1 on the wire: product == zeroed oracle.
        hdr0, blocks = open_raw(str(raw_b)).header(0), [
            open_raw(str(raw_b)).read_block(i) for i in range(4)]
        assert _read(tmp_path / "blc01.fil") == _zero_masked_ref(
            tmp_path, hdr0, blocks, [1])

    def test_duplicate_seat_names_rejected(self, tmp_path):
        from blit.stream import SessionSupervisor

        seats = [{"name": "x", "out": "a.fil"},
                 {"name": "x", "out": "b.fil"}]
        with pytest.raises(ValueError):
            SessionSupervisor(seats, work_dir=str(tmp_path))

    def test_cursor_rejoin_under_packet_source(self, tmp_path):
        """Satellite drill: kill the consumer mid-session while the
        packet stream is ALSO dropping a block — the restarted seat
        rejoins from its cursor and the product still equals the
        zero-filled oracle."""
        from blit.recover import StreamSupervisor

        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw, nblocks=6)
        ref = _zero_masked_ref(tmp_path, hdr0, blocks, [3])
        out = tmp_path / "s.fil"
        sup = StreamSupervisor(
            str(raw), str(out), kind="reduce",
            knobs=dict(nfft=NFFT, nint=NINT, chunk_frames=CHUNK_FRAMES,
                       tune_online=False),
            source={"kind": "packet-replay", "raw": str(raw),
                    "rate": 1e6, "packet_ntime": 64,
                    "drop_blocks": [3]},
            faults="stream.chunk:kill:after=2",
            lease_ttl_s=3.0, poll_s=0.05,
        )
        rep = sup.run()
        assert rep["recovered"]
        assert len(rep["attempts"]) >= 2
        assert rep["result"]["masked"] == 1
        assert rep["result"]["packet"]["gaps"] == 1
        assert _read(out) == ref

    def test_session_cli_smoke(self, tmp_path):
        from blit.__main__ import main

        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=2)
        ref = _batch(raw, tmp_path / "ref.fil")
        spec = {"seats": [self._seat_spec(raw, tmp_path / "s.fil")]}
        spec_path = tmp_path / "session.json"
        spec_path.write_text(json.dumps(spec))
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["session", str(spec_path),
                       "--work-dir", str(tmp_path / "work"),
                       "--lease-ttl", "3.0", "--poll", "0.05"])
        assert rc == 0
        rep = json.loads(buf.getvalue())
        assert rep["kind"] == "session" and rep["ok"]
        assert _read(tmp_path / "s.fil") == ref
