"""Search plane (ISSUE 6): Taylor-tree dedoppler + ``.hits`` products.

Coverage map:

- the drift transform against an O(T·D·F) brute-force oracle summing
  the EXACT tree paths (integer-valued data → float32 sums are exact in
  any association, so the comparison is BYTE equality, not allclose);
- the pallas kernel (interpret mode — the CPU tier-1 path) bitwise
  against the pure-lax reference;
- device-side threshold + per-band top-k packing/decode;
- end-to-end recovery of an injected DRIFTING tone (the
  blit.testing injector) through RAW → spectra → search, both drift
  signs, within one drift step / one channel;
- ``.hits`` writers: atomic publish, sync↔async byte identity,
  window-split resume replay reproducing the uninterrupted bytes;
- ProductService integration (kind="hits"): fingerprints, cache hits,
  dense-array round trip;
- SiteConfig search knobs + BLIT_SEARCH_* env overrides;
- `blit search` CLI smoke (in-process main, like tests/test_cli.py).
"""

import json
import os

import numpy as np
import pytest

from blit.__main__ import main
from blit.io.hits import (
    HitsWriter,
    ResumableHitsWriter,
    WindowHits,
    read_hits,
    write_hits,
)
from blit.observability import Timeline
from blit.ops import pallas_dedoppler as pd
from blit.search import (
    DedopplerReducer,
    Hit,
    SearchCursor,
    hits_from_array,
    hits_to_array,
)
from blit.testing import synth_raw, synth_raw_sequence, tone_drift_for

NFFT = 128
T = 8  # window_spectra for the end-to-end tests


def _synth(path, windows=3, obsnchan=2, ntap=4, drift_bins=0.0,
           tone_chan=None, seed=1, **kw):
    """A recording sized for exactly ``windows`` full search windows
    (plus the PFB tail) with an optional drifting tone."""
    ntime = (T * windows + ntap - 1) * NFFT
    tone_drift = tone_drift_for(NFFT, T, drift_bins)
    return synth_raw(
        str(path), nblocks=2, obsnchan=obsnchan,
        ntime_per_block=-(-ntime // 2), seed=seed, tone_chan=tone_chan,
        tone_drift=tone_drift, **kw,
    )


def _reducer(**kw):
    kw.setdefault("nfft", NFFT)
    kw.setdefault("window_spectra", T)
    kw.setdefault("top_k", 4)
    kw.setdefault("snr_threshold", 2.0)
    kw.setdefault("kernel", "reference")
    return DedopplerReducer(**kw)


class TestTaylorTree:
    def test_golden_against_brute_force_exact(self):
        # Integer-valued float32 data: every partial sum is exact, so
        # tree and brute force agree BYTE-for-byte whatever the
        # association order.
        rng = np.random.default_rng(0)
        for Tw, F in ((4, 37), (16, 96), (32, 64)):
            x = rng.integers(0, 200, size=(Tw, F)).astype(np.float32)
            tree = np.asarray(pd.taylor_tree(x, kernel="reference"))
            brute = pd.brute_force_dedoppler(x).astype(np.float32)
            assert np.array_equal(tree, brute), (Tw, F)

    def test_pallas_kernel_bitwise_matches_reference(self):
        rng = np.random.default_rng(1)
        x = rng.normal(50.0, 5.0, size=(16, 200)).astype(np.float32)
        ref = np.asarray(pd.taylor_tree(x, kernel="reference"))
        pal = np.asarray(
            pd.taylor_tree(x, kernel="pallas", interpret=True, tile=64))
        assert np.array_equal(ref, pal)

    def test_tree_path_shift_invariants(self):
        # Drift-d path: anchored at 0, monotone, total shift == d at the
        # last sample (the convention hits/frequencies decode under).
        for Tw in (2, 8, 32):
            for d in range(Tw):
                shifts = [pd.tree_path_shift(d, t, Tw) for t in range(Tw)]
                assert shifts[0] == 0
                assert shifts[-1] == d
                assert all(b - a in (0, 1)
                           for a, b in zip(shifts, shifts[1:]))

    def test_drift_spectra_negative_sign(self):
        # A tone walking DOWN the band shows up at negative drift,
        # anchored at its t=0 channel.
        Tw, F = 16, 128
        x = np.zeros((Tw, F), np.float32)
        d, f0 = 5, 80
        for t in range(Tw):
            x[t, f0 - pd.tree_path_shift(d, t, Tw)] = 1.0
        dd = np.asarray(pd.drift_spectra(x, kernel="reference"))
        assert dd.shape == (2 * Tw - 1, F)
        row, col = np.unravel_index(np.argmax(dd), dd.shape)
        assert pd.drift_rates(Tw)[row] == -d
        assert col == f0
        assert dd[row, col] == Tw

    def test_band_edge_paths_read_zeros(self):
        # A path running off the top of the band sums only its in-band
        # samples (the zero padding), never wraps onto low channels.
        Tw, F = 8, 16
        x = np.ones((Tw, F), np.float32)
        tree = np.asarray(pd.taylor_tree(x, kernel="reference"))
        brute = pd.brute_force_dedoppler(x).astype(np.float32)
        assert np.array_equal(tree, brute)
        # Max drift at the last channel: only the t=0 sample is in band.
        assert tree[Tw - 1, F - 1] == 1.0

    def test_window_validation(self):
        x = np.zeros((6, 8), np.float32)  # not a power of two
        with pytest.raises(ValueError):
            pd.taylor_tree(x, kernel="reference")
        with pytest.raises(ValueError):
            pd.dedoppler_hits(np.zeros((4, 10), np.float32),
                              np.float32(0), nbands=3, kernel="reference")


class TestHitExtraction:
    def test_per_band_top_k_and_threshold(self):
        Tw, F, k = 8, 64, 3
        rng = np.random.default_rng(2)
        x = rng.normal(10, 1, size=(Tw, F)).astype(np.float32)
        d, f0 = 3, 10
        for t in range(Tw):
            x[t, f0 + pd.tree_path_shift(d, t, Tw)] += 25.0
        packed = np.asarray(pd.dedoppler_hits(
            x, np.float32(5.0), top_k=k, nbands=2, kernel="reference"))
        assert packed.shape == (2, k, pd.HIT_PACK_COLS)
        snr, power, drift, chan, band = pd.unpack_hits(packed)
        # The tone dominates band 0; sub-threshold cells were sentineled
        # on device and dropped by the decode.
        assert len(snr) >= 1
        assert drift[0] == d and chan[0] == f0 and band[0] == 0
        assert np.all(snr >= 5.0)

    def test_max_drift_mask(self):
        Tw, F = 8, 64
        x = np.zeros((Tw, F), np.float32)
        d, f0 = 6, 20
        for t in range(Tw):
            x[t, f0 + pd.tree_path_shift(d, t, Tw)] = 50.0
        packed = np.asarray(pd.dedoppler_hits(
            x, np.float32(0.0), top_k=4, nbands=1, max_drift_bins=3,
            kernel="reference"))
        _, _, drift, _, _ = pd.unpack_hits(packed)
        assert np.all(np.abs(drift) <= 3)


class TestInjectedToneRecovery:
    """The drifting-tone injector closes the loop: known (f₀, ḟ, SNR)
    in, top hit out, within one drift step and one channel."""

    @pytest.mark.parametrize("drift_bins", [0, 3, -3])
    def test_recovers_injected_drift(self, tmp_path, drift_bins):
        raw = tmp_path / "tone.raw"
        _synth(raw, windows=2, tone_chan=1, drift_bins=drift_bins,
               tone_amp=30.0)
        red = _reducer(snr_threshold=6.0)
        hdr, hits = red.search(str(raw))
        assert hdr["search_windows"] == 2
        assert hits, "injected tone produced no hits"
        top = max(hits, key=lambda h: h.snr)
        assert abs(top.drift_bins - drift_bins) <= 1
        # The tone sits in coarse channel 1 (one band per coarse chan).
        assert top.band == 1
        # Physical decode is self-consistent with the header.
        assert top.freq_mhz == pytest.approx(
            hdr["fch1"] + top.chan * hdr["foff"])
        if drift_bins:
            assert np.sign(top.drift_hz_s) == np.sign(
                drift_bins * hdr["foff"])

    def test_recovers_through_worker_pool(self, tmp_path):
        # The pool path (ISSUE 6 acceptance): the same recovery through
        # workers.search_raw fanned out on a WorkerPool — hit records
        # cross the wire as plain dicts.
        from blit import workers
        from blit.parallel.pool import WorkerPool
        from blit.search.hits import hit_from_record

        raw = tmp_path / "tone.raw"
        _synth(raw, windows=2, tone_chan=1, drift_bins=3, tone_amp=30.0)
        with WorkerPool(["w1"], backend="thread") as pool:
            (res,) = pool.run_on(
                [1], workers.search_raw, [(str(raw),)],
                kwargs=dict(nfft=NFFT, window_spectra=T, top_k=4,
                            snr_threshold=6.0, kernel="reference"),
            )
        hdr, records = res
        hits = [hit_from_record(r) for r in records]
        assert hits, "pool search produced no hits"
        top = max(hits, key=lambda h: h.snr)
        assert abs(top.drift_bins - 3) <= 1 and top.band == 1

    def test_recovery_through_pallas_interpret(self, tmp_path):
        raw = tmp_path / "tone.raw"
        _synth(raw, windows=2, tone_chan=0, drift_bins=2, tone_amp=30.0)
        red = _reducer(kernel="pallas", interpret=True, snr_threshold=6.0)
        _, hits = red.search(str(raw))
        top = max(hits, key=lambda h: h.snr)
        assert abs(top.drift_bins - 2) <= 1 and top.band == 0


class TestHitsIO:
    def _hits(self, n=3):
        return [
            Hit(snr=10.0 + i, power=5.0, drift_bins=i - 1, chan=100 + i,
                band=0, window=0, t_start=0, freq_mhz=8000.5,
                drift_hz_s=0.25 * i)
            for i in range(n)
        ]

    def test_write_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "x.hits")
        hdr = {"nchans": 256, "search_window_spectra": T}
        write_hits(path, hdr, self._hits())
        rh, rhits = read_hits(path)
        assert rh["nchans"] == 256
        assert rhits == self._hits()
        assert not os.path.exists(path + ".partial")

    def test_atomic_publish_and_abort(self, tmp_path):
        path = str(tmp_path / "x.hits")
        w = HitsWriter(path, {"search_window_spectra": T})
        w.append(WindowHits(0, self._hits()))
        # Not yet published: only the .partial exists.
        assert not os.path.exists(path) and os.path.exists(path + ".partial")
        w.abort()
        assert not os.path.exists(path + ".partial")

    def test_resumable_truncates_unclaimed_tail(self, tmp_path):
        path = str(tmp_path / "x.hits")
        hdr = {"search_window_spectra": T}
        cur = SearchCursor("r.raw", NFFT, 4, 1, window_spectra=T)
        w = ResumableHitsWriter(path, hdr, 0, cur)
        w.append(WindowHits(0, self._hits()))
        claimed = os.path.getsize(path)
        # Simulate a crash mid-window-1: bytes past the cursor's claim.
        with open(path, "a") as f:
            f.write("GARBAGE NOT JSON\n")
        w.abort()
        cur2 = SearchCursor.load(path)
        assert cur2 is not None and cur2.windows_done == 1
        w2 = ResumableHitsWriter(path, hdr, cur2.windows_done, cur2)
        assert os.path.getsize(path) == claimed
        w2.close()
        assert not os.path.exists(SearchCursor.path_for(path))

    def test_dense_encoding_roundtrip_large_chan(self):
        # Hi-res channel indices exceed f32's 2^24 integer range; the
        # split encoding must stay exact.
        hdr = {"fch1": 8437.5, "foff": -1e-6, "tsamp": 0.5,
               "search_window_spectra": 16}
        hits = [
            Hit(snr=12.5, power=3.0, drift_bins=-7, chan=(1 << 26) + 12345,
                band=63, window=9, t_start=144,
                freq_mhz=8437.5 + ((1 << 26) + 12345) * -1e-6,
                drift_hz_s=-7 * -1e-6 * 1e6 / (15 * 0.5)),
        ]
        arr = hits_to_array(hits)
        assert arr.shape == (1, 1, 8) and arr.dtype == np.float32
        assert hits_from_array(arr, hdr) == hits


class TestDedopplerReducer:
    def test_sync_async_hits_products_byte_identical(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, windows=3, tone_chan=1)
        out_a = str(tmp_path / "a.hits")
        out_s = str(tmp_path / "s.hits")
        _reducer().search_to_file(str(raw), out_a)
        _reducer(async_output=False).search_to_file(str(raw), out_s)
        with open(out_a, "rb") as fa, open(out_s, "rb") as fs:
            assert fa.read() == fs.read()

    def test_blit_sync_output_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_SYNC_OUTPUT", "1")
        red = _reducer()
        assert red.async_output is False

    def test_resume_replay_reproduces_bytes(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, windows=3, tone_chan=0)
        ref = str(tmp_path / "ref.hits")
        _reducer().search_to_file(str(raw), ref)

        # Simulate an interrupted resumable run: window 0 durable, then
        # crash (abort keeps file + cursor as the resume point).
        out = str(tmp_path / "res.hits")
        red = _reducer()
        from blit.io.guppi import open_raw

        hdr = red.header_for(open_raw(str(raw)))
        stream = red._search_stream(open_raw(str(raw)), hdr)
        first = next(stream)[1]
        stream.close()  # tear the feed down before the resumed run
        from blit.pipeline import ReductionCursor

        size, mtime = ReductionCursor.stat_raw(str(raw))
        cur = SearchCursor(
            str(raw), NFFT, 4, 1, window_spectra=T, top_k=4,
            snr_threshold=2.0, raw_size=size, raw_mtime_ns=mtime)
        w = ResumableHitsWriter(out, hdr, 0, cur)
        w.append(WindowHits(0, first))
        w.abort()

        # The resumed run skips window 0 via the skip-frames replay and
        # finishes the product byte-identical to the uninterrupted one.
        hdr2 = _reducer().search_resumable(str(raw), out)
        assert hdr2["search_windows"] == 3
        with open(ref, "rb") as fr, open(out, "rb") as fo:
            ref_bytes = fr.read()
            assert ref_bytes == fo.read()
        # search_nhits counts EVERY hit line in the finished product,
        # resumed windows included — not just this run's.
        assert hdr2["search_nhits"] == ref_bytes.count(b"\n") - 1
        assert not os.path.exists(SearchCursor.path_for(out))

    def test_kernel_choice_does_not_fork_product_bytes(self, tmp_path):
        # reference and pallas(interpret) are bitwise-identical by
        # construction, so the .hits product — header line included —
        # must not record (or fork on) the kernel choice.
        raw = tmp_path / "r.raw"
        _synth(raw, windows=2, tone_chan=1)
        out_r = str(tmp_path / "ref.hits")
        out_p = str(tmp_path / "pal.hits")
        _reducer(kernel="reference").search_to_file(str(raw), out_r)
        _reducer(kernel="pallas", interpret=True).search_to_file(
            str(raw), out_p)
        with open(out_r, "rb") as fr, open(out_p, "rb") as fp:
            assert fr.read() == fp.read()

    def test_resume_with_overlong_cursor_starts_fresh(self, tmp_path):
        # A cursor claiming more bytes than the file holds must not
        # truncate-EXTEND a NUL hole into the product: fresh start.
        raw = tmp_path / "r.raw"
        _synth(raw, windows=2, tone_chan=0)
        ref = str(tmp_path / "ref.hits")
        _reducer().search_to_file(str(raw), ref)
        out = str(tmp_path / "o.hits")
        _reducer().search_to_file(str(raw), out)
        from blit.pipeline import ReductionCursor

        size, mtime = ReductionCursor.stat_raw(str(raw))
        cur = SearchCursor(
            str(raw), NFFT, 4, 1, window_spectra=T, top_k=4,
            snr_threshold=2.0, windows_done=1,
            byte_offset=os.path.getsize(out) + 999,
            raw_size=size, raw_mtime_ns=mtime)
        cur.save(out)
        hdr = _reducer().search_resumable(str(raw), out)
        assert hdr["search_windows"] == 2
        with open(ref, "rb") as fr, open(out, "rb") as fo:
            assert fr.read() == fo.read()

    def test_resume_identity_mismatch_starts_fresh(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, windows=2, tone_chan=0)
        out = str(tmp_path / "o.hits")
        _reducer().search_resumable(str(raw), out)
        # A different SNR threshold is a different product: a stale
        # cursor must not graft onto it.
        red = _reducer(snr_threshold=3.0)
        cur = SearchCursor.load(out)
        assert cur is None  # completed: sidecar removed
        hdr = red.search_resumable(str(raw), out)
        assert hdr["search_snr_threshold"] == 3.0

    def test_multifile_sequence_and_window_split(self, tmp_path):
        # The same stream split across .NNNN.raw members searches
        # identically to the per-window decomposition: window w covers
        # spectra [wT, (w+1)T) wherever the file boundaries fall.
        paths, _ = synth_raw_sequence(
            str(tmp_path / "seq"), nfiles=2, blocks_per_file=1,
            obsnchan=2, ntime_per_block=(T * 2 + 3) * NFFT // 2 + NFFT,
            seed=3, tone_chan=1)
        hdr, hits = _reducer().search(paths)
        assert hdr["search_windows"] >= 2
        assert all(h.window < hdr["search_windows"] for h in hits)

    def test_search_telemetry(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, windows=2, tone_chan=0)
        from blit import observability

        red = _reducer(async_output=False)
        red.search(str(raw))
        hists = red.timeline.report()["hists"]
        assert "search.tree_s" in hists and hists["search.tree_s"]["n"] == 2
        assert "search.hits_per_window" in hists
        names = [s.name for s in observability.tracer().spans()]
        assert "search.stream" in names and "search.window" in names

    def test_empty_recording_rejected(self, tmp_path):
        p = tmp_path / "empty.raw"
        p.write_bytes(b"")
        with pytest.raises(ValueError):
            _reducer().search(str(p))


class TestSearchCursorDrills:
    """SearchCursor edge cases that landed untested in PR 6 (ISSUE 7
    satellite): the fsync-before-claim crash replay — bytes beyond the
    cursor's claim are truncated and re-reduced identically — and the
    truncate-beyond-EOF boundary, mirroring the ReductionCursor resume
    drills (tests/test_resume_fbh5.py)."""

    def _interrupted(self, tmp_path, claimed_windows=1):
        """A reference product plus an 'interrupted' resumable twin with
        ``claimed_windows`` durably claimed, returning
        ``(raw, ref_path, out_path, per_window_hits)``."""
        from blit.io.guppi import open_raw
        from blit.pipeline import ReductionCursor

        raw = tmp_path / "r.raw"
        _synth(raw, windows=3, tone_chan=0)
        ref = str(tmp_path / "ref.hits")
        _reducer().search_to_file(str(raw), ref)
        out = str(tmp_path / "res.hits")
        red = _reducer()
        hdr = red.header_for(open_raw(str(raw)))
        stream = red._search_stream(open_raw(str(raw)), hdr)
        per_window = []
        for _ in range(3):
            per_window.append(next(stream)[1])
        stream.close()
        size, mtime = ReductionCursor.stat_raw(str(raw))
        cur = SearchCursor(
            str(raw), NFFT, 4, 1, window_spectra=T, top_k=4,
            snr_threshold=2.0, raw_size=size, raw_mtime_ns=mtime)
        w = ResumableHitsWriter(out, hdr, 0, cur)
        for k in range(claimed_windows):
            w.append(WindowHits(k, per_window[k]))
        w.abort()
        return raw, ref, out, per_window

    def test_unclaimed_tail_truncated_and_replayed(self, tmp_path):
        # Crash AFTER window 1's lines hit the file but BEFORE the
        # cursor claimed them (the fsync-before-claim ordering's only
        # legal torn state): resume must truncate the unclaimed tail
        # and replay it, finishing byte-identical.
        raw, ref, out, per_window = self._interrupted(tmp_path)
        with open(out, "a") as f:
            f.write(WindowHits(1, per_window[1]).lines)
        hdr = _reducer().search_resumable(str(raw), out)
        assert hdr["search_windows"] == 3
        with open(ref, "rb") as fr, open(out, "rb") as fo:
            assert fr.read() == fo.read()
        assert not os.path.exists(SearchCursor.path_for(out))

    def test_torn_line_tail_truncated(self, tmp_path):
        # A crash mid-write leaves half a JSON line past the claim:
        # resume truncates it rather than splicing garbage mid-product.
        raw, ref, out, per_window = self._interrupted(tmp_path)
        with open(out, "a") as f:
            f.write(WindowHits(1, per_window[1]).lines[:17])
        hdr = _reducer().search_resumable(str(raw), out)
        assert hdr["search_windows"] == 3
        with open(ref, "rb") as fr, open(out, "rb") as fo:
            assert fr.read() == fo.read()

    def test_cursor_claim_exactly_at_eof_resumes(self, tmp_path):
        # The truncate-beyond-EOF guard is a strict inequality: a claim
        # equal to the file length is the CLEAN crash state and must
        # resume (not start fresh).
        raw, ref, out, _ = self._interrupted(tmp_path)
        cur = SearchCursor.load(out)
        assert cur.byte_offset == os.path.getsize(out)
        assert cur.windows_done == 1
        hdr = _reducer().search_resumable(str(raw), out)
        assert hdr["search_windows"] == 3
        # Resumed, not restarted: window 0 was not re-searched.
        with open(ref, "rb") as fr, open(out, "rb") as fo:
            assert fr.read() == fo.read()

    def test_cursor_one_byte_past_eof_starts_fresh(self, tmp_path):
        # One byte past EOF is already corrupt: POSIX truncate would
        # EXTEND a NUL hole into the product — must start fresh.
        raw, ref, out, _ = self._interrupted(tmp_path)
        cur = SearchCursor.load(out)
        cur.byte_offset = os.path.getsize(out) + 1
        cur.save(out)
        hdr = _reducer().search_resumable(str(raw), out)
        assert hdr["search_windows"] == 3
        with open(ref, "rb") as fr, open(out, "rb") as fo:
            assert fr.read() == fo.read()


class TestServiceHits:
    def test_hits_product_through_service_and_cache(self, tmp_path):
        from blit.serve import ProductRequest, ProductService
        from blit.serve.cache import ProductCache, fingerprint_for

        raw = str(tmp_path / "r.raw")
        _synth(raw, windows=2, tone_chan=1)
        tl = Timeline()
        req = ProductRequest(raw=raw, nfft=NFFT, kind="hits",
                             window_spectra=T, top_k=4, snr_threshold=2.0)
        # Search knobs separate the fingerprint from the filterbank ask
        # over the same bytes.
        fil = ProductRequest(raw=raw, nfft=NFFT)
        assert (fingerprint_for(req.reducer(), raw)
                != fingerprint_for(fil.reducer(), raw))
        with ProductService(
            cache=ProductCache(str(tmp_path / "cache"), timeline=tl),
            timeline=tl,
        ) as svc:
            hdr, data = svc.get(req, timeout=120)
            assert hdr["nchans"] == 8 and hdr["nifs"] == 1
            hits = hits_from_array(data, hdr)
            direct_hdr, direct = DedopplerReducer(
                nfft=NFFT, window_spectra=T, top_k=4, snr_threshold=2.0,
            ).search(raw)
            assert hits == direct
            # Second ask: served from cache, no reduction.
            t2 = svc.submit(req)
            assert t2.source in ("ram", "disk")
            hdr2, data2 = svc.result(t2)
            assert np.array_equal(data, data2)

    def test_request_validation(self):
        from blit.serve import ProductRequest

        with pytest.raises(ValueError):
            ProductRequest(raw="x.raw", top_k=4)  # search knob, no kind
        with pytest.raises(ValueError):
            ProductRequest(raw="x.raw", kind="hits", stokes="IQUV")
        with pytest.raises(ValueError):
            ProductRequest(raw="x.raw", kind="nope")


class TestSearchConfig:
    def test_env_overrides(self, monkeypatch):
        from blit.config import search_defaults

        base = search_defaults()
        monkeypatch.setenv("BLIT_SEARCH_WINDOW", "16")
        monkeypatch.setenv("BLIT_SEARCH_TOP_K", "3")
        monkeypatch.setenv("BLIT_SEARCH_SNR", "7.5")
        monkeypatch.setenv("BLIT_SEARCH_MAX_DRIFT", "5")
        d = search_defaults()
        assert d == {"window_spectra": 16, "top_k": 3,
                     "snr_threshold": 7.5, "max_drift_bins": 5}
        assert base["window_spectra"] == 64  # SiteConfig default

    def test_negative_max_drift_means_unlimited(self, monkeypatch):
        # Headers/cursors encode "no limit" as -1; feeding that back
        # (env, or knobs copied off a product header) must round-trip
        # to unlimited, not mask every drift row into zero hits.
        from blit.config import search_defaults

        monkeypatch.setenv("BLIT_SEARCH_MAX_DRIFT", "-1")
        assert search_defaults()["max_drift_bins"] is None
        red = DedopplerReducer(nfft=NFFT, max_drift_bins=-1)
        assert red.max_drift_bins is None
        assert red.fingerprint_extra()["max_drift_bins"] is None

    def test_reducer_resolves_defaults(self, monkeypatch):
        monkeypatch.setenv("BLIT_SEARCH_WINDOW", "16")
        monkeypatch.setenv("BLIT_SEARCH_SNR", "4.0")
        red = DedopplerReducer(nfft=NFFT)
        assert red.window_spectra == 16
        assert red.snr_threshold == 4.0
        assert red.fingerprint_extra()["window_spectra"] == 16


class TestSearchCLI:
    def test_search_smoke(self, tmp_path, capsys):
        raw = tmp_path / "r.raw"
        _synth(raw, windows=2, tone_chan=1, drift_bins=2, tone_amp=30.0)
        out = str(tmp_path / "o.hits")
        rc = main(["search", str(raw), "-o", out, "--nfft", str(NFFT),
                   "--window-spectra", str(T), "--snr", "6.0",
                   "--top-k", "4", "--kernel", "reference"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["output"] == out and doc["windows"] == 2
        hdr, hits = read_hits(out)
        assert hdr["search_window_spectra"] == T
        assert len(hits) == doc["hits"]
        top = max(hits, key=lambda h: h.snr)
        assert abs(top.drift_bins - 2) <= 1

    def test_search_resume_flag(self, tmp_path, capsys):
        raw = tmp_path / "r.raw"
        _synth(raw, windows=2, tone_chan=0)
        out = str(tmp_path / "o.hits")
        rc = main(["search", str(raw), "-o", out, "--nfft", str(NFFT),
                   "--window-spectra", str(T), "--snr", "2.0", "--resume"])
        assert rc == 0
        assert os.path.exists(out)
        assert not os.path.exists(SearchCursor.path_for(out))
