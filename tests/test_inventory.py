"""Inventory crawl over a synthetic directory tree covering the reference's
edge cases (src/gbtworkerfunctions.jl:68-129): symlinked sessions, regex
filtering at every level, malformed names -> warn-and-skip, missing root."""

import os

from blit.inventory import InventoryRecord, get_inventory, to_dataframe


def mkfile(path):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"x")


def build_tree(root):
    s1 = "AGBT22B_999_01"
    s2 = "AGBT22B_999_02"
    # session 1, two players, one matching file each + one non-matching product
    for player, host in [("BLP00", "blc00"), ("BLP01", "blc01")]:
        base = f"{root}/{s1}/GUPPI/{player}"
        mkfile(f"{base}/{host}_guppi_59897_21221_HD_84406_0011.rawspec.0002.h5")
        mkfile(f"{base}/{host}_guppi_59897_21221_HD_84406_0011.rawspec.0001.h5")
    # a player dir that must be filtered out (bad name — reference's malformed
    # regex would have accepted it; ours must not)
    mkfile(f"{root}/{s1}/GUPPI/BLPd3/blc03_guppi_59897_21221_HD_84406_0011.rawspec.0002.h5")
    # a non-session dir to be filtered
    mkfile(f"{root}/junkdir/GUPPI/BLP00/blc00_guppi_1_2_X_0001.rawspec.0002.h5")
    # a matching-name file whose guppi name doesn't parse -> warn-and-skip
    mkfile(f"{root}/{s1}/GUPPI/BLP00/garbage.rawspec.0002.h5")
    # session 2 as real dir, session 3 as symlink to it
    mkfile(f"{root}/{s2}/GUPPI/BLP11/blc11_guppi_59898_100_VOYAGER1_0001.rawspec.0002.h5")
    os.symlink(f"{root}/{s2}", f"{root}/AGBT22B_999_03")
    return root


def test_crawl(tmp_path, caplog):
    root = build_tree(str(tmp_path))
    with caplog.at_level("WARNING", logger="blit.inventory"):
        inv = get_inventory(root=root, worker=5, host="testhost")
    files = [os.path.basename(r.file) for r in inv]
    # 2 from session1 + 1 from session2 + 1 via the session3 symlink
    assert len(inv) == 4
    assert all(f.endswith("0002.h5") for f in files)
    # the malformed-name file triggered a warning and was skipped
    assert any("garbage" in rec.message for rec in caplog.records)
    # field stamping
    assert all(r.host == "testhost" and r.worker == 5 for r in inv)
    # band/bank parsed from the player path component
    r0 = [r for r in inv if r.session == "AGBT22B_999_01"][0]
    assert (r0.band, r0.bank) == (0, 0)
    assert r0.scan == "0011"
    assert r0.src_name == "HD_84406"
    assert r0.imjd == 59897 and r0.smjd == 21221
    # symlinked session appears under its own (symlink) session name
    sessions = {r.session for r in inv}
    assert sessions == {"AGBT22B_999_01", "AGBT22B_999_02", "AGBT22B_999_03"}


def test_missing_root_returns_empty(tmp_path):
    assert get_inventory(root=str(tmp_path / "nope")) == []


def test_custom_file_re(tmp_path):
    root = build_tree(str(tmp_path))
    inv = get_inventory(r"0001\.h5$", root=root)
    assert len(inv) == 2
    assert all(r.file.endswith("0001.h5") for r in inv)


def test_to_dataframe(tmp_path):
    root = build_tree(str(tmp_path))
    inv1 = get_inventory(root=root, worker=1)
    inv2 = []  # ragged per-worker inventories are first-class
    df = to_dataframe([inv1, inv2])
    assert list(df.columns) == list(InventoryRecord._fields)
    assert len(df) == 4
    # the reference README's canonical groupby workflow (README.md:95-157)
    g = df.groupby(["session", "scan"]).size()
    assert g.loc[("AGBT22B_999_01", "0011")] == 2


class TestRawSequenceDedup:
    def test_duplicate_members_deduped(self):
        # Shared filesystem: two workers inventory the SAME member file.
        # The sequence must not double (GuppiScan would read the
        # recording twice as if it were longer); first reporter wins.
        from blit.inventory import raw_sequences

        mk = lambda host, f, w: InventoryRecord(
            1, 2, "S", "0001", "src", 0, 0, host, f, w)
        out = raw_sequences([
            mk("h1", "/d/x.0000.raw", 1),
            mk("h2", "/d/x.0000.raw", 2),
            mk("h1", "/d/x.0001.raw", 1),
        ])
        assert len(out) == 1
        rec, paths = out[0]
        assert paths == ["/d/x.0000.raw", "/d/x.0001.raw"]
        assert rec.worker == 1  # first reporter
