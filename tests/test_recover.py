"""The crash-recovery plane (blit/recover.py, ISSUE 12).

Unit legs: heartbeat leases, the replan ladder (reshaped mesh vs pool
fallback), the /healthz degradation hook.  End-to-end legs: real
supervised multi-process sharded scans under seeded ``kill``/``hang``
faults — detection within the lease budget, degrade-and-resume, and
final products BYTE-IDENTICAL to an uninterrupted pool-oracle run —
plus the supervised live-consumer rejoin drill (``StreamSupervisor``)
and the ``blit chaos`` / ``ingest-bench --chaos`` CLI surfaces.

The subprocess drills each pay child jax imports; sizes are the chaos
CLI's smallest (2x2 grid, nfft=32) so the whole module stays well
inside the tier-1 budget.
"""

import json
import os
import time

import pytest

from blit.observability import Timeline
from blit.recover import (
    Lease,
    RECOVER_HISTS,
    ScanPlan,
    ScanSupervisor,
    StreamSupervisor,
    active_supervisors,
    lease_age_s,
    read_lease,
    replan,
)
from blit.testing import synth_raw

NFFT, WF = 32, 4


def _grid(tmp_path, nband=2, nbank=2, nchan=2):
    bank_bw = -187.5 / nbank
    grid = []
    for b in range(nband):
        row = []
        for k in range(nbank):
            p = str(tmp_path / f"blc{b}{k}.raw")
            synth_raw(p, nblocks=2, obsnchan=nchan, ntime_per_block=512,
                      seed=b * 8 + k, tone_chan=k % nchan, obsbw=bank_bw,
                      obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw)
            row.append(p)
        grid.append(row)
    return grid


def _pool_oracle(grid, tmp_path):
    from blit.parallel.scan import reduce_scan_pool_to_files

    d = tmp_path / "oracle"
    d.mkdir(exist_ok=True)
    return reduce_scan_pool_to_files(
        grid, out_dir=str(d), nfft=NFFT, despike=False,
        window_frames=WF)


def _bytes(path):
    with open(path, "rb") as f:
        return f.read()


class TestLease:
    def test_beat_refreshes_and_reads_back(self, tmp_path):
        d = str(tmp_path / "leases")
        lease = Lease(d, 3)
        lease.beat(window=7)
        doc = read_lease(d, 3)
        assert doc["proc"] == 3 and doc["window"] == 7
        assert doc["pid"] == os.getpid()
        age = lease_age_s(d, 3)
        assert age is not None and age < 5.0

    def test_missing_lease_has_no_age(self, tmp_path):
        assert lease_age_s(str(tmp_path), 0) is None

    def test_staleness_grows_without_beats(self, tmp_path):
        d = str(tmp_path)
        lease = Lease(d, 0)
        lease.beat()
        # Backdate the lease file: age is judged by mtime, exactly what
        # a SIGKILLed process leaves behind.
        past = time.time() - 100
        os.utime(Lease.path_for(d, 0), (past, past))
        assert lease_age_s(d, 0) > 99


class TestReplan:
    def test_full_pod_plans_sharded(self):
        assert replan(2, 4, 4, 2) == ScanPlan("sharded", 2, 4)

    def test_survivor_with_whole_mesh_reshapes(self):
        # One host with enough chips for the whole mesh: sharded, 1 proc.
        assert replan(2, 2, 4, 1) == ScanPlan("sharded", 1, 4)

    def test_survivor_too_small_degrades_to_pool(self):
        # The surviving host cannot hold the mesh: pool fallback.
        assert replan(2, 2, 2, 1) == ScanPlan("pool")

    def test_band_row_splitting_is_refused(self):
        # 4 procs over a 2x4 mesh would give each 2 chips — half a band
        # row.  The planner must pick 2 procs (whole rows), not 4.
        assert replan(2, 4, 8, 4) == ScanPlan("sharded", 2, 4)

    def test_no_survivors_is_pool(self):
        assert replan(2, 2, 4, 0) == ScanPlan("pool")


class TestHealthHook:
    def test_mid_recovery_degrades_healthz(self, tmp_path):
        from blit import monitor
        from blit.recover import _register, _unregister

        pub = monitor.MetricsPublisher(interval_s=60, spool_dir=None,
                                       port=None)
        try:
            h = pub.health()
            assert h["status"] == "ok" and h["ok"] is True
            assert h["reasons"] == []
            state = {"kind": "reduce", "phase": "recovering",
                     "attempt": 1, "plan": "pool"}
            key = _register(state)
            try:
                assert any(s["phase"] == "recovering"
                           for s in active_supervisors())
                h = pub.health()
                assert h["status"] == "degraded" and h["ok"] is False
                assert any(r.startswith("recover:") for r in h["reasons"])
            finally:
                _unregister(key)
            h = pub.health()
            assert h["status"] == "ok"
        finally:
            pub.close()


@pytest.mark.timeout(280)
class TestScanSupervisorDrills:
    def _sup(self, grid, out_dir, *, devices_per_proc, faults,
             tl=None, **kw):
        return ScanSupervisor(
            grid, out_dir=str(out_dir), kind="reduce", nfft=NFFT,
            despike=False, window_frames=WF, nprocs=2,
            devices_per_proc=devices_per_proc, lease_ttl_s=3.0,
            poll_s=0.1, max_attempts=3, faults=faults,
            timeline=tl if tl is not None else Timeline(), **kw)

    def test_kill_reshapes_mesh_and_resumes_byte_identical(
            self, tmp_path):
        # SIGKILL proc 0 at window 2 of a 2-process pod whose hosts each
        # hold the WHOLE mesh: detection via process exit, re-plan to a
        # 1-process sharded pod, resume from the cursors — products
        # byte-identical to the uninterrupted pool oracle, and the
        # recover.* histograms populated.
        grid = _grid(tmp_path)
        oracle = _pool_oracle(grid, tmp_path)
        tl = Timeline()
        sup = self._sup(grid, tmp_path / "prod", devices_per_proc=4,
                        faults={0: "mesh.window:kill:after=2"}, tl=tl)
        rep = sup.run()
        assert rep["recovered"] is True
        assert rep["attempts"][0]["failure"]["why"] == "died"
        assert rep["attempts"][0]["failure"]["rc"] == -9
        assert rep["attempts"][1]["plan"] == "sharded"
        assert rep["attempts"][1]["nprocs"] == 1
        for b, (opath, _) in oracle.items():
            got = str(tmp_path / "prod" / os.path.basename(opath))
            assert _bytes(got) == _bytes(opath), f"band {b} differs"
        hists = tl.report().get("hists", {})
        for h in RECOVER_HISTS:
            assert hists.get(h, {}).get("n", 0) >= 1, h
        # No stale cursors after a clean finish.
        assert not [p for p in os.listdir(tmp_path / "prod")
                    if p.endswith(".cursor")]

    def test_kill_without_mesh_capacity_falls_back_to_pool(
            self, tmp_path):
        # Hosts hold only their own mesh share: losing one makes the
        # mesh unformable and the supervisor must degrade to the PR 2
        # pool path — still byte-identical.
        grid = _grid(tmp_path)
        oracle = _pool_oracle(grid, tmp_path)
        sup = self._sup(grid, tmp_path / "prod", devices_per_proc=2,
                        faults={0: "mesh.window:kill:after=2"})
        rep = sup.run()
        assert rep["recovered"] is True
        assert rep["attempts"][1]["plan"] == "pool"
        for b, (opath, _) in oracle.items():
            got = str(tmp_path / "prod" / os.path.basename(opath))
            assert _bytes(got) == _bytes(opath), f"band {b} differs"
        assert not [p for p in os.listdir(tmp_path / "prod")
                    if p.endswith(".cursor")]

    def test_hang_detected_by_lease_expiry(self, tmp_path):
        # A wedged (not dead) peer: the injected hang sleeps far past
        # the lease TTL while the process stays alive — detection must
        # come from lease staleness, and the hung child must be killed.
        grid = _grid(tmp_path)
        oracle = _pool_oracle(grid, tmp_path)
        sup = self._sup(grid, tmp_path / "prod", devices_per_proc=4,
                        faults={0: "mesh.window:hang:after=2:hang=120"})
        rep = sup.run()
        assert rep["recovered"] is True
        fail = rep["attempts"][0]["failure"]
        assert fail["why"] == "hung"
        # Detection latency is bounded by TTL + poll slack.
        assert fail["detect_s"] < 3.0 + 2.0
        for b, (opath, _) in oracle.items():
            got = str(tmp_path / "prod" / os.path.basename(opath))
            assert _bytes(got) == _bytes(opath), f"band {b} differs"


@pytest.mark.timeout(280)
class TestStreamSupervisorDrill:
    def test_killed_consumer_rejoins_byte_identical(self, tmp_path):
        from blit.pipeline import RawReducer

        raw = str(tmp_path / "live.raw")
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=512,
                  seed=3)
        oracle = str(tmp_path / "oracle.fil")
        RawReducer(nfft=NFFT, chunk_frames=WF,
                   tune_online=False).reduce_to_file(raw, oracle)
        out = str(tmp_path / "live.fil")
        tl = Timeline()
        sup = StreamSupervisor(
            raw, out, kind="reduce",
            knobs=dict(nfft=NFFT, chunk_frames=WF, tune_online=False),
            replay_rate=500.0, faults="stream.chunk:kill:after=2",
            lease_ttl_s=3.0, poll_s=0.05, max_attempts=3, timeline=tl)
        rep = sup.run()
        assert rep["recovered"] is True
        assert rep["attempts"][0]["failure"]["rc"] == -9
        assert _bytes(out) == _bytes(oracle)
        from blit.stream import StreamCursor

        assert StreamCursor.load(out) is None  # removed on completion
        hists = tl.report().get("hists", {})
        assert hists.get("recover.detect_s", {}).get("n", 0) >= 1


@pytest.mark.timeout(280)
class TestChaosCLI:
    def test_chaos_stream_drill_json(self, tmp_path, capsys):
        from blit.__main__ import main

        json_out = str(tmp_path / "chaos.json")
        rc = main([
            "chaos", "--workload", "stream", "--lease-ttl", "3",
            "--poll", "0.05", "--work-dir", str(tmp_path / "work"),
            "--json-out", json_out,
        ])
        assert rc == 0
        with open(json_out) as f:
            rep = json.load(f)
        assert rep["recovered"] is True
        assert rep["byte_identical"] is True
        assert rep["recover"]["recover.detect_s"].get("n", 0) >= 1
        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(line)["byte_identical"] is True
