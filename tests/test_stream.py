"""Streaming ingest plane (ISSUE 7): byte-identity goldens (a stream of
a completed recording == the batch reduction, for .fil/.h5/.hits, under
reordering/duplicate/dropped-chunk faults with masking engaged), the
watermark lateness semantics, the growing-file tailer, the latency
metrics, and the `blit stream` / `ingest-bench --live` CLI legs."""

import io
import contextlib
import json
import os
import threading
import time

import pytest

from blit import faults, observability
from blit.config import stream_defaults
from blit.faults import FaultRule
from blit.io.guppi import open_raw, write_raw
from blit.observability import StallWatchdog, Timeline
from blit.pipeline import RawReducer
from blit.stream import (
    FileTailSource,
    LiveRawStream,
    QueueSource,
    ReplaySource,
    chunks_of,
    stream_reduce,
    stream_search,
)
from blit.testing import synth_raw

NFFT = 256
NINT = 2
CHUNK_FRAMES = 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


@pytest.fixture(autouse=True)
def _flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path / "flight"))
    os.makedirs(str(tmp_path / "flight"), exist_ok=True)


def _synth(path, nblocks=4, overlap=NFFT, seed=1, **kw):
    return synth_raw(str(path), nblocks=nblocks, obsnchan=2,
                     ntime_per_block=(8 + 3) * NFFT, overlap=overlap,
                     seed=seed, tone_chan=1, **kw)


def _reducer(**kw):
    kw.setdefault("timeline", Timeline())
    return RawReducer(nfft=NFFT, nint=NINT, chunk_frames=CHUNK_FRAMES,
                      **kw)


def _batch(raw, out):
    _reducer().reduce_to_file(str(raw), str(out))
    with open(out, "rb") as f:
        return f.read()


def _read(path):
    with open(path, "rb") as f:
        return f.read()


class TestByteIdentityGolden:
    """The plane's golden contract: stream ≡ batch, byte for byte."""

    def test_replay_fil_identical_to_batch(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        out = tmp_path / "s.fil"
        hdr = stream_reduce(ReplaySource(str(raw), rate=1e6), str(out),
                            reducer=_reducer())
        assert _read(out) == ref
        # The clean path reports itself clean.
        assert hdr["stream_masked_chunks"] == 0
        assert hdr["stream_late_chunks"] == 0
        assert hdr["stream_dup_chunks"] == 0
        assert hdr["stream_chunks"] == 4

    def test_replay_h5_identical_to_batch(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = tmp_path / "ref.h5"
        _reducer().reduce_to_file(str(raw), str(ref))
        out = tmp_path / "s.h5"
        stream_reduce(ReplaySource(str(raw), rate=1e6), str(out),
                      reducer=_reducer())
        assert _read(out) == _read(ref)

    def test_stream_search_hits_identical_to_batch(self, tmp_path):
        from blit.search import DedopplerReducer

        raw = tmp_path / "r.raw"
        _synth(raw)

        def searcher():
            return DedopplerReducer(
                nfft=NFFT, nint=NINT, chunk_frames=CHUNK_FRAMES,
                window_spectra=8, snr_threshold=2.0, top_k=4,
                timeline=Timeline())

        ref = tmp_path / "ref.hits"
        searcher().search_to_file(str(raw), str(ref))
        out = tmp_path / "s.hits"
        hdr = stream_search(ReplaySource(str(raw), rate=1e6), str(out),
                            searcher=searcher())
        assert _read(out) == _read(ref)
        assert hdr["search_windows"] >= 2
        assert hdr["search_nhits"] > 0

    def test_sync_output_plane_identical(self, tmp_path):
        # The A/B lever holds on the live plane too.
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        out = tmp_path / "s.fil"
        stream_reduce(ReplaySource(str(raw), rate=1e6), str(out),
                      reducer=_reducer(async_output=False))
        assert _read(out) == ref

    def test_reordered_and_duplicated_chunks_repair(self, tmp_path):
        # Late-but-within-budget arrivals reorder; duplicates drop —
        # the product must not notice either.
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        cs = chunks_of(open_raw(str(raw)))
        qs = QueueSource()
        for c in (cs[1], cs[0], cs[2], cs[2], cs[3], cs[0]):
            qs.push(c)
        qs.finish(total=4)
        out = tmp_path / "s.fil"
        hdr = stream_reduce(qs, str(out), reducer=_reducer(),
                            lateness_s=10.0)
        assert _read(out) == ref
        assert hdr["stream_dup_chunks"] == 2
        assert hdr["stream_masked_chunks"] == 0


class TestWatermarkMasking:
    def _zero_masked_ref(self, tmp_path, hdr0, blocks, masked):
        """Batch comparator: the same recording with the masked blocks'
        samples zeroed — exactly what zero-weight masking must yield."""
        zb = [b.copy() for b in blocks]
        for i in masked:
            zb[i][:] = 0
        zraw = tmp_path / "zeroed.raw"
        write_raw(str(zraw), hdr0, zb)
        return _batch(zraw, tmp_path / "zref.fil")

    def test_dropped_chunk_masks_zero_weight(self, tmp_path):
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        ref = self._zero_masked_ref(tmp_path, hdr0, blocks, [2])
        cs = chunks_of(open_raw(str(raw)))
        qs = QueueSource()
        for c in (cs[0], cs[1], cs[3]):  # chunk 2 never arrives
            qs.push(c)
        qs.finish(total=4)
        out = tmp_path / "s.fil"
        hdr = stream_reduce(qs, str(out), reducer=_reducer(),
                            lateness_s=0.1)
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 1
        assert hdr["_masked_chunks"] == [2]
        # Zero-filled samples degrade every output row whose PFB window
        # touches them — and no more.
        assert 0 < hdr["stream_degraded_spectra"] < hdr["nsamps"]
        # The degradation is loud everywhere a healthy run reports:
        # fault counter, flight dump, header.
        assert faults.counters().get("mask.chunk") == 1
        assert hdr["stream_flight_dump"] is not None
        assert os.path.exists(hdr["stream_flight_dump"])
        with open(hdr["stream_flight_dump"]) as f:
            doc = json.load(f)
        assert "masked" in doc["reason"]

    def test_late_chunk_after_mask_is_dropped(self, tmp_path):
        # A straggler past the budget must be counted + dropped, never
        # spliced into already-emitted history.
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        ref = self._zero_masked_ref(tmp_path, hdr0, blocks, [1])
        cs = chunks_of(open_raw(str(raw)))
        qs = QueueSource()
        qs.push(cs[0])
        qs.push(cs[2])  # proof chunk 1 is missing

        def straggler():
            time.sleep(0.5)  # well past the 0.1 s budget
            qs.push(cs[1])
            qs.push(cs[3])
            qs.finish(total=4)

        t = threading.Thread(target=straggler)
        t.start()
        out = tmp_path / "s.fil"
        hdr = stream_reduce(qs, str(out), reducer=_reducer(),
                            lateness_s=0.1)
        t.join()
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 1
        assert hdr["stream_late_chunks"] == 1
        assert hdr["_masked_chunks"] == [1]

    def test_missing_tail_masked_after_eos(self, tmp_path):
        # EOS is evidence too: a gap before a declared total masks once
        # the budget expires, instead of waiting forever.
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        ref = self._zero_masked_ref(tmp_path, hdr0, blocks, [3])
        cs = chunks_of(open_raw(str(raw)))
        qs = QueueSource()
        for c in cs[:3]:
            qs.push(c)
        qs.finish(total=4)  # chunk 3 never comes
        out = tmp_path / "s.fil"
        hdr = stream_reduce(qs, str(out), reducer=_reducer(),
                            lateness_s=0.1)
        assert _read(out) == ref
        assert hdr["_masked_chunks"] == [3]

    def test_injected_drop_and_dup_fault_modes(self, tmp_path):
        # The stream.chunk injection point (faults.py drop/dup modes):
        # a BLIT_FAULTS-style drill masks one chunk and dedups another.
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        ref = self._zero_masked_ref(tmp_path, hdr0, blocks, [1])
        faults.install(
            FaultRule("stream.chunk", "drop", times=1, after=1),
            FaultRule("stream.chunk", "dup", times=1, after=2),
        )
        out = tmp_path / "s.fil"
        hdr = stream_reduce(ReplaySource(str(raw), rate=1e6), str(out),
                            reducer=_reducer(), lateness_s=0.1)
        assert _read(out) == ref
        assert hdr["stream_masked_chunks"] == 1
        assert hdr["stream_dup_chunks"] == 1
        c = faults.counters()
        assert c.get("fault.stream.chunk.drop") == 1
        assert c.get("fault.stream.chunk.dup") == 1

    def test_empty_stream_rejected(self):
        qs = QueueSource()
        qs.finish(total=0)
        with pytest.raises(ValueError, match="empty stream"):
            LiveRawStream(qs, lateness_s=0.1).header(0)


class TestFileTail:
    def _write_slowly(self, src_path, dst_path, done_path, parts=6,
                      dt=0.02):
        data = _read(src_path)
        step = -(-len(data) // parts)

        def run():
            with open(dst_path, "wb") as f:
                for i in range(0, len(data), step):
                    f.write(data[i:i + step])
                    f.flush()
                    time.sleep(dt)
            with open(done_path, "w"):
                pass

        t = threading.Thread(target=run)
        t.start()
        return t

    def test_tail_growing_file_identical_to_batch(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        live = str(tmp_path / "live.0000.raw")
        t = self._write_slowly(str(raw), live,
                               str(tmp_path / "live.done"))
        out = tmp_path / "s.fil"
        hdr = stream_reduce(FileTailSource(live, poll_s=0.005),
                            str(out), reducer=_reducer())
        t.join()
        assert _read(out) == ref
        assert hdr["stream_chunks"] == 4
        assert hdr["stream_masked_chunks"] == 0

    def test_tail_follows_sequence_members(self, tmp_path):
        # The recorder rolls to .0001.raw mid-session; the tailer must
        # follow and the stitched product must match the batch scan.
        raw = tmp_path / "r.raw"
        hdr0, blocks = _synth(raw)
        m0 = str(tmp_path / "seq.0000.raw")
        m1 = str(tmp_path / "seq.0001.raw")
        write_raw(m0, hdr0, blocks[:2])
        h1 = dict(hdr0)
        h1["PKTIDX"] = sum(
            b.shape[1] - hdr0.get("OVERLAP", 0) for b in blocks[:2])
        write_raw(m1, h1, blocks[2:])
        ref = tmp_path / "ref.fil"
        _reducer().reduce_to_file([m0, m1], str(ref))

        def recorder():
            time.sleep(0.1)
            with open(str(tmp_path / "seq.done"), "w"):
                pass

        t = threading.Thread(target=recorder)
        t.start()
        out = tmp_path / "s.fil"
        hdr = stream_reduce(FileTailSource(m0, poll_s=0.005), str(out),
                            reducer=_reducer())
        t.join()
        assert _read(out) == _read(ref)
        assert hdr["stream_chunks"] == 4

    def test_idle_timeout_ends_session(self, tmp_path):
        # Recorder dies without a done marker: the tail must end (and
        # the partial product publish) instead of following forever.
        raw = tmp_path / "r.raw"
        _synth(raw)
        live = str(tmp_path / "live.0000.raw")
        with open(str(raw), "rb") as f:
            open(live, "wb").write(f.read())
        out = tmp_path / "s.fil"
        hdr = stream_reduce(
            FileTailSource(live, poll_s=0.01, idle_timeout_s=0.15),
            str(out), reducer=_reducer())
        assert hdr["stream_chunks"] == 4
        assert _read(out) == _batch(raw, tmp_path / "ref.fil")

    def test_half_written_block_not_delivered(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw, nblocks=2)
        data = _read(str(raw))
        live = str(tmp_path / "live.0000.raw")
        with open(live, "wb") as f:
            f.write(data[:len(data) - 100])  # final block torn
        src = FileTailSource(live, poll_s=0.005)
        c = src.get(timeout=0.05)
        assert c is not None and c.seq == 0
        assert src.get(timeout=0.05) is None  # block 1 incomplete
        with open(live, "ab") as f:
            f.write(data[len(data) - 100:])
        c = src.get(timeout=0.05)
        assert c is not None and c.seq == 1


class TestLatencyMetrics:
    def test_chunk_to_product_histogram_and_gauges(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        red = _reducer()
        out = tmp_path / "s.fil"
        stream_reduce(ReplaySource(str(raw), rate=1e6), str(out),
                      reducer=red)
        rep = red.timeline.report()
        lat = rep["hists"]["stream.chunk_to_product_s"]
        assert lat["n"] >= 4  # one observation per product append
        assert lat["p99"] >= lat["p50"] >= 0.0
        assert "stream.watermark_lag_s" in rep["gauges"]
        assert rep["stream.chunks"]["calls"] == 4

    def test_default_reducer_records_on_process_timeline(self, tmp_path):
        # The CI telemetry artifact rides the process timeline: entry
        # points that build their own reducer must land stream.* there.
        raw = tmp_path / "r.raw"
        _synth(raw)
        tl = observability.process_timeline()
        before = tl.hists["stream.chunk_to_product_s"].n
        out = tmp_path / "s.fil"
        stream_reduce(ReplaySource(str(raw), rate=1e6), str(out),
                      nfft=NFFT, nint=NINT, chunk_frames=CHUNK_FRAMES)
        assert tl.hists["stream.chunk_to_product_s"].n > before


class TestStallWatchdog:
    def test_unit_semantics(self):
        wd = StallWatchdog(None, "x")
        assert wd.poll_s(0.3) == 0.3
        wd.check("never trips")  # unarmed: no-op
        wd = StallWatchdog(0.2, "x", what="test stall")
        assert wd.poll_s(0.5) == 0.1
        wd._beat -= 1.0
        assert wd.stalled()
        assert not wd.stalled(active=False)
        with pytest.raises(RuntimeError, match="stalled here"):
            wd.check("stalled here")

    def test_wedged_source_trips_feed_watchdog(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        cs = chunks_of(open_raw(str(raw)))
        qs = QueueSource()
        qs.push(cs[0])  # first chunk arrives, then the source wedges
        out = tmp_path / "s.fil"
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="stall"):
            stream_reduce(qs, str(out), reducer=_reducer(),
                          lateness_s=0.05, stall_timeout_s=0.3)
        assert time.monotonic() - t0 < 10

    def test_quiet_source_without_watchdog_is_patient(self, tmp_path):
        # No stall timeout armed (the default): a slow-but-alive
        # recorder must not trip anything.
        raw = tmp_path / "r.raw"
        _synth(raw)
        cs = chunks_of(open_raw(str(raw)))
        qs = QueueSource()

        def trickle():
            for c in cs:
                time.sleep(0.05)
                qs.push(c)
            qs.finish(total=4)

        t = threading.Thread(target=trickle)
        t.start()
        out = tmp_path / "s.fil"
        hdr = stream_reduce(qs, str(out), reducer=_reducer(),
                            lateness_s=5.0)
        t.join()
        assert hdr["stream_masked_chunks"] == 0


class TestStreamConfig:
    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("BLIT_STREAM_LATENESS", "7.5")
        monkeypatch.setenv("BLIT_STREAM_POLL", "0.25")
        monkeypatch.setenv("BLIT_STREAM_IDLE_TIMEOUT", "12")
        monkeypatch.setenv("BLIT_STREAM_STALL_TIMEOUT", "-1")
        d = stream_defaults()
        assert d["lateness_s"] == 7.5
        assert d["poll_s"] == 0.25
        assert d["idle_timeout_s"] == 12.0
        assert d["stall_timeout_s"] is None  # negative = unarmed

    def test_defaults_reach_live_stream_and_tailer(self, monkeypatch,
                                                   tmp_path):
        monkeypatch.setenv("BLIT_STREAM_LATENESS", "3.25")
        monkeypatch.setenv("BLIT_STREAM_IDLE_TIMEOUT", "9")
        live = LiveRawStream(QueueSource())
        assert live.lateness_s == 3.25
        src = FileTailSource(str(tmp_path / "x.0000.raw"))
        assert src.idle_timeout_s == 9.0


class TestWorkersAndCLI:
    def test_workers_stream_raw_replay(self, tmp_path):
        from blit import workers

        raw = tmp_path / "r.raw"
        _synth(raw)
        ref = _batch(raw, tmp_path / "ref.fil")
        out = tmp_path / "w.fil"
        hdr = workers.stream_raw(str(raw), str(out), replay_rate=1e6,
                                 nfft=NFFT, nint=NINT,
                                 chunk_frames=CHUNK_FRAMES)
        assert _read(out) == ref
        assert hdr["stream_chunks"] == 4

    def _main(self, argv):
        from blit.__main__ import main

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(argv)
        return rc, json.loads(buf.getvalue().strip().splitlines()[-1])

    def test_cli_stream_smoke(self, tmp_path):
        # The tier-1 CLI smoke (ISSUE 7 satellite): accelerated replay
        # through `blit stream`, latency percentiles in the report.
        raw = tmp_path / "r.raw"
        _synth(raw)
        out = str(tmp_path / "s.fil")
        rc, rep = self._main([
            "stream", str(raw), "-o", out, "--nfft", str(NFFT),
            "--nint", str(NINT), "--replay-rate", "1000",
        ])
        assert rc == 0
        assert rep["output"] == out
        assert rep["masked_chunks"] == 0
        assert rep["chunk_to_product_p99_s"] >= rep[
            "chunk_to_product_p50_s"] >= 0.0
        assert _read(out) == _batch(raw, tmp_path / "ref.fil")

    def test_cli_stream_search_smoke(self, tmp_path):
        raw = tmp_path / "r.raw"
        _synth(raw)
        out = str(tmp_path / "s.hits")
        rc, rep = self._main([
            "stream", str(raw), "-o", out, "--nfft", str(NFFT),
            "--search", "--window-spectra", "8", "--snr", "2.0",
            "--replay-rate", "1000",
        ])
        assert rc == 0
        assert rep["windows"] >= 1
        assert os.path.exists(out)

    def test_ingest_bench_live_and_drill(self, tmp_path):
        # The accelerated-replay latency leg: zero dropped windows on
        # the clean path; the seeded late-chunk drill masks (does not
        # wedge) and leaves a flight dump.
        rc, rep = self._main([
            "ingest-bench", "--nfft", str(NFFT), "--chunk-frames", "4",
            "--chunks", "4", "--blocks", "4", "--live",
            "--live-rate", "8", "--live-seconds", "0.2", "--live-drill",
        ])
        assert rc == 0
        live = rep["live"]
        assert live["degraded_spectra"] == 0
        assert live["late_chunks"] == 0
        assert live["chunk_to_product_p99_s"] >= live[
            "chunk_to_product_p50_s"] > 0.0
        drill = rep["live_drill"]
        assert drill["masked_chunks"] == 1
        assert drill["late_chunks"] == 1
        assert drill["degraded_spectra"] > 0
        assert os.path.exists(drill["flight_dump"])
