"""Auxiliary-subsystem tests (SURVEY.md §5): stage timing, structured
logging, inventory persistence, resumable reduction cursors, multi-host
helpers."""

import logging

import numpy as np
import pytest

from blit.inventory import InventoryRecord, load_inventories, save_inventories
from blit.observability import Timeline, configure_logging, profile_trace


class TestTimeline:
    def test_stage_accumulation(self):
        tl = Timeline()
        with tl.stage("read", nbytes=1000):
            pass
        with tl.stage("read", nbytes=500):
            pass
        with tl.stage("reduce"):
            pass
        rep = tl.report()
        assert rep["read"]["calls"] == 2
        assert rep["read"]["bytes"] == 1500
        assert rep["read"]["seconds"] >= 0
        assert rep["reduce"]["calls"] == 1

    def test_stage_records_on_exception(self):
        tl = Timeline()
        with pytest.raises(RuntimeError):
            with tl.stage("bad"):
                raise RuntimeError("x")
        assert tl.report()["bad"]["calls"] == 1

    def test_profile_trace_none_is_noop(self):
        with profile_trace(None):
            x = 1
        assert x == 1

    def test_byte_free_flag_survives_to_report(self):
        tl = Timeline()
        with tl.stage("wait", byte_free=True):
            pass
        with tl.stage("move", nbytes=10):
            pass
        rep = tl.report()
        assert rep["wait"]["byte_free"] is True
        assert "byte_free" not in rep["move"]
        assert tl.stages["wait"].byte_free

    def test_snapshot_since_deltas(self):
        # The per-window stage record the windowed drivers report.
        tl = Timeline()
        with tl.stage("read", nbytes=100):
            pass
        snap = tl.snapshot()
        with tl.stage("read", nbytes=50):
            pass
        with tl.stage("write", nbytes=7):
            pass
        delta = tl.since(snap)
        assert delta["read"]["calls"] == 1
        assert delta["read"]["bytes"] == 50
        assert delta["write"]["bytes"] == 7
        assert tl.since(tl.snapshot()) == {}

    def test_host_context_logging(self, capsys, blit_logger_restored):
        logger = logging.getLogger("blit.testlog")
        configure_logging(worker=7)
        logger.info("hello")
        err = capsys.readouterr().err
        assert "/w7" in err and "hello" in err


class TestInventoryPersistence:
    def test_ragged_roundtrip(self, tmp_path):
        invs = [
            [InventoryRecord(1, 2, "S", "0001", "A", 0, 1, "h0", "f0", 1)],
            [],
            [
                InventoryRecord(3, 4, "S", "0002", "B", 1, 2, "h2", "f1", 3),
                InventoryRecord(5, 6, "T", "0003", "C", 2, 3, "h2", "f2", 3),
            ],
        ]
        p = str(tmp_path / "inv.jsonl")
        assert save_inventories(p, invs) == 3
        assert load_inventories(p) == invs


class TestResumableReduction:
    def _setup(self, tmp_path):
        jax = pytest.importorskip("jax")  # noqa: F841
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        raw = str(tmp_path / "x.raw")
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=1024,
                  tone_chan=1)
        return raw, RawReducer(nfft=64, nint=2, chunk_frames=4)

    def test_fresh_run_equals_plain_reduction(self, tmp_path):
        from blit.io.sigproc import read_fil_data
        from blit.pipeline import RawReducer, ReductionCursor

        raw, red = self._setup(tmp_path)
        out = str(tmp_path / "x.fil")
        hdr = red.reduce_resumable(raw, out)
        rhdr, data = read_fil_data(out)
        _, want = RawReducer(nfft=64, nint=2, chunk_frames=4).reduce(raw)
        np.testing.assert_array_equal(np.asarray(data), want)
        assert hdr["nsamps"] == rhdr["nsamps"] == want.shape[0]
        import os

        assert not os.path.exists(ReductionCursor.path_for(out))

    def test_interrupted_run_resumes_identically(self, tmp_path):
        from blit.io.sigproc import read_fil_data
        from blit.pipeline import RawReducer, ReductionCursor

        raw, red = self._setup(tmp_path)
        out = str(tmp_path / "x.fil")

        # Simulate a crash after the first slab landed: fail the
        # write-behind sink's second append (ISSUE 4 — the async output
        # plane's realistic crash seam; the writer-thread failure
        # re-raises clean on the consumer side).
        from blit import faults
        from blit.faults import FaultRule

        class Boom(Exception):
            pass

        red_crash = RawReducer(nfft=64, nint=2, chunk_frames=4)
        faults.install(FaultRule(point="sink.write", mode="fail",
                                 after=1, times=-1, exc=Boom))
        try:
            with pytest.raises(Boom):
                red_crash.reduce_resumable(raw, out)
        finally:
            faults.clear()
            faults.reset_counters()

        cur = ReductionCursor.load(out)
        assert cur is not None and cur.frames_done == 4  # one slab landed

        # Resume and compare against the uninterrupted run.
        red2 = RawReducer(nfft=64, nint=2, chunk_frames=4)
        red2.reduce_resumable(raw, out)
        _, data = read_fil_data(out)
        _, want = RawReducer(nfft=64, nint=2, chunk_frames=4).reduce(raw)
        np.testing.assert_array_equal(np.asarray(data), want)

    def test_config_mismatch_restarts(self, tmp_path):
        from blit.pipeline import RawReducer, ReductionCursor

        raw, red = self._setup(tmp_path)
        out = str(tmp_path / "x.fil")
        # A cursor written by a different config must be ignored.
        ReductionCursor(raw, nfft=32, ntap=4, nint=1, stokes="I",
                        frames_done=2).save(out)
        hdr = red.reduce_resumable(raw, out)
        assert hdr["nsamps"] > 0

    def test_window_mismatch_restarts(self, tmp_path):
        from blit.pipeline import RawReducer, ReductionCursor

        raw, red = self._setup(tmp_path)
        out = str(tmp_path / "x.fil")
        size, mtime_ns = ReductionCursor.stat_raw(raw)
        # Same nfft/ntap/nint/stokes but a different PFB window: resuming
        # would splice spectra from two different filters into one product.
        cur = ReductionCursor(
            raw, nfft=64, ntap=4, nint=2, stokes="I", frames_done=2,
            window="hanning", raw_size=size, raw_mtime_ns=mtime_ns,
        )
        assert not cur.matches(red, raw)

    def test_modified_raw_input_restarts(self, tmp_path):
        from blit.pipeline import RawReducer, ReductionCursor

        raw, red = self._setup(tmp_path)
        out = str(tmp_path / "x.fil")
        size, mtime_ns = ReductionCursor.stat_raw(raw)
        cur = ReductionCursor(
            raw, nfft=64, ntap=4, nint=2, stokes="I", frames_done=2,
            window=red.window, raw_size=size, raw_mtime_ns=mtime_ns,
        )
        assert cur.matches(red, raw)
        # Append a byte: the input is no longer what the cursor described.
        with open(raw, "ab") as f:
            f.write(b"\0")
        assert not cur.matches(red, raw)
        # Legacy cursor without identity fields must not match either.
        legacy = ReductionCursor(raw, nfft=64, ntap=4, nint=2, stokes="I",
                                 frames_done=2)
        assert not legacy.matches(red, raw)

    def test_fil_rejects_h5_only_options(self, tmp_path):
        # .h5 resume is supported (tests/test_resume_fbh5.py); the .fil
        # path still refuses the .h5-only knobs.
        raw, red = self._setup(tmp_path)
        with pytest.raises(ValueError, match="uncompressed"):
            red.reduce_resumable(raw, str(tmp_path / "x.fil"),
                                 compression="gzip")
        with pytest.raises(ValueError, match="chunks"):
            red.reduce_resumable(raw, str(tmp_path / "x.fil"),
                                 chunks=(4, 1, 8))

    def test_skip_frames_matches_tail(self, tmp_path):
        from blit.io.guppi import GuppiRaw
        from blit.pipeline import RawReducer

        raw, red = self._setup(tmp_path)
        full = np.concatenate(list(red.stream(GuppiRaw(raw))), axis=0)
        red2 = RawReducer(nfft=64, nint=2, chunk_frames=4)
        tail = np.concatenate(
            list(red2.stream(GuppiRaw(raw), skip_frames=8)), axis=0
        )
        np.testing.assert_array_equal(tail, full[4:])  # 8 frames = 4 spectra


class TestMultihost:
    def test_player_maps_single_process(self):
        jax = pytest.importorskip("jax")
        from blit.parallel.mesh import make_mesh
        from blit.parallel.multihost import local_players, player_map

        m = make_mesh(2, 4)
        pm = player_map(m)
        assert len(pm) == 8 and (1, 3) in pm
        # Single process: every player is local.
        assert len(local_players(m)) == 8


class TestReviewRegressions:
    def test_init_multihost_single_process_no_cluster(self):
        jax = pytest.importorskip("jax")  # noqa: F841
        from blit.parallel.multihost import init_multihost

        # No cluster env: must return False, not raise; and be idempotent.
        assert init_multihost() is False
        assert init_multihost() is False

    def test_configure_logging_idempotent(self, blit_logger_restored):
        root = logging.getLogger("blit")
        before = len(
            [h for h in root.handlers if not getattr(h, "_blit_handler", False)]
        )
        configure_logging(worker=1)
        configure_logging(worker=2)
        ours = [h for h in root.handlers if getattr(h, "_blit_handler", False)]
        assert len(ours) == 1
        assert root.propagate is False  # no double emission via root
        for h in ours:
            root.removeHandler(h)
        assert len(root.handlers) == before

    def test_reduce_raw_resume_without_out_path_rejected(self):
        from blit import workers

        with pytest.raises(ValueError, match="resume"):
            workers.reduce_raw("x.raw", resume=True)

    def test_save_inventories_accepts_generators(self, tmp_path):
        invs = [[InventoryRecord(1, 2, "S", "0001", "A", 0, 1, "h", "f", 1)], []]
        p = str(tmp_path / "g.jsonl")
        save_inventories(p, (iter(i) for i in invs))
        assert load_inventories(p) == invs
