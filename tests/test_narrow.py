"""Compression-aware readback narrowing (blit/ops/narrow.py) and the
pinned host staging pool (blit/hostmem.py) — ISSUE 8 tentpole b/c.

The load-bearing pins: device-side quantization is BITWISE identical to
the host rule (that is what lets nbits<32 products narrow before D2H by
default), async and sync quantized products are byte-identical files,
and resume under a changed quantization starts fresh instead of
splicing.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit import hostmem  # noqa: E402
from blit.ops.narrow import narrow_device, narrow_host  # noqa: E402
from blit.pipeline import RawReducer  # noqa: E402
from blit.testing import synth_raw  # noqa: E402


class TestNarrowRule:
    @pytest.mark.parametrize("nbits", [8, 16])
    def test_device_matches_host_bitwise(self, nbits):
        rng = np.random.default_rng(7)
        x = (rng.normal(100.0, 40.0, size=(64, 2, 257))
             .astype(np.float32))
        # Include exact halves (round-half-even territory), the range
        # edges, and clipped extremes.
        x[0, 0, :8] = [0.5, 1.5, 2.5, -3.0, 254.5, 255.5, 1e9, -1e9]
        host = narrow_host(x, nbits, scale=0.5, offset=2.0)
        dev = np.asarray(narrow_device(
            jax.numpy.asarray(x), nbits, scale=0.5, offset=2.0))
        assert host.dtype == dev.dtype
        np.testing.assert_array_equal(host, dev)

    def test_nbits32_is_identity(self):
        x = np.arange(6, dtype=np.float32).reshape(2, 1, 3)
        assert narrow_host(x, 32) is not None
        np.testing.assert_array_equal(narrow_host(x, 32), x)
        np.testing.assert_array_equal(
            np.asarray(narrow_device(jax.numpy.asarray(x), 32)), x)

    def test_bad_nbits_rejected(self):
        with pytest.raises(ValueError, match="nbits"):
            narrow_host(np.zeros(1, np.float32), 4)
        with pytest.raises(ValueError, match="nbits"):
            RawReducer(nfft=64, nbits=12)


class TestQuantizedProducts:
    def _raw(self, tmp_path):
        p = str(tmp_path / "q.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=2048,
                  tone_chan=1)
        return p

    @pytest.mark.parametrize("nbits", [8, 16])
    def test_async_equals_sync_bytes(self, tmp_path, nbits):
        # THE tentpole-c acceptance: the async plane narrows ON DEVICE
        # before D2H, the sync path narrows on the host — same file.
        p = self._raw(tmp_path)
        kw = dict(nfft=64, nint=2, chunk_frames=4, nbits=nbits,
                  quant_scale=0.05, quant_offset=1.0)
        a, s = str(tmp_path / "a.fil"), str(tmp_path / "s.fil")
        RawReducer(**kw).reduce_to_file(p, a)
        RawReducer(async_output=False, **kw).reduce_to_file(p, s)
        with open(a, "rb") as fa, open(s, "rb") as fs:
            assert fa.read() == fs.read()
        from blit.io.sigproc import read_fil_data

        hdr, data = read_fil_data(a)
        assert hdr["nbits"] == nbits
        assert np.asarray(data).dtype == (np.uint8 if nbits == 8
                                          else np.uint16)
        assert np.asarray(data).any()  # the tone quantizes above zero

    def test_narrow_product_is_smaller(self, tmp_path):
        p = self._raw(tmp_path)
        f32 = str(tmp_path / "f.fil")
        q8 = str(tmp_path / "q.fil")
        RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_to_file(p, f32)
        RawReducer(nfft=64, nint=2, chunk_frames=4, nbits=8,
                   quant_scale=0.05).reduce_to_file(p, q8)
        # Same spectra count, ~1/4 the payload (header bytes differ).
        assert os.path.getsize(q8) < os.path.getsize(f32) / 3

    def test_resume_replay_byte_identical(self, tmp_path):
        # Crash after the first slabs, resume, and the finished product
        # matches an uninterrupted run byte for byte (the skip-frames
        # replay re-quantizes identically).
        from blit.pipeline import ReductionCursor

        p = self._raw(tmp_path)
        kw = dict(nfft=64, nint=2, chunk_frames=4, nbits=8,
                  quant_scale=0.05)
        whole = str(tmp_path / "whole.fil")
        RawReducer(**kw).reduce_to_file(p, whole)

        out = str(tmp_path / "r.fil")
        red = RawReducer(**kw)
        hdr = red.reduce_resumable(p, out)
        assert hdr["nsamps"] > 0
        # Simulate a crash that kept a durable prefix: truncate to half
        # the rows and restore a cursor claiming them.
        from blit.io.sigproc import read_fil_header

        fhdr, off = read_fil_header(out)
        half = fhdr["nsamps"] // 2
        with open(out, "r+b") as f:
            f.truncate(off + half * fhdr["nchans"] * fhdr["nifs"] * 1)
        cur = ReductionCursor(
            p, 64, 4, 2, "I", half * 2, raw_size=os.path.getsize(p),
            raw_mtime_ns=os.stat(p).st_mtime_ns, nbits=8, quant_scale=0.05,
        )
        cur.save(out)
        RawReducer(**kw).reduce_resumable(p, out)
        with open(out, "rb") as fr, open(whole, "rb") as fw:
            assert fr.read() == fw.read()

    def test_resume_quant_mismatch_starts_fresh(self, tmp_path):
        # A cursor written under different quantization must NOT be
        # resumed into (splicing 8-bit and f32 spectra would corrupt the
        # product silently).
        from blit.pipeline import ReductionCursor

        p = self._raw(tmp_path)
        out = str(tmp_path / "m.fil")
        red8 = RawReducer(nfft=64, nint=2, chunk_frames=4, nbits=8,
                          quant_scale=0.05)
        cur = ReductionCursor(
            p, 64, 4, 2, "I", 4, raw_size=os.path.getsize(p),
            raw_mtime_ns=os.stat(p).st_mtime_ns, nbits=32,
        )
        assert not cur.matches(red8, p)  # the identity guard itself

    def test_h5_rejects_quantization(self, tmp_path):
        p = self._raw(tmp_path)
        red = RawReducer(nfft=64, nint=2, nbits=8)
        with pytest.raises(ValueError, match="FBH5"):
            red.reduce_to_file(p, str(tmp_path / "x.h5"))
        with pytest.raises(ValueError, match="FBH5"):
            red.reduce_resumable(p, str(tmp_path / "y.h5"))

    def test_stream_and_reduce_honor_nbits(self, tmp_path):
        # The nbits knob applies UNIFORMLY: stream()/reduce() return the
        # same quantized narrow product reduce_to_file writes — a reducer
        # constructed with nbits=8 never silently hands back float32.
        from blit.io.guppi import GuppiRaw
        from blit.io.sigproc import read_fil_data

        p = self._raw(tmp_path)
        kw = dict(nfft=64, nint=2, chunk_frames=4, nbits=8,
                  quant_scale=0.05)
        slabs = list(RawReducer(**kw).stream(GuppiRaw(p)))
        assert slabs and all(s.dtype == np.uint8 for s in slabs)
        sync = list(RawReducer(async_output=False, **kw).stream(
            GuppiRaw(p)))
        np.testing.assert_array_equal(np.concatenate(slabs, axis=0),
                                      np.concatenate(sync, axis=0))
        hdr, data = RawReducer(**kw).reduce(p)
        assert hdr["nbits"] == 8 and data.dtype == np.uint8
        out = str(tmp_path / "m.fil")
        RawReducer(**kw).reduce_to_file(p, out)
        fhdr, fdata = read_fil_data(out)
        np.testing.assert_array_equal(
            data.reshape(fdata.shape), np.asarray(fdata))


class TestHostStaging:
    def test_aligned_empty_alignment(self):
        for shape in [(3, 5), (1,), (17, 33, 2)]:
            a = hostmem.aligned_empty(shape, np.int8)
            assert a.ctypes.data % 4096 == 0
            assert a.shape == tuple(shape) and a.flags.c_contiguous

    def test_pool_reuses_exact_shape(self):
        pool = hostmem.SlabPool(budget_bytes=1 << 20)
        a = pool.take((64, 4), np.int8)
        marker = a.ctypes.data
        pool.give(a)
        b = pool.take((64, 4), np.int8)
        assert b.ctypes.data == marker  # the same faulted storage
        assert pool.take((64, 8), np.int8).ctypes.data != marker
        assert pool.stats()["reused"] == 1

    def test_pool_budget_evicts(self):
        pool = hostmem.SlabPool(budget_bytes=1000)
        big = pool.take((2000,), np.int8)
        pool.give(big)  # over budget → dropped
        assert pool.stats()["free_bytes"] == 0
        small = [pool.take((400,), np.int8) for _ in range(3)]
        for s in small:
            pool.give(s)
        st = pool.stats()
        assert st["free_bytes"] <= 1000 and st["dropped"] >= 1

    def test_eviction_counts_agree_with_telemetry(self):
        # stats()["dropped"] and the staging.drop timeline counter must
        # agree, eviction path included (review fix).
        from blit import observability

        tl = observability.process_timeline()
        before = tl.stages["staging.drop"].calls
        pool = hostmem.SlabPool(budget_bytes=1000)
        held = [pool.take((400,), np.int8) for _ in range(4)]
        for h in held:  # 4 x 400 B into a 1000 B budget → evictions
            pool.give(h)
        assert pool.stats()["dropped"] > 0
        assert tl.stages["staging.drop"].calls - before == \
            pool.stats()["dropped"]

    def test_zero_budget_disables(self):
        pool = hostmem.SlabPool(budget_bytes=0)
        a = pool.take((16,), np.int8)
        pool.give(a)
        assert pool.stats()["free_bytes"] == 0

    def test_reduction_reuses_staging_across_reducers(self, tmp_path):
        # The cross-stream contract: a SECOND reducer of the same shape
        # (the serve-layer pattern) stages through the first one's
        # retired slabs instead of allocating.
        p = str(tmp_path / "s.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=2048)
        pool = hostmem.slab_pool()
        RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_to_file(
            p, str(tmp_path / "one.fil"))
        reused0 = pool.stats()["reused"]
        RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_to_file(
            p, str(tmp_path / "two.fil"))
        assert pool.stats()["reused"] > reused0
