"""Name/path parsing parity with the reference regexes
(src/gbtworkerfunctions.jl:35-61, src/gbt.jl:50-52)."""

import pytest

from blit import naming


H5 = "/datax/dibas/AGBT22B_999_01/GUPPI/BLP42/blc42_guppi_59897_21221_HD_84406_0011.rawspec.0002.h5"
RAW = "/datax/dibas/AGBT22B_999_01/GUPPI/BLP17/blc17_guppi_59897_21221_HD_84406_0011.0000.raw"


def test_parse_guppi_h5():
    p = naming.parse_guppi_name(H5)
    assert p is not None
    assert (p.band, p.bank) == (4, 2)
    assert p.host == "blc42"
    assert (p.imjd, p.smjd) == (59897, 21221)
    assert p.src == "HD_84406"
    assert p.scan == "0011"


def test_parse_guppi_raw():
    p = naming.parse_guppi_name(RAW)
    assert p is not None
    assert (p.band, p.bank) == (1, 7)
    assert p.scan == "0011"
    assert p.src == "HD_84406"


def test_parse_guppi_no_player_component():
    # band/bank path component and host prefix are both optional.
    p = naming.parse_guppi_name("guppi_59897_21221_HD_84406_0011.rawspec.0002.h5")
    assert p is not None
    assert p.band is None and p.bank is None and p.host is None
    assert p.imjd == 59897


def test_parse_guppi_optional_numeric_field():
    # The optional (\d+_)? between smjd and src (e.g. frequency tag).
    p = naming.parse_guppi_name("/BLP00/guppi_59897_21221_12345_VOYAGER1_0002.0000.raw")
    assert p is not None
    assert p.src == "VOYAGER1"
    assert p.scan == "0002"


def test_parse_guppi_deeply_nested():
    # The reference regex allows at most one path component between /BLPbb/
    # and the file, losing band/bank for deeper nesting; blit parses the
    # player component at any depth (blit.naming module docstring).
    p = naming.parse_guppi_name(
        "/datax/dibas/S/GUPPI/BLP35/sub/deep/blc35_guppi_1_2_SRC_0001.rawspec.0002.h5"
    )
    assert p is not None and (p.band, p.bank) == (3, 5)


def test_parse_guppi_rightmost_player_wins():
    # A BLP-like component in the root path must not shadow the real player
    # directory (the one closest to the file).
    p = naming.parse_guppi_name(
        "/mnt/BLP00/datax/S/GUPPI/BLP42/blc42_guppi_1_2_SRC_0001.rawspec.0002.h5"
    )
    assert p is not None and (p.band, p.bank) == (4, 2)


def test_parse_guppi_rejects_nonmatching():
    assert naming.parse_guppi_name("/tmp/notaguppifile.h5") is None


def test_parse_rawspec():
    p = naming.parse_rawspec_name(H5)
    assert p is not None
    assert p.product == "0002"
    assert (p.band, p.bank) == (4, 2)


def test_parse_rawspec_requires_suffix():
    assert naming.parse_rawspec_name(RAW) is None
    # and requires the /BLPbb/ component:
    assert (
        naming.parse_rawspec_name("guppi_59897_21221_X_0011.rawspec.0002.h5") is None
    )


def test_session_re():
    assert naming.SESSION_RE.search("AGBT22B_999_01")
    assert naming.SESSION_RE.search("TGBT21A_1_05")
    assert not naming.SESSION_RE.search("XGBT22B_999_01")


def test_player_re_fixed():
    # The reference's malformed player regex accepted junk like "BLPd3"
    # (SURVEY.md §2.1); the corrected regex must not.
    m = naming.PLAYER_RE.match("BLP42")
    assert m and m.group("band") == "4" and m.group("bank") == "2"
    assert naming.PLAYER_RE.match("BLPd3") is None
    assert naming.PLAYER_RE.match("BLP89") is None
    assert naming.PLAYER_RE.match("BLP421") is None


def test_player_name():
    assert naming.player_name(4, 2) == "BLP42"
    with pytest.raises(ValueError):
        naming.player_name(8, 0)
