"""Multi-chip data-plane tests on the virtual 8-device CPU mesh
(blit/parallel/mesh.py): sharded channelize, all_gather stitch, despike."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops.channelize import channelize_np, pfb_coeffs  # noqa: E402
from blit.ops.despike import despike  # noqa: E402
from blit.parallel import mesh as M  # noqa: E402


NFFT, NTAP, NINT = 64, 4, 2


def reduce_np(voltages, nfft=NFFT, nint=NINT, stokes="I", do_despike=0):
    """Host golden: per-(band,bank) NumPy reduction + channel-axis concat."""
    h = pfb_coeffs(NTAP, nfft)
    nband, nbank = voltages.shape[:2]
    bands = []
    for b in range(nband):
        banks = [
            channelize_np(voltages[b, k], h, nfft=nfft, ntap=NTAP, nint=nint,
                          stokes=stokes)
            for k in range(nbank)
        ]
        band = np.concatenate(banks, axis=-1)
        if do_despike >= 2:
            band = despike(band, do_despike)
        bands.append(band)
    return np.stack(bands)


def make_band_voltages(nband, nbank, nchan=2, ntime=(NTAP - 1 + 2 * NINT) * NFFT,
                       seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(-40, 40, size=(nband, nbank, nchan, ntime, 2, 2),
                        dtype=np.int8)


class TestMakeMesh:
    def test_shape_and_axes(self):
        m = M.make_mesh(2, 4)
        assert m.devices.shape == (2, 4)
        assert m.axis_names == ("band", "bank")

    def test_too_few_devices(self):
        with pytest.raises(ValueError, match="need 128 devices"):
            M.make_mesh(16, 8)


class TestBandReduce:
    @pytest.mark.parametrize("nband,nbank", [(1, 8), (2, 4)])
    def test_stitched_matches_host_golden(self, nband, nbank):
        v = make_band_voltages(nband, nbank)
        m = M.make_mesh(nband, nbank)
        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT))
        out = M.band_reduce(
            M.shard_voltages(v, m), coeffs, mesh=m, nfft=NFFT, ntap=NTAP,
            nint=NINT, stitch=True,
        )
        want = reduce_np(v)
        got = np.asarray(out)
        assert got.shape == want.shape == (nband, 2, 1, nbank * 2 * NFFT)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=0.5)

    def test_unstitched_layout_matches_golden_globally(self):
        # The frequency-sharded product concatenates to the same global array.
        v = make_band_voltages(2, 4)
        m = M.make_mesh(2, 4)
        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT))
        out = M.band_reduce(
            M.shard_voltages(v, m), coeffs, mesh=m, nfft=NFFT, ntap=NTAP,
            nint=NINT, stitch=False,
        )
        np.testing.assert_allclose(np.asarray(out), reduce_np(v), rtol=1e-4,
                                   atol=0.5)

    def test_stitched_despike(self):
        v = make_band_voltages(1, 8)
        m = M.make_mesh(1, 8)
        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT))
        out = M.band_reduce(
            M.shard_voltages(v, m), coeffs, mesh=m, nfft=NFFT, ntap=NTAP,
            nint=NINT, stitch=True, despike_nfpc=NFFT,
        )
        want = reduce_np(v, do_despike=NFFT)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=0.5)
        # DC fine channel must equal its lower neighbor everywhere.
        got = np.asarray(out)
        np.testing.assert_array_equal(
            got[..., NFFT // 2 :: NFFT], got[..., NFFT // 2 - 1 :: NFFT]
        )

    def test_sharded_despike_equals_stitched_despike(self):
        v = make_band_voltages(1, 8)
        m = M.make_mesh(1, 8)
        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT))
        a = M.band_reduce(M.shard_voltages(v, m), coeffs, mesh=m, nfft=NFFT,
                          nint=NINT, stitch=False, despike_nfpc=NFFT)
        b = M.band_reduce(M.shard_voltages(v, m), coeffs, mesh=m, nfft=NFFT,
                          nint=NINT, stitch=True, despike_nfpc=NFFT)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-3)


class TestStitchStandalone:
    def test_stitch_bands_roundtrip(self):
        # A sharded (band, t, nif, chan) array stitches to the identity.
        m = M.make_mesh(2, 4)
        x = np.arange(2 * 3 * 1 * 32, dtype=np.float32).reshape(2, 3, 1, 32)
        xs = jax.device_put(x, M.filterbank_sharding(m, stitched=False))
        out = M.stitch_bands(xs, m)
        np.testing.assert_array_equal(np.asarray(out), x)
        # Output really is replicated across banks / sharded over band.
        assert M.filterbank_sharding(m, True).is_equivalent_to(
            out.sharding, out.ndim
        )
