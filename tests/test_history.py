"""Fleet history & incident forensics plane (blit/history.py; ISSUE 20).

Covers the tentpole end to end — the tiered ring store (downsampling
exactness across tier boundaries, fixed disk budget under a simulated
week, restart re-adoption, concurrent read-while-write, fleet merge of
two peers' stores), the median/MAD anomaly baseline (fires on an
injected step, quiet on a seeded steady baseline, kill switch +
per-metric sensitivity), incident bundles (self-contained: the
exemplar trace id resolves into the bundle's own request records),
`blit slo-report` against a hand-computed oracle (and its JSON riding
`bench_metrics`), the shared window grammar, the wall-clock anchor
satellite, and the torn-tail drill (a writer SIGKILLed mid-line heals
and counts on every monitor-path reader)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from blit import history as H
from blit import monitor, observability
from blit.config import SiteConfig, history_defaults
from blit.history import (
    AnomalyDetector,
    HistoryStore,
    IncidentBundler,
    TierSpec,
    bucket_point,
    list_incidents,
    load_incident,
    merge_buckets,
    parse_when,
    read_ring,
    render_incident,
    render_incidents,
    render_slo_report,
    slo_report,
    sparkline,
    window_seconds,
)
from blit.monitor import MetricsPublisher, SLObjective, bench_metrics
from blit.observability import HistogramStats, Timeline, wall_anchor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

T0 = 1_700_000_000.0  # aligned-enough epoch for bucket math


@pytest.fixture(autouse=True)
def clean_history(monkeypatch, tmp_path):
    """Hermetic history env: no leaked store/bundler/publisher state."""
    for var in ("BLIT_HISTORY_DIR", "BLIT_HISTORY_RAW_S",
                "BLIT_HISTORY_ANOMALY", "BLIT_HISTORY_SENSITIVITY",
                "BLIT_INCIDENT_DIR", "BLIT_REQUEST_LOG",
                "BLIT_MONITOR_SPOOL", "BLIT_MONITOR_PORT"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path / "flight"))
    (tmp_path / "flight").mkdir(exist_ok=True)
    H.reset_bundler()
    monitor.shutdown_publisher()
    yield
    H.reset_bundler()
    monitor.shutdown_publisher()


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _tick_delta(calls=2, nbytes=1 << 20, seconds=0.01, lat=0.02):
    """One synthetic per-tick Timeline delta: a stage with bytes, a
    byte-free counter, and a latency histogram sample."""
    tl = Timeline()
    s = tl.stages["ingest.chunks"]
    s.calls += calls
    s.seconds += seconds
    s.bytes += nbytes
    tl.count("ingest.retries", 1)
    tl.observe("serve.request_s", lat)
    return tl


def _small_tiers():
    return [TierSpec("raw", 1.0, 32), TierSpec("mid", 8.0, 32),
            TierSpec("slow", 64.0, 8)]


# -- window grammar ----------------------------------------------------------


class TestWindowGrammar:
    def test_window_seconds(self):
        assert window_seconds("90") == 90.0
        assert window_seconds("90s") == 90.0
        assert window_seconds("15m") == 900.0
        assert window_seconds("2h") == 7200.0
        assert window_seconds("1d") == 86400.0
        assert window_seconds("1w") == 604800.0
        assert window_seconds("1.5h") == 5400.0

    def test_parse_when(self):
        now = T0
        assert parse_when("now", now) == now
        assert parse_when("15m", now) == now - 900.0
        assert parse_when(str(T0 - 5.0), now) == T0 - 5.0
        assert parse_when("30", now) == now - 30.0

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError):
            window_seconds("soon")


# -- the tiered ring store ---------------------------------------------------


class TestHistoryStore:
    def test_tier_downsampling_conserves_counts_and_sums(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        nticks, calls, nbytes = 24, 3, 1 << 20
        for _ in range(nticks):
            store.append(clock(), 1.0, _tick_delta(calls, nbytes),
                         gauges={"sched.depth": 4.0},
                         burn={"api": (1, 10)})
            clock.advance(1.0)
        store.close()

        ro = HistoryStore(str(tmp_path / "h"), create=False)
        for tier in ("raw", "mid", "slow"):
            recs = ro.buckets(T0 - 1, clock(), tier=tier)
            assert recs, tier
            st = [r["stages"]["ingest.chunks"] for r in recs]
            assert sum(s["calls"] for s in st) == nticks * calls, tier
            assert sum(s["bytes"] for s in st) == nticks * nbytes, tier
            hs = [r["hists"]["serve.request_s"] for r in recs]
            assert sum(h["n"] for h in hs) == nticks, tier
            total = sum(h["total"] for h in hs)
            assert total == pytest.approx(nticks * 0.02), tier
            assert sum(r["n"] for r in recs) == nticks, tier
            burn = [r["burn"]["api"] for r in recs]
            assert sum(b["bad"] for b in burn) == nticks
            assert sum(b["total"] for b in burn) == nticks * 10
            # Byte-free counters conserve too (calls carry the count).
            assert sum(r["stages"]["ingest.retries"]["calls"]
                       for r in recs) == nticks

    def test_series_projection(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        for _ in range(8):
            store.append(clock(), 1.0,
                         _tick_delta(nbytes=1_000_000_000, seconds=1.0),
                         gauges={"sched.depth": 7.0})
            clock.advance(1.0)
        pts = store.series("ingest.chunks", T0, clock(), tier="raw")
        assert pts and all(p["kind"] == "stage" for p in pts)
        assert pts[0]["gbps"] == pytest.approx(1.0, rel=0.01)
        lat = store.series("serve.request_s", T0, clock(), tier="raw")
        assert lat and lat[0]["kind"] == "hist" and lat[0]["n"] == 1
        g = store.series("sched.depth", T0, clock(), tier="raw")
        assert g and g[0]["value"] == 7.0
        assert "ingest.chunks" in store.metrics()
        store.close()

    def test_disk_budget_fixed_under_a_simulated_week(self, tmp_path):
        clock = FakeClock()
        tiers = [TierSpec("raw", 10.0, 60), TierSpec("mid", 300.0, 48),
                 TierSpec("slow", 3600.0, 48)]
        store = HistoryStore(str(tmp_path / "h"), tiers=tiers,
                             slot_bytes=4096, clock=clock)
        expected = sum(H._HDR_BYTES + t.slots * 4096 for t in tiers)
        sizes = []
        for day in range(7):
            for _ in range(288):  # one tick per 300 s
                store.append(clock(), 300.0, _tick_delta(),
                             burn={"api": (0, 10)})
                clock.advance(300.0)
            sizes.append(store.disk_usage())
        store.close()
        # The budget is claimed at creation and NEVER grows — day 1
        # equals day 7 equals the arithmetic of the tier spec.
        assert sizes == [expected] * 7
        for t in tiers:
            assert os.path.getsize(tmp_path / "h" / f"{t.name}.ring") \
                == H._HDR_BYTES + t.slots * 4096
        # And the rings still answer: the slow tier holds the tail of
        # the week.
        ro = HistoryStore(str(tmp_path / "h"), create=False, clock=clock)
        recs = ro.buckets(clock() - 47 * 3600.0, clock(), tier="slow")
        assert len(recs) >= 40

    def test_oldest_bucket_overwrite_wraps(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"),
                             tiers=[TierSpec("raw", 1.0, 4)],
                             slot_bytes=4096, clock=clock)
        for i in range(10):
            store.append(clock(), 1.0, _tick_delta(calls=i + 1))
            clock.advance(1.0)
        store.close()
        _, recs, _ = read_ring(str(tmp_path / "h" / "raw.ring"))
        assert len(recs) == 4  # the ring holds exactly `slots` buckets
        assert [r["stages"]["ingest.chunks"]["calls"] for r in recs] \
            == [7, 8, 9, 10]

    def test_restart_adopts_partial_bucket(self, tmp_path):
        clock = FakeClock()
        tiers = [TierSpec("raw", 60.0, 8)]
        store = HistoryStore(str(tmp_path / "h"), tiers=tiers,
                             slot_bytes=4096, clock=clock)
        store.append(clock(), 1.0, _tick_delta(calls=5))
        store.close()
        # Same bucket window, new process: the second store must FOLD
        # into the slot the first one wrote, not zero it.
        store2 = HistoryStore(str(tmp_path / "h"), tiers=tiers,
                              slot_bytes=4096, clock=clock)
        store2.append(clock.advance(1.0), 1.0, _tick_delta(calls=2))
        store2.close()
        _, recs, _ = read_ring(str(tmp_path / "h" / "raw.ring"))
        assert len(recs) == 1
        assert recs[0]["stages"]["ingest.chunks"]["calls"] == 7
        assert recs[0]["n"] == 2

    def test_reader_adopts_file_geometry_not_config(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"),
                             tiers=[TierSpec("raw", 2.0, 16)],
                             slot_bytes=4096, clock=clock)
        store.append(clock(), 1.0, _tick_delta())
        store.close()
        # Reopen under a DIFFERENT configured geometry: the on-disk
        # header wins, so old slots keep addressing correctly.
        store2 = HistoryStore(str(tmp_path / "h"),
                              tiers=[TierSpec("raw", 7.0, 99)],
                              slot_bytes=8192, clock=clock)
        store2.append(clock.advance(2.0), 1.0, _tick_delta())
        store2.close()
        hdr, recs, _ = read_ring(str(tmp_path / "h" / "raw.ring"))
        assert hdr["bucket_s"] == 2.0 and hdr["slots"] == 16
        assert os.path.getsize(tmp_path / "h" / "raw.ring") \
            == H._HDR_BYTES + 16 * 4096

    def test_torn_slot_heals_and_counts(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"),
                             tiers=[TierSpec("raw", 1.0, 8)],
                             slot_bytes=4096, clock=clock)
        for _ in range(4):
            store.append(clock(), 1.0, _tick_delta())
            clock.advance(1.0)
        store.close()
        path = tmp_path / "h" / "raw.ring"
        # Tear one occupied slot the way a dead writer would: garbage
        # over the front of the slot.
        i = int(T0 // 1.0) % 8
        with open(path, "r+b") as f:
            f.seek(H._HDR_BYTES + i * 4096)
            f.write(b"\xffGARBAGE\xff")
        ro = HistoryStore(str(tmp_path / "h"), create=False, clock=clock)
        recs = ro.buckets(T0 - 1, clock(), tier="raw")
        assert len(recs) == 3  # healed: the other buckets still read
        assert ro.torn_slots == 1

    def test_slot_overflow_sheds_hists_first(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"),
                             tiers=[TierSpec("raw", 60.0, 4)],
                             slot_bytes=2048, clock=clock)
        tl = Timeline()
        for i in range(200):  # enough distinct hists to bust 2 KB
            tl.observe(f"metric.{i:03d}_s", 0.01)
        tl.stages["ingest.chunks"].bytes += 5
        tl.stages["ingest.chunks"].calls += 1
        store.append(clock(), 1.0, tl)
        store.close()
        assert store.overflow_slots >= 1
        _, recs, torn = read_ring(str(tmp_path / "h" / "raw.ring"))
        assert torn == 0 and len(recs) == 1
        assert recs[0].get("overflow") is True
        # Stage accounting survives the shed; the hists were dropped.
        assert recs[0]["stages"]["ingest.chunks"]["calls"] == 1

    def test_concurrent_read_while_write(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        stop = threading.Event()
        errors = []

        def reader():
            ro = HistoryStore(str(tmp_path / "h"), create=False,
                              clock=clock)
            while not stop.is_set():
                try:
                    for rec in ro.buckets(T0 - 1, clock() + 1):
                        assert "t0" in rec
                except Exception as e:  # noqa: BLE001 — the assertion
                    errors.append(e)
                    return

        threads = [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for _ in range(300):
            store.append(clock(), 0.1, _tick_delta())
            clock.advance(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        store.close()
        assert not errors

    def test_fleet_merge_of_two_peers_stores(self, tmp_path):
        clock = FakeClock()
        a = HistoryStore(str(tmp_path / "a"), tiers=_small_tiers(),
                         slot_bytes=4096, clock=clock)
        b = HistoryStore(str(tmp_path / "b"), tiers=_small_tiers(),
                         slot_bytes=4096, clock=clock)
        for _ in range(8):
            a.append(clock(), 1.0, _tick_delta(calls=1, nbytes=100),
                     burn={"api": (1, 5)})
            b.append(clock(), 1.0, _tick_delta(calls=2, nbytes=200),
                     burn={"api": (0, 5)})
            clock.advance(1.0)
        a.close()
        b.close()
        ra = HistoryStore(str(tmp_path / "a"), create=False,
                          clock=clock).buckets(T0 - 1, clock(), tier="raw")
        rb = HistoryStore(str(tmp_path / "b"), create=False,
                          clock=clock).buckets(T0 - 1, clock(), tier="raw")
        merged = merge_buckets([ra, rb])
        assert len(merged) == len(ra) == len(rb)
        st = [r["stages"]["ingest.chunks"] for r in merged]
        assert sum(s["calls"] for s in st) == 8 * 3
        assert sum(s["bytes"] for s in st) == 8 * 300
        hs = [r["hists"]["serve.request_s"] for r in merged]
        assert sum(h["n"] for h in hs) == 16
        burn = [r["burn"]["api"] for r in merged]
        assert sum(x["bad"] for x in burn) == 8
        assert sum(x["total"] for x in burn) == 80
        # Commutative: the other order folds identically.
        assert merge_buckets([rb, ra]) == merged

    def test_merge_in_materializes_peer_buckets(self, tmp_path):
        clock = FakeClock()
        a = HistoryStore(str(tmp_path / "a"), tiers=_small_tiers(),
                         slot_bytes=4096, clock=clock)
        a.append(clock(), 1.0, _tick_delta(calls=4))
        recs = a.buckets(T0 - 1, clock() + 1, tier="raw")
        a.close()
        door = HistoryStore(str(tmp_path / "door"), tiers=_small_tiers(),
                            slot_bytes=4096, clock=clock)
        assert door.merge_in(recs) == len(recs)
        got = door.buckets(T0 - 1, clock() + 1, tier="raw")
        door.close()
        assert got[0]["stages"]["ingest.chunks"]["calls"] == 4

    def test_bucket_point_slo_projection(self):
        rec = {"t0": T0, "bucket_s": 60.0, "burn": {"api":
                                                    {"bad": 3,
                                                     "total": 12}}}
        p = bucket_point(rec, "slo.api")
        assert p["kind"] == "slo" and p["value"] == 0.25
        assert bucket_point(rec, "nope") is None


# -- anomaly baselines -------------------------------------------------------


def _an(**kw):
    kw.setdefault("z", 5.0)
    kw.setdefault("window", 40)
    kw.setdefault("min_n", 10)
    kw.setdefault("consecutive", 3)
    clock = kw.pop("clock", FakeClock())
    rec = observability.FlightRecorder()
    return AnomalyDetector(recorder=rec, clock=clock, **kw), clock


class TestAnomaly:
    def test_quiet_on_seeded_steady_baseline(self):
        import random

        rng = random.Random(20)
        det, clock = _an()
        fired = []
        for _ in range(300):
            fired += det.observe(
                {"serve.request_s.p99_s": rng.gauss(0.050, 0.004)},
                clock.advance(1.0))
        assert fired == []
        assert det.breached() == []

    def test_injected_step_fires_within_window(self):
        import random

        rng = random.Random(7)
        det, clock = _an()
        for _ in range(60):
            det.observe({"serve.request_s.p99_s": rng.gauss(0.050, 0.004)},
                        clock.advance(1.0))
        fired = []
        for i in range(10):
            fired += det.observe({"serve.request_s.p99_s": 0.250},
                                 clock.advance(1.0))
        # Exactly one page (consecutive=3 → tick 3), then latched.
        assert len(fired) == 1
        a = fired[0]
        assert a["class"] == "anomaly"
        assert a["metric"] == "serve.request_s.p99_s"
        assert a["z"] >= 5.0
        assert a.get("flight_dump")  # first breach forces the dump
        assert det.breached() == ["serve.request_s.p99_s"]
        # Recovery re-arms: back at baseline, the latch clears.
        for _ in range(3):
            det.observe({"serve.request_s.p99_s": 0.050},
                        clock.advance(1.0))
        assert det.breached() == []

    def test_one_noisy_sample_never_pages(self):
        det, clock = _an(consecutive=3)
        for _ in range(30):
            det.observe({"g": 1.0}, clock.advance(1.0))
        assert det.observe({"g": 100.0}, clock.advance(1.0)) == []
        assert det.observe({"g": 1.0}, clock.advance(1.0)) == []
        assert det.breached() == []

    def test_throughput_pages_on_drop_not_rise(self):
        det, clock = _an(consecutive=1)
        for _ in range(30):
            det.observe({"ingest.chunks.gbps": 10.0}, clock.advance(1.0))
        assert det.observe({"ingest.chunks.gbps": 100.0},
                           clock.advance(1.0)) == []  # faster is fine
        fired = det.observe({"ingest.chunks.gbps": 0.5},
                            clock.advance(1.0))
        assert len(fired) == 1  # a drop is the page

    def test_per_metric_sensitivity_env(self, monkeypatch):
        monkeypatch.setenv("BLIT_HISTORY_SENSITIVITY",
                           "serve.request_s.p99_s=2.5, other=9")
        d = history_defaults(SiteConfig())
        assert d["anomaly_overrides"] == {
            "serve.request_s.p99_s": 2.5, "other": 9.0}
        det = AnomalyDetector(z=6.0,
                              overrides=d["anomaly_overrides"])
        assert det.threshold_for("serve.request_s.p99_s") == 2.5
        assert det.threshold_for("unknown") == 6.0

    def test_kill_switch_env(self, monkeypatch):
        monkeypatch.setenv("BLIT_HISTORY_ANOMALY", "0")
        assert history_defaults(SiteConfig())["anomaly"] is False
        pub = MetricsPublisher(
            interval_s=3600.0, spool_dir="", port=-1,
            config=SiteConfig(history_dir=None))
        assert pub.anomaly is None
        pub.close()

    def test_series_values_skips_idle(self):
        tl = _tick_delta(nbytes=2_000_000_000, seconds=1.0)
        vals = H.series_values(tl, {"sched.depth": 3.0})
        assert vals["ingest.chunks.gbps"] == pytest.approx(2.0)
        assert vals["serve.request_s.p99_s"] > 0
        assert vals["sched.depth"] == 3.0
        # ingest.retries is byte-free — no gbps series for it.
        assert not any(k.startswith("ingest.retries") for k in vals)
        assert H.series_values(Timeline()) == {}


# -- the publisher wiring ----------------------------------------------------


class TestPublisherIntegration:
    def test_tick_feeds_store_and_sample_carries_anchor(self, tmp_path):
        cfg = SiteConfig(history_dir=str(tmp_path / "h"),
                         history_raw_s=1.0,
                         slo_objectives=[{"name": "api",
                                          "metric": "serve.request_s",
                                          "threshold": 0.1,
                                          "kind": "latency"}])
        tl = Timeline()
        pub = MetricsPublisher(interval_s=0.05, spool_dir="", port=-1,
                               timeline=tl, config=cfg)
        assert pub.history is not None and pub.anomaly is not None
        for i in range(3):
            s = tl.stages["ingest.chunks"]
            s.calls += 1
            s.seconds += 0.01
            s.bytes += 1 << 20
            tl.observe("serve.request_s", 0.01)
            sample = pub.tick()
        anchor = sample["anchor"]
        assert set(anchor) == {"epoch", "mono"}
        assert anchor == wall_anchor()
        pub.close()
        ro = HistoryStore(str(tmp_path / "h"), create=False)
        now = time.time()
        recs = ro.buckets(now - 60, now + 60, tier="raw")
        total = sum(r["stages"]["ingest.chunks"]["calls"] for r in recs)
        assert total == 3
        # SLO burn observations ride the buckets (the default config
        # declares objectives).
        assert any(r.get("burn") for r in recs)

    def test_anomaly_breach_pages_and_bundles(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_REQUEST_LOG", str(tmp_path / "req"))
        cfg = SiteConfig(history_dir=str(tmp_path / "h"),
                         history_raw_s=1.0,
                         history_anomaly_window=16,
                         history_anomaly_min_n=5,
                         history_anomaly_consecutive=2,
                         history_anomaly_z=5.0,
                         incident_dir=str(tmp_path / "inc"))
        tl = Timeline()
        pub = MetricsPublisher(interval_s=0.05, spool_dir="", port=-1,
                               timeline=tl, config=cfg)
        rlog = observability.RequestLog(
            os.path.join(str(tmp_path / "req"),
                         "requests-peer.jsonl"))
        rlog.record(rid="r1", trace="tr-bundle", role="peer",
                    status="ok", duration_s=0.2, client="c1")
        rlog.close()
        import random

        rng = random.Random(3)
        for _ in range(10):  # steady baseline
            tl.hists["serve.request_s"].observe(rng.gauss(0.02, 0.001),
                                                trace_id="tr-bundle")
            pub.tick()
        assert pub.health()["ok"]
        alerts = []
        for _ in range(4):  # injected 20x latency step
            tl.hists["serve.request_s"].observe(0.4,
                                                trace_id="tr-bundle")
            alerts += pub.tick()["alerts"]
        anomaly_alerts = [a for a in alerts if a["class"] == "anomaly"]
        assert len(anomaly_alerts) == 1
        health = pub.health()
        assert not health["ok"]
        assert any(r.startswith("anomaly:serve.request_s")
                   for r in health["reasons"])
        pub.close()
        bundles = list_incidents(str(tmp_path / "inc"))
        assert len(bundles) == 1
        b = load_incident(bundles[0]["path"])
        # The self-containment contract: the bundle's exemplar trace
        # resolves into its OWN request records, no reach outside the
        # bundle dir.
        trace = b["manifest"]["trace"]
        assert trace == "tr-bundle"
        assert any(r.get("trace") == trace for r in b["requests"])
        assert b["flight"] is not None
        assert b["flight"]["anchor"] == wall_anchor()
        assert b["history"]["buckets"]
        assert b["healthz"]["reasons"]
        text = render_incident(b)
        assert "anomaly" in text and "tr-bundle" in text
        listing = render_incidents(bundles)
        assert "anomaly" in listing

    def test_quiet_baseline_means_zero_bundles(self, tmp_path):
        cfg = SiteConfig(history_dir=str(tmp_path / "h"),
                         history_raw_s=1.0,
                         history_anomaly_window=16,
                         history_anomaly_min_n=5,
                         history_anomaly_consecutive=2,
                         incident_dir=str(tmp_path / "inc"))
        tl = Timeline()
        pub = MetricsPublisher(interval_s=0.05, spool_dir="", port=-1,
                               timeline=tl, config=cfg)
        import random

        rng = random.Random(11)
        for _ in range(40):
            tl.hists["serve.request_s"].observe(rng.gauss(0.02, 0.001))
            sample = pub.tick()
            assert sample["alerts"] == []
        pub.close()
        assert list_incidents(str(tmp_path / "inc")) == []

    def test_incident_cooldown_one_bundle_per_storm(self, tmp_path):
        clock = FakeClock()
        b = IncidentBundler(str(tmp_path / "inc"), window_s=60.0,
                            cooldown_s=300.0, clock=clock)
        first = b.snapshot("slo:api", "breach 1")
        assert first is not None
        clock.advance(1.0)
        assert b.snapshot("slo:api", "breach 2") is None  # cooled down
        assert b.snapshot("anomaly:x", "other kind") is not None
        clock.advance(400.0)
        assert b.snapshot("slo:api", "breach 3") is not None
        assert len(list_incidents(str(tmp_path / "inc"))) == 3


# -- slo-report --------------------------------------------------------------


class TestSloReport:
    def test_attainment_matches_hand_computed_oracle(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        # Hand oracle: 20 ticks × (bad=3, total=50) → 60/1000 bad;
        # attainment 0.94; budget 0.1 → spend 0.6.
        for _ in range(20):
            store.append(clock(), 1.0, _tick_delta(),
                         burn={"api": (3, 50)})
            clock.advance(1.0)
        objs = [SLObjective(name="api", metric="serve.request_s",
                            threshold=0.1, budget=0.1)]
        doc = slo_report(store, objectives=objs, window_s=120.0,
                         now=clock())
        store.close()
        o = doc["objectives"]["api"]
        assert o["bad"] == 60 and o["total"] == 1000
        assert o["attainment"] == pytest.approx(0.94)
        assert o["budget_spent"] == pytest.approx(0.6)
        assert doc["metrics"]["slo.api_attained"] == pytest.approx(0.94)
        assert "0.94" in render_slo_report(doc)

    def test_latency_fallback_recomputes_from_hist_state(self, tmp_path):
        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        tl = Timeline()
        for v in [0.01] * 9 + [10.0]:  # one sample far above threshold
            tl.observe("serve.request_s", v)
        store.append(clock(), 1.0, tl)  # note: NO burn block stored
        objs = [SLObjective(name="api", metric="serve.request_s",
                            threshold=1.0, budget=0.5)]
        doc = slo_report(store, objectives=objs, window_s=60.0,
                         now=clock.advance(1.0))
        store.close()
        o = doc["objectives"]["api"]
        assert o["total"] == 10 and o["bad"] == 1
        assert o["attainment"] == pytest.approx(0.9)

    def test_empty_window_is_full_attainment(self, tmp_path):
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=FakeClock())
        objs = [SLObjective(name="api", metric="m", threshold=1.0)]
        doc = slo_report(store, objectives=objs, window_s=60.0, now=T0)
        store.close()
        assert doc["objectives"]["api"]["attainment"] == 1.0
        assert doc["objectives"]["api"]["budget_spent"] == 0.0

    def test_bench_metrics_ingests_the_report(self):
        doc = {"metrics": {"slo.api_attained": 0.94,
                           "slo.ingest_attained": 1.0}}
        out = bench_metrics(doc)
        assert out == {"slo.api_attained": 0.94,
                       "slo.ingest_attained": 1.0}
        assert not monitor.metric_lower_is_better("slo.api_attained")


# -- torn-tail drills (satellite) --------------------------------------------


class TestTornTails:
    def test_read_spool_heals_and_counts(self, tmp_path):
        spool = tmp_path / "spool"
        spool.mkdir()
        good = {"t": T0, "seq": 1, "host": "h", "pid": 1,
                "timeline": {"stages": {}}}
        with open(spool / "h-1.jsonl", "w") as f:
            f.write(json.dumps(good) + "\n")
            f.write('{"t": 170')  # the SIGKILL tear: no newline
        tl = observability.process_timeline()
        before = tl.stages["monitor.torn_lines"].calls \
            if "monitor.torn_lines" in tl.stages else 0
        samples = monitor.read_spool(str(spool), tail=5)
        assert len(samples) == 1 and samples[0]["seq"] == 1
        assert tl.stages["monitor.torn_lines"].calls == before + 1

    def test_read_requests_heals_and_counts(self, tmp_path):
        d = tmp_path / "req"
        d.mkdir()
        with open(d / "requests-peer.jsonl", "w") as f:
            f.write(json.dumps({"t": T0, "rid": "a", "status": "ok"})
                    + "\n")
            f.write('{"t": 17, "rid": "tor')
        tl = observability.process_timeline()
        before = tl.stages["monitor.torn_lines"].calls \
            if "monitor.torn_lines" in tl.stages else 0
        recs = monitor.read_requests(str(d))
        assert [r["rid"] for r in recs] == ["a"]
        assert tl.stages["monitor.torn_lines"].calls == before + 1

    def test_kill_mid_write_drill(self, tmp_path):
        """A real SIGKILL mid-line: the child writes one whole record,
        then half a record with no newline, then blocks; every monitor-
        path reader over the spool must heal."""
        spool = tmp_path / "spool"
        spool.mkdir()
        child = subprocess.Popen(
            [sys.executable, "-c", f"""
import json, sys, time
f = open({str(spool / "h-9.jsonl")!r}, "w")
f.write(json.dumps({{"t": 1.0, "seq": 0, "host": "h", "pid": 9,
                     "timeline": {{"stages": {{}}}}}}) + "\\n")
f.write('{{"t": 2.0, "seq": 1, "host": "h"')  # torn: no newline
f.flush()
print("ready", flush=True)
time.sleep(60)
"""],
            stdout=subprocess.PIPE)
        try:
            assert child.stdout.readline().strip() == b"ready"
            child.send_signal(signal.SIGKILL)
            child.wait(timeout=30)
        finally:
            if child.poll() is None:
                child.kill()
        samples = monitor.read_spool(str(spool), tail=10)
        assert [s["seq"] for s in samples] == [0]
        report, latest = monitor.merge_spool(str(spool))
        assert len(latest) == 1  # blit top renders despite the tear

    def test_incident_ingest_heals_torn_request_lines(self, tmp_path):
        bundle = tmp_path / "incident-x"
        bundle.mkdir()
        with open(bundle / "incident.json", "w") as f:
            json.dump({"kind": "slo:api", "t": T0, "reason": "r"}, f)
        with open(bundle / "requests.jsonl", "w") as f:
            f.write(json.dumps({"t": T0, "trace": "tr1"}) + "\n")
            f.write('{"t": 17, "trace": "to')
        b = load_incident(str(bundle))
        assert len(b["requests"]) == 1
        assert b["torn_lines"] == 1
        assert "healed" in render_incident(b)


# -- wall-clock anchor (satellite) -------------------------------------------


class TestAnchor:
    def test_anchor_is_one_stable_pair(self):
        a = wall_anchor()
        assert set(a) == {"epoch", "mono"}
        assert a == wall_anchor()  # captured at import, not per call
        # The pair is coherent: epoch - mono is a plausible origin.
        assert a["epoch"] - a["mono"] <= time.time()

    def test_flight_dump_carries_and_renders_anchor(self, tmp_path):
        rec = observability.FlightRecorder()
        path = rec.dump("anchor test", path=str(tmp_path / "d.json"),
                        force=True)
        with open(path) as f:
            doc = json.load(f)
        assert doc["anchor"] == wall_anchor()
        text = observability.render_flight_dump(doc)
        assert "anchor" in text and "mono origin" in text

    def test_telemetry_snapshot_carries_anchor(self):
        snap = observability.telemetry_snapshot()
        assert snap["anchor"] == wall_anchor()


# -- CLI surface -------------------------------------------------------------


class TestCli:
    def _store(self, tmp_path):
        # Near-now clock: the CLI windows anchor at real time.time().
        clock = FakeClock(time.time() - 15.0)
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        for _ in range(10):
            store.append(clock(), 1.0, _tick_delta(),
                         burn={"api": (1, 10)})
            clock.advance(1.0)
        store.close()
        return str(tmp_path / "h")

    def test_slo_report_cli_json_and_artifact(self, tmp_path, capsys,
                                              monkeypatch):
        from blit.__main__ import main

        d = self._store(tmp_path)
        out = tmp_path / "slo.json"
        # The reader's config declares NO "api" objective — the burn
        # counts recorded in the store still report (the store
        # outranks the reader's config).
        rc = main(["slo-report", d, "--window", "1d", "--json",
                   "--out", str(out)])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["objectives"]["api"]["bad"] == 10
        assert doc["objectives"]["api"]["total"] == 100
        assert doc["metrics"]["slo.api_attained"] == pytest.approx(0.9)
        assert json.loads(out.read_text()) == doc

    def test_incident_cli_list_and_show(self, tmp_path, capsys):
        from blit.__main__ import main

        clock = FakeClock()
        b = IncidentBundler(str(tmp_path / "inc"), window_s=60.0,
                            cooldown_s=1.0, clock=clock)
        path = b.snapshot("slo:api", "drill", alert={
            "t": T0, "class": "slo", "objective": "api",
            "metric": "serve.request_s"})
        assert path
        rc = main(["incidents", "--dir", str(tmp_path / "inc")])
        assert rc == 0
        assert "slo:api" in capsys.readouterr().out
        rc = main(["incident", "show", path, "--window", "15m"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "slo:api" in out and "timeline" in out
        rc = main(["incident", "show", path, "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["manifest"]["kind"] == "slo:api"

    def test_incidents_cli_needs_a_dir(self, capsys):
        from blit.__main__ import main

        with pytest.raises(SystemExit):
            main(["incidents"])

    def test_top_history_sparklines(self, tmp_path, capsys):
        from blit.__main__ import main

        d = self._store(tmp_path)
        spool = tmp_path / "spool"
        spool.mkdir()
        sample = {"t": time.time(), "seq": 0, "host": "h", "pid": 1,
                  "timeline": {"stages": {}}, "delta": {"stages": {}},
                  "slo": {}}
        (spool / "h-1.jsonl").write_text(json.dumps(sample) + "\n")
        rc = main(["top", "--spool", str(spool), "--once",
                   "--history", d])
        assert rc == 0
        out = capsys.readouterr().out
        assert "history" in out and "ingest.chunks" in out
        # The sparkline glyphs actually render.
        assert any(ch in out for ch in "▁▂▃▄▅▆▇█")

    def test_requests_since_until_window(self, tmp_path, capsys):
        from blit.__main__ import main

        d = tmp_path / "req"
        d.mkdir()
        now = time.time()
        with open(d / "requests-x.jsonl", "w") as f:
            for dt, rid in [(-7200, "old"), (-60, "recent"),
                            (-1, "fresh")]:
                f.write(json.dumps({"t": now + dt, "rid": rid,
                                    "status": "ok",
                                    "duration_s": 0.01}) + "\n")
        rc = main(["requests", str(d), "--since", "15m", "--json"])
        assert rc == 0
        rids = [json.loads(line)["rid"] for line in
                capsys.readouterr().out.splitlines() if line]
        assert rids == ["recent", "fresh"]
        rc = main(["requests", str(d), "--since", "15m", "--until",
                   "30", "--json"])
        assert rc == 0
        rids = [json.loads(line)["rid"] for line in
                capsys.readouterr().out.splitlines() if line]
        assert rids == ["recent"]

    def test_sparkline_shapes(self):
        assert sparkline([]) == ""
        assert sparkline([1.0, 1.0, 1.0]) == "▁▁▁"
        s = sparkline([0, 1, 2, 3], width=4)
        assert s[0] == "▁" and s[-1] == "█"


# -- the serve-plane surface -------------------------------------------------


class TestServeSurface:
    def test_peer_history_doc_shape(self, tmp_path):
        from types import SimpleNamespace

        from blit.serve.http import _history_doc, history_query

        clock = FakeClock()
        store = HistoryStore(str(tmp_path / "h"), tiers=_small_tiers(),
                             slot_bytes=4096, clock=clock)
        store.append(clock(), 1.0, _tick_delta(calls=5))
        store.close()
        since, until, tier = history_query(
            f"/history?since={T0 - 10}&until={T0 + 10}&tier=raw")
        assert (since, until, tier) == (T0 - 10, T0 + 10, "raw")
        pub = SimpleNamespace(
            history=HistoryStore(str(tmp_path / "h"), create=False,
                                 clock=clock))
        doc = _history_doc(
            pub, f"/history?since={T0 - 10}&until={T0 + 10}&tier=raw")
        assert doc["enabled"] is True
        assert doc["buckets"][0]["stages"]["ingest.chunks"]["calls"] == 5
        off = _history_doc(SimpleNamespace(history=None), "/history")
        assert off["enabled"] is False and off["buckets"] == []

    def test_history_query_window_grammar(self):
        from blit.serve.http import history_query

        since, until, tier = history_query("/history?since=15m")
        assert until - since == pytest.approx(900.0, abs=5.0)
        assert tier is None

    def test_peer_route_and_door_merge_over_the_wire(self, tmp_path):
        from blit.serve.cache import ProductCache
        from blit.serve.fleet import FleetFrontDoor
        from blit.serve.http import PeerServer, http_json
        from blit.serve.scheduler import Scheduler
        from blit.serve.service import ProductService

        lease_dir = str(tmp_path / "leases")
        servers, peers = [], {}
        for i in range(2):
            tl = Timeline()
            cfg = SiteConfig(history_dir=str(tmp_path / f"hist{i}"),
                             history_raw_s=1.0,
                             history_anomaly=False)
            svc = ProductService(
                cache=ProductCache(str(tmp_path / f"cache{i}"),
                                   ram_bytes=1 << 24, timeline=tl),
                scheduler=Scheduler(max_concurrency=2, queue_depth=8,
                                    timeline=tl, retry_seed=i),
                timeline=tl, config=cfg)
            ps = PeerServer(svc, name=f"peer{i}",
                            lease_dir=lease_dir, proc=i,
                            beat_interval_s=0.05, config=cfg).start()
            # Land one known stage delta in each peer's ring.
            s = tl.stages["ingest.chunks"]
            s.calls += i + 1
            s.seconds += 0.01
            s.bytes += 1000
            ps._pub.tick()
            servers.append((ps, svc))
            peers[f"peer{i}"] = ps.url
        door = None
        try:
            # The peer-side route answers over the real wire.
            status, _, body = http_json(
                "GET", peers["peer0"], "/history?since=1h")
            assert status == 200 and body["enabled"]
            calls = sum(r["stages"]["ingest.chunks"]["calls"]
                        for r in body["buckets"])
            assert calls == 1
            # The door fans out and merges both peers' buckets.
            door = FleetFrontDoor(peers, lease_dir=lease_dir,
                                  peer_ttl_s=5.0, poll_s=0.05,
                                  health_poll_s=0.2)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                door.observe()
                if all(p.watch.seen for p in door._peers.values()):
                    break
                time.sleep(0.05)
            now = time.time()
            doc = door.history(now - 3600, now)
            assert sorted(doc["peers"]) == ["peer0", "peer1"]
            assert doc["skipped"] == []
            calls = sum(r["stages"]["ingest.chunks"]["calls"]
                        for r in doc["buckets"])
            assert calls == 3  # 1 + 2, folded by bucket
        finally:
            if door is not None:
                door.close()
            for ps, svc in servers:
                ps.close()
                svc.close(5)
