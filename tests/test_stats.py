"""Kurtosis golden tests against scipy (StatsBase.kurtosis semantics:
excess, biased central moments — README.md:216-217)."""

import numpy as np
import scipy.stats

from blit.ops import kurtosis


def test_excess_kurtosis_matches_scipy():
    rng = np.random.default_rng(42)
    x = rng.normal(size=1000)
    got = kurtosis(x[:, None, None])[0, 0]
    want = scipy.stats.kurtosis(x, fisher=True, bias=True)
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_kurtosis_shape_and_values_3d():
    rng = np.random.default_rng(7)
    data = rng.normal(size=(500, 2, 8))  # (time, pol, chan)
    got = kurtosis(data, axis=0)
    assert got.shape == (2, 8)
    want = scipy.stats.kurtosis(data, axis=0, fisher=True, bias=True)
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_kurtosis_constant_plus_spike():
    # A distribution with heavy tails has positive excess kurtosis.
    x = np.concatenate([np.zeros(999), [100.0]])
    assert kurtosis(x[:, None, None])[0, 0] > 100
