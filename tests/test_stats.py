"""Kurtosis golden tests against scipy (StatsBase.kurtosis semantics:
excess, biased central moments — README.md:216-217)."""

import numpy as np
import scipy.stats

from blit.ops import kurtosis


def test_excess_kurtosis_matches_scipy():
    rng = np.random.default_rng(42)
    x = rng.normal(size=1000)
    got = kurtosis(x[:, None, None])[0, 0]
    want = scipy.stats.kurtosis(x, fisher=True, bias=True)
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_kurtosis_shape_and_values_3d():
    rng = np.random.default_rng(7)
    data = rng.normal(size=(500, 2, 8))  # (time, pol, chan)
    got = kurtosis(data, axis=0)
    assert got.shape == (2, 8)
    want = scipy.stats.kurtosis(data, axis=0, fisher=True, bias=True)
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_kurtosis_constant_plus_spike():
    # A distribution with heavy tails has positive excess kurtosis.
    x = np.concatenate([np.zeros(999), [100.0]])
    assert kurtosis(x[:, None, None])[0, 0] > 100


class TestDeviceKurtosis:
    """On-device statistics (SURVEY.md §2.2 StatsBase → JAX moment kernels):
    the same kernel jitted on the accelerator, golden vs host NumPy."""

    def _fil(self, tmp_path):
        import pytest

        pytest.importorskip("jax")
        from blit.testing import synth_fil

        p = str(tmp_path / "k.fil")
        synth_fil(p, nsamps=512, nifs=2, nchans=16, seed=3)
        return p

    def test_device_matches_host(self, tmp_path):
        from blit import workers

        p = self._fil(tmp_path)
        host = workers.get_kurtosis(p)
        dev = workers.get_kurtosis(p, device=True)
        assert dev.shape == host.shape == (16, 2)
        # Same float32 moment arithmetic modulo summation order.
        np.testing.assert_allclose(dev, host, rtol=2e-3, atol=2e-3)

    def test_device_with_idxs(self, tmp_path):
        from blit import workers

        p = self._fil(tmp_path)
        host = workers.get_kurtosis(p, (slice(0, 256), 0, slice(None)))
        dev = workers.get_kurtosis(
            p, (slice(0, 256), 0, slice(None)), device=True
        )
        assert dev.shape == (16, 1)
        np.testing.assert_allclose(dev, host, rtol=2e-3, atol=2e-3)

    def test_pool_fanout_device(self, tmp_path):
        from blit import gbt
        from blit.parallel.pool import WorkerPool

        p = self._fil(tmp_path)
        pool = WorkerPool(["h0", "h1"], backend="local")
        try:
            maps = gbt.get_kurtosis([1, 2], [p, p], device=True, pool=pool)
            np.testing.assert_allclose(maps[0], maps[1])
        finally:
            pool.shutdown()
