"""End-to-end orchestration tests: the reference README's full workflow
(setup workers -> inventories -> headers -> data -> kurtosis -> scan load)
against a synthetic multi-player observation tree, on every pool backend."""

import numpy as np
import pytest

from blit import gbt, testing
from blit.parallel import pool as pool_mod
from blit.parallel.pool import WorkerError, WorkerPool


@pytest.fixture(autouse=True)
def fresh_pool():
    pool_mod.reset_pool()
    yield
    pool_mod.reset_pool()


@pytest.fixture()
def tree(tmp_path):
    root = str(tmp_path / "dibas")
    players = tuple((0, b) for b in range(4))  # band 0, banks 0..3
    paths = testing.build_observation_tree(
        root, scans=("0011", "0012"), players=players, nsamps=16, nchans=64
    )
    return root, paths


@pytest.mark.parametrize("backend", ["local", "thread", "process"])
def test_full_workflow(tree, backend):
    root, paths = tree
    # one "host" per player dir; all local, but the pool contract is the same
    pool = WorkerPool([f"fakehost{i}" for i in range(4)], backend=backend)
    invs = gbt.get_inventories(pool=pool, root=root)
    assert len(invs) == 4
    # every worker sees the whole local tree here; each inventory has 8 files
    assert all(len(inv) == 8 for inv in invs)
    inv = invs[0]
    # worker/host stamping follows the pool
    assert {r.worker for r in invs[2]} == {3}
    assert {r.host for r in invs[2]} == {"fakehost2"}

    recs = [r for r in inv if r.scan == "0011"]
    wids = [1] * len(recs)
    files = [r.file for r in recs]
    hdrs = gbt.get_headers(wids, files, pool=pool)
    assert all(h["nchans"] == 64 for h in hdrs)

    datas = gbt.get_data(wids, files, fqav_by=8, pool=pool)
    assert all(d.shape == (16, 1, 8) for d in datas)

    ks = gbt.get_kurtosis(wids, files, pool=pool)
    assert all(k.shape == (64, 1) for k in ks)
    pool.shutdown()


def test_setup_workers_returns_live_pool(tree):
    root, _ = tree
    p1 = gbt.setup_workers(["a", "b"], backend="local")
    p2 = gbt.setup_workers(["c"], backend="local")
    assert p2 is p1  # fixed wart: live pool, not empty list (src/gbt.jl:20-22)
    assert len(p1) == 2


def test_size_mismatch_asserts(tree):
    pool = WorkerPool(["h"], backend="local")
    with pytest.raises(ValueError):
        gbt.get_headers([1, 1], ["only_one_file"], pool=pool)


def test_error_capture(tree):
    root, _ = tree
    pool = WorkerPool(["h1", "h2"], backend="thread")
    res = gbt.get_headers(
        [1, 2], ["/nonexistent/file.h5", "/also/missing.fil"],
        pool=pool, on_error="capture",
    )
    assert all(isinstance(r, WorkerError) for r in res)
    assert res[0].worker == 1 and res[1].host == "h2"
    with pytest.raises(Exception):
        gbt.get_headers([1], ["/nonexistent/file.h5"], pool=pool)
    pool.shutdown()


def test_load_scan_stitch_and_despike(tree):
    root, _ = tree
    pool = WorkerPool(["h"], backend="local")
    invs = gbt.get_inventories(pool=pool, root=root)
    inv = [invs[0]]  # single worker's view
    out = gbt.load_scan(inv, "AGBT22B_999_01", "0011", pool=pool)
    assert set(out) == {0}
    hdr, data = out[0]
    # 4 banks x 64 chans stitched along the channel axis, bank-ascending
    assert data.shape == (16, 1, 256)
    assert hdr["nchans"] == 256 and hdr["nsamps"] == 16
    # stitched in bank order: bank 0's data comes first
    d0 = gbt.get_data([1], [r.file for r in inv[0] if r.scan == "0011" and r.bank == 0], pool=pool)[0]
    exp = d0.copy()
    nfpc = hdr["nfpc"]
    if nfpc >= 2 and 64 % nfpc == 0:
        from blit.ops.despike import despike

        exp = despike(exp, nfpc)
    np.testing.assert_allclose(data[:, :, :64], exp)


def test_load_scan_missing_banks_ok(tree, caplog):
    root, _ = tree
    pool = WorkerPool(["h"], backend="local")
    invs = gbt.get_inventories(pool=pool, root=root)
    # drop bank 2 to make it ragged
    inv = [[r for r in invs[0] if r.bank != 2]]
    with caplog.at_level("WARNING", logger="blit.gbt"):
        out = gbt.load_scan(inv, "AGBT22B_999_01", "0011", pool=pool)
    hdr, data = out[0]
    assert data.shape[-1] == 3 * 64
    assert any("only banks" in r.message for r in caplog.records)


def test_load_scan_empty():
    pool = WorkerPool(["h"], backend="local")
    assert gbt.load_scan([[]], "NOPE", "0000", pool=pool) == {}


def test_load_scan_dedupes_duplicate_bank_records(tree):
    # Shared filesystem: two workers report the same bank file.  The band
    # must stitch each bank ONCE (not double-width).
    root, _ = tree
    pool = WorkerPool(["h1", "h2"], backend="local")
    invs = gbt.get_inventories(pool=pool, root=root)
    # Both workers saw the whole tree: every file appears twice across
    # the per-worker inventories.
    out = gbt.load_scan(invs, "AGBT22B_999_01", "0011", pool=pool)
    hdr, data = out[0]
    assert data.shape == (16, 1, 256)  # 4 banks x 64, not 8 x 64
    assert hdr["nchans"] == 256
    pool.shutdown()


def test_save_load_inventories_roundtrip_worker_errors(tree, tmp_path):
    from blit.inventory import load_inventories, save_inventories

    root, _ = tree
    pool = WorkerPool(["h"], backend="local")
    invs = gbt.get_inventories(pool=pool, root=root)
    dead = WorkerError(worker=2, host="blc77",
                       error=RuntimeError("ssh: no route to host"))
    p = str(tmp_path / "inv.jsonl")
    n = save_inventories(p, [invs[0], dead, []])
    assert n == len(invs[0])
    restored = load_inventories(p)
    assert restored[0] == invs[0]
    assert isinstance(restored[1], WorkerError)
    assert restored[1].host == "blc77" and restored[1].worker == 2
    assert "no route to host" in str(restored[1].error)
    assert restored[2] == []
    # The restored shape feeds consumers exactly like live output: the
    # error entry is skipped (not crashed on) by the scan resolver.
    from blit.inventory import scan_grid

    with pytest.raises(ValueError, match="no RAW sequences"):
        scan_grid(restored, "AGBT22B_999_01", "0011")  # fbh5 tree: no .raw
    pool.shutdown()


def test_error_entries_skipped_everywhere(tree, tmp_path):
    # Every consumer of the ragged inventories shape must skip error
    # entries — WorkerError AND bare Exception — identically.
    from blit.inventory import (
        load_inventories,
        save_inventories,
        to_dataframe,
    )

    root, _ = tree
    pool = WorkerPool(["h"], backend="local")
    invs = gbt.get_inventories(pool=pool, root=root)
    ragged = [invs[0], WorkerError(2, "blc01", RuntimeError("x")),
              RuntimeError("bare")]
    df = to_dataframe(ragged)
    assert len(df) == len(invs[0])
    out = gbt.load_scan(ragged, "AGBT22B_999_01", "0011", pool=pool)
    assert set(out) == {0}
    p = str(tmp_path / "inv.jsonl")
    save_inventories(p, ragged)
    restored = load_inventories(p)
    assert len(restored) == 3 and len(to_dataframe(restored)) == len(invs[0])
    pool.shutdown()
