"""File-fed collective products (VERDICT r3 item 4): per-antenna RAW
recordings → sharded planar voltages → beamform / FX correlator, golden
against the NumPy references fed from the same files."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.io.guppi import open_raw  # noqa: E402
from blit.ops.channelize import pfb_coeffs  # noqa: E402
from blit.parallel.antenna import (  # noqa: E402
    load_antennas_mesh,
    load_correlator_mesh,
)
from blit.parallel.beamform import beamform, beamform_np  # noqa: E402
from blit.parallel.correlator import correlate, correlate_np  # noqa: E402
from blit.parallel.mesh import make_mesh  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NANT, NCHAN, NTIME, NPOL = 8, 4, 512, 2
NFFT, NTAP = 16, 4


@pytest.fixture(scope="module")
def ant_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("ants")
    paths = []
    for a in range(NANT):
        p = str(d / f"ant{a}.raw")
        synth_raw(p, nblocks=2, obsnchan=NCHAN, ntime_per_block=NTIME // 2,
                  seed=100 + a, tone_chan=a % NCHAN)
        paths.append(p)
    return paths


def complex_voltages(paths, ntime):
    """The files' samples as the goldens' complex (nant, nchan, t, npol)."""
    out = []
    for p in paths:
        raw = open_raw(p)
        blocks = []
        for i in range(raw.nblocks):
            nt = raw.block_ntime_kept(i)
            buf = np.empty((NCHAN, nt, NPOL, 2), np.int8)
            raw.read_block_into(i, buf, 0, nt)
            blocks.append(buf)
        v = np.concatenate(blocks, axis=1)[:, :ntime]
        out.append(v[..., 0].astype(np.float32)
                   + 1j * v[..., 1].astype(np.float32))
    return np.stack(out).astype(np.complex64)


class TestFileFedBeamform:
    def test_matches_numpy_golden(self, ant_files):
        mesh = make_mesh(1, 8)
        hdr, (vr, vi) = load_antennas_mesh(ant_files, mesh=mesh)
        ntime = hdr["_ntime"]
        assert vr.shape == (NANT, NCHAN, ntime, NPOL)
        rng = np.random.default_rng(3)
        w = (rng.standard_normal((5, NANT, NCHAN))
             + 1j * rng.standard_normal((5, NANT, NCHAN))
             ).astype(np.complex64)
        from blit.parallel.beamform import weight_sharding

        ws = weight_sharding(mesh)
        wput = (
            jax.device_put(w.real.astype(np.float32), ws),
            jax.device_put(w.imag.astype(np.float32), ws),
        )
        power = beamform((vr, vi), wput, mesh=mesh, nint=4)
        golden = beamform_np(complex_voltages(ant_files, ntime), w, nint=4)
        np.testing.assert_allclose(np.asarray(power), golden,
                                   rtol=1e-4, atol=1e-2)

    def test_max_samples_caps_span(self, ant_files):
        mesh = make_mesh(1, 8)
        hdr, (vr, _) = load_antennas_mesh(ant_files, mesh=mesh,
                                          max_samples=128)
        assert hdr["_ntime"] == 128 and vr.shape[2] == 128

    def test_indivisible_antennas_rejected(self, ant_files):
        mesh = make_mesh(1, 8)
        with pytest.raises(ValueError, match="divide over"):
            load_antennas_mesh(ant_files[:6], mesh=mesh)

    def test_missing_file_fails_loud(self, ant_files, tmp_path):
        mesh = make_mesh(1, 8)
        bad = list(ant_files)
        bad[3] = str(tmp_path / "nope.raw")
        with pytest.raises(ValueError, match="antennas \\[3\\] failed"):
            load_antennas_mesh(bad, mesh=mesh)


class TestFileFedCorrelator:
    def test_matches_numpy_golden(self, ant_files):
        mesh = make_mesh(2, 4)
        hdr, (vr, vi) = load_correlator_mesh(
            ant_files[:4], mesh=mesh, nfft=NFFT, ntap=NTAP,
        )
        ntime = hdr["_ntime"]
        assert ntime % (2 * NFFT) == 0
        coeffs = pfb_coeffs(NTAP, NFFT).astype(np.float32)
        visr, visi = correlate((vr, vi), jax.numpy.asarray(coeffs),
                               mesh=mesh, nfft=NFFT, ntap=NTAP)
        golden = correlate_np(
            complex_voltages(ant_files[:4], ntime), coeffs, NFFT, NTAP,
            nsegments=2,
        )
        np.testing.assert_allclose(np.asarray(visr), golden.real,
                                   rtol=1e-3, atol=0.5)
        np.testing.assert_allclose(np.asarray(visi), golden.imag,
                                   rtol=1e-3, atol=0.5)

    def test_short_recording_rejected(self, tmp_path):
        paths = []
        for a in range(2):
            p = str(tmp_path / f"s{a}.raw")
            synth_raw(p, nblocks=1, obsnchan=4, ntime_per_block=64,
                      seed=a)
            paths.append(p)
        mesh = make_mesh(2, 4)
        with pytest.raises(ValueError, match="blocks per band segment"):
            load_correlator_mesh(paths, mesh=mesh, nfft=64)

    def test_channel_split_must_divide(self, tmp_path):
        paths = []
        for a in range(2):
            p = str(tmp_path / f"c{a}.raw")
            synth_raw(p, nblocks=2, obsnchan=3, ntime_per_block=256,
                      seed=a)
            paths.append(p)
        mesh = make_mesh(2, 4)
        with pytest.raises(ValueError, match="divide over"):
            load_correlator_mesh(paths, mesh=mesh, nfft=16)
