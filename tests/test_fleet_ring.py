"""Consistent-hash ring invariants (blit/serve/ring.py; ISSUE 14
satellite): uniform load spread within bounds, minimal key movement on
peer join/leave, DETERMINISTIC ownership across processes (sha256, not
PYTHONHASHSEED-poisoned ``hash()``), and replica sets that never
collapse onto one host."""

import json
import random
import subprocess
import sys

from blit.serve.ring import HashRing, ring_hash

KEYS = [f"fingerprint-{i:05d}" for i in range(4000)]
PEERS = [f"peer{i}" for i in range(8)]


class TestSpread:
    def test_uniform_within_bounds(self):
        # With 128 vnodes per peer, every peer's share of a large
        # keyspace stays within a small factor of fair — the bound a
        # fleet's capacity planning relies on.
        ring = HashRing(PEERS, vnodes=128)
        spread = ring.spread(KEYS)
        fair = len(KEYS) / len(PEERS)
        assert sum(spread.values()) == len(KEYS)
        for peer, n in spread.items():
            assert 0.45 * fair <= n <= 2.0 * fair, (peer, n, fair)

    def test_every_peer_owns_something(self):
        ring = HashRing(PEERS, vnodes=128)
        assert all(n > 0 for n in ring.spread(KEYS).values())


class TestMinimalMovement:
    def test_leave_moves_only_the_leavers_keys(self):
        before = HashRing(PEERS, vnodes=128)
        after = HashRing(PEERS, vnodes=128)
        victim = PEERS[3]
        after.remove(victim)
        owned = before.spread(KEYS)[victim]
        moved, total = before.moved(KEYS, after)
        # EXACTLY the victim's keys move (consistent hashing's whole
        # point): everyone else's owner is untouched.
        assert moved == owned
        assert moved <= 2.0 * total / len(PEERS)
        for k in KEYS:
            if before.owner(k) != victim:
                assert after.owner(k) == before.owner(k)

    def test_join_moves_only_to_the_joiner(self):
        small = HashRing(PEERS[:-1], vnodes=128)
        grown = HashRing(PEERS[:-1], vnodes=128)
        grown.add(PEERS[-1])
        for k in KEYS:
            if grown.owner(k) != PEERS[-1]:
                assert grown.owner(k) == small.owner(k)
        moved, total = small.moved(KEYS, grown)
        assert 0 < moved <= 2.0 * total / len(PEERS)

    def test_remove_then_readd_restores_ownership(self):
        ring = HashRing(PEERS, vnodes=64)
        want = {k: ring.owner(k) for k in KEYS[:500]}
        ring.remove(PEERS[2])
        ring.add(PEERS[2])
        assert {k: ring.owner(k) for k in KEYS[:500]} == want


class TestResizeDeltas:
    """The elastic plane's ring arithmetic (ISSUE 17): incoming_keys /
    departing_keys predict EXACTLY the keys a membership flip moves —
    the warm handoff streams that set and nothing else."""

    def test_incoming_keys_match_a_real_join(self):
        ring = HashRing(PEERS[:-1], vnodes=128)
        predicted = ring.incoming_keys(PEERS[-1], KEYS)
        grown = HashRing(PEERS[:-1], vnodes=128)
        grown.add(PEERS[-1])
        assert predicted == [k for k in KEYS
                             if grown.owner(k) == PEERS[-1]]
        assert predicted  # the joiner takes a real share

    def test_departing_keys_are_the_leavers_share(self):
        ring = HashRing(PEERS, vnodes=128)
        dep = ring.departing_keys(PEERS[3], KEYS)
        assert len(dep) == ring.spread(KEYS)[PEERS[3]]
        assert all(ring.owner(k) == PEERS[3] for k in dep)

    def test_incoming_of_a_member_is_its_current_share(self):
        # Asking "what would move to X" when X is already in the ring
        # must answer X's existing share — the shadow ring is the ring.
        ring = HashRing(PEERS, vnodes=128)
        assert ring.incoming_keys(PEERS[2], KEYS) == \
            ring.departing_keys(PEERS[2], KEYS)


class TestResizeChurn:
    def test_random_resize_sequences_move_only_flipped_keys(self):
        # The churn property (ISSUE 17 satellite): N seeded random
        # join/leave sequences; after EVERY step the set of keys whose
        # owner changed is EXACTLY the predicted incoming/departing
        # set, and the uniform-spread bound survives the churn.
        rng = random.Random(1234)
        keys = KEYS[:1500]
        for trial in range(5):
            members = [f"t{trial}-peer{i}" for i in range(5)]
            spares = [f"t{trial}-spare{j}" for j in range(6)]
            ring = HashRing(members, vnodes=128)
            for step in range(8):
                before = {k: ring.owner(k) for k in keys}
                join = spares and (rng.random() < 0.5
                                   or len(members) <= 2)
                if join:
                    peer = spares.pop()
                    predicted = set(ring.incoming_keys(peer, keys))
                    ring.add(peer)
                    members.append(peer)
                    changed = {k for k in keys
                               if ring.owner(k) != before[k]}
                    assert changed == predicted, (trial, step, peer)
                    assert all(ring.owner(k) == peer for k in changed)
                else:
                    peer = members.pop(rng.randrange(len(members)))
                    predicted = set(ring.departing_keys(peer, keys))
                    ring.remove(peer)
                    changed = {k for k in keys
                               if ring.owner(k) != before[k]}
                    assert changed == predicted, (trial, step, peer)
                    assert all(before[k] == peer for k in changed)
                spread = ring.spread(keys)
                fair = len(keys) / len(ring)
                for nm, n in spread.items():
                    assert 0.35 * fair <= n <= 2.3 * fair, \
                        (trial, step, nm, n, fair)


class TestDeterminism:
    def test_sha256_positions_are_stable(self):
        # Pin two literal positions: a refactor that silently changes
        # the hash breaks every deployed ring's ownership.
        assert ring_hash("peer0#0") == int.from_bytes(
            __import__("hashlib").sha256(b"peer0#0").digest()[:8], "big")
        assert ring_hash("a") != ring_hash("b")

    def test_ownership_identical_across_processes(self):
        # The cross-process agreement contract: a SEPARATE interpreter
        # (fresh PYTHONHASHSEED) computes the same owner sets.
        ring = HashRing(PEERS, vnodes=64, replicas=3)
        keys = KEYS[:50]
        local = {k: ring.owners(k) for k in keys}
        code = (
            "import json, sys\n"
            "from blit.serve.ring import HashRing\n"
            "peers = json.loads(sys.argv[1]); keys = json.loads(sys.argv[2])\n"
            "ring = HashRing(peers, vnodes=64, replicas=3)\n"
            "print(json.dumps({k: ring.owners(k) for k in keys}))\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code, json.dumps(PEERS),
             json.dumps(keys)],
            capture_output=True, text=True, check=True)
        assert json.loads(out.stdout) == local


class TestReplicaSets:
    def test_replicas_are_distinct_peers(self):
        ring = HashRing(PEERS, vnodes=128, replicas=3)
        for k in KEYS[:1000]:
            owners = ring.owners(k)
            assert len(owners) == 3
            assert len(set(owners)) == 3  # never collapse onto one host

    def test_fewer_peers_than_replicas_returns_them_all(self):
        ring = HashRing(["a", "b"], replicas=3)
        for k in KEYS[:50]:
            assert sorted(ring.owners(k)) == ["a", "b"]

    def test_exclude_skips_without_shrinking_the_walk(self):
        ring = HashRing(PEERS, vnodes=64, replicas=2)
        k = KEYS[0]
        owner = ring.owner(k)
        owners = ring.owners(k, exclude=[owner])
        assert owner not in owners
        assert len(owners) == 2

    def test_empty_ring(self):
        ring = HashRing([])
        assert ring.owners("anything") == []
        assert ring.owner("anything") is None

    def test_membership_idempotent(self):
        ring = HashRing(["a"])
        assert not ring.add("a")
        assert ring.add("b")
        assert ring.remove("b")
        assert not ring.remove("b")
        assert ring.peers() == ["a"]
