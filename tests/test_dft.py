"""Planar matmul-DFT tests (blit/ops/dft.py) — the TPU FFT path — against
np.fft golden values, including the four-step decomposition."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


@pytest.fixture(autouse=True)
def nan_guard():
    """SURVEY.md §5 sanitizer plan: every golden run in this module executes
    under jax_debug_nans, so a NaN produced anywhere in the reduction
    (relevant with reduced-precision MXU paths) fails loudly here rather
    than silently polluting products."""
    jax.config.update("jax_debug_nans", True)
    yield
    jax.config.update("jax_debug_nans", False)


from blit.ops import dft as D  # noqa: E402
from blit.ops.channelize import channelize, fft_planar, pfb_coeffs  # noqa: E402


def planar(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


class TestDirectDFT:
    @pytest.mark.parametrize("n", [8, 128, 1000])
    def test_matches_numpy(self, n):
        xr, xi = planar((3, n))
        yr, yi = D.dft(jnp.asarray(xr), jnp.asarray(xi),
                       precision=jax.lax.Precision.HIGHEST)
        wr, wi = D.dft_np(xr, xi)
        np.testing.assert_allclose(np.asarray(yr), wr, rtol=1e-3, atol=1e-3 * n)
        np.testing.assert_allclose(np.asarray(yi), wi, rtol=1e-3, atol=1e-3 * n)

    def test_matrix_symmetry(self):
        wr, wi = D.dft_matrices(64)
        np.testing.assert_array_equal(wr, wr.T)
        np.testing.assert_array_equal(wi, wi.T)


class TestFourStepDFT:
    @pytest.mark.parametrize("n", [1 << 13, 1 << 16])
    def test_matches_numpy(self, n):
        xr, xi = planar((2, n), seed=1)
        yr, yi = D.dft(jnp.asarray(xr), jnp.asarray(xi),
                       precision=jax.lax.Precision.HIGHEST)
        wr, wi = D.dft_np(xr, xi)
        scale = np.abs(wr + 1j * wi).max()
        assert np.abs(np.asarray(yr) - wr).max() / scale < 1e-4
        assert np.abs(np.asarray(yi) - wi).max() / scale < 1e-4

    def test_tone_localization_1M(self):
        # Full 1M-point four-step: a pure tone lands in exactly its bin with
        # the right amplitude (cheap O(N·(N1+N2)) sanity check at scale).
        n = 1 << 20
        k0 = 123_457
        t = np.arange(n)
        ang = -2 * np.pi * ((k0 * t) % n) / n  # exp(+2πi k0 t / n) conj trick
        xr = np.cos(ang).astype(np.float32)
        xi = -np.sin(ang).astype(np.float32)
        yr, yi = D.dft(jnp.asarray(xr), jnp.asarray(xi),
                       precision=jax.lax.Precision.HIGHEST)
        p = np.asarray(yr) ** 2 + np.asarray(yi) ** 2
        assert p.argmax() == k0
        assert p[k0] == pytest.approx(float(n) ** 2, rel=1e-3)
        mask = np.ones(n, bool)
        mask[k0] = False
        assert p[mask].max() < 1e-4 * p[k0]

    def test_large_prime_raises(self):
        with pytest.raises(NotImplementedError):
            xr, xi = planar((8191,))  # prime > DIRECT_DFT_MAX has no split
            D.dft(jnp.asarray(xr), jnp.asarray(xi))

    def test_default_factors_policy(self):
        assert D.default_factors(1 << 20) == (128, 128, 64)
        assert D.default_factors(1 << 13) == (128, 64)
        assert D.default_factors(1024) == (1024,)
        for n in [1 << 13, 1 << 16, 1 << 20, 1 << 22]:
            f = D.default_factors(n)
            assert int(np.prod(f)) == n and max(f) <= D.DIRECT_DFT_MAX

    @pytest.mark.parametrize("factors", [(128, 64), (64, 128), (32, 16, 16)])
    def test_explicit_factors_match_numpy(self, factors):
        n = int(np.prod(factors))
        xr, xi = planar((2, n), seed=7)
        yr, yi = D.dft(jnp.asarray(xr), jnp.asarray(xi), factors=factors,
                       precision=jax.lax.Precision.HIGHEST)
        wr, wi = D.dft_np(xr, xi)
        scale = np.abs(wr + 1j * wi).max()
        assert np.abs(np.asarray(yr) - wr).max() / scale < 1e-5
        assert np.abs(np.asarray(yi) - wi).max() / scale < 1e-5

    def test_bad_factors_raise(self):
        xr, xi = planar((64,))
        with pytest.raises(ValueError, match="do not multiply"):
            D.dft(jnp.asarray(xr), jnp.asarray(xi), factors=(8, 4))

    @pytest.mark.parametrize("factors", [(16, 8), (8, 4, 4)])
    def test_twisted_order_untwists_to_natural(self, factors):
        # order="twisted" skips the per-level transposes; untwist() must
        # restore exactly the natural-order spectrum, at any level count.
        n = int(np.prod(factors))
        xr, xi = planar((3, n), seed=5)
        nat = D.dft(jnp.asarray(xr), jnp.asarray(xi), factors=factors,
                    precision=jax.lax.Precision.HIGHEST)
        twi = D.dft(jnp.asarray(xr), jnp.asarray(xi), factors=factors,
                    precision=jax.lax.Precision.HIGHEST, order="twisted")
        for u, v in zip(nat, twi):
            np.testing.assert_allclose(
                np.asarray(D.untwist(v, factors)), np.asarray(u),
                rtol=1e-5, atol=1e-4,
            )

    def test_untwist_is_pure_permutation(self):
        factors = (4, 8, 2)
        n = int(np.prod(factors))
        x = jnp.asarray(np.arange(2 * n, dtype=np.float32).reshape(2, n))
        y = np.asarray(D.untwist(x, factors))
        assert sorted(y[0].tolist()) == sorted(np.asarray(x)[0].tolist())
        # Digit arithmetic: twisted-flat (k1, k2, k3) row-major ->
        # natural k = k1 + f1*k2 + f1*f2*k3.
        f1, f2, f3 = factors
        for t in (0, 1, 17, 63):
            k3 = t % f3
            k2 = (t // f3) % f2
            k1 = t // (f2 * f3)
            k = k1 + f1 * k2 + f1 * f2 * k3
            assert y[0, k] == np.asarray(x)[0, t]

    @pytest.mark.parametrize("dft_order", ["auto", "natural", "twisted"])
    def test_channelize_multilevel_matmul_matches_numpy(self, dft_order):
        # nfft > DIRECT_DFT_MAX forces the multi-level path end to end
        # through detection — in both spectra orders (the twisted variant
        # adds the power untwist; same product either way).
        from blit.ops.channelize import channelize_np

        rng = np.random.default_rng(7)
        nfft = 8192
        v = rng.integers(-40, 40, size=(2, 6 * nfft, 2, 2), dtype=np.int8)
        h = pfb_coeffs(4, nfft)
        got = np.asarray(channelize(jnp.asarray(v), jnp.asarray(h), nfft=nfft,
                                    nint=1, stokes="I", fft_method="matmul",
                                    precision="highest", dft_order=dft_order))
        want = channelize_np(v, h, nfft=nfft, nint=1, stokes="I")
        assert np.abs(got - want).max() / np.abs(want).max() < 1e-4


class TestFFTPlanarDispatch:
    def test_matmul_method_matches_xla(self):
        xr, xi = planar((4, 256), seed=2)
        a = fft_planar(jnp.asarray(xr), jnp.asarray(xi), method="matmul",
                       precision=jax.lax.Precision.HIGHEST)
        b = fft_planar(jnp.asarray(xr), jnp.asarray(xi), method="direct")
        for u, v in zip(a, b):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v), rtol=1e-3,
                                       atol=0.1)

    def test_channelize_matmul_matches_xla_path(self):
        rng = np.random.default_rng(3)
        nfft = 128
        v = rng.integers(-40, 40, size=(2, 6 * nfft, 2, 2), dtype=np.int8)
        h = jnp.asarray(pfb_coeffs(4, nfft))
        a = np.asarray(channelize(jnp.asarray(v), h, nfft=nfft, nint=3,
                                  stokes="full", fft_method="matmul",
                                  precision="highest"))
        b = np.asarray(channelize(jnp.asarray(v), h, nfft=nfft, nint=3,
                                  stokes="full", fft_method="direct"))
        assert np.abs(a - b).max() / np.abs(b).max() < 1e-4
