"""Pallas DFT-stage kernel tests — interpreter mode on CPU (the real-TPU
path is exercised by bench.py / the driver's compile checks)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops import dft as D  # noqa: E402
from blit.ops import pallas_dft as P  # noqa: E402


def planar(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal(shape).astype(np.float32)),
            jnp.asarray(rng.standard_normal(shape).astype(np.float32)))


class TestStageKernel:
    @pytest.mark.parametrize("with_twiddle", [False, True])
    def test_matches_reference(self, with_twiddle):
        n, m, b = 16, 256, 3
        xr, xi = planar((b, n, m))
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        tr = ti = None
        if with_twiddle:
            tr, ti = (jnp.asarray(a) for a in D.twiddles(n, m))
        got = P.dft_stage(xr, xi, wr, wi, tr, ti, interpret=True)
        want = P.stage_reference(xr, xi, wr, wi, tr, ti)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=1e-4, atol=1e-3)

    def test_tiling_indivisible_m_falls_back(self):
        n, m = 8, 96  # m not divisible by the default tile
        xr, xi = planar((2, n, m), seed=1)
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_stage(xr, xi, wr, wi, interpret=True)
        want = P.stage_reference(xr, xi, wr, wi)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   rtol=1e-4, atol=1e-3)

    def test_multi_batch_dims(self):
        n, m = 8, 128
        xr, xi = planar((2, 3, n, m), seed=2)
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_stage(xr, xi, wr, wi, interpret=True)
        assert got[0].shape == (2, 3, n, m)
        want = P.stage_reference(xr, xi, wr, wi)
        np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                                   rtol=1e-4, atol=1e-3)


class TestLastKernel:
    def test_matches_direct_dft(self):
        n, b = 64, 512
        xr, xi = planar((b, n), seed=3)
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_last(xr, xi, wr, wi, interpret=True)
        z = np.fft.fft(np.asarray(xr) + 1j * np.asarray(xi))
        np.testing.assert_allclose(np.asarray(got[0]), z.real, rtol=1e-3,
                                   atol=1e-2)
        np.testing.assert_allclose(np.asarray(got[1]), z.imag, rtol=1e-3,
                                   atol=1e-2)

    def test_row_tiling_fallback(self):
        n = 32
        xr, xi = planar((100, n), seed=4)  # 100 not divisible by 256
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_last(xr, xi, wr, wi, interpret=True)
        z = np.fft.fft(np.asarray(xr) + 1j * np.asarray(xi))
        np.testing.assert_allclose(np.asarray(got[0]), z.real, rtol=1e-3,
                                   atol=1e-2)


class TestDftIntegration:
    def test_auto_is_off_on_cpu(self):
        # CPU backend must not route through pallas (no interpret flag there).
        xr, xi = planar((2, 1 << 13), seed=5)
        yr, yi = D.dft(xr, xi)  # would crash if pallas were chosen
        wr, wi = D.dft_np(np.asarray(xr), np.asarray(xi))
        scale = np.abs(wr + 1j * wi).max()
        assert np.abs(np.asarray(yr) - wr).max() / scale < 1e-3
