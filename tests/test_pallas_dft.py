"""Pallas DFT-stage kernel tests — interpreter mode on CPU (the real-TPU
path is exercised by bench.py / the driver's compile checks)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops import dft as D  # noqa: E402
from blit.ops import pallas_dft as P  # noqa: E402


def planar(shape, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal(shape).astype(np.float32)),
            jnp.asarray(rng.standard_normal(shape).astype(np.float32)))


class TestStageKernel:
    @pytest.mark.parametrize("with_twiddle", [False, True])
    def test_matches_reference(self, with_twiddle):
        n, m, b = 16, 256, 3
        xr, xi = planar((b, n, m))
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        tr = ti = None
        if with_twiddle:
            tr, ti = (jnp.asarray(a) for a in D.twiddles(n, m))
        got = P.dft_stage(xr, xi, wr, wi, tr, ti, interpret=True)
        want = P.stage_reference(xr, xi, wr, wi, tr, ti)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=1e-4, atol=1e-3)

    def test_tiling_indivisible_m_falls_back(self):
        n, m = 8, 96  # m not divisible by the default tile
        xr, xi = planar((2, n, m), seed=1)
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_stage(xr, xi, wr, wi, interpret=True)
        want = P.stage_reference(xr, xi, wr, wi)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   rtol=1e-4, atol=1e-3)

    def test_multi_batch_dims(self):
        n, m = 8, 128
        xr, xi = planar((2, 3, n, m), seed=2)
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_stage(xr, xi, wr, wi, interpret=True)
        assert got[0].shape == (2, 3, n, m)
        want = P.stage_reference(xr, xi, wr, wi)
        np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                                   rtol=1e-4, atol=1e-3)


class TestLastKernel:
    def test_matches_direct_dft(self):
        n, b = 64, 512
        xr, xi = planar((b, n), seed=3)
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_last(xr, xi, wr, wi, interpret=True)
        z = np.fft.fft(np.asarray(xr) + 1j * np.asarray(xi))
        np.testing.assert_allclose(np.asarray(got[0]), z.real, rtol=1e-3,
                                   atol=1e-2)
        np.testing.assert_allclose(np.asarray(got[1]), z.imag, rtol=1e-3,
                                   atol=1e-2)

    def test_row_tiling_fallback(self):
        n = 32
        xr, xi = planar((100, n), seed=4)  # 100 not divisible by 256
        wr, wi = (jnp.asarray(a) for a in D.dft_matrices(n))
        got = P.dft_last(xr, xi, wr, wi, interpret=True)
        z = np.fft.fft(np.asarray(xr) + 1j * np.asarray(xi))
        np.testing.assert_allclose(np.asarray(got[0]), z.real, rtol=1e-3,
                                   atol=1e-2)


class TestDftIntegration:
    def test_auto_is_off_on_cpu(self):
        # CPU backend must not route through pallas (no interpret flag there).
        xr, xi = planar((2, 1 << 13), seed=5)
        yr, yi = D.dft(xr, xi)  # would crash if pallas were chosen
        wr, wi = D.dft_np(np.asarray(xr), np.asarray(xi))
        scale = np.abs(wr + 1j * wi).max()
        assert np.abs(np.asarray(yr) - wr).max() / scale < 1e-3


class TestDftTail2:
    @pytest.mark.parametrize("f2,f3,tile_b", [(8, 4, 4), (16, 8, 2), (8, 8, 3)])
    def test_matches_two_factor_dft(self, f2, f3, tile_b):
        # dft_tail2 == a natural-order (f2, f3)-factored DFT of each row
        # (the tail of a 3-factor transform after its stage 1).
        m = f2 * f3
        xr, xi = planar((2, 3, m), seed=6)
        got_r, got_i = P.dft_tail2(jnp.asarray(xr), jnp.asarray(xi), f2, f3,
                                   tile_b=tile_b, interpret=True)
        want_r, want_i = D.dft(jnp.asarray(xr), jnp.asarray(xi),
                               factors=(f2, f3),
                               precision=jax.lax.Precision.HIGHEST)
        np.testing.assert_allclose(np.asarray(got_r), np.asarray(want_r),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(got_i), np.asarray(want_i),
                                   rtol=1e-4, atol=1e-3)

    def test_channelize_guard(self):
        # tail_kernel='pallas' needs fused1 + exactly 3 factors.
        from blit.ops.channelize import channelize, pfb_coeffs

        v = jnp.zeros((1, 7 * 8192, 2, 2), jnp.int8)
        h = jnp.asarray(pfb_coeffs(4, 8192))
        with pytest.raises(ValueError, match="tail_kernel"):
            channelize(v, h, nfft=8192, fft_method="matmul",
                       pfb_kernel="fused1", tail_kernel="pallas")

    def test_vmem_gate_and_conflict(self):
        from blit.ops.channelize import channelize, pfb_coeffs
        from blit.ops.pallas_dft import tail2_fits

        assert tail2_fits(48 * 2 * 8 * 128, 128, 64, "bfloat16")  # prod
        assert not tail2_fits(1, 2048, 4096)  # huge panels, even tile_b=1
        v = jnp.zeros((1, 7 * 8192, 2, 2), jnp.int8)
        h = jnp.asarray(pfb_coeffs(4, 8192))
        # The explicit pallas+pallas pair (the fused tail+detect) is
        # ineligible at a 2-factor nfft.
        with pytest.raises(ValueError, match="fused tail"):
            channelize(v, h, nfft=8192, fft_method="matmul",
                       pfb_kernel="fused1", detect_kernel="pallas",
                       tail_kernel="pallas")
