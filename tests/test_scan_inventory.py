"""Inventory-driven mesh workflow (VERDICT r3 item 1): synthetic
observation tree → get_inventory → scan_grid → load_scan_mesh(session,
scan) / reduce_scan_mesh_to_files, golden-tested against the host
pipeline — the reference's whole-scan call shape (``loadscan(session,
scan, suffix)``, src/gbt.jl:99) driving the TPU data plane."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.inventory import get_inventory, scan_grid  # noqa: E402
from blit.io.sigproc import read_fil_data  # noqa: E402
from blit.ops.fqav import fqav_range  # noqa: E402
from blit.parallel.scan import (  # noqa: E402
    load_scan_mesh,
    reduce_scan_mesh_to_files,
)
from blit.pipeline import RawReducer  # noqa: E402
from blit.testing import build_observation_tree  # noqa: E402

SESSION = "AGBT22B_999_01"
SCAN = "0011"
NFFT, NINT = 64, 2
PLAYERS = ((0, 0), (0, 1), (0, 2), (0, 3))


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("datax"))
    build_observation_tree(
        root, session=SESSION, scans=(SCAN, "0012"), players=PLAYERS,
        kind="raw", nchans=2, nfiles=2, raw_ntime=512,
    )
    invs = [get_inventory(file_re=r"\.raw$", root=root)]
    return root, invs


def host_golden(invs, fqav_by=1, stokes="I"):
    """Per-bank RawReducer over the same sequences, channel-concatenated."""
    _, _, grid = scan_grid(invs, SESSION, SCAN)
    banks = []
    for paths in grid[0]:
        red = RawReducer(nfft=NFFT, nint=NINT, fqav_by=fqav_by,
                         stokes=stokes)
        _, d = red.reduce(paths)
        banks.append(d)
    return np.concatenate(banks, axis=-1)


class TestScanGrid:
    def test_grid_shape_and_band_ids(self, tree):
        _, invs = tree
        band_ids, bank_ids, grid = scan_grid(invs, SESSION, SCAN)
        assert band_ids == [0] and bank_ids == [0, 1, 2, 3]
        assert len(grid) == 1 and len(grid[0]) == 4
        # Each cell is the full 2-file .NNNN.raw sequence, sorted.
        for k, paths in enumerate(grid[0]):
            assert len(paths) == 2
            assert paths == sorted(paths)
            assert f"BLP0{k}/" in paths[0]

    def test_scan_filter(self, tree):
        _, invs = tree
        b12, _, g12 = scan_grid(invs, SESSION, "0012")
        assert b12 == [0]
        assert g12[0][0] != scan_grid(invs, SESSION, SCAN)[2][0][0]

    def test_unknown_scan_rejected(self, tree):
        _, invs = tree
        with pytest.raises(ValueError, match="no RAW sequences"):
            scan_grid(invs, SESSION, "9999")

    def test_ragged_grid_rejected(self, tree):
        _, invs = tree
        # A second band missing one bank the first has: the (band, bank)
        # rectangle has a hole.  (Dropping a bank from EVERY band just
        # shrinks the grid — only cross-band raggedness is an error.)
        fake_band1 = [
            r._replace(band=1, file=r.file.replace("BLP0", "BLP1"))
            for r in invs[0]
            if r.bank != 3
        ]
        with pytest.raises(ValueError, match="rectangular"):
            scan_grid([invs[0] + fake_band1], SESSION, SCAN)

    def test_worker_error_entries_skipped(self, tree):
        # The REAL captured-failure type (a dataclass, not an Exception):
        # get_inventories(on_error="capture") returns these inline.
        from blit.parallel.pool import WorkerError

        _, invs = tree
        dead = WorkerError(worker=9, host="blc99",
                           error=RuntimeError("worker died"))
        band_ids, _, _ = scan_grid(invs + [dead], SESSION, SCAN)
        assert band_ids == [0]


class TestLoadScanMeshFromInventory:
    def test_matches_host_pipeline(self, tree):
        _, invs = tree
        hdr, out = load_scan_mesh(
            SESSION, SCAN, inventories=invs, nfft=NFFT, nint=NINT,
            despike=False,
        )
        got = np.asarray(out)
        want = host_golden(invs)[: got.shape[1]]
        assert hdr["nchans"] == want.shape[-1] == got.shape[-1]
        np.testing.assert_allclose(got[0], want, rtol=1e-4, atol=0.5)

    def test_session_form_needs_inventories(self):
        with pytest.raises(ValueError, match="session-form"):
            load_scan_mesh(SESSION, SCAN, nfft=NFFT)

    def test_explicit_grid_rejects_inventories(self, tree):
        _, invs = tree
        with pytest.raises(ValueError, match="explicit raw_paths"):
            load_scan_mesh([["x.raw"]], inventories=invs, nfft=NFFT)


class TestMeshFqav:
    def test_fqav_matches_host(self, tree):
        _, invs = tree
        hdr, out = load_scan_mesh(
            SESSION, SCAN, inventories=invs, nfft=NFFT, nint=NINT,
            fqav_by=4, despike=False,
        )
        got = np.asarray(out)
        want = host_golden(invs, fqav_by=4)[: got.shape[1]]
        assert got.shape[-1] == want.shape[-1] == 4 * 2 * NFFT // 4
        np.testing.assert_allclose(got[0], want, rtol=1e-4, atol=2.0)

    def test_fqav_header_math(self, tree):
        _, invs = tree
        h1, _ = load_scan_mesh(SESSION, SCAN, inventories=invs, nfft=NFFT,
                               nint=NINT, despike=False)
        h4, _ = load_scan_mesh(SESSION, SCAN, inventories=invs, nfft=NFFT,
                               nint=NINT, fqav_by=4, despike=False)
        fch1, foff, nchans = fqav_range(
            h1["fch1"], h1["foff"], h1["nchans"], 4
        )
        assert h4["foff"] == pytest.approx(foff)
        assert h4["fch1"] == pytest.approx(fch1)
        assert h4["nchans"] == nchans and h4["nfpc"] == NFFT // 4
        # Same total band span either way.
        assert abs(h4["foff"]) * h4["nchans"] == pytest.approx(
            abs(h1["foff"]) * h1["nchans"]
        )


class TestReduceScanMeshToFiles:
    def test_windowed_products_match_unwindowed(self, tree, tmp_path):
        _, invs = tree
        hdr, out = load_scan_mesh(
            SESSION, SCAN, inventories=invs, nfft=NFFT, nint=NINT,
        )
        whole = np.asarray(out)
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, window_frames=4,
        )
        assert list(written) == [0]
        path, whdr = written[0]
        assert path.endswith("band0.fil") and whdr["nsamps"] == whole.shape[1]
        rhdr, data = read_fil_data(path)
        assert rhdr["nchans"] == hdr["nchans"]
        assert rhdr["fch1"] == pytest.approx(hdr["fch1"])
        np.testing.assert_allclose(
            np.asarray(data), whole[0], rtol=1e-4, atol=0.5
        )

    def test_fqav_product_matches_host(self, tree, tmp_path):
        _, invs = tree
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, fqav_by=4, despike=False, window_frames=6,
        )
        _, data = read_fil_data(written[0][0])
        want = host_golden(invs, fqav_by=4)[: data.shape[0]]
        np.testing.assert_allclose(np.asarray(data), want, rtol=1e-4,
                                   atol=2.0)

    def test_no_partial_left_behind(self, tree, tmp_path):
        _, invs = tree
        reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT,
        )
        assert not list(tmp_path.glob("*.partial"))

    def test_max_frames_caps_product(self, tree, tmp_path):
        _, invs = tree
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, max_frames=4,
        )
        _, data = read_fil_data(written[0][0])
        assert data.shape[0] == 4 // NINT

    def test_h5_product_matches_fil(self, tree, tmp_path):
        # The mesh writer's .h5 leg (FBH5Writer, bitshuffle) carries the
        # same payload as the .fil leg.
        from blit.io.fbh5 import read_fbh5_data, read_fbh5_header

        _, invs = tree
        fil = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, window_frames=4,
        )
        h5 = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, window_frames=4, compression="bitshuffle",
        )
        assert h5[0][0].endswith("band0.h5")
        _, fdata = read_fil_data(fil[0][0])
        np.testing.assert_array_equal(
            read_fbh5_data(h5[0][0]), np.asarray(fdata)
        )
        hh = read_fbh5_header(h5[0][0])
        assert hh["nchans"] == fil[0][1]["nchans"]
        assert hh["fch1"] == pytest.approx(fil[0][1]["fch1"])
        assert not list(tmp_path.glob("*.partial"))

    def test_creation_failure_leaves_no_partials(self, tree, tmp_path):
        _, invs = tree
        bad = str(tmp_path / "no_such_dir" / "band0.fil")
        with pytest.raises(FileNotFoundError):
            reduce_scan_mesh_to_files(
                SESSION, SCAN, inventories=invs, out_paths=[bad],
                nfft=NFFT, nint=NINT,
            )
        assert not list(tmp_path.rglob("*.partial"))

    def test_midstream_failure_drops_partials(self, tree, tmp_path,
                                              monkeypatch):
        # The reduction dying between windows must abort every writer:
        # no .partial siblings, no valid-looking truncated products.
        from blit.parallel import mesh as M

        _, invs = tree
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("synthetic device failure")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError, match="synthetic device failure"):
            reduce_scan_mesh_to_files(
                SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
                nfft=NFFT, nint=NINT, window_frames=4,
            )
        assert not list(tmp_path.glob("*.partial"))
        assert not list(tmp_path.glob("*.fil"))


class TestWindowEquivalenceFuzz:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_window_configs_match_unwindowed(self, tree, tmp_path,
                                                    seed):
        # Property: for ANY window size, nint, and fqav the windowed
        # streaming product equals the one-shot mesh reduction (PFB
        # overlap re-reads, nint-aligned windows, ragged last window).
        rng = np.random.default_rng(seed)
        _, invs = tree
        nint = int(rng.choice([1, 2, 4]))
        fqav = int(rng.choice([1, 2, 8]))
        wf = int(rng.integers(1, 9))
        _, out = load_scan_mesh(
            SESSION, SCAN, inventories=invs, nfft=NFFT, nint=nint,
            fqav_by=fqav,
        )
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=nint, fqav_by=fqav, window_frames=wf,
        )
        _, data = read_fil_data(written[0][0])
        np.testing.assert_allclose(
            np.asarray(data), np.asarray(out)[0], rtol=1e-4, atol=0.5,
            err_msg=f"nint={nint} fqav={fqav} window_frames={wf}",
        )


class TestBf16StagesMeshProduct:
    def test_bf16_stages_match_f32_within_rounding(self, tree, tmp_path):
        # The single-chip pipeline's biggest measured lever (DESIGN §3)
        # reaches the mesh path: dtype="bfloat16" runs the per-chip
        # channelizer stages half-width; the product stays float32 and
        # matches the f32 reduction within bf16 rounding.
        _, invs = tree
        f32_dir, bf_dir = tmp_path / "f32", tmp_path / "bf16"
        f32_dir.mkdir(), bf_dir.mkdir()
        reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(f32_dir),
            nfft=NFFT, nint=NINT, window_frames=4,
        )
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(bf_dir),
            nfft=NFFT, nint=NINT, window_frames=4, dtype="bfloat16",
        )
        _, a = read_fil_data(str(f32_dir / "band0.fil"))
        hdr, b = read_fil_data(written[0][0])
        assert np.asarray(b).dtype == np.float32
        scale = float(np.abs(np.asarray(a)).max())
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-2, atol=2e-2 * scale)

    def test_dtype_flip_restarts_resume_fresh(self, tree, tmp_path,
                                              monkeypatch):
        # dtype is output-affecting: a resume under the other dtype must
        # restart fresh (cursor identity), not splice mixed-rounding
        # spectra.
        from blit.parallel import mesh as M

        _, invs = tree
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            # Call 3: one window is already FLUSHED (the loop keeps one
            # window in flight, so the first append happens after the
            # 2nd dispatch) — the cursor genuinely claims progress and
            # the dtype-flipped resume must DISCARD it, not splice.
            if len(calls) == 3:
                raise RuntimeError("boom")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError):
            reduce_scan_mesh_to_files(
                SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
                nfft=NFFT, nint=NINT, window_frames=4, resume=True,
                despike=False,
            )
        _, partial = read_fil_data(str(tmp_path / "band0.fil"), mmap=False)
        assert partial.shape[0] > 0  # the identity guard has work to undo
        monkeypatch.setattr(M, "band_reduce", real)
        reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, window_frames=4, resume=True,
            dtype="bfloat16", despike=False,
        )
        _, data = read_fil_data(str(tmp_path / "band0.fil"))
        want = host_golden(invs)[: data.shape[0]]
        scale = float(np.abs(want).max())
        np.testing.assert_allclose(np.asarray(data), want, rtol=2e-2,
                                   atol=2e-2 * scale)


class TestFullStokesMeshProduct:
    def test_iquv_product_matches_host(self, tree, tmp_path):
        # Full polarimetry through the WHOLE mesh workflow: the nif=4
        # product streams per band with nifs=4 headers, matching the
        # host pipeline's IQUV reduction (the fused tail2_detect product
        # generalization, bench leg stokes_iquv_gbps).
        _, invs = tree
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, stokes="IQUV", despike=False,
            window_frames=4,
        )
        hdr, data = read_fil_data(written[0][0])
        assert hdr["nifs"] == 4 and data.shape[1] == 4
        want = host_golden(invs, stokes="IQUV")[: data.shape[0]]
        np.testing.assert_allclose(np.asarray(data), want, rtol=1e-4,
                                   atol=0.5)


class TestBoundedDefaultWindow:
    def test_library_default_windows_the_scan(self, tree, tmp_path,
                                              monkeypatch):
        # window_frames=None must bound the device window at EVERY entry
        # point, not just the CLI: the library derives the HBM-safe
        # default from nfft.  (Shrunk here so the synthetic scan spans
        # several windows; the product must still match one-shot.)
        import blit.config as C
        from blit.observability import Timeline

        _, invs = tree
        monkeypatch.setattr(C, "default_window_frames", lambda nfft: 4)
        tl = Timeline()
        written = reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, timeline=tl,
        )
        assert tl.stages["read"].calls > 1  # it actually windowed
        _, out = load_scan_mesh(SESSION, SCAN, inventories=invs,
                                nfft=NFFT, nint=NINT)
        _, data = read_fil_data(written[0][0])
        np.testing.assert_allclose(np.asarray(data), np.asarray(out)[0],
                                   rtol=1e-4, atol=0.5)


class TestMeshResume:
    def run_resumable(self, invs, outdir, **kw):
        return reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(outdir),
            nfft=NFFT, nint=NINT, window_frames=4, resume=True, **kw,
        )

    def test_interrupted_run_resumes_to_identical_product(
        self, tree, tmp_path, monkeypatch
    ):
        from blit.parallel import mesh as M

        _, invs = tree
        golden_dir = tmp_path / "golden"
        golden_dir.mkdir()
        self.run_resumable(invs, golden_dir)
        _, golden = read_fil_data(str(golden_dir / "band0.fil"))

        # Crash mid-stream on the third device window.
        crash_dir = tmp_path / "crash"
        crash_dir.mkdir()
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 3:
                raise RuntimeError("synthetic crash")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError, match="synthetic crash"):
            self.run_resumable(invs, crash_dir)
        # The partial product + cursor sidecar survive the crash.
        out = crash_dir / "band0.fil"
        assert out.exists() and (crash_dir / "band0.fil.cursor").exists()
        _, partial = read_fil_data(str(out), mmap=False)
        assert 0 < partial.shape[0] < golden.shape[0]

        # Resume: continues from the checkpoint, finishes, removes the
        # cursor, and the product is IDENTICAL to the uninterrupted run.
        monkeypatch.setattr(M, "band_reduce", real)
        written = self.run_resumable(invs, crash_dir)
        assert not (crash_dir / "band0.fil.cursor").exists()
        _, data = read_fil_data(str(out))
        np.testing.assert_array_equal(np.asarray(data), np.asarray(golden))
        assert written[0][1]["nsamps"] == golden.shape[0]

    def test_config_change_restarts_from_scratch(self, tree, tmp_path,
                                                 monkeypatch):
        from blit.parallel import mesh as M

        _, invs = tree
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("boom")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError):
            self.run_resumable(invs, tmp_path)
        monkeypatch.setattr(M, "band_reduce", real)
        # Different fqav_by: the cursor must NOT match — the run restarts
        # cleanly instead of splicing incompatible spectra.
        written = self.run_resumable(invs, tmp_path, fqav_by=2,
                                     despike=False)
        _, data = read_fil_data(written[0][0])
        want = host_golden(invs, fqav_by=2)[: data.shape[0]]
        np.testing.assert_allclose(np.asarray(data), want, rtol=1e-4,
                                   atol=1.0)

    def test_h5_bitshuffle_interrupted_resumes_identically(
        self, tree, tmp_path, monkeypatch
    ):
        # The native-format twin of the .fil resume above (VERDICT r4
        # missing item 2): bitshuffle FBH5 band products crash-resume via
        # resize-truncate, decoded payload identical to an uninterrupted
        # run, with chunk rows tied to the window granularity so the
        # pod-agreed restart offset stays chunk-aligned.
        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        from blit.io.fbh5 import read_fbh5_data
        from blit.parallel import mesh as M

        _, invs = tree
        golden_dir = tmp_path / "golden"
        golden_dir.mkdir()
        self.run_resumable(invs, golden_dir, compression="bitshuffle")
        golden = read_fbh5_data(str(golden_dir / "band0.h5"))

        crash_dir = tmp_path / "crash"
        crash_dir.mkdir()
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 3:
                raise RuntimeError("synthetic crash")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError, match="synthetic crash"):
            self.run_resumable(invs, crash_dir, compression="bitshuffle")
        out = crash_dir / "band0.h5"
        assert out.exists() and (crash_dir / "band0.h5.cursor").exists()
        partial = read_fbh5_data(str(out))
        assert 0 < partial.shape[0] < golden.shape[0]

        monkeypatch.setattr(M, "band_reduce", real)
        written = self.run_resumable(invs, crash_dir,
                                     compression="bitshuffle")
        assert not (crash_dir / "band0.h5.cursor").exists()
        np.testing.assert_array_equal(read_fbh5_data(str(out)), golden)
        assert written[0][1]["nsamps"] == golden.shape[0]

    def test_compression_with_fil_paths_rejected_before_collectives(
        self, tree, tmp_path
    ):
        # The mismatch must raise on EVERY process before any collective
        # (out_paths is globally known): a per-band raise would fire only
        # on band-owning processes and deadlock the rest in the window
        # loop.  Exercised here through explicit .fil out_paths.
        _, invs = tree
        with pytest.raises(ValueError, match="uncompressed"):
            reduce_scan_mesh_to_files(
                SESSION, SCAN, inventories=invs,
                out_paths=[str(tmp_path / "band0.fil")],
                nfft=NFFT, nint=NINT, window_frames=4,
                compression="bitshuffle", resume=True,
            )

    def test_h5_window_change_restarts_fresh(self, tree, tmp_path,
                                             monkeypatch):
        # Bitshuffle .h5 chunk rows derive from the window granularity, so
        # a resume under a different --window-frames must restart fresh
        # (window_rows is part of the cursor identity), not die on the
        # writer's chunk-mismatch refusal.
        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        from blit.io.fbh5 import read_fbh5_data
        from blit.parallel import mesh as M

        _, invs = tree
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("boom")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError):
            self.run_resumable(invs, tmp_path, compression="bitshuffle")
        monkeypatch.setattr(M, "band_reduce", real)
        golden_dir = tmp_path / "golden"
        golden_dir.mkdir()
        reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(golden_dir),
            nfft=NFFT, nint=NINT, window_frames=6,
            compression="bitshuffle",
        )
        reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(tmp_path),
            nfft=NFFT, nint=NINT, window_frames=6, resume=True,
            compression="bitshuffle",
        )
        np.testing.assert_array_equal(
            read_fbh5_data(str(tmp_path / "band0.h5")),
            read_fbh5_data(str(golden_dir / "band0.h5")),
        )

    def test_completed_resumable_equals_plain(self, tree, tmp_path):
        _, invs = tree
        plain = tmp_path / "plain"
        res = tmp_path / "res"
        plain.mkdir(), res.mkdir()
        reduce_scan_mesh_to_files(
            SESSION, SCAN, inventories=invs, out_dir=str(plain),
            nfft=NFFT, nint=NINT, window_frames=4,
        )
        self.run_resumable(invs, res)
        _, a = read_fil_data(str(plain / "band0.fil"))
        _, b = read_fil_data(str(res / "band0.fil"))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_despike_flip_restarts_from_scratch(self, tree, tmp_path,
                                                monkeypatch):
        # despike is output-affecting: a resume with the flag flipped must
        # NOT splice despiked and raw spectra (cursor identity includes
        # despike_nfpc).
        from blit.parallel import mesh as M

        _, invs = tree
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("boom")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError):
            self.run_resumable(invs, tmp_path)  # despike=True default
        monkeypatch.setattr(M, "band_reduce", real)
        self.run_resumable(invs, tmp_path, despike=False)
        _, data = read_fil_data(str(tmp_path / "band0.fil"))
        want = host_golden(invs)[: data.shape[0]]  # un-despiked golden
        np.testing.assert_allclose(np.asarray(data), want, rtol=1e-4,
                                   atol=0.5)
