"""Ingest autotuner + tuning profiles (blit/tune.py; ISSUE 8 tentpole).

The convergence tests replace the stopwatch with a SIMULATED stage-cost
model, so they are deterministic on CPU and need no accelerator: the
model encodes a known optimum and the sweep must find it — twice, with
identical trial sequences.
"""

import json
import os

import pytest

jax = pytest.importorskip("jax")

from blit import tune as T  # noqa: E402


def cost_model(optimum, *, scale=1.0):
    """A convex (single-basin) synthetic GB/s surface peaking at
    ``optimum``: each knob contributes a penalty growing with its
    log/step distance from the optimum — the shape real sweeps show
    (too-small chunks pay dispatch overhead, too-deep rotations pay
    memory pressure)."""
    import math

    def measure(knobs):
        pen = 0.0
        pen += abs(math.log2(knobs["chunk_frames"])
                   - math.log2(optimum["chunk_frames"]))
        pen += 0.5 * abs(knobs["prefetch_depth"]
                         - optimum["prefetch_depth"])
        pen += 0.5 * abs(knobs["out_depth"] - optimum["out_depth"])
        return scale * 10.0 / (1.0 + pen)

    return measure


class TestOfflineConvergence:
    def test_converges_to_model_optimum(self):
        opt = {"chunk_frames": 32, "prefetch_depth": 4, "out_depth": 3}
        best, trials = T.tune(
            cost_model(opt),
            base={"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2},
            max_trials=40,
        )
        assert best == opt
        assert len(trials) <= 40

    def test_base_clamped_into_loadable_bounds(self):
        # A caller base above the sweep's own ladder bounds must be
        # clamped BEFORE scoring — otherwise an out-of-range base can
        # win, persist, and be silently rejected by load_profile on
        # every later run (tuning.source reads "default" while the
        # operator believes the rig is tuned).
        best, trials = T.tune(
            lambda k: 1.0,
            base={"chunk_frames": T.MAX_CHUNK_FRAMES * 4,
                  "prefetch_depth": 99, "out_depth": 0},
            max_trials=12,
        )
        assert 0 < best["chunk_frames"] <= T.MAX_CHUNK_FRAMES
        assert T.MIN_DEPTH <= best["prefetch_depth"] <= T.MAX_DEPTH
        assert T.MIN_DEPTH <= best["out_depth"] <= T.MAX_DEPTH
        for t in trials:  # no candidate ever left the loadable range
            assert t["chunk_frames"] <= T.MAX_CHUNK_FRAMES

    def test_deterministic_trial_sequence(self):
        opt = {"chunk_frames": 16, "prefetch_depth": 3, "out_depth": 2}
        runs = [T.tune(cost_model(opt), base={"chunk_frames": 4},
                       max_trials=30) for _ in range(2)]
        assert runs[0][0] == runs[1][0] == opt
        assert runs[0][1] == runs[1][1]  # identical evaluation log

    def test_respects_nint_granularity(self):
        # chunk_frames candidates stay multiples of nint (integration
        # windows must not straddle chunks — the RawReducer contract).
        opt = {"chunk_frames": 24, "prefetch_depth": 2, "out_depth": 2}
        best, trials = T.tune(cost_model(opt), nint=6,
                              base={"chunk_frames": 6}, max_trials=40)
        assert all(t["chunk_frames"] % 6 == 0 for t in trials)
        assert best["chunk_frames"] % 6 == 0

    def test_budget_bounds_measurements(self):
        opt = {"chunk_frames": 1024, "prefetch_depth": 8, "out_depth": 8}
        _, trials = T.tune(cost_model(opt), base={"chunk_frames": 8},
                           max_trials=5)
        assert len(trials) == 5

    def test_marginally_worse_smaller_knob_wins_tie(self):
        # A smaller candidate WITHIN rel_tol of best (even slightly
        # below) is a tie and the smaller knob wins — measurement noise
        # must not ratchet the sweep toward big knobs.
        def measure(k):
            return 1.0 if k["prefetch_depth"] >= 3 else 0.995

        best, _ = T.tune(measure,
                         base={"chunk_frames": 8, "prefetch_depth": 3,
                               "out_depth": 2},
                         max_trials=20, rel_tol=0.01)
        assert best["prefetch_depth"] == T.MIN_DEPTH

    def test_flat_surface_keeps_smaller_knobs(self):
        # Ties (within rel_tol) must prefer the cheaper setting, not
        # drift toward deep rotations that buy nothing.
        best, _ = T.tune(lambda k: 1.0,
                         base={"chunk_frames": 8, "prefetch_depth": 3,
                               "out_depth": 3}, max_trials=30)
        assert best["prefetch_depth"] == T.MIN_DEPTH
        assert best["out_depth"] == T.MIN_DEPTH


class TestProfileStore:
    def _mkprofile(self, **fp_kw):
        key, ident = T.rig_fingerprint(**fp_kw)
        return T.TuningProfile(key=key, rig=ident, chunk_frames=16,
                               prefetch_depth=3, out_depth=4,
                               score_gbps=1.5, trials=9)

    def test_round_trip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        prof = self._mkprofile(nfft=1024, nint=1)
        path = T.save_profile(prof)
        assert os.path.dirname(path) == str(tmp_path)
        got = T.load_profile(prof.key)
        assert got is not None
        assert got.knobs() == prof.knobs()
        assert got.score_gbps == prof.score_gbps
        assert got.rig == prof.rig
        # and through the public lookup:
        hit = T.lookup(nfft=1024, nint=1)
        assert hit is not None and hit.knobs() == prof.knobs()

    def test_missing_and_corrupt_ignored(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        key, _ = T.rig_fingerprint(nfft=512, nint=1)
        assert T.load_profile(key) is None
        with open(T._profile_path(key), "w") as f:
            f.write("{not json")
        assert T.load_profile(key) is None

    def test_corrupt_or_unbounded_knobs_ignored(self, tmp_path,
                                                monkeypatch):
        """The integrity hash covers only the rig identity — knob values
        must be validated separately, and a bad profile must be IGNORED
        (never crash RawReducer construction: reduce/scan/serve/stream
        would all be dead on that rig until the file is deleted)."""
        import json as _json

        from blit.pipeline import RawReducer

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        prof = self._mkprofile(nfft=1024, nint=1)
        path = T.save_profile(prof)
        for bad in (None, "junk", 0, -1, T.MAX_CHUNK_FRAMES * 8):
            doc = _json.load(open(path))
            doc["chunk_frames"] = bad
            with open(path, "w") as f:
                _json.dump(doc, f)
            assert T.load_profile(prof.key) is None, bad
        doc = _json.load(open(path))
        doc["chunk_frames"] = 8
        doc["out_depth"] = T.MAX_DEPTH + 100  # tampered-but-numeric
        with open(path, "w") as f:
            _json.dump(doc, f)
        assert T.load_profile(prof.key) is None
        # And the reducer construction path survives a bad profile for
        # ITS key too (falls back to defaults, no exception).
        key, ident = T.rig_fingerprint(
            **RawReducer(nfft=64, nint=2)._tune_fingerprint_kw())
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=6, prefetch_depth=3,
            out_depth=4))
        p2 = T._profile_path(key)
        doc = _json.load(open(p2))
        doc["chunk_frames"] = None
        with open(p2, "w") as f:
            _json.dump(doc, f)
        red = RawReducer(nfft=64, nint=2)
        assert red.tuning_provenance()["sources"]["chunk_frames"] == \
            "default"

    def test_stale_profile_for_other_rig_ignored(self, tmp_path,
                                                 monkeypatch):
        # Regression pin (ISSUE 8 satellite): a profile copied from a
        # different rig fingerprint must be IGNORED, not trusted.  Write
        # a valid profile, then store it under the key of a DIFFERENT
        # workload shape — load must reject the identity mismatch.
        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        prof = self._mkprofile(nfft=1024, nint=1)
        other_key, _ = T.rig_fingerprint(nfft=2048, nint=1)
        prof.key = other_key  # content no longer hashes to its key
        T.save_profile(prof)
        assert T.load_profile(other_key) is None
        assert T.lookup(nfft=2048, nint=1) is None

    def test_workload_shape_selects_profile(self, tmp_path, monkeypatch):
        # Different nfft → different key → no crosstalk.
        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        T.save_profile(self._mkprofile(nfft=1024, nint=1))
        assert T.lookup(nfft=1024, nint=1) is not None
        assert T.lookup(nfft=4096, nint=1) is None
        assert T.lookup(nfft=1024, nint=16) is None

    def test_site_config_tune_dir_applies_without_explicit_config(
            self, tmp_path, monkeypatch):
        """SiteConfig.tune_dir must govern the default (config=None)
        path every production caller uses — not just an explicitly
        passed config object (the hostmem staging_pool_bytes rule).
        Env still wins."""
        from blit import config as C

        monkeypatch.delenv("BLIT_TUNE_DIR", raising=False)
        monkeypatch.setattr(C.DEFAULT, "tune_dir", str(tmp_path / "site"))
        assert T.profile_dir() == str(tmp_path / "site")
        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path / "env"))
        assert T.profile_dir() == str(tmp_path / "env")

    def test_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        T.save_profile(self._mkprofile(nfft=1024, nint=1))
        monkeypatch.setenv("BLIT_TUNE", "0")
        assert T.lookup(nfft=1024, nint=1) is None


class TestReducerAutoload:
    def test_reducer_loads_profile_automatically(self, tmp_path,
                                                 monkeypatch):
        from blit.pipeline import RawReducer

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        red0 = RawReducer(nfft=64, nint=2)  # no profile yet: defaults
        assert red0.tuning_provenance()["sources"]["chunk_frames"] == \
            "default"
        key, ident = T.rig_fingerprint(
            **RawReducer(nfft=64, nint=2)._tune_fingerprint_kw())
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=6, prefetch_depth=3,
            out_depth=4))
        red = RawReducer(nfft=64, nint=2)
        assert (red.chunk_frames, red.prefetch_depth, red.out_depth) == \
            (6, 3, 4)
        prov = red.tuning_provenance()
        assert prov["sources"] == {k: "profile" for k in T.KNOBS}
        assert prov["profile"]["key"] == key
        # Explicit knobs always win over the profile.
        red2 = RawReducer(nfft=64, nint=2, chunk_frames=8,
                          prefetch_depth=2)
        assert red2.chunk_frames == 8 and red2.prefetch_depth == 2
        assert red2.out_depth == 4  # unset knob still resolves from it
        # And the kill switch restores the defaults.
        monkeypatch.setenv("BLIT_TUNE", "0")
        red3 = RawReducer(nfft=64, nint=2)
        assert red3.chunk_frames != 6 and red3.prefetch_depth == 2

    def test_profile_chunk_frames_rounded_to_nint(self, tmp_path,
                                                  monkeypatch):
        from blit.pipeline import RawReducer

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        key, ident = T.rig_fingerprint(
            **RawReducer(nfft=64, nint=4)._tune_fingerprint_kw())
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=6, prefetch_depth=2,
            out_depth=2))
        red = RawReducer(nfft=64, nint=4)
        assert red.chunk_frames % 4 == 0  # the nint rounding still runs

    def test_profile_nchan_mismatch_warns_once(self, tmp_path, monkeypatch,
                                               caplog):
        """nchan is deliberately NOT in the fingerprint key (lookup
        happens before any recording is open) — so a profile measured on
        a different-width recording must at least announce itself: one
        warning per stream plus a provenance block naming both widths."""
        import logging

        from blit.pipeline import RawReducer

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        key, ident = T.rig_fingerprint(
            **RawReducer(nfft=64, nint=2)._tune_fingerprint_kw())
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=6, prefetch_depth=3,
            out_depth=4, tuned_nchan=8))
        red = RawReducer(nfft=64, nint=2)
        with caplog.at_level(logging.WARNING, logger="blit.pipeline"):
            red._note_stream_nchan(2)
            red._note_stream_nchan(2)  # same stream width: no repeat
        warns = [r for r in caplog.records
                 if "tuning profile" in r.getMessage()]
        assert len(warns) == 1
        assert red.tuning_provenance()["profile_nchan_mismatch"] == {
            "tuned": 8, "stream": 2}
        # Matching width, or a legacy profile (tuned_nchan=0), is silent.
        red2 = RawReducer(nfft=64, nint=2)
        red2._note_stream_nchan(8)
        assert "profile_nchan_mismatch" not in red2.tuning_provenance()

    def test_search_reducer_inherits_profile(self, tmp_path, monkeypatch):
        from blit.pipeline import RawReducer
        from blit.search import DedopplerReducer

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        key, ident = T.rig_fingerprint(
            **RawReducer(nfft=128, nint=1)._tune_fingerprint_kw())
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=8, prefetch_depth=4,
            out_depth=5))
        red = DedopplerReducer(nfft=128, nint=1, window_spectra=8)
        assert (red.prefetch_depth, red.out_depth) == (4, 5)


class TestOnlineTuner:
    def _stages(self, *, disp, dev, ingest=0.0, wall=1.0, calls=8):
        return {
            "dispatch": {"seconds": disp * calls, "calls": calls},
            "device": {"seconds": dev * calls, "calls": calls},
            "ingest": {"seconds": ingest, "calls": calls},
            "stream": {"seconds": wall, "calls": 1},
        }

    def test_dispatch_bound_doubles_chunk(self):
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        rec = T.recommend_from_stages(
            self._stages(disp=0.5, dev=1.0), {}, cur)
        assert rec.knobs["chunk_frames"] == 16
        assert any("dispatch-bound" in r for r in rec.reasons)

    def test_readback_lag_deepens_out(self):
        # PERSISTENT lag (median, not a single burst) is the deepen
        # signal — p99 over ~8 warmup samples is just the max, and chunk
        # 1's compile-sized sample would trip it on every cold run.
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        hists = {
            "out.readback_lag_s": {"n": 8, "p50": 0.2, "p99": 0.5},
            "out.chunk_latency_s": {"n": 8, "p50": 0.05, "p99": 0.1},
        }
        rec = T.recommend_from_stages(
            self._stages(disp=0.01, dev=1.0), hists, cur)
        assert rec.knobs["out_depth"] == 3
        # One outlier in an otherwise healthy plane does NOT deepen.
        hists["out.readback_lag_s"] = {"n": 8, "p50": 0.05, "p99": 5.0}
        rec = T.recommend_from_stages(
            self._stages(disp=0.01, dev=1.0), hists, cur)
        assert rec.knobs["out_depth"] == 2

    def test_producer_bound_deepens_prefetch(self):
        # Per-chunk file read dominates per-chunk hidden work — and the
        # rule must hold MID-STREAM, where the 'stream' wall stage has
        # not yet closed (its seconds read 0 until stream end).
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        stages = self._stages(disp=0.01, dev=0.1, ingest=8 * 0.5, wall=0.0)
        rec = T.recommend_from_stages(stages, {}, cur)
        assert rec.knobs["prefetch_depth"] == 3
        assert any("producer-bound" in r for r in rec.reasons)

    def test_balanced_plane_changes_nothing(self):
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        rec = T.recommend_from_stages(
            self._stages(disp=0.01, dev=1.0), {}, cur)
        assert rec.knobs == cur and rec.reasons == []

    def test_converges_during_first_windows(self):
        # The tuner reads the timeline ONCE, at the warmup boundary, and
        # publishes tune.rec_* gauges — then goes dormant.
        from blit.observability import Timeline

        tl = Timeline()
        with tl.stage("stream"):
            pass
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        tuner = T.OnlineTuner(tl, cur, warmup_chunks=4)
        for i in range(4):
            tl.stages["dispatch"].calls += 1
            tl.stages["dispatch"].seconds += 0.5
            tl.stages["device"].calls += 1
            tl.stages["device"].seconds += 1.0
            tuner.observe_chunk()
            assert tuner.converged == (i == 3)
        assert tuner.recommendation.knobs["chunk_frames"] == 16
        assert tl.gauges["tune.rec_chunk_frames"].last == 16.0

    def test_first_chunk_compile_excluded(self):
        # Chunk 1's dispatch stage includes the XLA compile; a cold run
        # must not look dispatch-bound because of it (regression: the
        # online recommendation doubled chunk_frames on every cold run,
        # ratcheting the persisted profile x2 per run under
        # BLIT_TUNE_ONLINE=1).
        from blit.observability import Timeline

        tl = Timeline()
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        tuner = T.OnlineTuner(tl, cur, warmup_chunks=4)
        for i in range(4):
            tl.stages["dispatch"].calls += 1
            tl.stages["dispatch"].seconds += 5.0 if i == 0 else 0.01
            tl.stages["device"].calls += 1
            tl.stages["device"].seconds += 1.0
            tuner.observe_chunk()
            # REAL pipeline ordering: the readback thread records chunk
            # i's lag AFTER observe_chunk(i) — so chunk 1's
            # compile-sized sample lands after the tuner's snapshot and
            # survives the hist delta.  The median-based heuristic must
            # shrug it off anyway.
            tl.observe("out.readback_lag_s", 5.0 if i == 0 else 0.001)
            tl.observe("out.chunk_latency_s", 0.01)
        assert tuner.converged
        assert tuner.recommendation.knobs == cur  # compile not counted

    def test_persistence_is_opt_in(self, tmp_path, monkeypatch):
        from blit.observability import Timeline

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        monkeypatch.delenv("BLIT_TUNE_ONLINE", raising=False)
        tl = Timeline()
        cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
        tuner = T.OnlineTuner(tl, cur, warmup_chunks=2)
        for _ in range(2):
            tl.stages["dispatch"].calls += 1
            tl.stages["dispatch"].seconds += 0.5
            tl.stages["device"].calls += 1
            tl.stages["device"].seconds += 1.0
            tuner.observe_chunk()
        assert tuner.converged
        assert tuner.maybe_persist(nfft=64, nint=1) is None
        assert os.listdir(tmp_path) == []
        monkeypatch.setenv("BLIT_TUNE_ONLINE", "1")
        path = tuner.maybe_persist(nfft=64, nint=1)
        assert path is not None and os.path.exists(path)
        prof = T.lookup(nfft=64, nint=1)
        assert prof is not None and prof.source == "online"
        assert prof.chunk_frames == 16

    def test_online_never_clobbers_measured_offline(self, tmp_path,
                                                    monkeypatch):
        # A `blit tune` sweep MEASURED its knobs; the online heuristic is
        # one warmup window, possibly under a transient load spike.  With
        # BLIT_TUNE_ONLINE=1 the recommendation must not replace the
        # measured profile at the same key — but may replace a prior
        # ONLINE profile (heuristic vs heuristic: newest wins).
        from blit.observability import Timeline

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        monkeypatch.setenv("BLIT_TUNE_ONLINE", "1")
        key, ident = T.rig_fingerprint(nfft=64, nint=1)
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=4, prefetch_depth=2,
            out_depth=2, score_gbps=1.5, source="offline"))

        def converged_tuner():
            tl = Timeline()
            cur = {"chunk_frames": 8, "prefetch_depth": 2, "out_depth": 2}
            tuner = T.OnlineTuner(tl, cur, warmup_chunks=2)
            for _ in range(2):
                tl.stages["dispatch"].calls += 1
                tl.stages["dispatch"].seconds += 0.5
                tl.stages["device"].calls += 1
                tl.stages["device"].seconds += 1.0
                tuner.observe_chunk()
            assert tuner.converged
            return tuner

        assert converged_tuner().maybe_persist(nfft=64, nint=1) is None
        prof = T.load_profile(key)
        assert prof.source == "offline" and prof.chunk_frames == 4
        # An online profile at the key IS replaceable.
        T.save_profile(T.TuningProfile(
            key=key, rig=ident, chunk_frames=4, prefetch_depth=2,
            out_depth=2, source="online"))
        assert converged_tuner().maybe_persist(nfft=64, nint=1) is not None
        assert T.load_profile(key).chunk_frames == 16

    def test_online_profile_feeds_next_run(self, tmp_path, monkeypatch):
        # End to end: a reduction run under BLIT_TUNE_ONLINE=1 persists
        # its converged recommendation; the NEXT reducer construction
        # picks it up automatically.
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path))
        monkeypatch.setenv("BLIT_TUNE_ONLINE", "1")
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=4096)
        red = RawReducer(nfft=64, nint=1, chunk_frames=4)
        red.reduce_to_file(p, str(tmp_path / "x.fil"))
        # Whatever the tuner decided, a persisted profile (if its
        # recommendation moved a knob) must round-trip into a fresh
        # reducer; a no-move run persists nothing and defaults hold.
        prof = T.lookup(**red._tune_fingerprint_kw())
        red2 = RawReducer(nfft=64, nint=1)
        if prof is not None:
            assert red2.chunk_frames == prof.chunk_frames
        else:
            assert red2.tuning_provenance()["sources"]["chunk_frames"] \
                == "default"


class TestTuneCLI:
    def test_tune_then_scan_loads_profile(self, tmp_path, monkeypatch,
                                          capsys):
        """The acceptance pin: `blit tune` writes a profile; a
        subsequent `blit scan` on the same rig (same workload shape,
        no --window-frames) loads it automatically and reports the
        provenance."""
        from blit.__main__ import main
        from blit.testing import build_observation_tree

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path / "profiles"))
        rc = main(["tune", "--nfft", "64", "--nint", "2", "--nchan", "2",
                   "--chunk-frames", "4", "--chunks", "2", "--blocks", "2",
                   "--trials", "3"])
        out = capsys.readouterr().out
        assert rc == 0
        rep = json.loads(out)
        assert os.path.exists(rep["profile"])
        assert rep["trials"] and rep["winner"]

        root = str(tmp_path / "datax")
        build_observation_tree(root, kind="raw", players=((0, 0), (0, 1)),
                               nchans=2, nfiles=2, raw_ntime=512)
        rc = main(["scan", root, "AGBT22B_999_01", "0011",
                   "-o", str(tmp_path), "--nfft", "64", "--nint", "2"])
        out = capsys.readouterr().out
        assert rc == 0
        stats = json.loads(out.strip().splitlines()[-1])
        assert stats["tuning"]["source"] == "profile"
        assert stats["tuning"]["key"] == rep["key"]
        # The executed window is the profile's chunk_frames (nint-rounded).
        want = max((rep["winner"]["chunk_frames"] // 2) * 2, 2)
        assert stats["window_frames"] == want

    def test_reduce_uses_profile_after_tune(self, tmp_path, monkeypatch,
                                            capsys):
        from blit.__main__ import main
        from blit.pipeline import RawReducer

        monkeypatch.setenv("BLIT_TUNE_DIR", str(tmp_path / "profiles"))
        rc = main(["tune", "--nfft", "64", "--nint", "1", "--nchan", "2",
                   "--chunk-frames", "4", "--chunks", "2", "--blocks", "2",
                   "--trials", "3"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 0
        red = RawReducer(nfft=64, nint=1)
        assert red.chunk_frames == rep["winner"]["chunk_frames"]
        assert red.prefetch_depth == rep["winner"]["prefetch_depth"]
        assert red.out_depth == rep["winner"]["out_depth"]
