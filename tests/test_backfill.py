"""``blit backfill`` (ISSUE 19 tentpole #3): walk an archive root,
derive + publish every product, resumable via the fsync-before-claim
completion ledger — a kill mid-run never re-derives completed products
on resume and always finishes byte-identical to an uninterrupted run;
torn ledger tail lines and the publish→claim crash window both fail
toward re-work, never toward fake completion."""

import glob
import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytest.importorskip("jax")

from blit.__main__ import main  # noqa: E402
from blit.testing import build_observation_tree  # noqa: E402

SESSION = "AGBT25A_999_01"
NFFT = 16
RAW_NTIME = 64  # x2 blocks/file = 8 frames at nfft=16


@pytest.fixture
def archive(tmp_path):
    root = str(tmp_path / "archive")
    build_observation_tree(root, SESSION, scans=("0001", "0002"),
                           players=((0, 0), (0, 1)), kind="raw",
                           nchans=2, raw_ntime=RAW_NTIME, nfiles=1)
    return root


def run_backfill(archive, cache_dir, *extra):
    out = cache_dir + ".report.json"
    rc = main(["backfill", archive, "--cache-dir", cache_dir,
               "--nfft", str(NFFT), "--bytes-per-s", "0",
               "--json-out", out, *extra])
    with open(out) as f:
        return rc, json.load(f)


def cache_digests(cache_dir):
    return {os.path.basename(p):
            hashlib.sha256(open(p, "rb").read()).hexdigest()
            for p in glob.glob(os.path.join(cache_dir, "*.h5"))}


class TestBackfill:
    def test_full_run_derives_every_product(self, tmp_path, archive):
        rc, rep = run_backfill(archive, str(tmp_path / "cache"))
        assert rc == 0
        assert rep["products_total"] == 4  # 2 scans x 2 players
        assert rep["derived"] == 4 and not rep["errors"]
        assert len(cache_digests(str(tmp_path / "cache"))) == 4

    def test_rerun_is_a_ledger_noop(self, tmp_path, archive):
        cd = str(tmp_path / "cache")
        run_backfill(archive, cd)
        rc, rep = run_backfill(archive, cd)
        assert rc == 0
        assert rep["derived"] == 0
        assert rep["skipped_ledger"] == rep["products_total"] == 4

    def test_interrupted_resume_matches_uninterrupted(self, tmp_path,
                                                      archive):
        one = str(tmp_path / "one-shot")
        run_backfill(archive, one)
        resumed = str(tmp_path / "resumed")
        rc, rep = run_backfill(archive, resumed, "--limit", "2")
        assert rc == 0 and rep["derived"] == 2
        rc, rep = run_backfill(archive, resumed)
        assert rc == 0
        assert rep["skipped_ledger"] == 2 and rep["derived"] == 2
        assert cache_digests(one) == cache_digests(resumed)

    def test_torn_ledger_tail_rederives_not_trusts(self, tmp_path,
                                                   archive):
        cd = str(tmp_path / "cache")
        run_backfill(archive, cd, "--limit", "2")
        ledger = os.path.join(cd, "backfill.ledger.jsonl")
        lines = open(ledger).read().splitlines()
        torn = lines[-1][: len(lines[-1]) // 2]  # half a record
        with open(ledger, "w") as f:
            f.write("\n".join(lines[:-1]) + "\n" + torn)
        rc, rep = run_backfill(archive, cd)
        assert rc == 0
        # The torn claim does not count as completed — its product is
        # found already published (the publish→claim window) and is
        # re-CLAIMED without re-deriving.
        assert rep["skipped_ledger"] == 1
        assert rep["skipped_cached"] == 1
        assert rep["derived"] == 2
        # The healed ledger now covers everything.
        rc, rep = run_backfill(archive, cd)
        assert rep["skipped_ledger"] == 4

    def test_publish_claim_window_claims_without_rederive(
            self, tmp_path, archive):
        cd = str(tmp_path / "cache")
        run_backfill(archive, cd)
        digests = cache_digests(cd)
        ledger = os.path.join(cd, "backfill.ledger.jsonl")
        lines = open(ledger).read().splitlines()
        with open(ledger, "w") as f:
            f.write("\n".join(lines[:-1]) + "\n")
        mtimes = {p: os.path.getmtime(p)
                  for p in glob.glob(os.path.join(cd, "*.h5"))}
        rc, rep = run_backfill(archive, cd)
        assert rc == 0
        assert rep["skipped_cached"] == 1 and rep["derived"] == 0
        assert cache_digests(cd) == digests
        # Published files untouched — the claim is ledger-only.
        assert mtimes == {p: os.path.getmtime(p) for p in mtimes}

    def test_sigkill_drill_resumes_byte_identical(self, tmp_path,
                                                  archive):
        # The acceptance kill drill, for real: pace the walker hard so
        # each product sleeps off a large debt, SIGKILL it after the
        # first claim lands, then resume unpaced — completed products
        # are not re-derived and the result matches an uninterrupted
        # run byte for byte.
        one = str(tmp_path / "one-shot")
        run_backfill(archive, one)
        cd = str(tmp_path / "killed")
        ledger = os.path.join(cd, "backfill.ledger.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "blit", "backfill", archive,
             "--cache-dir", cd, "--nfft", str(NFFT),
             "--bytes-per-s", "10"],  # ~minutes of debt per product
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env)
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if os.path.exists(ledger) and open(ledger).read().count(
                        "\n") >= 1:
                    break
                if proc.poll() is not None:
                    pytest.fail("backfill exited before the kill")
                time.sleep(0.05)
            else:
                pytest.fail("first claim never landed")
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(30)
        claimed_before = open(ledger).read().count("\n")
        assert claimed_before >= 1
        rc, rep = run_backfill(archive, cd)
        assert rc == 0
        assert rep["skipped_ledger"] + rep["skipped_cached"] >= claimed_before
        assert rep["derived"] <= 4 - claimed_before
        assert cache_digests(one) == cache_digests(cd)

    def test_errors_are_reported_not_fatal(self, tmp_path, archive):
        # A rotted member errors THAT product and keeps going — rc 1,
        # the rest derived.  (The crawl indexes by NAME; the rot is
        # only discovered when the reduce opens the recording.)
        victims = glob.glob(os.path.join(
            archive, SESSION, "GUPPI", "BLP01", "*_0002.0000.raw"))
        assert victims
        with open(victims[0], "wb") as f:
            f.write(b"not a GUPPI recording")
        cd = str(tmp_path / "cache")
        rc, rep = run_backfill(archive, cd)
        assert rc == 1
        assert len(rep["errors"]) == 1 and "BLP01" in rep["errors"][0]
        assert rep["derived"] == 3
        assert rep["products_total"] == 4
