"""Child process for the 2-process SHARDED-scan pod test
(tests/test_multiprocess.py): ``reduce_scan_sharded_to_files`` — the
fully-threaded sharded reduction plane (per-shard pinned feeds, async
addressable-shard readback, write-behind sinks) — executed for real
under ``jax.distributed``, each process feeding only its own players'
files and writing only its own band rows' products.

Run as: ``python tests/_mh_sharded_child.py <pid> <nproc> <port> <outdir>``.

The parent byte-compares the pod's products against the single-process
pool-path oracle over the identical synthetic scan (same seeds) —
the ISSUE 9 byte-identity contract, under real multi-host sharding.
Follows the PR 8 deflake discipline: ``signal_ready`` barrier marker
after ``init_multihost``, output to parent-redirected files.
"""

import json
import os
import sys


def main() -> None:
    pid, nproc, port, outdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from blit.parallel.multihost import init_multihost, local_players

    active = init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cpu_collectives="gloo",
    )
    assert active and jax.process_count() == nproc

    # Bring-up barrier marker (tests/test_multiprocess.py).
    from blit.testing import signal_ready

    signal_ready(outdir, pid)

    from blit.observability import Timeline
    from blit.parallel import mesh as M
    from blit.parallel.sharded import reduce_scan_sharded_to_files
    from blit.testing import synth_raw

    NBAND, NBANK, NFFT, NINT, NCHAN = 2, 4, 32, 2, 2
    mesh = M.make_mesh(NBAND, NBANK)
    local = sorted(local_players(mesh))

    # Write ONLY this process's players' files, into a private directory:
    # the grid entries for non-local players name files that do not exist
    # here, proving the sharded feed never touches them.
    priv = os.path.join(outdir, f"proc{pid}")
    os.makedirs(priv, exist_ok=True)
    bank_bw = -187.5 / NBANK
    paths = [
        [os.path.join(priv, f"blc{b}{k}.raw") for k in range(NBANK)]
        for b in range(NBAND)
    ]
    for b, k in local:
        synth_raw(
            paths[b][k], nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
            seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
            obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw,
        )

    # Shared product directory: bands are disjointly owned (the bank-0
    # chip's process writes the row), so the two children never collide.
    prod = os.path.join(outdir, "products")
    os.makedirs(prod, exist_ok=True)
    tl = Timeline()
    written = reduce_scan_sharded_to_files(
        paths, out_dir=prod, nfft=NFFT, nint=NINT, despike=False,
        window_frames=4, mesh=mesh, timeline=tl,
    )
    assert written, "every process of this 2x4 pod owns a band row"
    for band, (path, hdr) in written.items():
        assert os.path.exists(path), path
        assert hdr["nchans"] == NBANK * NCHAN * NFFT, hdr

    # Every window moved ICI bytes through the cross-bank stitch.
    assert tl.stages["mesh.ici"].calls > 0
    assert tl.stages["mesh.ici"].bytes > 0

    with open(os.path.join(outdir, f"proc{pid}.json"), "w") as f:
        json.dump(
            {
                "local": [list(x) for x in local],
                "bands": sorted(written),
                "nsamps": {
                    str(b): int(h["nsamps"])
                    for b, (_, h) in written.items()
                },
            },
            f,
        )
    print("CHILD-SHARDED-OK", flush=True)


if __name__ == "__main__":
    main()
