"""Crash-resumable FBH5 products (VERDICT r4 missing item 2): BL's native
product format (src/gbtworkerfunctions.jl:141-155) must survive a crash the
way ``.fil`` products do — cursor sidecar, resize-truncate to the last
durable slab, decoded payload identical to an uninterrupted run."""

import contextlib
import os

import numpy as np
import pytest

pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.faults import FaultRule  # noqa: E402
from blit.io.fbh5 import ResumableFBH5Writer, read_fbh5_data  # noqa: E402
from blit.pipeline import RawReducer, ReductionCursor  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

HDR = {"fch1": 8000.0, "foff": -0.1, "tsamp": 1.0, "nbits": 32,
       "source_name": "SYNTH"}


def make_red():
    return RawReducer(nfft=64, nint=2, chunk_frames=4)


@pytest.fixture
def raw(tmp_path):
    p = str(tmp_path / "x.raw")
    synth_raw(p, nblocks=4, obsnchan=2, ntime_per_block=1024, tone_chan=1)
    return p


class Boom(Exception):
    pass


@contextlib.contextmanager
def crash_after(n_slabs):
    """Crash the product path after exactly ``n_slabs`` slab appends
    landed, via the write-behind sink's fault-injection point (ISSUE 4:
    the async output plane moved the append onto a writer thread, so the
    realistic crash seam is ``sink.write`` — the failure is recorded
    writer-side and re-raises clean on the consumer thread)."""
    faults.install(FaultRule(point="sink.write", mode="fail",
                             after=n_slabs, times=-1, exc=Boom))
    try:
        yield
    finally:
        faults.clear()
        faults.reset_counters()


def test_cursor_sidecar_paths_in_lockstep():
    # blit.io.fbh5 dodges a pipeline dependency by duplicating the
    # sidecar naming rule; this pin keeps the two in lockstep.
    from blit.io.fbh5 import _cursor_path

    assert _cursor_path("/x/y.h5") == ReductionCursor.path_for("/x/y.h5")


def test_cursor_matches_is_member_order_insensitive(tmp_path):
    # Regression (ISSUE 3 satellite): a multi-file scan sequence is the
    # same recording whatever order a glob listed its members in —
    # open_raw sorts members before reading — so a cursor recorded under
    # one ordering must match a resume (and a cache fingerprint) under
    # another.  Before the fix, matches() compared the path/stat lists
    # positionally and any reordering forced a spurious fresh start.
    paths = []
    for i in range(3):
        p = str(tmp_path / f"x.{i:04d}.raw")
        synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=256, seed=i)
        paths.append(p)
    red = make_red()
    size, mtime_ns = ReductionCursor.stat_raw(paths)
    cur = ReductionCursor(paths, red.nfft, red.ntap, red.nint, red.stokes,
                          window=red.window, raw_size=size,
                          raw_mtime_ns=mtime_ns)
    assert cur.matches(red, paths)
    assert cur.matches(red, list(reversed(paths)))
    assert cur.matches(red, [paths[1], paths[2], paths[0]])
    # Still a real identity check: a different member set must NOT match.
    assert not cur.matches(red, paths[:2])
    other = str(tmp_path / "x.0003.raw")
    synth_raw(other, nblocks=1, obsnchan=2, ntime_per_block=256, seed=9)
    assert not cur.matches(red, [paths[0], paths[1], other])


class TestWriterDurability:
    """ResumableFBH5Writer's own contract, driven directly."""

    def test_plain_checkpoints_every_append(self, tmp_path):
        p = str(tmp_path / "x.h5")
        cur = ReductionCursor(p, 64, 4, 2, "I")
        w = ResumableFBH5Writer(p, HDR, 2, 16, 0, 2, cur)
        data = np.random.default_rng(0).standard_normal(
            (10, 2, 16)).astype(np.float32)
        w.append(data[:6])
        assert cur.frames_done == 12  # 6 rows * nint, claimed immediately
        assert ReductionCursor.load(p).frames_done == 12
        w.append(data[6:])
        w.close()
        np.testing.assert_array_equal(read_fbh5_data(p), data)
        assert not os.path.exists(ReductionCursor.path_for(p))

    def test_bitshuffle_claims_only_flushed_chunks(self, tmp_path):
        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        p = str(tmp_path / "x.h5")
        cur = ReductionCursor(p, 64, 4, 2, "I")
        w = ResumableFBH5Writer(p, HDR, 2, 16, 0, 2, cur,
                                compression="bitshuffle",
                                chunks=(4, 2, 16))
        data = np.random.default_rng(1).standard_normal(
            (11, 2, 16)).astype(np.float32)
        w.append(data[:6])  # one full chunk (4) + 2 buffered
        assert cur.frames_done == 4 * 2  # chunk-aligned claim only
        w.append(data[6:9])  # 5 buffered -> one more chunk, 1 buffered
        assert cur.frames_done == 8 * 2
        # A crash here loses only the buffered row; the claim is durable.
        w.abort()
        cur2 = ReductionCursor.load(p)
        assert cur2.frames_done == 16
        # Resume from the claim and finish.
        w2 = ResumableFBH5Writer(p, HDR, 2, 16, 8, 2, cur2,
                                 compression="bitshuffle",
                                 chunks=(4, 2, 16))
        w2.append(data[8:])
        w2.close()
        np.testing.assert_array_equal(read_fbh5_data(p), data)

    def test_resume_truncates_unclaimed_tail(self, tmp_path):
        p = str(tmp_path / "x.h5")
        cur = ReductionCursor(p, 64, 4, 2, "I")
        w = ResumableFBH5Writer(p, HDR, 1, 8, 0, 2, cur)
        a = np.arange(6 * 8, dtype=np.float32).reshape(6, 1, 8)
        w.append(a)
        w.abort()
        # Tamper: pretend the last 2 rows were never claimed (crash between
        # data landing and cursor save is the other direction and is
        # covered by the fsync-before-cursor ordering).
        cur2 = ReductionCursor.load(p)
        start = (cur2.frames_done // 2) - 2
        w2 = ResumableFBH5Writer(p, HDR, 1, 8, start, 2, cur2)
        assert w2.nsamps == 4
        b = 100 + np.arange(2 * 8, dtype=np.float32).reshape(2, 1, 8)
        w2.append(b)
        w2.close()
        got = read_fbh5_data(p)
        np.testing.assert_array_equal(got[:4], a[:4])
        np.testing.assert_array_equal(got[4:], b)

    def test_bitshuffle_refuses_misaligned_restart(self, tmp_path):
        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        p = str(tmp_path / "x.h5")
        cur = ReductionCursor(p, 64, 4, 2, "I")
        with pytest.raises(ValueError, match="aligned"):
            ResumableFBH5Writer(p, HDR, 2, 16, 3, 2, cur,
                                compression="bitshuffle", chunks=(4, 2, 16))

    def test_resume_refuses_filter_mismatch(self, tmp_path):
        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        p = str(tmp_path / "x.h5")
        cur = ReductionCursor(p, 64, 4, 2, "I")
        w = ResumableFBH5Writer(p, HDR, 2, 16, 0, 2, cur, chunks=(4, 2, 16))
        w.append(np.zeros((4, 2, 16), np.float32))
        w.abort()
        # Writing bitshuffle payloads through a plain pipeline would store
        # undecodable chunks; the writer must refuse, not corrupt.
        with pytest.raises(ValueError, match="filter"):
            ResumableFBH5Writer(p, HDR, 2, 16, 4, 2,
                                ReductionCursor.load(p),
                                compression="bitshuffle", chunks=(4, 2, 16))


class TestReduceResumableH5:
    @pytest.mark.parametrize("compression", [None, "bitshuffle"])
    def test_fresh_run_equals_plain_reduction(self, tmp_path, raw,
                                              compression):
        out = str(tmp_path / "x.h5")
        hdr = make_red().reduce_resumable(raw, out, compression=compression)
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)
        assert hdr["nsamps"] == want.shape[0]
        assert not os.path.exists(ReductionCursor.path_for(out))

    @pytest.mark.parametrize("compression", [None, "bitshuffle"])
    def test_interrupted_run_resumes_identically(self, tmp_path, raw,
                                                 compression):
        out = str(tmp_path / "x.h5")
        # chunks sized so each slab (chunk_frames=4 / nint=2 = 2 rows)
        # flushes a whole bitshuffle chunk — the claim is then non-zero
        # after one slab for both codecs.
        chunks = (2, 1, 128)
        with crash_after(1), pytest.raises(Boom):
            make_red().reduce_resumable(raw, out, compression=compression,
                                        chunks=chunks)
        cur = ReductionCursor.load(out)
        assert cur is not None and cur.frames_done == 4  # one slab landed
        assert cur.compression == (compression or "none")

        make_red().reduce_resumable(raw, out, compression=compression,
                                    chunks=chunks)
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)
        assert not os.path.exists(ReductionCursor.path_for(out))

    def test_bitshuffle_default_chunks_resume_restarts_clean(self, tmp_path,
                                                             raw):
        # With the default 16-row chunks a 2-row slab never completes a
        # chunk before the crash: the claim is legitimately 0 and the
        # resume is a clean fresh start, not a corrupt splice.
        out = str(tmp_path / "x.h5")
        with crash_after(1), pytest.raises(Boom):
            make_red().reduce_resumable(raw, out, compression="bitshuffle")
        assert ReductionCursor.load(out).frames_done == 0
        make_red().reduce_resumable(raw, out, compression="bitshuffle")
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)

    def test_compression_flip_restarts_fresh(self, tmp_path, raw):
        out = str(tmp_path / "x.h5")
        with crash_after(1), pytest.raises(Boom):
            make_red().reduce_resumable(raw, out)
        # Same config, different codec: identity mismatch -> fresh start
        # (NOT the writer's filter-mismatch refusal, and NOT corruption).
        make_red().reduce_resumable(raw, out, compression="bitshuffle")
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)

    def test_chunks_flip_restarts_fresh(self, tmp_path, raw):
        # chunks= is part of the resume identity for the same reason as
        # compression: the dataset's chunk grid is fixed at creation, so
        # a mismatch must restart fresh — not die on the writer's
        # chunk-mismatch refusal.
        out = str(tmp_path / "x.h5")
        with crash_after(1), pytest.raises(Boom):
            make_red().reduce_resumable(raw, out, chunks=(2, 1, 128))
        make_red().reduce_resumable(raw, out)  # default chunks
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)

    def test_tampered_raw_restarts_fresh(self, tmp_path, raw):
        out = str(tmp_path / "x.h5")
        with crash_after(1), pytest.raises(Boom):
            make_red().reduce_resumable(raw, out)
        # Replace the recording with a DIFFERENT valid one (new mtime and
        # payload): the cursor's input identity no longer matches, so the
        # resume must restart fresh and reduce the new bytes.
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=1024,
                  tone_chan=0, seed=7)
        make_red().reduce_resumable(raw, out)
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)


class TestCorruptTargetFallback:
    """ADVICE r5 medium: libhdf5 metadata updates between checkpoints are
    not crash-atomic — a SIGKILL can leave a target the resume path cannot
    open while the cursor sidecar still parses.  The resume must fall back
    to a fresh start (identity-mismatch behavior), never raise."""

    def test_probe_rejects_garbage_and_accepts_good(self, tmp_path):
        from blit.io.fbh5 import resume_target_ok
        from blit.io.fbh5 import write_fbh5

        good = str(tmp_path / "good.h5")
        data = np.random.default_rng(0).standard_normal(
            (6, 1, 8)).astype(np.float32)
        write_fbh5(good, HDR, data)
        assert resume_target_ok(good, 1, 8, 6)
        assert not resume_target_ok(good, 1, 8, 7)  # claims > rows
        assert not resume_target_ok(good, 2, 8, 4)  # wrong geometry
        bad = str(tmp_path / "bad.h5")
        with open(bad, "wb") as f:
            f.write(b"\x00not hdf5 at all" * 64)
        assert not resume_target_ok(bad, 1, 8, 1)
        assert not resume_target_ok(str(tmp_path / "absent.h5"), 1, 8, 1)

    def test_corrupt_target_restarts_fresh(self, tmp_path, raw, caplog):
        import logging

        out = str(tmp_path / "x.h5")
        with crash_after(1), pytest.raises(Boom):
            make_red().reduce_resumable(raw, out)
        cur = ReductionCursor.load(out)
        assert cur is not None and cur.frames_done > 0
        # Smash the HDF5 superblock — the file no longer opens, but the
        # cursor (own tmp-rename+fsync discipline) still parses.
        with open(out, "r+b") as f:
            f.write(b"\xde\xad\xbe\xef" * 128)
        with caplog.at_level(logging.WARNING, logger="blit.pipeline"):
            make_red().reduce_resumable(raw, out)
        assert "starting fresh" in caplog.text
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)
        assert not os.path.exists(ReductionCursor.path_for(out))


class TestSigkillResume:
    def test_sigkill_mid_reduction_resumes_identically(self, tmp_path):
        # The real crash, not an injected exception: a subprocess running
        # the bitshuffle .h5 reduction is SIGKILLed once its cursor
        # claims progress (no cleanup, no atexit — the durability
        # ordering alone must leave a resumable prefix).  The resumed
        # product must equal an uninterrupted run bit-for-bit (decoded).
        import json
        import signal
        import subprocess
        import sys
        import time

        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        raw = str(tmp_path / "x.raw")
        synth_raw(raw, nblocks=6, obsnchan=2, ntime_per_block=2048,
                  tone_chan=1)
        out = str(tmp_path / "x.h5")
        # chunk_frames=2: ~90 fsync'd cursor updates per run — a wide
        # window for the 2 ms poll to land the kill mid-run.
        child = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from blit.pipeline import RawReducer\n"
            "RawReducer(nfft=64, nint=2, chunk_frames=2).reduce_resumable("
            f"{raw!r}, {out!r}, compression='bitshuffle', "
            "chunks=(1, 1, 128))\n"
        )
        env = {**os.environ, "PYTHONPATH": ""}  # keep the axon plugin out
        p = subprocess.Popen([sys.executable, "-c", child], env=env,
                             stderr=subprocess.PIPE, text=True)
        deadline = time.time() + 120
        killed = False
        cursor = ReductionCursor.path_for(out)
        while time.time() < deadline and p.poll() is None:
            try:
                if json.load(open(cursor))["frames_done"] > 0:
                    p.send_signal(signal.SIGKILL)
                    killed = True
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.002)
        if p.poll() is None and not killed:
            p.kill()  # deadline expired with a hung child: don't leak it
        _, err = p.communicate(timeout=60)
        if not killed:
            # Startup crash vs genuinely-too-fast must be distinguishable.
            pytest.fail(
                f"child was not killed mid-run (rc={p.returncode}); "
                f"stderr:\n{(err or '')[-2000:]}"
            )
        assert os.path.exists(out) and os.path.exists(cursor)
        make_red().reduce_resumable(raw, out, compression="bitshuffle",
                                    chunks=(1, 1, 128))
        _, want = make_red().reduce(raw)
        np.testing.assert_array_equal(read_fbh5_data(out), want)
        assert not os.path.exists(cursor)


class TestCLI:
    def test_reduce_resume_h5_bitshuffle(self, tmp_path, raw, capsys):
        import json

        from blit.__main__ import main

        out = str(tmp_path / "x.h5")
        rc = main(["reduce", raw, "-o", out, "--nfft", "64", "--nint", "2",
                   "--compression", "bitshuffle", "--resume"])
        assert rc == 0
        stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        _, want = make_red().reduce(raw)
        assert stats["nsamps"] == want.shape[0]
        np.testing.assert_array_equal(read_fbh5_data(out), want)
