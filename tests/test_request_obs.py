"""Fleet request observability (blit ISSUE 15): cross-host trace
propagation over the serve HTTP wire, per-request access records
(RequestLog + `blit requests`), histogram exemplars (OpenMetrics
exposition + `blit trace-view --exemplar`), per-reason flight-dump rate
limiting, flight-dump trace correlation, tracer thread-safety under
hedged/coalesced concurrency, and the real-subprocess stitched-trace
acceptance drill."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

pytest.importorskip("jax")

from blit import faults, monitor, observability  # noqa: E402
from blit.config import DEFAULT, request_log_defaults  # noqa: E402
from blit.faults import FaultRule  # noqa: E402
from blit.observability import (  # noqa: E402
    FlightRecorder,
    HistogramStats,
    RequestLog,
    Timeline,
    cross_process_pairs,
    render_flight_dump,
)
from blit.serve import (  # noqa: E402
    FleetFrontDoor,
    Overloaded,
    PeerServer,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.serve.http import (  # noqa: E402
    SPAN_HEADER,
    TIER_HEADER,
    TRACE_HEADER,
    http_json,
    wire_request,
)
from blit.testing import synth_raw  # noqa: E402

NFFT = 128
NTIME = (8 + 3) * NFFT


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


def make_req(tmp_path, i=0):
    p = str(tmp_path / f"r{i}.raw")
    synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=NTIME, seed=i)
    return ProductRequest(raw=p, nfft=NFFT, nint=1)


# -- RequestLog --------------------------------------------------------------


class TestRequestLog:
    def test_records_land_as_json_lines(self, tmp_path):
        rl = RequestLog(str(tmp_path / "r.jsonl"))
        rl.record(rid="a", status="ok", duration_s=0.5, tier=None)
        rl.close()
        recs = monitor.read_requests(str(tmp_path / "r.jsonl"))
        assert len(recs) == 1
        assert recs[0]["rid"] == "a" and recs[0]["status"] == "ok"
        assert "tier" not in recs[0]  # None-valued fields dropped
        assert recs[0]["t"] > 0

    def test_size_rotation_bounds_the_log(self, tmp_path):
        rl = RequestLog(str(tmp_path / "r.jsonl"), max_bytes=4096,
                        max_files=3)
        for i in range(3000):
            rl.record(rid=f"req-{i:06d}", status="ok", duration_s=0.001)
        rl.close()
        files = rl.files()
        assert 1 <= len(files) <= 3
        total = sum(os.path.getsize(f) for f in files)
        # Bounded forever: at most max_files * (max_bytes + one record).
        assert total < 3 * (4096 + 512)
        # The NEWEST records survive rotation.
        recs = monitor.read_requests(str(tmp_path))
        assert recs[-1]["rid"] == "req-002999"

    def test_concurrent_appends_never_tear(self, tmp_path):
        rl = RequestLog(str(tmp_path / "r.jsonl"), max_bytes=1 << 20)

        def hammer(k):
            for i in range(200):
                rl.record(rid=f"t{k}-{i}", status="ok")

        threads = [threading.Thread(target=hammer, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rl.close()
        recs = monitor.read_requests(str(tmp_path / "r.jsonl"))
        assert len(recs) == 800  # every line parseable — no torn writes

    def test_defaults_resolve_env_over_config(self, monkeypatch,
                                              tmp_path):
        monkeypatch.setenv("BLIT_REQUEST_LOG", str(tmp_path))
        monkeypatch.setenv("BLIT_REQUEST_LOG_MAX_BYTES", "1234")
        d = request_log_defaults(DEFAULT)
        assert d["dir"] == str(tmp_path) and d["max_bytes"] == 1234
        monkeypatch.setenv("BLIT_REQUEST_LOG", "")
        assert request_log_defaults(
            DEFAULT.with_(request_log_dir="/x"))["dir"] is None


# -- histogram exemplars -----------------------------------------------------


class TestExemplars:
    def test_observe_under_a_span_retains_the_trace(self):
        h = HistogramStats()
        with observability.span("probe") as sp:
            h.observe(0.25)
        ex = h.tail_exemplar()
        assert ex is not None and ex["trace"] == sp.trace_id
        assert ex["value"] == 0.25 and ex["le"] >= 0.25

    def test_kill_switch(self):
        observability.set_exemplars(False)
        try:
            h = HistogramStats()
            with observability.span("probe"):
                h.observe(0.25)
            assert h.tail_exemplar() is None
        finally:
            observability.set_exemplars(True)

    def test_no_ambient_span_no_exemplar(self):
        h = HistogramStats()
        h.observe(0.25)
        assert h.tail_exemplar() is None

    def test_state_roundtrip_and_merge_keeps_newest(self):
        a = HistogramStats()
        a.observe(0.25, trace_id="old")
        a.exemplars[list(a.exemplars)[0]][2] = 100.0  # age it
        b = HistogramStats.from_state(a.state())
        assert b.tail_exemplar()["trace"] == "old"
        c = HistogramStats()
        c.observe(0.25, trace_id="new")
        b.merge(c)
        assert b.tail_exemplar()["trace"] == "new"
        # reset clears them (identity-preserving zero).
        b.reset()
        assert b.tail_exemplar() is None

    def test_prometheus_exposition_and_parse(self):
        tl = Timeline()
        with observability.span("probe") as sp:
            tl.observe("sched.wait_s", 0.25)
        snap = {"host": "h", "pid": 1, "worker": 0,
                "timeline": tl.state(), "faults": {}, "spans": []}
        report = observability.merge_fleet([snap])
        # The DEFAULT text exposition stays exemplar-free — the legacy
        # Prometheus text parser would reject the suffix.
        plain = observability.render_prometheus(report)
        assert "# {" not in plain and "# EOF" not in plain
        # The negotiated OpenMetrics exposition carries them + # EOF.
        text = observability.render_prometheus(report, openmetrics=True)
        assert "# {" in text
        assert text.rstrip().endswith("# EOF")
        # The plain parser tolerates (and drops) exemplar suffixes...
        samples = monitor.parse_prometheus(text)
        assert any(n == "blit_latency_seconds_bucket"
                   for n, _, _ in samples)
        # ...and the exemplar parser reads them back.
        exes = monitor.parse_prometheus_exemplars(text)
        assert any(ex["labels"].get("trace_id") == sp.trace_id
                   and ex["value"] == 0.25 for _, _, ex in exes)

    def test_metrics_endpoint_negotiates_openmetrics(self, tmp_path):
        """Accept: application/openmetrics-text flips the /metrics body
        (and content type) into the exemplar-bearing exposition; a
        legacy scrape stays plain."""
        from blit.observability import OPENMETRICS_CTYPE

        tl = Timeline()
        svc = ProductService(
            cache=ProductCache(None, ram_bytes=1 << 24, timeline=tl),
            scheduler=Scheduler(timeline=tl), timeline=tl)
        peer = PeerServer(svc, name="om").start()
        try:
            svc.get(make_req(tmp_path), timeout=120)  # spans + hists
            status, hdrs, body = http_json("GET", peer.url, "/metrics")
            assert status == 200 and "# {" not in body
            assert hdrs["content-type"].startswith("text/plain")
            status, hdrs, body = http_json(
                "GET", peer.url, "/metrics",
                headers={"Accept": "application/openmetrics-text"})
            assert status == 200
            assert hdrs["content-type"] == OPENMETRICS_CTYPE
            assert body.rstrip().endswith("# EOF")
            assert monitor.parse_prometheus(body)
        finally:
            peer.close()
            svc.close(5)


# -- flight recorder satellites ----------------------------------------------


class TestFlightDumps:
    def test_rate_limit_is_per_reason(self, tmp_path, monkeypatch):
        """ISSUE 15 satellite (the two-reason pin): an SLO-breach dump
        must not starve a first-of-kind stall dump on the shared
        clock — but repeats of ONE reason still rate-limit."""
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = FlightRecorder(min_interval_s=60.0)
        assert rec.dump("SLO breach: w burning 14x") is not None
        # Same reason class, seconds later: rate-limited.
        assert rec.dump("SLO breach: w burning 20x") is None
        # A DIFFERENT reason class lands immediately.
        assert rec.dump("blit-feed: producer stalled — no progress") \
            is not None
        # And its own repeats rate-limit independently.
        assert rec.dump("blit-feed: producer stalled again") is None
        # force still overrides.
        assert rec.dump("SLO breach: w again", force=True) is not None
        assert len(list(tmp_path.glob("blit-flight-*.json"))) == 3

    def test_explicit_key_overrides_derivation(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = FlightRecorder(min_interval_s=60.0)
        assert rec.dump("one reason", key="k") is not None
        assert rec.dump("totally different reason", key="k") is None

    def test_key_table_is_bounded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = FlightRecorder(min_interval_s=60.0)
        for i in range(2 * FlightRecorder._MAX_DUMP_KEYS):
            rec.dump(f"reason-{i}: x")
        assert len(rec._last_dump) <= FlightRecorder._MAX_DUMP_KEYS

    def test_dump_records_ambient_trace(self, tmp_path, monkeypatch):
        """ISSUE 15 satellite: a flight dump carries the trace that
        tripped it, and trace-view prints it."""
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = FlightRecorder(min_interval_s=0.0)
        with observability.span("incident") as sp:
            path = rec.dump("stall: drill")
        doc = json.load(open(path))
        assert doc["trace"] == sp.trace_id
        assert doc["span"]
        out = render_flight_dump(doc)
        assert f"trace  : {sp.trace_id}" in out
        # Outside any span: no trace keys, no trace line.
        path2 = rec.dump("stall: drill 2", force=True)
        doc2 = json.load(open(path2))
        assert "trace" not in doc2
        assert "trace  :" not in render_flight_dump(doc2)


# -- service-level access records --------------------------------------------


class TestServiceRecords:
    def _service(self, tmp_path, reqlog=True, **sched_kw):
        tl = Timeline()
        cfg = DEFAULT.with_(
            request_log_dir=str(tmp_path / "reqlog") if reqlog else None)
        return ProductService(
            cache=ProductCache(None, ram_bytes=1 << 24, timeline=tl),
            scheduler=Scheduler(timeline=tl, **sched_kw),
            timeline=tl, config=cfg)

    def test_disabled_writes_zero_records(self, tmp_path):
        svc = self._service(tmp_path, reqlog=False)
        try:
            assert svc.request_log is None
            svc.get(make_req(tmp_path), timeout=120)
        finally:
            svc.close(5)
        assert not list(tmp_path.rglob("requests-*.jsonl*"))

    def test_one_record_per_outcome(self, tmp_path):
        """Every get() — served, refused, deadline-dead — appends
        exactly one record with the right status/code."""
        from blit.serve.scheduler import DeadlineExpired

        svc = self._service(tmp_path)
        req = make_req(tmp_path)
        try:
            svc.get(req, timeout=120, client="a")       # ok (derived)
            svc.get(req, timeout=120, client="a")       # ok (ram hit)
            with pytest.raises(DeadlineExpired):
                # A burned deadline is rejected at admission → 504.
                svc.get(ProductRequest(raw=req.raw, nfft=NFFT, nint=4),
                        timeout=1, deadline_s=-1.0, client="dead")
            svc._draining = True
            with pytest.raises(Overloaded):              # refused → 503
                svc.get(req, timeout=1, client="shed")
            svc._draining = False
        finally:
            svc.close(30)
        recs = [r for r in monitor.read_requests(str(tmp_path / "reqlog"))
                if r["role"] == "serve"]
        assert len(recs) == 4
        ok = [r for r in recs if r["status"] == "ok"]
        assert len(ok) == 2
        assert ok[0]["tier"] == "derive" and ok[0]["code"] == 200
        assert ok[1]["tier"] == "ram" and ok[1]["bytes"] > 0
        dead = [r for r in recs if r["client"] == "dead"][0]
        assert dead["status"] == "deadline" and dead["code"] == 504
        assert dead["deadline_left_s"] < 0
        shed = [r for r in recs if r["client"] == "shed"][0]
        assert shed["status"] == "overloaded" and shed["code"] == 503

    def test_record_carries_ambient_trace_and_queue_wait(self, tmp_path):
        svc = self._service(tmp_path)
        try:
            with observability.span("caller") as sp:
                svc.get(make_req(tmp_path, 1), timeout=120)
        finally:
            svc.close(5)
        recs = monitor.read_requests(str(tmp_path / "reqlog"))
        assert recs and recs[0]["trace"] == sp.trace_id
        assert "queue_wait_s" in recs[0] and "duration_s" in recs[0]


# -- the in-process fleet rig ------------------------------------------------


class Fleet:
    """Two in-process peers + a door with request logging on and
    explicit observe() ticks — the ISSUE 14 test rig plus the ISSUE 15
    observability surface."""

    def __init__(self, tmp_path, npeers=2, **door_kw):
        self.reqlog = str(tmp_path / "reqlog")
        cfg = DEFAULT.with_(request_log_dir=self.reqlog)
        self.lease_dir = str(tmp_path / "leases")
        self.servers = []
        peers = {}
        for i in range(npeers):
            tl = Timeline()
            svc = ProductService(
                cache=ProductCache(str(tmp_path / f"cache{i}"),
                                   ram_bytes=1 << 24, timeline=tl),
                scheduler=Scheduler(max_concurrency=2, queue_depth=8,
                                    timeline=tl, retry_seed=i),
                timeline=tl)
            ps = PeerServer(svc, name=f"peer{i}",
                            lease_dir=self.lease_dir, proc=i,
                            beat_interval_s=0.05, config=cfg).start()
            self.servers.append(ps)
            peers[f"peer{i}"] = ps.url
        kw = dict(peer_ttl_s=5.0, poll_s=0.05, health_poll_s=0.5,
                  hedge_floor_s=5.0, request_timeout_s=60.0, config=cfg)
        kw.update(door_kw)
        self.timeline = Timeline()
        self.door = FleetFrontDoor(peers, lease_dir=self.lease_dir,
                                   timeline=self.timeline, **kw)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            self.door.observe()
            if all(p.watch.seen for p in self.door._peers.values()):
                break
            time.sleep(0.05)

    def close(self):
        self.door.close()
        for s in self.servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001
                pass
            s.service.close(5)


@pytest.fixture
def fleet(tmp_path):
    f = Fleet(tmp_path)
    yield f
    f.close()


def spans_by_name(name):
    return [s for s in observability.tracer().span_dicts()
            if s["name"] == name]


class TestTracePropagation:
    def test_peer_spans_parent_onto_door_dispatch(self, fleet,
                                                  tmp_path):
        """Tentpole #1: the door's fleet.request → fleet.dispatch chain
        continues into serve.reduce THROUGH the HTTP wire (in-process
        servers here; the subprocess twin is the acceptance drill)."""
        observability.tracer().reset()
        fleet.door.get(make_req(tmp_path), client="tp")
        fr = spans_by_name("fleet.request")
        fd = spans_by_name("fleet.dispatch")
        sr = spans_by_name("serve.reduce")
        assert len(fr) == 1 and len(fd) >= 1 and len(sr) == 1
        assert fd[0]["parent"] == fr[0]["span"]
        assert sr[0]["trace"] == fr[0]["trace"]
        assert sr[0]["parent"] in {d["span"] for d in fd}
        # The hedge verdict + routing outcome land on the parent span.
        assert fr[0]["attrs"]["peer"] in ("peer0", "peer1")
        assert fr[0]["attrs"]["tier"] == "derive"

    def test_wire_headers_reactivate_the_context(self, fleet,
                                                 tmp_path):
        """A raw HTTP caller's trace context is adopted by the peer:
        the peer-side spans join the CALLER's trace id."""
        observability.tracer().reset()
        req = make_req(tmp_path, 1)
        wire = wire_request(req)
        status, hdrs, body = http_json(
            "POST", fleet.servers[0].url, "/product", wire,
            timeout=60.0,
            headers={TRACE_HEADER: "cafe.1", SPAN_HEADER: "cafe.2"})
        assert status == 200
        assert hdrs.get(TIER_HEADER.lower()) == "derive"
        sr = spans_by_name("serve.reduce")
        assert sr and sr[0]["trace"] == "cafe.1"
        assert sr[0]["parent"] == "cafe.2"

    def test_hedge_appears_as_sibling_span_tagged(self, tmp_path):
        fleet = Fleet(tmp_path, hedge_floor_s=0.05)
        try:
            observability.tracer().reset()
            faults.install(FaultRule(point="peer.request", mode="delay",
                                     delay_s=0.6, times=-1))
            fleet.door.get(make_req(tmp_path, 2), client="hedger")
            # The losing dispatch's span lands when ITS thread finishes
            # (first-wins returned already) — wait for it.
            deadline = time.monotonic() + 10
            while (len(spans_by_name("fleet.dispatch")) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            fd = spans_by_name("fleet.dispatch")
            fr = spans_by_name("fleet.request")
            assert len(fd) == 2
            assert {d["attrs"]["hedge"] for d in fd} == {0, 1}
            # Siblings: both parent onto the one request span.
            assert {d["parent"] for d in fd} == {fr[0]["span"]}
            # The winner/loser outcome lands on the parent.
            assert fr[0]["attrs"]["hedged"] == 1
            assert fr[0]["attrs"]["hedge_won"] in (0, 1)
        finally:
            fleet.close()

    def test_concurrent_requests_never_cross_contaminate(self, fleet,
                                                         tmp_path):
        """ISSUE 15 satellite: hedged dispatch and coalesced followers
        run on shared threads — every span's trace_id must match its
        OWN request (assert per-trace consistency under concurrency)."""
        observability.tracer().reset()
        reqs = [make_req(tmp_path, 10 + i) for i in range(4)]
        errs = []

        def one(i):
            try:
                # Two callers per product: the second coalesces.
                fleet.door.get(reqs[i % len(reqs)], client=f"c{i}")
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        spans = observability.tracer().span_dicts()
        by_id = {s["span"]: s for s in spans}
        roots = {s["span"]: s for s in spans
                 if s["name"] == "fleet.request"}
        assert len(roots) == 8
        # Walk every span up its parent chain: the root it reaches must
        # belong to the SAME trace — a cross-contaminated thread-local
        # would parent a span onto another request's chain.
        for s in spans:
            cur = s
            while cur.get("parent") and cur["parent"] in by_id:
                parent = by_id[cur["parent"]]
                assert parent["trace"] == s["trace"], (s, parent)
                cur = parent
        # And each request's serve.reduce (when it ran) shares the
        # root's trace; a trace never holds two different roots.
        for s in spans:
            if s["name"] != "fleet.request":
                continue
            same_trace_roots = [r for r in roots.values()
                                if r["trace"] == s["trace"]]
            assert same_trace_roots == [s]


class TestDoorRecords:
    def test_exactly_one_record_per_200_503_504(self, fleet, tmp_path):
        req = make_req(tmp_path, 3)
        fleet.door.get(req, client="ok")                      # 200
        from blit.serve.scheduler import DeadlineExpired

        with pytest.raises(DeadlineExpired):                  # 504
            fleet.door.get(make_req(tmp_path, 4), client="dead",
                           deadline_s=-1.0)
        fleet.door._draining = True                           # 503
        with pytest.raises(Overloaded):
            fleet.door.get(req, client="shed")
        fleet.door._draining = False
        recs = monitor.filter_requests(
            monitor.read_requests(fleet.reqlog), role="door")
        assert len(recs) == 3
        by_status = {r["client"]: (r["status"], r["code"]) for r in recs}
        assert by_status["ok"] == ("ok", 200)
        assert by_status["dead"] == ("deadline", 504)
        assert by_status["shed"] == ("overloaded", 503)
        ok = [r for r in recs if r["client"] == "ok"][0]
        assert ok["peer"] in ("peer0", "peer1")
        assert ok["tier"] == "derive" and ok["bytes"] > 0
        assert ok["trace"] and ok["rid"]

    def test_peer_record_rides_the_doors_request_id(self, fleet,
                                                    tmp_path):
        fleet.door.get(make_req(tmp_path, 5), client="rid")
        recs = monitor.read_requests(fleet.reqlog)
        door = [r for r in recs if r["role"] == "door"
                and r["client"] == "rid"]
        peer = [r for r in recs if r["role"] == "peer"
                and r["client"] == "rid"]
        assert door and peer
        assert peer[0]["rid"] == door[0]["rid"]
        assert peer[0]["trace"] == door[0]["trace"]
        assert peer[0]["queue_wait_s"] >= 0

    def test_request_s_exemplar_resolves_to_the_request(self, fleet,
                                                        tmp_path):
        """Tentpole #3 acceptance shape: the fleet.request_s tail
        bucket's exemplar IS one of the logged requests' traces."""
        for i in range(3):
            fleet.door.get(make_req(tmp_path, 20 + i), client="ex")
        ex = fleet.timeline.hists["fleet.request_s"].tail_exemplar()
        assert ex is not None
        traces = {r["trace"] for r in monitor.filter_requests(
            monitor.read_requests(fleet.reqlog), role="door")}
        assert ex["trace"] in traces


class TestCrossProcessPairs:
    def test_edges_detected_from_id_prefixes(self):
        """Span ids embed a per-process prefix, so a cross-process
        parent/child edge is detectable from ids alone — but only
        counted when BOTH ends are present in the stitched set."""
        spans = [
            {"span": "aaa.1", "parent": None},
            {"span": "aaa.2", "parent": "aaa.1"},   # same process
            {"span": "bbb.1", "parent": "aaa.2"},   # cross process
            {"span": "ccc.1", "parent": "zzz.9"},   # parent not present
        ]
        assert cross_process_pairs(spans) == 1


# -- CLI surfaces ------------------------------------------------------------


class TestRequestsCLI:
    def _spool(self, tmp_path):
        rl = RequestLog(str(tmp_path / "requests-door-h-1.jsonl"))
        rl.record(rid="a", trace="t.1", role="door", client="c0",
                  status="ok", code=200, tier="ram", duration_s=0.004,
                  bytes=10)
        rl.record(rid="b", trace="t.2", role="door", client="c1",
                  status="overloaded", code=503, duration_s=0.5)
        rl.close()
        return str(tmp_path)

    def test_table_filter_and_aggregate(self, tmp_path, capsys):
        from blit.__main__ import main

        spool = self._spool(tmp_path)
        assert main(["requests", spool]) == 0
        out = capsys.readouterr().out
        assert "t.1" in out and "t.2" in out
        assert main(["requests", spool, "--slow-ms", "100"]) == 0
        out = capsys.readouterr().out
        assert "t.2" in out and "t.1" not in out
        assert main(["requests", spool, "--status", "503",
                     "--json"]) == 0
        out = capsys.readouterr().out
        assert json.loads(out.strip())["rid"] == "b"
        assert main(["requests", spool, "--aggregate", "--json"]) == 0
        agg = json.loads(capsys.readouterr().out)
        assert agg["records"] == 2
        assert agg["by_status"] == {"ok": 1, "overloaded": 1}
        assert agg["slowest"][0]["trace"] == "t.2"

    def test_aggregate_groups_by_session_scan(self, tmp_path, capsys):
        # ISSUE 19 satellite: door records for catalog-addressed asks
        # carry session/scan, and the aggregate groups on them — the
        # operator's "which scans are hot" view.
        from blit.__main__ import main

        rl = RequestLog(str(tmp_path / "requests-door-h-1.jsonl"))
        for i in range(3):
            rl.record(rid=f"s{i}", trace=f"s.{i}", role="door",
                      client="c", status="ok", code=200, tier="ram",
                      duration_s=0.002, bytes=5,
                      session="AGBT25A_999_01", scan="0001")
        rl.record(rid="x", trace="s.9", role="door", client="c",
                  status="ok", code=200, tier="ram", duration_s=0.9,
                  bytes=5, session="AGBT25A_999_01", scan="0002")
        rl.record(rid="y", trace="s.10", role="door", client="c",
                  status="ok", code=200, tier="derive",
                  duration_s=0.003, bytes=5)  # explicit-path ask
        rl.close()
        assert main(["requests", str(tmp_path), "--aggregate",
                     "--json"]) == 0
        agg = json.loads(capsys.readouterr().out)
        assert agg["by_scan"] == {"AGBT25A_999_01/0001": 3,
                                  "AGBT25A_999_01/0002": 1}
        slow = agg["slowest"][0]
        assert slow["session"] == "AGBT25A_999_01"
        assert slow["scan"] == "0002"


class TestTraceViewFleet:
    def _snapshot(self, tmp_path):
        # Two fake processes: door (aaa) and peer (bbb); the peer's
        # serve.reduce parents onto the door's dispatch span.
        spans = [
            {"name": "fleet.request", "span": "aaa.1", "trace": "aaa.9",
             "parent": None, "t0": 1.0, "duration_s": 0.5, "host": "h",
             "worker": 0, "tid": 1},
            {"name": "fleet.dispatch", "span": "aaa.2", "trace": "aaa.9",
             "parent": "aaa.1", "t0": 1.01, "duration_s": 0.4,
             "host": "h", "worker": 0, "tid": 1,
             "attrs": {"hedge": 1}},
            {"name": "serve.reduce", "span": "bbb.1", "trace": "aaa.9",
             "parent": "aaa.2", "t0": 1.02, "duration_s": 0.3,
             "host": "h", "worker": 0, "tid": 2},
        ]
        h = HistogramStats()
        h.observe(0.5, trace_id="aaa.9")
        path = str(tmp_path / "fleet.snapshot.json")
        with open(path, "w") as f:
            json.dump({"spans": spans,
                       "hists": {"fleet.request_s": h.state()}}, f)
        return path

    def test_stitch_summary_and_exemplar(self, tmp_path, capsys):
        from blit.__main__ import main

        snap = self._snapshot(tmp_path)
        out_path = str(tmp_path / "trace.json")
        assert main(["trace-view", "--fleet", snap, "--out", out_path,
                     "--exemplar", "fleet.request_s"]) == 0
        out = capsys.readouterr().out
        head = json.loads(out.splitlines()[0])
        assert head["spans"] == 3 and head["processes"] == 2
        assert head["cross_process_pairs"] == 1
        assert head["exemplar"]["trace"] == "aaa.9"
        # The exemplar's trace tree prints, hedge tag included.
        assert "serve.reduce" in out and "hedge=1" in out
        doc = json.load(open(out_path))
        assert len([e for e in doc["traceEvents"]
                    if e.get("ph") == "X"]) == 3

    def test_missing_exemplar_fails_loudly(self, tmp_path, capsys):
        from blit.__main__ import main

        snap = self._snapshot(tmp_path)
        assert main(["trace-view", "--fleet", snap,
                     "--exemplar", "no.such_metric"]) == 1

    def test_spool_dir_source(self, tmp_path, capsys):
        """A monitor spool with span batches is a stitchable source
        (tentpole #4's spool half)."""
        from blit.__main__ import main

        pub = monitor.MetricsPublisher(
            interval_s=3600.0, spool_dir=str(tmp_path / "spool"),
            port=-1, spans=True)
        observability.tracer().reset()
        with observability.span("spooled") as sp:
            observability.process_timeline().observe("sched.wait_s", 0.1)
        pub.tick()
        pub.close()
        assert main(["trace-view", "--fleet",
                     str(tmp_path / "spool")]) == 0
        head = json.loads(capsys.readouterr().out.splitlines()[0])
        assert head["spans"] >= 1
        spans, hists = monitor.gather_trace_sources(
            [str(tmp_path / "spool")])
        assert any(s["span"] == sp.span_id for s in spans)
        assert "sched.wait_s" in hists

    def test_trace_view_classic_dump_still_works(self, tmp_path,
                                                 capsys, monkeypatch):
        from blit.__main__ import main

        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = FlightRecorder(min_interval_s=0.0)
        path = rec.dump("classic: drill")
        assert main(["trace-view", path]) == 0
        assert "classic: drill" in capsys.readouterr().out


# -- the real-subprocess acceptance drill ------------------------------------


@pytest.mark.slow
class TestFleetEndToEndTrace:
    def test_subprocess_fleet_stitches_one_trace(self, tmp_path):
        """ISSUE 15 acceptance: a real-subprocess fleet (hedge drill —
        the tiny hedge floor forces hedged dispatch on the slow cold
        reductions) produces ONE stitched trace in which a peer-side
        serve.reduce span's parent is a front-door span from ANOTHER
        process, and the fleet.request_s tail-bucket exemplar resolves
        to a logged trace via `blit trace-view`."""
        trace_out = str(tmp_path / "fleet-trace.json")
        reqlog = str(tmp_path / "reqlog")
        res = subprocess.run(
            [sys.executable, "-m", "blit", "serve-bench", "--fleet",
             "--requests", "16", "--distinct", "3", "--clients", "3",
             "--peers", "2", "--nfft", "128",
             "--trace-out", trace_out, "--request-log", reqlog],
            capture_output=True, text=True, timeout=560,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert res.returncode == 0, res.stderr[-2000:]
        rep = json.loads(res.stdout.strip().splitlines()[-1])
        # ≥1 cross-process parent/child pair in the artifact (the CI
        # fleet-smoke assertion, pinned here too).
        assert rep["trace"]["cross_process_pairs"] >= 1, rep["trace"]
        assert rep["trace"]["processes"] >= 2
        assert rep["request_log"]["door_records"] == 16
        assert rep["request_log"]["p99_s"] > 0
        # The saved snapshot re-stitches: find a peer-side serve.reduce
        # whose parent lives in a DIFFERENT process (the door's
        # dispatch span).
        snap = json.load(open(rep["trace"]["snapshot"]))
        spans = snap["spans"]
        by_id = {s["span"]: s for s in spans}
        proc = observability.span_process
        cross = [
            s for s in spans
            if s["name"] == "serve.reduce" and s.get("parent") in by_id
            and proc(s["parent"]) != proc(s["span"])
            and by_id[s["parent"]]["name"] == "fleet.dispatch"
        ]
        assert cross, "no cross-process serve.reduce→fleet.dispatch edge"
        # The exemplar resolves through `blit trace-view --fleet` to a
        # trace that is ALSO in the request log (page → exemplar →
        # trace → request record, the runbook loop).
        res2 = subprocess.run(
            [sys.executable, "-m", "blit", "trace-view", "--fleet",
             rep["trace"]["snapshot"],
             "--exemplar", "fleet.request_s"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert res2.returncode == 0, res2.stderr[-2000:]
        head = json.loads(res2.stdout.splitlines()[0])
        ex_trace = head["exemplar"]["trace"]
        logged = {r["trace"] for r in monitor.filter_requests(
            monitor.read_requests(reqlog), role="door")}
        assert ex_trace in logged
        assert f"trace {ex_trace}" in res2.stdout

    def test_request_log_compare_disabled_is_free(self, tmp_path):
        """Acceptance bound: disabled request logging adds ZERO records
        (measured) and the A/B report prices the enabled pass."""
        res = subprocess.run(
            [sys.executable, "-m", "blit", "serve-bench",
             "--requests", "24", "--distinct", "4", "--clients", "3",
             "--nfft", "128", "--request-log-compare"],
            capture_output=True, text=True, timeout=560,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert res.returncode == 0, res.stderr[-2000:]
        rep = json.loads(res.stdout.strip().splitlines()[-1])
        assert rep["request_log_compare"] is True
        assert rep["off_records"] == 0
        assert rep["on_records"] == 24
        assert "overhead_pct" in rep
