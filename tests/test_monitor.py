"""Live monitoring & SLO plane (blit/monitor.py; ISSUE 11).

Covers the tentpole end to end — interval publisher (delta sampling,
spool, HTTP endpoints), native Prometheus histogram exposition
(round-trip parse), the multi-window burn-rate SLO evaluator with its
breach actions (alert + forced flight dump + scheduler shed), the
deterministic SLO drill (BLIT_FAULTS latency injection → alert → dump →
measurable shed → recovery), dump rate-limiting under an alert storm,
`blit top` / `blit telemetry --watch`, and the `blit bench-diff`
perf-regression gate over both synthetic trajectories and the
checked-in BENCH_*.json history."""

import json
import math
import os
import threading
import time
import urllib.request

import pytest

from blit import faults, monitor, observability
from blit.monitor import (
    BurnRateEvaluator,
    MetricsPublisher,
    SLObjective,
    bad_fraction,
    bench_diff,
    bench_metrics,
    load_bench_json,
    parse_prometheus,
)
from blit.observability import (
    FlightRecorder,
    HistogramStats,
    Timeline,
    hist_bucket_edges,
    merge_fleet,
    render_prometheus,
    telemetry_snapshot,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_monitor(monkeypatch, tmp_path):
    """Hermetic monitoring env: no leaked publisher, faults, or flight
    dumps between tests."""
    for var in ("BLIT_MONITOR_SPOOL", "BLIT_MONITOR_PORT",
                "BLIT_MONITOR_INTERVAL", "BLIT_SLO_SERVE_WAIT_P99",
                "BLIT_SLO_STREAM_P99", "BLIT_SLO_INGEST_GBPS_FLOOR"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path / "flight"))
    (tmp_path / "flight").mkdir()
    faults.clear()
    faults.reset_counters()
    monitor.shutdown_publisher()
    yield
    monitor.shutdown_publisher()
    faults.clear()
    faults.reset_counters()


def _flight_dumps(tmp_path):
    return sorted((tmp_path / "flight").glob("blit-flight-*.json"))


def wait_for(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.02)


# -- native Prometheus histograms (satellite 1) ------------------------------


class TestPrometheusNative:
    def _report_for(self, values, name="lat.s"):
        tl = Timeline()
        for v in values:
            tl.observe(name, v)
        snap = {"host": "h", "pid": 1, "worker": 0,
                "timeline": tl.state(), "faults": {}, "spans": []}
        return tl, merge_fleet([snap])

    def test_bucket_series_round_trip(self):
        """The pinned satellite contract: cumulative ``_bucket`` counts
        at the log2 edges reconstruct the EXACT HistogramStats bucket
        counts, and ``_sum``/``_count`` are exact."""
        values = [2e-6, 5e-6, 5e-6, 0.03, 0.5, 0.5, 12.0]
        tl, report = self._report_for(values)
        text = render_prometheus(report)
        samples = parse_prometheus(text)  # raises on unparseable lines
        edges = hist_bucket_edges()
        cum = {}
        for name, labels, value in samples:
            if (name == "blit_latency_seconds_bucket"
                    and labels["name"] == "lat.s"
                    and labels["le"] != "+Inf"):
                cum[float(labels["le"])] = int(value)
        # Cumulative counts must be non-decreasing in le and reconstruct
        # the per-bucket counts by differencing.
        les = sorted(cum)
        counts = {}
        prev = 0
        for le in les:
            assert cum[le] >= prev
            counts[le] = cum[le] - prev
            prev = cum[le]
        h = tl.hists["lat.s"]
        expect = {edges[i]: c for i, c in enumerate(h.counts) if c}
        got = {le: c for le, c in counts.items() if c}
        assert {round(math.log2(le / 1e-6)) for le in got} == \
            {round(math.log2(le / 1e-6)) for le in expect}
        assert sorted(got.values()) == sorted(expect.values())
        inf = [v for n, la, v in samples
               if n == "blit_latency_seconds_bucket"
               and la["name"] == "lat.s" and la["le"] == "+Inf"]
        assert inf == [float(len(values))]
        count = [v for n, la, v in samples
                 if n == "blit_latency_seconds_count"
                 and la["name"] == "lat.s"]
        assert count == [float(len(values))]
        total = [v for n, la, v in samples
                 if n == "blit_latency_seconds_sum"
                 and la["name"] == "lat.s"]
        assert total[0] == pytest.approx(sum(values))

    def test_help_and_type_lines(self):
        _, report = self._report_for([0.1])
        text = render_prometheus(report)
        assert "# TYPE blit_latency_seconds histogram" in text
        assert "# HELP blit_latency_seconds " in text
        assert "# TYPE blit_latency_quantile gauge" in text
        # The pre-existing families keep their heads (tests elsewhere
        # pin them too).
        assert "# TYPE blit_stage_seconds_total counter" in text

    def test_label_value_escaping_round_trips(self):
        nasty = 'we"ird\\name\nwith newline'
        _, report = self._report_for([0.25], name=nasty)
        text = render_prometheus(report)
        samples = parse_prometheus(text)
        names = {la.get("name") for n, la, _ in samples
                 if n == "blit_latency_seconds_count"}
        assert nasty in names

    def test_legacy_report_without_raw_state_still_renders(self):
        """A saved pre-ISSUE-11 fleet report (quantile block only) must
        render its quantile gauges without bucket series or a crash."""
        _, report = self._report_for([0.1])
        for e in report["hosts"].values():
            e.pop("hist_state")
        text = render_prometheus(report)
        samples = parse_prometheus(text)
        names = {n for n, _, _ in samples}
        assert "blit_latency_quantile" in names
        assert "blit_latency_seconds_bucket" not in names


# -- SLO math ----------------------------------------------------------------


class TestBadFraction:
    def test_counts_only_buckets_fully_above_threshold(self):
        h = HistogramStats()
        for v in (0.001, 0.001, 0.2, 0.9):
            h.observe(v)
        bad, total = bad_fraction(h, 0.05)
        assert (bad, total) == (2, 4)
        # Conservative: a sample in the bucket straddling the threshold
        # is not bad.
        bad, _ = bad_fraction(h, 0.15)  # 0.2 lands in (0.131, 0.262]
        assert bad == 1  # only 0.9's bucket lies fully above 0.15


class TestBurnRate:
    def _delta(self, values, metric="sched.wait_s"):
        d = Timeline()
        for v in values:
            d.observe(metric, v)
        return d

    def test_breach_fires_alert_and_dump_and_shed(self, tmp_path):
        rec = FlightRecorder(min_interval_s=60.0)
        ev = BurnRateEvaluator(
            [SLObjective(name="w", metric="sched.wait_s",
                         threshold=0.01, budget=0.01)],
            fast_window=3, slow_window=6, fast_burn=14.0, slow_burn=2.0,
            recorder=rec)
        shed_calls = []
        ev.add_shed_hook(shed_calls.append)
        alerts = ev.observe(self._delta([0.5] * 10), 1.0)
        assert len(alerts) == 1
        a = alerts[0]
        assert a["objective"] == "w" and a["burn_fast"] >= 14.0
        assert a.get("flight_dump") and os.path.exists(a["flight_dump"])
        assert shed_calls == [0.5]
        assert ev.breached() == ["w"]
        assert ev.report()["w"]["breached"] is True

    def test_within_budget_never_breaches(self):
        ev = BurnRateEvaluator(
            [SLObjective(name="w", metric="m", threshold=0.01,
                         budget=0.5)],
            fast_window=2, slow_window=4, fast_burn=2.0, slow_burn=2.0)
        for _ in range(10):
            assert ev.observe(self._delta([0.001, 0.001, 0.5], "m"),
                              1.0) == []
        assert ev.breached() == []

    def test_multi_window_confirmation_stops_flapping(self, tmp_path):
        """A one-round spike on a long good history trips the FAST
        window but not the SLOW one — no page (the multi-window rule)."""
        ev = BurnRateEvaluator(
            [SLObjective(name="w", metric="m", threshold=0.01,
                         budget=0.5)],
            fast_window=1, slow_window=8, fast_burn=2.0, slow_burn=2.0,
            recorder=FlightRecorder(min_interval_s=60.0))
        for _ in range(7):
            ev.observe(self._delta([0.001], "m"), 1.0)
        alerts = ev.observe(self._delta([0.5], "m"), 1.0)
        st = ev.report()["w"]
        assert st["burn_fast"] >= 2.0  # the spike alone torches fast
        assert st["burn_slow"] < 2.0   # 1 bad of 8 — budget holds
        assert alerts == []

    def test_throughput_floor_objective(self, tmp_path):
        rec = FlightRecorder(min_interval_s=60.0)
        ev = BurnRateEvaluator(
            [SLObjective(name="gbps", metric="ingest", kind="throughput",
                         threshold=1.0, budget=0.01)],
            fast_window=1, slow_window=2, fast_burn=2.0, slow_burn=2.0,
            recorder=rec)
        # Idle interval: the stage never ran — no observation, no breach.
        assert ev.observe(Timeline(), 1.0) == []
        slow = Timeline()
        with slow.stage("ingest", nbytes=1000):
            time.sleep(0.002)
        assert len(ev.observe(slow, 1.0)) == 1  # ~0.0005 GB/s < 1.0

    def test_recovery_releases_the_shed(self, tmp_path):
        ev = BurnRateEvaluator(
            [SLObjective(name="w", metric="m", threshold=0.01,
                         budget=0.01)],
            fast_window=2, slow_window=2, fast_burn=2.0, slow_burn=2.0,
            recorder=FlightRecorder(min_interval_s=60.0))
        shed_calls = []
        ev.add_shed_hook(shed_calls.append)
        ev.observe(self._delta([0.5] * 4, "m"), 1.0)
        assert shed_calls == [0.5]
        for _ in range(3):  # clean intervals: no samples at all
            ev.observe(Timeline(), 1.0)
        assert shed_calls == [0.5, 0.0]

    def test_alert_storm_rate_limits_dumps_and_stays_fast(
            self, tmp_path):
        """ISSUE 11 satellite: repeated breaches must not spam flight
        dumps (first breach forces one file; the rest ride the
        recorder's rate limit) or block the hot path."""
        rec = FlightRecorder(min_interval_s=3600.0)
        ev = BurnRateEvaluator(
            [SLObjective(name="w", metric="m", threshold=0.01,
                         budget=0.01)],
            fast_window=1, slow_window=2, fast_burn=2.0, slow_burn=2.0,
            recorder=rec)
        t0 = time.perf_counter()
        fired = 0
        for _ in range(50):
            fired += len(ev.observe(self._delta([0.5] * 3, "m"), 1.0))
        elapsed = time.perf_counter() - t0
        assert fired == 50  # every breach alerts...
        assert len(_flight_dumps(tmp_path)) == 1  # ...ONE dump file
        assert elapsed < 5.0  # and the loop never blocked
        assert len(ev.alerts) == 50


# -- the publisher -----------------------------------------------------------


class TestMetricsPublisher:
    def test_delta_sampling_and_spool(self, tmp_path):
        tl = Timeline()
        spool = tmp_path / "spool"
        pub = MetricsPublisher(interval_s=999.0, spool_dir=str(spool),
                               timeline=tl)
        with tl.stage("ingest", nbytes=1000):
            pass
        tl.observe("lat.s", 0.5)
        s1 = pub.tick()
        assert s1["delta"]["stages"]["ingest"]["bytes"] == 1000
        assert s1["delta"]["hists"]["lat.s"]["n"] == 1
        # Second interval: only the NEW work appears in the delta.
        tl.observe("lat.s", 0.5)
        tl.observe("lat.s", 0.5)
        s2 = pub.tick()
        assert "ingest" not in s2["delta"]["stages"]
        assert s2["delta"]["hists"]["lat.s"]["n"] == 2
        # The cumulative state still carries everything (fleet merges).
        assert s2["timeline"]["hists"]["lat.s"]["n"] == 3
        pub.close()
        report, samples = monitor.merge_spool(str(spool))
        assert len(samples) == 1  # newest line per process file
        assert samples[0]["seq"] == 1
        host = observability.hostname()
        assert report["hosts"][host]["stages"]["ingest"]["calls"] == 1

    def test_http_endpoints(self, tmp_path):
        tl = Timeline()
        with tl.stage("ingest", nbytes=512):
            pass
        tl.observe("lat.s", 0.1)
        with MetricsPublisher(interval_s=999.0, port=0,
                              timeline=tl) as pub:
            assert pub.port
            health = json.load(urllib.request.urlopen(
                pub.url + "/healthz", timeout=10))
            assert health["ok"] is True
            text = urllib.request.urlopen(
                pub.url + "/metrics", timeout=10).read().decode()
            samples = parse_prometheus(text)  # CI contract: parseable
            names = {n for n, _, _ in samples}
            assert "blit_stage_bytes_total" in names
            assert "blit_latency_seconds_bucket" in names
            snap = json.load(urllib.request.urlopen(
                pub.url + "/snapshot", timeout=10))
            assert snap["host"] == observability.hostname()
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(pub.url + "/nope", timeout=10)

    def test_background_loop_ticks(self, tmp_path):
        tl = Timeline()
        with tl.stage("ingest", nbytes=1):
            pass
        pub = MetricsPublisher(interval_s=0.05,
                               spool_dir=str(tmp_path / "s"),
                               timeline=tl).start()
        wait_for(lambda: pub.seq >= 2)
        pub.close()

    def test_watch_unwatch_refcount(self, tmp_path):
        pub = MetricsPublisher(interval_s=999.0)
        tl = Timeline()
        with tl.stage("x", nbytes=1, byte_free=True):
            pass
        pub.watch(tl)
        pub.watch(tl)  # nested publishing scopes
        pub.unwatch(tl)
        assert "x" in pub.merged_timeline().stages  # still watched once
        pub.unwatch(tl)
        assert "x" not in pub.merged_timeline().stages
        pub.close()

    def test_device_gauges_never_crash(self):
        import jax

        jax.devices()  # jax is imported + initialized in the suite
        tl = Timeline()
        monitor.device_gauges(tl)  # CPU: usually no memory_stats — ok

    def test_ensure_publisher_env_gated(self, monkeypatch, tmp_path):
        assert monitor.ensure_publisher() is None  # disabled: no-op
        monkeypatch.setenv("BLIT_MONITOR_SPOOL", str(tmp_path / "sp"))
        monkeypatch.setenv("BLIT_MONITOR_INTERVAL", "900")
        pub = monitor.ensure_publisher()
        assert pub is not None
        assert monitor.ensure_publisher() is pub  # singleton
        monitor.shutdown_publisher()

    def test_reduce_auto_publishes_when_enabled(
            self, monkeypatch, tmp_path):
        """Flipping BLIT_MONITOR_SPOOL makes a plain reduce_to_file
        spool at least one sample carrying its stage table — the
        ``_pump`` publishing hook (pipeline.py)."""
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        spool = tmp_path / "spool"
        monkeypatch.setenv("BLIT_MONITOR_SPOOL", str(spool))
        monkeypatch.setenv("BLIT_MONITOR_INTERVAL", "900")
        raw = tmp_path / "r.raw"
        synth_raw(str(raw), nblocks=1, obsnchan=2,
                  ntime_per_block=(8 + 3) * 256)
        RawReducer(nfft=256, tune_online=False).reduce_to_file(
            str(raw), str(tmp_path / "r.fil"))
        monitor.shutdown_publisher()
        report, samples = monitor.merge_spool(str(spool))
        assert samples, "no spool sample published"
        host = observability.hostname()
        assert report["hosts"][host]["stages"]["ingest"]["bytes"] > 0


# -- the SLO drill (acceptance) ----------------------------------------------


class TestSLODrill:
    def test_injected_latency_breaches_dumps_and_sheds(self, tmp_path):
        """Acceptance (ISSUE 11): a deterministic BLIT_FAULTS latency
        injection breaches a configured objective → burn-rate alert +
        forced flight dump + a MEASURABLE scheduler shed; recovery
        releases the shed."""
        from blit.serve.scheduler import Scheduler

        # The BLIT_FAULTS drill grammar, armed through the same parser
        # the env hook uses (docs/WORKFLOWS.md).
        faults.install_spec("sched.dispatch:delay:times=-1:delay=0.03")
        s = Scheduler(max_concurrency=1, queue_depth=64)
        jobs = [s.submit(lambda: None, client=f"c{i}") for i in range(6)]
        for j in jobs:
            j.result(timeout=30)
        pub = MetricsPublisher(
            interval_s=999.0, timeline=s.timeline,
            objectives=[SLObjective(name="serve-queue-wait",
                                    metric="sched.wait_s",
                                    threshold=0.01, budget=0.01)])
        pub.slo.attach_scheduler(s)
        base = 4
        s.max_concurrency = base
        sample = pub.tick()
        # Burn-rate alert...
        assert sample["slo"]["serve-queue-wait"]["breached"] is True
        assert sample["alerts"] and \
            sample["alerts"][0]["burn_fast"] >= 14.0
        # ...forced flight dump...
        dump = sample["alerts"][0].get("flight_dump")
        assert dump and os.path.exists(dump)
        doc = json.load(open(dump))
        assert "SLO breach: serve-queue-wait" in doc["reason"]
        # ...and a measurable scheduler shed.
        assert s.shed_level() == 0.5
        assert s.effective_budget() == base // 2
        # Recovery: the fault cleared, clean intervals drain the burn
        # windows, the shed releases.
        faults.clear()
        for _ in range(pub.slo.slow_window + 1):
            pub.tick()
        assert s.shed_level() == 0.0
        assert s.effective_budget() == base
        pub.close()

    def test_service_attaches_publisher_and_shed(
            self, monkeypatch, tmp_path):
        """ProductService wires the env-enabled publisher: its timeline
        is watched and SLO breaches shed ITS scheduler."""
        from blit.serve import ProductService

        monkeypatch.setenv("BLIT_MONITOR_SPOOL", str(tmp_path / "sp"))
        monkeypatch.setenv("BLIT_MONITOR_INTERVAL", "900")
        monkeypatch.setenv("BLIT_SLO_SERVE_WAIT_P99", "0.01")
        svc = ProductService()
        pub = monitor.ensure_publisher()
        assert pub is not None and svc._publisher is pub
        assert any(o.name == "serve-queue-wait"
                   for o in pub.slo.objectives)
        # A breach sheds the service's scheduler through the hook.
        delta = Timeline()
        for _ in range(50):
            delta.observe("sched.wait_s", 1.0)
        pub.slo.observe(delta, 1.0)
        assert svc.scheduler.shed_level() == 0.5
        assert svc.stats()["shed"] == 0.5
        svc.close()
        monitor.shutdown_publisher()


# -- blit top / telemetry --watch --------------------------------------------


class TestTopCli:
    def test_top_once_renders_spool(self, tmp_path, capsys):
        from blit.__main__ import main

        tl = Timeline()
        with tl.stage("ingest", nbytes=10 ** 6):
            pass
        tl.observe("out.chunk_latency_s", 0.01)
        spool = tmp_path / "spool"
        pub = MetricsPublisher(
            interval_s=999.0, spool_dir=str(spool), timeline=tl,
            objectives=[SLObjective(name="lat",
                                    metric="out.chunk_latency_s",
                                    threshold=10.0)])
        pub.tick()
        pub.close()
        assert main(["top", "--once", "--spool", str(spool)]) == 0
        out = capsys.readouterr().out
        assert "blit top" in out
        assert "ingest" in out
        assert "tail out.chunk_latency_s" in out
        assert "slo" in out and "lat" in out

    def test_top_once_renders_url(self, tmp_path, capsys):
        from blit.__main__ import main

        tl = Timeline()
        with tl.stage("ingest", nbytes=4096):
            pass
        with MetricsPublisher(interval_s=999.0, port=0,
                              timeline=tl) as pub:
            assert main(["top", "--once", "--url", pub.url]) == 0
        out = capsys.readouterr().out
        assert "ingest" in out

    def test_top_during_live_ingest_bench(self, tmp_path, capsys):
        """Acceptance (ISSUE 11): `blit top --once` renders a live
        snapshot DURING `ingest-bench --live` — the bench publishes to
        a spool on an interval; top reads it mid-run."""
        from blit.__main__ import main

        spool = tmp_path / "spool"
        rc = {}

        def bench():
            rc["rc"] = main([
                "ingest-bench", "--nfft", "256", "--nchan", "2",
                "--chunk-frames", "4", "--chunks", "4", "--blocks", "2",
                "--live", "--live-seconds", "3.0",
                "--monitor-spool", str(spool),
                "--monitor-interval", "0.05",
            ])

        t = threading.Thread(target=bench, daemon=True)
        t.start()
        try:
            wait_for(lambda: monitor.read_spool(str(spool)), timeout=120)
            assert main(["top", "--once", "--spool", str(spool)]) == 0
            out = capsys.readouterr().out
            assert "blit top" in out
        finally:
            t.join(timeout=300)
        assert rc.get("rc") == 0
        report = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert report["monitor"]["samples"] >= 1
        assert report["live"]["chunks"] > 0

    def test_telemetry_watch_shares_refresh_loop(self, capsys):
        """Satellite: `blit telemetry --watch N` re-harvests and
        re-renders on `blit top`'s frame loop (ANSI clear per frame)."""
        from blit.__main__ import main

        with observability.process_timeline().stage("probe.watch",
                                                    nbytes=1):
            pass
        rc = main(["telemetry", "--watch", "0.01", "--iterations", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert out.count(monitor.ANSI_CLEAR) == 2
        assert "probe.watch" in out


# -- bench-diff (the CI perf gate) -------------------------------------------


class TestBenchDiff:
    BASE = {"metric": "ingest_GBps", "value": 10.0, "unit": "GB/s",
            "fqav16_gbps": 5.0,
            "config": {"backend": "cpu", "name": "cpu"}}

    def _wrap(self, doc, n=1, rc=0):
        return {"n": n, "cmd": "python bench.py", "rc": rc,
                "tail": "noise\n" + json.dumps(doc), "parsed": doc}

    def test_metrics_extraction(self):
        m = bench_metrics(self.BASE)
        assert m == {"ingest_GBps": 10.0, "fqav16_gbps": 5.0}
        ib = {"legs": [{"async_output": True, "ingest_gbps": 0.5,
                        "overlap_efficiency": 1.4},
                       {"async_output": False, "ingest_gbps": 0.4,
                        "overlap_efficiency": 0.9}],
              "async_speedup": 1.25}
        m = bench_metrics(ib)
        assert m["async.ingest_gbps"] == 0.5
        assert m["sync.ingest_gbps"] == 0.4
        assert m["async_speedup"] == 1.25

    def test_pass_regress_improve_new(self):
        baselines = [dict(self.BASE, value=9.0, fqav16_gbps=4.0),
                     dict(self.BASE, value=11.0, fqav16_gbps=6.0)]
        fresh = dict(self.BASE, value=10.5, fqav16_gbps=2.0,
                     new_leg_gbps=1.0)
        v = bench_diff(fresh, baselines, rel_tol=0.2)
        rows = v["metrics"]
        assert rows["ingest_GBps"]["status"] == "ok"
        assert rows["fqav16_gbps"]["status"] == "regress"  # < 4*0.8
        assert rows["new_leg_gbps"]["status"] == "new"
        assert v["verdict"] == "regress"
        assert v["regressed"] == ["fqav16_gbps"]
        good = bench_diff(dict(self.BASE, value=30.0), baselines,
                          rel_tol=0.2)
        assert good["metrics"]["ingest_GBps"]["status"] == "improved"
        assert good["verdict"] == "pass"

    def test_serve_record_metrics_dict_extraction(self):
        # ISSUE 16: serve-bench --archive-day records carry a flat
        # "metrics" dict — hit rate / GB/s / speedup plus latency
        # quantiles — which bench_metrics ingests directly.
        rep = {"serve_bench": "archive-day",
               "config": {"backend": "cpu"},
               "metrics": {"fleet_hit_rate": 0.94,
                           "fleet_wire_gbps": 0.028,
                           "wire_speedup": 1.12,
                           "fleet_request_p99_s": 1.5,
                           "not_a_metric": 7.0,
                           "errors": "nope"}}
        m = bench_metrics(rep)
        assert m == {"fleet_hit_rate": 0.94, "fleet_wire_gbps": 0.028,
                     "wire_speedup": 1.12, "fleet_request_p99_s": 1.5}

    def test_archive_day_r02_keys_pin(self):
        # ISSUE 19: the archive-plane record's new keys — catalog
        # lookup quantiles (lower-is-better), per-tier hit rates and
        # SLO attainment (higher-is-better) — must ALL extract, while
        # tier_derive_rate stays report-only (a rising derive rate is
        # a regression, so it must not ride the higher-is-better
        # extractor).
        from blit.monitor import metric_lower_is_better

        rep = {"serve_bench": "archive-day",
               "config": {"backend": "cpu"},
               "metrics": {"catalog_lookup_p50_s": 0.0001,
                           "catalog_lookup_p99_s": 0.002,
                           "tier_ram_hit_rate": 0.5,
                           "tier_disk_hit_rate": 0.1,
                           "tier_wire_hit_rate": 0.2,
                           "tier_cold_hit_rate": 0.05,
                           "tier_derive_rate": 0.15,
                           "slo_attained": 0.98}}
        m = bench_metrics(rep)
        assert set(m) == {"catalog_lookup_p50_s",
                          "catalog_lookup_p99_s",
                          "tier_ram_hit_rate", "tier_disk_hit_rate",
                          "tier_wire_hit_rate", "tier_cold_hit_rate",
                          "slo_attained"}
        assert metric_lower_is_better("catalog_lookup_p99_s")
        assert not metric_lower_is_better("tier_cold_hit_rate")
        assert not metric_lower_is_better("slo_attained")
        # And the band inverts for the catalog quantile exactly like
        # the serve quantiles.
        def r(p99):
            return {"config": {"backend": "cpu"},
                    "metrics": {"catalog_lookup_p99_s": p99}}

        worse = bench_diff(r(0.08), [r(0.002), r(0.003)], rel_tol=0.2)
        assert worse["metrics"]["catalog_lookup_p99_s"][
            "status"] == "regress"

    def test_latency_quantiles_invert_the_band(self):
        # Lower-is-better: a p99 RISING above the noise band regresses;
        # dropping below it improves.  Higher-is-better metrics in the
        # same record keep their direction.
        def rec(p99, hr=0.9):
            return {"config": {"backend": "cpu"},
                    "metrics": {"fleet_request_p99_s": p99,
                                "fleet_hit_rate": hr}}

        baselines = [rec(1.0), rec(1.2)]
        worse = bench_diff(rec(2.0), baselines, rel_tol=0.2)
        assert worse["metrics"]["fleet_request_p99_s"][
            "status"] == "regress"
        assert worse["verdict"] == "regress"
        better = bench_diff(rec(0.5), baselines, rel_tol=0.2)
        assert better["metrics"]["fleet_request_p99_s"][
            "status"] == "improved"
        assert better["verdict"] == "pass"
        inside = bench_diff(rec(1.1), baselines, rel_tol=0.2)
        assert inside["metrics"]["fleet_request_p99_s"][
            "status"] == "ok"
        # The higher-is-better metric still regresses from BELOW.
        low_hr = bench_diff(rec(1.0, hr=0.2), baselines, rel_tol=0.2)
        assert low_hr["metrics"]["fleet_hit_rate"][
            "status"] == "regress"

    def test_rig_filter_excludes_other_backends(self):
        tpu = dict(self.BASE, value=100.0,
                   config={"backend": "tpu", "name": "tpu"})
        v = bench_diff(dict(self.BASE, value=10.0), [tpu], rel_tol=0.2)
        assert v["baselines"] == 0
        assert v["baselines_skipped_other_rig"] == 1
        assert v["metrics"]["ingest_GBps"]["status"] == "new"
        assert v["verdict"] == "pass"
        crossed = bench_diff(dict(self.BASE, value=10.0), [tpu],
                             rel_tol=0.2, cross_rig=True)
        assert crossed["verdict"] == "regress"

    def test_wrapper_loading_prefers_parsed_then_tail(self, tmp_path):
        p = tmp_path / "BENCH_x.json"
        p.write_text(json.dumps(self._wrap(self.BASE)))
        assert load_bench_json(str(p))["value"] == 10.0
        w = self._wrap(self.BASE)
        w["parsed"] = None  # old wrapper: fall back to the tail line
        p.write_text(json.dumps(w))
        assert load_bench_json(str(p))["value"] == 10.0
        w["tail"] = "Traceback (most recent call last):\n  boom"
        p.write_text(json.dumps(w))
        with pytest.raises(ValueError):
            load_bench_json(str(p))

    def test_cli_flags_synthetic_regression_and_passes_history(
            self, tmp_path, capsys):
        """Acceptance (ISSUE 11): exit 2 on a synthetic regression, exit
        0 on a matching-trajectory fresh record — over wrapper files."""
        from blit.__main__ import main

        for i, val in enumerate((9.0, 10.0, 11.0)):
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(
                json.dumps(self._wrap(dict(self.BASE, value=val))))
        ok = tmp_path / "fresh_ok.json"
        ok.write_text(json.dumps(dict(self.BASE, value=10.2)))
        assert main(["bench-diff", "--baseline-dir", str(tmp_path),
                     str(ok)]) == 0
        bad = tmp_path / "fresh_bad.json"
        bad.write_text(json.dumps(dict(self.BASE, value=1.0)))
        rc = main(["bench-diff", "--baseline-dir", str(tmp_path),
                   str(bad)])
        assert rc == 2
        out = capsys.readouterr().out
        assert "REGRESS" in out

    def test_checked_in_trajectory_passes(self, capsys):
        """The repo's own BENCH history is a passing trajectory (the CI
        gate's steady-state leg): the newest record diffed against the
        older rounds — same-rig only, failed rounds skipped."""
        from blit.__main__ import main

        baselines = sorted(
            p for p in os.listdir(REPO)
            if p.startswith("BENCH_r") and p.endswith(".json"))
        assert baselines, "no checked-in BENCH trajectory?"
        fresh = os.path.join(REPO, baselines[-1])
        rc = main(["bench-diff", "--baseline-dir", REPO, fresh])
        assert rc == 0
        out = capsys.readouterr().out
        assert "PASS" in out

    def test_checked_in_regression_is_flagged(self, tmp_path, capsys):
        """A 5x-slower synthetic derived from the newest checked-in
        record must regress against the real trajectory (exit 2)."""
        from blit.__main__ import main

        baselines = sorted(
            p for p in os.listdir(REPO)
            if p.startswith("BENCH_r") and p.endswith(".json"))
        doc = load_bench_json(os.path.join(REPO, baselines[-1]))
        reg = {k: (v * 0.2 if isinstance(v, (int, float))
                   and not isinstance(v, bool) else v)
               for k, v in doc.items()}
        p = tmp_path / "regressed.json"
        p.write_text(json.dumps(reg))
        rc = main(["bench-diff", "--baseline-dir", REPO, str(p)])
        assert rc == 2
        assert "regress" in capsys.readouterr().out.lower()


# -- packaging / config ------------------------------------------------------


class TestPlumbing:
    def test_monitor_is_a_lazy_blit_submodule(self):
        import blit

        assert blit.monitor.MetricsPublisher is MetricsPublisher

    def test_monitor_defaults_env_overrides(self, monkeypatch):
        from blit.config import monitor_defaults

        assert monitor_defaults()["enabled"] is False
        monkeypatch.setenv("BLIT_MONITOR_PORT", "0")
        d = monitor_defaults()
        assert d["enabled"] is True and d["port"] == 0
        monkeypatch.setenv("BLIT_MONITOR_PORT", "-1")
        assert monitor_defaults()["port"] is None

    def test_slo_defaults_env_and_extras(self, monkeypatch):
        from blit.config import DEFAULT, slo_defaults

        assert slo_defaults() == []
        monkeypatch.setenv("BLIT_SLO_STREAM_P99", "0.25")
        objs = slo_defaults()
        assert objs == [{"name": "stream-latency", "kind": "latency",
                         "metric": "stream.chunk_to_product_s",
                         "threshold": 0.25, "budget": 0.01}]
        cfg = DEFAULT.with_(slo_ingest_gbps_floor=0.5, slo_objectives=[
            {"name": "x", "kind": "latency", "metric": "m",
             "threshold": 1.0}])
        names = [o["name"] for o in slo_defaults(cfg)]
        assert names == ["stream-latency", "ingest-throughput", "x"]

    def test_publisher_snapshot_merges_into_fleet(self):
        """The publisher's wire snapshot folds its whole watch set into
        ONE merge_fleet entry — two reducer timelines from one process
        must not dedupe each other away."""
        # A quiet base timeline (not the process one — other tests'
        # stages must not leak into the byte assertions below).
        pub = MetricsPublisher(interval_s=999.0, timeline=Timeline())
        a, b = Timeline(), Timeline()
        with a.stage("ingest", nbytes=10):
            pass
        with b.stage("write", nbytes=20):
            pass
        pub.watch(a)
        pub.watch(b)
        report = pub.fleet_report()
        host = observability.hostname()
        stages = report["hosts"][host]["stages"]
        assert stages["ingest"]["bytes"] == 10
        assert stages["write"]["bytes"] == 20
        pub.close()

    def test_fleet_report_still_merges_snapshots(self):
        # The hist_state addition must not disturb merge_fleet's shape.
        report = merge_fleet([telemetry_snapshot()])
        host = observability.hostname()
        assert "hist_state" in report["hosts"][host]


class TestHonestHealthz:
    """/healthz degrades honestly (ISSUE 12 satellite): "degraded" with
    machine-readable reasons when breakers are not closed, a recovery
    supervisor is mid-flight, or an SLO is in fast-burn — and the JSON
    shape is pinned."""

    _SHAPE = {"ok", "status", "reasons", "t", "host", "pid", "seq",
              "interval_s", "watching", "breached", "alerts"}

    def test_clean_process_is_ok_with_pinned_shape(self):
        pub = MetricsPublisher(interval_s=60, spool_dir=None, port=None)
        try:
            h = pub.health()
            assert self._SHAPE <= set(h)
            assert h["status"] == "ok" and h["ok"] is True
            assert h["reasons"] == []
        finally:
            pub.close()

    def test_tripped_breaker_degrades(self, monkeypatch):
        from blit.parallel import pool as pool_mod
        from blit.parallel.pool import WorkerPool

        pub = MetricsPublisher(interval_s=60, spool_dir=None, port=None)
        wp = WorkerPool(["h0"], backend="local")
        try:
            br = wp.workers[0].breaker
            for _ in range(br.threshold):
                br.record_failure()
            monkeypatch.setattr(pool_mod, "_current", wp)
            h = pub.health()
            assert h["status"] == "degraded" and h["ok"] is False
            assert any(r.startswith("breaker-open:") for r in h["reasons"])
            br.record_success()
            h = pub.health()
            assert h["status"] == "ok"
        finally:
            wp.shutdown()
            pub.close()

    def test_slo_fast_burn_degrades(self):
        pub = MetricsPublisher(
            interval_s=60, spool_dir=None, port=None,
            objectives=[{"name": "lat", "metric": "m.s",
                         "threshold": 0.01, "budget": 0.01}])
        try:
            tl = Timeline()
            for _ in range(50):
                tl.observe("m.s", 1.0)  # every sample is bad
            for _ in range(6):
                pub.slo.observe(
                    monitor._delta_timeline(tl, None), 1.0)
            assert pub.slo.breached() == ["lat"]
            h = pub.health()
            assert h["status"] == "degraded"
            assert "slo-fast-burn:lat" in h["reasons"]
        finally:
            pub.close()

    def test_recover_hook_degrades(self):
        from blit.recover import _register, _unregister

        pub = MetricsPublisher(interval_s=60, spool_dir=None, port=None)
        try:
            key = _register({"kind": "reduce", "phase": "recovering",
                             "attempt": 2, "plan": "sharded"})
            try:
                h = pub.health()
                assert h["status"] == "degraded"
                assert any(r.startswith("recover:") for r in h["reasons"])
            finally:
                _unregister(key)
            assert pub.health()["status"] == "ok"
        finally:
            pub.close()
