"""Health-checked worker pool (SURVEY.md §5; VERDICT r3 item 3): call
deadlines that KILL a wedged-but-alive agent, reuse-time ping health
checks, and fan-in timeouts — the liveness bounds the reference's
blocking ``fetch.`` lacked."""

import io
import os
import sys
import time

import pytest

from blit.agent import ping
from blit.parallel.pool import WorkerError, WorkerPool
from blit.parallel.remote import (
    _BANNER_SCAN_LIMIT,
    _await_banner,
    RemoteError,
    RemoteWorker,
    agent_env_with_repo,
    local_agent_command,
)

HERE = os.path.dirname(os.path.abspath(__file__))


def wedged_command():
    return [sys.executable, os.path.join(HERE, "_wedged_agent.py")]


def real_or_wedged_transport(host):
    return wedged_command() if host == "wedged" else local_agent_command()


class TestCallDeadline:
    def test_wedged_agent_times_out_and_is_killed(self):
        w = RemoteWorker("wedged", wedged_command(),
                         env=agent_env_with_repo(), call_timeout=1.0)
        t0 = time.monotonic()
        with pytest.raises(RemoteError) as ei:
            w.call(ping)
        assert ei.value.etype == "CallTimeout"
        assert time.monotonic() - t0 < 30
        # The agent was killed and forgotten: next use respawns.
        assert w._proc is None

    def test_none_timeout_still_blocks_on_healthy_agent(self):
        # call_timeout=None is the reference's blocking behavior; a healthy
        # agent answers and no watchdog interferes.
        w = RemoteWorker("h", local_agent_command(),
                         env=agent_env_with_repo(), call_timeout=None)
        try:
            assert w.call(ping) == "pong"
        finally:
            w.close()

    def test_broadcast_completes_with_live_results(self):
        # THE VERDICT scenario: one wedged agent must not block the
        # broadcast — it becomes a WorkerError, the rest stay live.
        pool = WorkerPool(
            ["h0", "wedged", "h2"], backend="remote",
            transport=real_or_wedged_transport,
            agent_env=agent_env_with_repo(), call_timeout=1.5,
        )
        try:
            res = pool.broadcast(ping, on_error="capture")
        finally:
            pool.shutdown()
        assert res[0] == "pong" and res[2] == "pong"
        assert isinstance(res[1], WorkerError)
        assert isinstance(res[1].error, RemoteError)
        assert res[1].error.etype == "CallTimeout"


class TestPingHealthCheck:
    def test_wedged_reuse_is_respawned(self):
        # First call answered, then the agent wedges: the reuse-time ping
        # must detect it, kill it, and respawn — the second call succeeds
        # on a fresh agent (ANSWER_FIRST serves exactly one request).
        env = dict(agent_env_with_repo(), ANSWER_FIRST="1")
        w = RemoteWorker("wedged", wedged_command(), env=env,
                         call_timeout=5.0, ping_timeout=0.5,
                         ping_min_idle=0.0)
        try:
            assert w.call(ping) == "pong"
            pid1 = w._proc.pid
            assert w.call(ping) == "pong"
            assert w._proc.pid != pid1  # health check forced a respawn
        finally:
            w.close()

    def test_healthy_reuse_keeps_agent(self):
        w = RemoteWorker("h", local_agent_command(),
                         env=agent_env_with_repo(), ping_timeout=10.0,
                         ping_min_idle=0.0)
        try:
            assert w.call(ping) == "pong"
            pid1 = w._proc.pid
            assert w.call(ping) == "pong"
            assert w._proc.pid == pid1
        finally:
            w.close()

    def test_recently_responsive_agent_skips_ping(self, monkeypatch):
        # Within ping_min_idle of a good reply the probe round trip is
        # skipped (a chatty fan-out must not pay double WAN latency).
        w = RemoteWorker("h", local_agent_command(),
                         env=agent_env_with_repo(), ping_timeout=10.0,
                         ping_min_idle=60.0)
        try:
            assert w.call(ping) == "pong"
            calls = []
            orig = w._transact

            def spy(proc, request, fn_path, timeout):
                calls.append(fn_path)
                return orig(proc, request, fn_path, timeout)

            monkeypatch.setattr(w, "_transact", spy)
            assert w.call(ping) == "pong"
            assert calls == ["blit.agent.ping"]  # the real call only, no probe
        finally:
            w.close()

    def test_err_ping_reply_counts_as_alive(self, monkeypatch):
        # An older remote blit without agent.ping() answers ("err", ...) —
        # the agent is alive and framed, so it must NOT be kill+respawned
        # on every reuse (that would degrade every call to a full ssh
        # round trip).
        w = RemoteWorker("h", local_agent_command(),
                         env=agent_env_with_repo(), ping_timeout=10.0,
                         ping_min_idle=0.0)
        try:
            assert w.call(ping) == "pong"
            pid1 = w._proc.pid
            orig = w._transact

            def old_agent(proc, request, fn_path, timeout):
                if fn_path == "ping":
                    # What an old agent's resolve() failure looks like.
                    orig(proc, request, fn_path, timeout)  # keep stream framed
                    return ("err", "AttributeError",
                            "module 'blit.agent' has no attribute 'ping'", "")
                return orig(proc, request, fn_path, timeout)

            monkeypatch.setattr(w, "_transact", old_agent)
            assert w.call(ping) == "pong"
            assert w._proc.pid == pid1  # alive: no respawn
        finally:
            w.close()

    def test_ping_disabled_skips_probe(self):
        w = RemoteWorker("h", local_agent_command(),
                         env=agent_env_with_repo(), ping_timeout=None)
        try:
            assert w.call(ping) == "pong"
            assert w.call(ping) == "pong"
        finally:
            w.close()


class TestFanInTimeout:
    def test_thread_backend_timeout_captured(self):
        pool = WorkerPool(["a", "b"], backend="thread")
        try:
            res = pool.run_on(
                [1, 2], time.sleep, [(1.0,), (0,)], on_error="capture",
                timeout=0.2,
            )
        finally:
            pool.shutdown()
        assert isinstance(res[0], WorkerError)
        assert isinstance(res[0].error, TimeoutError)
        assert res[1] is None  # time.sleep(0) completed

    def test_timeout_raises_without_capture(self):
        pool = WorkerPool(["a"], backend="thread")
        try:
            with pytest.raises(TimeoutError):
                pool.run_on([1], time.sleep, [(1.0,)], timeout=0.2)
        finally:
            pool.shutdown()

    def test_capture_past_deadline_fails_remaining_immediately(self):
        # One shared deadline across the ordered waits: once it has
        # passed, every remaining future gets an immediate-expiry poll —
        # wall clock is ~timeout, NOT the sum of the workers' sleeps.
        pool = WorkerPool(["a", "b", "c"], backend="thread")
        try:
            t0 = time.monotonic()
            res = pool.run_on(
                [1, 2, 3], time.sleep, [(0.4,), (5.0,), (5.0,)],
                on_error="capture", timeout=0.15,
            )
            wall = time.monotonic() - t0
        finally:
            pool.shutdown()
        assert all(isinstance(r, WorkerError) for r in res)
        assert all(isinstance(r.error, TimeoutError) for r in res)
        assert wall < 4.0  # never waited on the 5s sleepers

    def test_timeout_is_builtin_timeout_error(self):
        # Py<3.11 raises concurrent.futures.TimeoutError from the future;
        # the fan-in must normalize to the builtin so callers catch one
        # type (and the message names the late worker).
        pool = WorkerPool(["a"], backend="thread")
        try:
            res = pool.run_on([1], time.sleep, [(1.0,)],
                              on_error="capture", timeout=0.1)
        finally:
            pool.shutdown()
        assert type(res[0].error) is TimeoutError
        assert "worker 1" in str(res[0].error)


class TestBannerScan:
    def test_eof_before_handshake_is_agent_died(self):
        # ssh exits (bad host key, refused connection) before the agent
        # ever spoke: the scan must fail loudly as AgentDied, not hang.
        with pytest.raises(RemoteError) as ei:
            _await_banner(io.BytesIO(b"some ssh error\n"), "h")
        assert ei.value.etype == "AgentDied"
        assert "before handshake" in str(ei.value)

    def test_immediate_eof_is_agent_died(self):
        with pytest.raises(RemoteError) as ei:
            _await_banner(io.BytesIO(b""), "h")
        assert ei.value.etype == "AgentDied"

    def test_over_limit_banner_noise_is_no_handshake(self):
        # An rc file that babbles past the scan limit (or a shell prompt
        # loop) must be rejected as NoHandshake, bounded at the limit.
        noisy = io.BytesIO(b"x" * (_BANNER_SCAN_LIMIT + 64))
        with pytest.raises(RemoteError) as ei:
            _await_banner(noisy, "h")
        assert ei.value.etype == "NoHandshake"
        # The scan stopped AT the limit instead of draining the stream.
        assert noisy.tell() <= _BANNER_SCAN_LIMIT + 1


class TestConfigPlumbing:
    def test_pool_defaults_from_config(self):
        from blit.config import DEFAULT

        pool = WorkerPool(["a"], backend="local")
        try:
            # Opt-in deadline (ADVICE r4): no finite default sits above
            # every legitimate call, so the default is block-forever.
            assert pool.call_timeout is None and DEFAULT.call_timeout is None
            assert pool.ping_timeout == DEFAULT.ping_timeout == 30.0
        finally:
            pool.shutdown()

    def test_pool_override_reaches_remote_worker(self):
        pool = WorkerPool(
            ["h"], backend="remote", transport=real_or_wedged_transport,
            agent_env=agent_env_with_repo(), call_timeout=123.0,
            ping_timeout=7.0,
        )
        try:
            rw = pool.workers[0].remote
            assert rw.call_timeout == 123.0 and rw.ping_timeout == 7.0
        finally:
            pool.shutdown()

    def test_explicit_none_disables_deadlines(self):
        # None must mean "disable" (blocking fetch), not "inherit config".
        pool = WorkerPool(
            ["h"], backend="remote", transport=real_or_wedged_transport,
            agent_env=agent_env_with_repo(), call_timeout=None,
            ping_timeout=None,
        )
        try:
            rw = pool.workers[0].remote
            assert pool.call_timeout is None and rw.call_timeout is None
            assert pool.ping_timeout is None and rw.ping_timeout is None
        finally:
            pool.shutdown()


class TestHalfOpenProbe:
    """The half-open probe at the POOL surface (ISSUE 12 satellite):
    after the cooldown a tripped host admits ONE probe; success
    re-closes, failure re-trips — and pool.health() reports half_open
    so capacity consumers keep treating the probing host as degraded."""

    def _tripped_pool(self):
        pool = WorkerPool(["h0"], backend="local")
        br = pool.workers[0].breaker
        for _ in range(br.threshold):
            br.record_failure()
        assert pool.health()[0]["state"] == "open"
        base = time.monotonic()
        br.clock = lambda: base + br.cooldown_s + 1  # past the cooldown
        return pool, br

    def test_trip_half_open_close(self):
        pool, br = self._tripped_pool()
        assert br.allow()  # consumes THE probe slot
        row = pool.health()[0]
        assert row["state"] == "half-open" and row["half_open"] is True
        assert not br.allow()  # a second caller must NOT slip through
        br.record_success()
        row = pool.health()[0]
        assert row["state"] == "closed" and row["half_open"] is False
        pool.shutdown()

    def test_trip_half_open_retrip(self):
        pool, br = self._tripped_pool()
        assert br.allow()
        assert pool.health()[0]["half_open"] is True
        tripped = br.record_failure()
        assert tripped  # ONE probe failure re-trips, not threshold more
        row = pool.health()[0]
        assert row["state"] == "open" and row["trips"] == 2
        pool.shutdown()

    def test_half_open_host_stays_out_of_the_budget(self):
        # The recovered-then-flaky flap fix: only a fully CLOSED
        # breaker restores scheduler budget — the probe phase does not.
        from blit.serve.scheduler import Scheduler

        pool, br = self._tripped_pool()
        pool2 = WorkerPool(["h0", "h1"], backend="local")
        pool2.workers[0].breaker = br
        s = Scheduler(max_concurrency=2, pool=pool2)
        assert s.effective_budget() == 1  # open: degraded
        assert br.allow()  # half-open probe in flight
        assert s.effective_budget() == 1  # STILL degraded — no flap
        br.record_success()
        assert s.effective_budget() == 2  # closed: restored
        pool.shutdown()
        pool2.shutdown()
