"""Windowed collective data plane (VERDICT r5 missing #2 / ISSUE 1
tentpole): long recordings stream through bounded, double-buffered
windows — beam powers and visibilities must come out byte-identical
(float32) to the one-shot path on the same data, arbitrary start offset
included, with integration state carried across window boundaries."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.ops.channelize import pfb_coeffs  # noqa: E402
from blit.parallel.antenna import (  # noqa: E402
    AntennaStream,
    CorrelatorStream,
    load_antennas_mesh,
    load_correlator_mesh,
)
from blit.parallel.beamform import (  # noqa: E402
    beamform,
    beamform_accumulate,
    beamform_stream,
    weight_sharding,
)
from blit.parallel.correlator import (  # noqa: E402
    correlate,
    correlate_np,
    correlate_stream,
)
from blit.parallel.mesh import make_mesh  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NANT, NCHAN, NPOL = 4, 4, 2
KEPT = 960          # gap-free samples per recording
START = 48          # every test re-enters mid-recording
TOTAL = 896         # samples consumed from START (multiple of NINT)
W = 128             # beamform window (TOTAL/W = 7 windows)
NINT = 4
NFFT, NTAP, WF = 16, 4, 8  # correlator: 8-frame windows


@pytest.fixture(scope="module")
def ant_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("stream_ants")
    paths = []
    for a in range(NANT):
        p = str(d / f"ant{a}.raw")
        synth_raw(p, nblocks=2, obsnchan=NCHAN, ntime_per_block=KEPT // 2,
                  seed=100 + a, tone_chan=a % NCHAN)
        paths.append(p)
    return paths


@pytest.fixture(scope="module")
def weights():
    rng = np.random.default_rng(3)
    w = (rng.standard_normal((5, NANT, NCHAN))
         + 1j * rng.standard_normal((5, NANT, NCHAN))).astype(np.complex64)
    return w


def put_weights(w, mesh):
    ws = weight_sharding(mesh)
    return (jax.device_put(w.real.astype(np.float32), ws),
            jax.device_put(w.imag.astype(np.float32), ws))


class TestWindowedBeamform:
    def test_windowed_equals_one_shot_bitwise(self, ant_files, weights):
        # TOTAL >> W (7 windows) and a nonzero start offset: per-sample
        # phase/detect math and per-nint integration folds are window-
        # local, so the windowed stream must be BYTE-identical in f32.
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        _, vp = load_antennas_mesh(ant_files, mesh=mesh,
                                   start_sample=START, max_samples=TOTAL)
        one = np.asarray(beamform(vp, wput, mesh=mesh, nint=NINT))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL)
        assert feed.nwindows == 7
        got = np.concatenate(
            list(beamform_stream(feed, wput, mesh=mesh, nint=NINT)), axis=2
        )
        np.testing.assert_array_equal(got, one)

    def test_start_offset_actually_offsets(self, ant_files, weights):
        # The loaders are no longer pinned at sample 0: an offset load
        # equals the tail slice of a zero-offset load, bit for bit.
        mesh = make_mesh(1, 4)
        _, (vr0, _) = load_antennas_mesh(ant_files, mesh=mesh)
        _, (vrs, _) = load_antennas_mesh(ant_files, mesh=mesh,
                                         start_sample=START)
        np.testing.assert_array_equal(
            np.asarray(vrs), np.asarray(vr0)[:, :, START:]
        )

    def test_bf16_windowed_bounded_error(self, ant_files, weights):
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        _, vp = load_antennas_mesh(ant_files, mesh=mesh,
                                   start_sample=START, max_samples=TOTAL)
        one = np.asarray(beamform(vp, wput, mesh=mesh, nint=NINT))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL,
                             dtype="bfloat16")
        got = np.concatenate(
            list(beamform_stream(feed, wput, mesh=mesh, nint=NINT)), axis=2
        )
        # bf16 residency: weight rounding + bf16 partial sums (~1e-2 max
        # rel err on detected power, DESIGN.md §9 r5 addendum).
        np.testing.assert_allclose(got, one, rtol=3e-2,
                                   atol=3e-2 * np.abs(one).max())

    def test_chan_layout_windowed_bitwise(self, ant_files, weights):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from blit.ops.pallas_beamform import pack_weights

        mesh = make_mesh(1, 4)
        kwr, kwi = pack_weights(
            jnp.asarray(weights.real.astype(np.float32)),
            jnp.asarray(weights.imag.astype(np.float32)),
        )
        kwp = jax.device_put(
            (np.asarray(kwr), np.asarray(kwi)),
            NamedSharding(mesh, P(None, None, "bank")),
        )
        _, vpc = load_antennas_mesh(ant_files, mesh=mesh, layout="chan",
                                    start_sample=START, max_samples=TOTAL)
        one = np.asarray(beamform(vpc, kwp, mesh=mesh, nint=NINT,
                                  layout="chan"))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL,
                             layout="chan")
        got = np.concatenate(
            list(beamform_stream(feed, kwp, mesh=mesh, nint=NINT,
                                 layout="chan")),
            axis=3,  # chan layout: time is last
        )
        np.testing.assert_array_equal(got, one)

    def test_accumulate_carries_state_on_device(self, ant_files, weights):
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        _, vp = load_antennas_mesh(ant_files, mesh=mesh,
                                   start_sample=START, max_samples=TOTAL)
        one = np.asarray(beamform(vp, wput, mesh=mesh, nint=NINT))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL)
        tot = np.asarray(beamform_accumulate(feed, wput, mesh=mesh))
        np.testing.assert_allclose(
            tot, one.sum(axis=2, keepdims=True),
            rtol=1e-4, atol=1e-4 * np.abs(one).max(),
        )

    def test_window_must_hold_whole_integrations(self, ant_files, weights):
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=100,
                             start_sample=START, max_samples=TOTAL)
        with pytest.raises(ValueError, match="whole number"):
            list(beamform_stream(feed, wput, mesh=mesh, nint=3))

    def test_feed_stage_bytes(self, ant_files):
        # Every feed stage with nonzero seconds carries nonzero bytes (or
        # is declared byte-free) — the observability invariant.
        mesh = make_mesh(1, 4)
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL)
        for win in feed:
            win.release()
        assert set(feed.timeline.stages) >= {"ingest", "pack", "transfer"}
        for name, st in feed.timeline.stages.items():
            assert st.bytes > 0 or st.byte_free, name


class TestWindowedCorrelator:
    def one_shot(self, ant_files, mesh, **kw):
        _, cvp = load_correlator_mesh(ant_files, mesh=mesh, nfft=NFFT,
                                      ntap=NTAP, start_sample=START)
        import jax.numpy as jnp

        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT).astype(np.float32))
        return cvp, coeffs, correlate(cvp, coeffs, mesh=mesh, nfft=NFFT,
                                      ntap=NTAP, **kw)

    def test_windowed_equals_acc_frames_bitwise(self, ant_files):
        # total frames per band segment = 25 >> WF=8 (3 full windows + a
        # ragged 1-frame tail), nonzero start offset, PFB tail carried
        # between windows: byte-identical in f32 to the one-shot call at
        # the same accumulation granularity.
        mesh = make_mesh(2, 2)
        _, coeffs, one_acc = self.one_shot(ant_files, mesh, acc_frames=WF)
        feed = CorrelatorStream(ant_files, mesh=mesh, nfft=NFFT, ntap=NTAP,
                                window_frames=WF, start_sample=START)
        assert feed.nwindows == 4 and feed.spans[-1][1] == 1  # ragged tail
        visr, visi = correlate_stream(feed, coeffs, mesh=mesh, nfft=NFFT,
                                      ntap=NTAP)
        np.testing.assert_array_equal(np.asarray(visr),
                                      np.asarray(one_acc[0]))
        np.testing.assert_array_equal(np.asarray(visi),
                                      np.asarray(one_acc[1]))

    def test_windowed_close_to_default_and_golden(self, ant_files):
        from blit.io.guppi import open_raw

        mesh = make_mesh(2, 2)
        _, coeffs, one_def = self.one_shot(ant_files, mesh)
        feed = CorrelatorStream(ant_files, mesh=mesh, nfft=NFFT, ntap=NTAP,
                                window_frames=WF, start_sample=START)
        ntime = feed.seg * feed.nband
        visr, visi = correlate_stream(feed, coeffs, mesh=mesh, nfft=NFFT,
                                      ntap=NTAP)
        # vs the default one-shot: same math, different float sum order.
        np.testing.assert_allclose(np.asarray(visr), np.asarray(one_def[0]),
                                   rtol=1e-3, atol=0.5)
        # vs the complex NumPy golden fed the same offset samples.
        vs = []
        for p in ant_files:
            raw = open_raw(p)
            buf = np.empty((NCHAN, KEPT, NPOL, 2), np.int8)
            filled = 0
            for i in range(raw.nblocks):
                nt = raw.block_ntime_kept(i)
                raw.read_block_into(i, buf[:, filled:], 0, nt)
                filled += nt
            v = buf[:, START:START + ntime]
            vs.append(v[..., 0].astype(np.float32)
                      + 1j * v[..., 1].astype(np.float32))
        golden = correlate_np(np.stack(vs).astype(np.complex64),
                              pfb_coeffs(NTAP, NFFT).astype(np.float32),
                              NFFT, NTAP, nsegments=2)
        np.testing.assert_allclose(np.asarray(visr), golden.real,
                                   rtol=1e-3, atol=0.5)
        np.testing.assert_allclose(np.asarray(visi), golden.imag,
                                   rtol=1e-3, atol=0.5)

    def test_packed_layout_windowed_bitwise(self, ant_files):
        mesh = make_mesh(2, 2)
        _, coeffs, one_acc = self.one_shot(ant_files, mesh, acc_frames=WF,
                                           vis_layout="packed")
        feed = CorrelatorStream(ant_files, mesh=mesh, nfft=NFFT, ntap=NTAP,
                                window_frames=WF, start_sample=START)
        visr, visi = correlate_stream(feed, coeffs, mesh=mesh, nfft=NFFT,
                                      ntap=NTAP, vis_layout="packed")
        np.testing.assert_array_equal(np.asarray(visr),
                                      np.asarray(one_acc[0]))
        np.testing.assert_array_equal(np.asarray(visi),
                                      np.asarray(one_acc[1]))

    def test_bf16_windowed_bounded_error(self, ant_files):
        mesh = make_mesh(2, 2)
        _, coeffs, one_def = self.one_shot(ant_files, mesh)
        feed = CorrelatorStream(ant_files, mesh=mesh, nfft=NFFT, ntap=NTAP,
                                window_frames=WF, start_sample=START,
                                dtype="bfloat16")
        visr, _ = correlate_stream(feed, coeffs, mesh=mesh, nfft=NFFT,
                                   ntap=NTAP)
        ref = np.asarray(one_def[0])
        err = np.abs(np.asarray(visr) - ref).max() / np.abs(ref).max()
        assert err < 1e-2  # bf16 spectra staging bound (DESIGN.md §9 r5)

    def test_acc_frames_matches_default_within_rounding(self, ant_files):
        mesh = make_mesh(2, 2)
        _, _, one_def = self.one_shot(ant_files, mesh)
        _, _, one_acc = self.one_shot(ant_files, mesh, acc_frames=WF)
        np.testing.assert_allclose(np.asarray(one_acc[0]),
                                   np.asarray(one_def[0]),
                                   rtol=1e-3, atol=0.5)

    def test_empty_feed_raises(self):
        import jax.numpy as jnp

        mesh = make_mesh(2, 2)
        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT).astype(np.float32))
        with pytest.raises(ValueError, match="no windows"):
            correlate_stream(iter(()), coeffs, mesh=mesh, nfft=NFFT,
                             ntap=NTAP)


class TestFeedMachinery:
    def test_host_residency_is_prefetch_bounded(self, ant_files):
        # The feed allocates prefetch_depth slots, not one per window:
        # host memory is bounded by the rotation, not recording length.
        mesh = make_mesh(1, 4)
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=64,
                             max_samples=TOTAL, prefetch_depth=2)
        assert feed.nwindows == TOTAL // 64
        for win in feed:
            win.release()
        assert len(feed._store) == 2

    def test_correlator_stream_rejects_short_segments(self, tmp_path):
        paths = []
        for a in range(2):
            p = str(tmp_path / f"s{a}.raw")
            synth_raw(p, nblocks=1, obsnchan=4, ntime_per_block=64, seed=a)
            paths.append(p)
        mesh = make_mesh(2, 2)
        with pytest.raises(ValueError, match="blocks per band segment"):
            CorrelatorStream(paths, mesh=mesh, nfft=64, window_frames=4)

    def test_holding_every_window_raises_not_hangs(self, ant_files):
        # A consumer that keeps all prefetch_depth windows unreleased
        # while asking for more has starved the producer permanently —
        # that must be a loud RuntimeError, not a silent deadlock.
        mesh = make_mesh(1, 4)
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=64,
                             max_samples=TOTAL, prefetch_depth=2)
        held = []
        with pytest.raises(RuntimeError, match="starved"):
            for win in feed:
                held.append(win)  # never release
        for win in held:
            win.release()

    def test_stream_error_propagates(self, ant_files, tmp_path):
        # A producer-side failure re-raises in the consumer, not a hang.
        mesh = make_mesh(1, 4)
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             max_samples=TOTAL)
        os.truncate(ant_files[0], 200)  # decapitate after open
        try:
            with pytest.raises(Exception):
                for win in feed:
                    win.release()
        finally:
            synth_raw(ant_files[0], nblocks=2, obsnchan=NCHAN,
                      ntime_per_block=KEPT // 2, seed=100, tone_chan=0)
