"""Telemetry plane (ISSUE 5): spans + fan-out trace propagation,
log-bucketed histograms, fleet harvest/merge, the flight recorder, JSON
logging, and the telemetry/trace-view CLI.

Includes the ISSUE 5 Timeline-concurrency satellite: merge() is
commutative/associative on disjoint and overlapping stage keys, report()
survives producer-thread stage insertion, and reset() preserves object
identity (the BENCH_r05 "0 bytes" regression pin).
"""

import json
import logging
import threading
import time
from io import StringIO

import pytest

jax = pytest.importorskip("jax")

from blit import faults, observability  # noqa: E402
from blit.observability import (  # noqa: E402
    HistogramStats,
    Timeline,
    configure_logging,
    merge_fleet,
    render_fleet_text,
    render_flight_dump,
    render_prometheus,
    telemetry_snapshot,
)
from blit.parallel.pool import WorkerPool  # noqa: E402
from blit.parallel.remote import (  # noqa: E402
    agent_env_with_repo,
    local_agent_command,
)
from blit.testing import synth_raw  # noqa: E402


@pytest.fixture(autouse=True)
def clean_telemetry():
    """Drain the process-global tracer/flight ring around each test (the
    process timeline and fault counters are cumulative by design — tests
    assert deltas or structure, never absolute totals)."""
    tr = observability.tracer()
    was_enabled = tr.enabled
    tr.enabled = True
    tr.reset()
    observability.flight_recorder().clear()
    yield
    tr.enabled = was_enabled
    tr.reset()
    observability.flight_recorder().clear()


def local_transport(host):
    return local_agent_command()


# -- histograms -------------------------------------------------------------


class TestHistogramStats:
    def test_quantiles_within_one_bucket(self):
        h = HistogramStats()
        for v in [0.001] * 90 + [1.0] * 10:
            h.observe(v)
        r = h.report()
        assert r["n"] == 100
        # Log2 buckets: estimates are good to a factor of 2.
        assert 0.0005 <= r["p50"] <= 0.002
        assert 0.5 <= r["p99"] <= 2.0
        assert r["max"] == 1.0  # exact envelope, never a bucket estimate

    def test_bounded_memory(self):
        h = HistogramStats()
        for i in range(100_000):
            h.observe((i % 1000) * 1e-4)
        assert len(h.counts) == 64
        assert h.n == 100_000

    def test_merge_commutative(self):
        a, b = HistogramStats(), HistogramStats()
        for v in (0.01, 0.02, 5.0):
            a.observe(v)
        for v in (1e-7, 0.3):
            b.observe(v)
        ab = HistogramStats().merge(a).merge(b)
        ba = HistogramStats().merge(b).merge(a)
        assert ab.state() == ba.state()
        assert ab.n == 5 and ab.vmin == 1e-7 and ab.vmax == 5.0

    def test_state_roundtrip_is_exact(self):
        h = HistogramStats()
        for v in (0.004, 0.2, 7.0):
            h.observe(v)
        st = json.loads(json.dumps(h.state()))  # survives the wire
        assert HistogramStats.from_state(st).state() == h.state()

    def test_empty(self):
        r = HistogramStats().report()
        assert r == {"n": 0, "mean": 0.0, "p50": 0.0, "p90": 0.0,
                     "p99": 0.0, "max": 0.0}


# -- Timeline merge / concurrency (ISSUE 5 satellite) ----------------------


def _tl(stages=(), counts=(), gauges=(), hists=()):
    tl = Timeline()
    for name, calls, seconds, nbytes in stages:
        s = tl.stages[name]
        s.calls, s.seconds, s.bytes = calls, seconds, nbytes
    for name, n in counts:
        tl.count(name, n)
    for name, v in gauges:
        tl.gauge(name, v)
    for name, v in hists:
        tl.observe(name, v)
    return tl


class TestTimelineMerge:
    def test_merge_commutative_disjoint_and_overlapping(self):
        def mk_a():
            return _tl(stages=[("ingest", 2, 1.0, 100), ("device", 1, 0.5, 50)],
                       hists=[("lat", 0.01)])

        def mk_b():
            # Overlaps "ingest", disjoint "write".
            return _tl(stages=[("ingest", 3, 2.0, 300), ("write", 4, 0.25, 70)],
                       hists=[("lat", 0.04), ("wait", 1.0)])

        ab = Timeline().merge(mk_a()).merge(mk_b())
        ba = Timeline().merge(mk_b()).merge(mk_a())
        assert ab.state()["stages"] == ba.state()["stages"]
        assert ab.state()["hists"] == ba.state()["hists"]
        assert ab.stages["ingest"].calls == 5
        assert ab.stages["ingest"].bytes == 400
        assert ab.stages["write"].calls == 4
        assert ab.hists["lat"].n == 2

    def test_merge_associative(self):
        def mk(i):
            return _tl(stages=[("s", i, float(i), 10 * i),
                               (f"only{i}", 1, 0.1, 1)],
                       hists=[("h", 0.001 * (i + 1))])

        left = Timeline().merge(Timeline().merge(mk(1)).merge(mk(2))).merge(mk(3))
        right = Timeline().merge(mk(1)).merge(Timeline().merge(mk(2)).merge(mk(3)))
        assert left.state()["stages"] == right.state()["stages"]
        assert left.state()["hists"] == right.state()["hists"]

    def test_merge_byte_free_and_gauges(self):
        a = _tl(counts=[("retry", 2)], gauges=[("depth", 3.0)])
        b = _tl(gauges=[("depth", 9.0)])
        a.merge(b)
        assert a.stages["retry"].byte_free
        g = a.gauges["depth"]
        assert g.n == 2 and g.lo == 3.0 and g.hi == 9.0

    def test_state_roundtrip(self):
        tl = _tl(stages=[("x", 7, 1.25, 99)], counts=[("c", 3)],
                 gauges=[("g", 0.5)], hists=[("h", 0.02)])
        st = json.loads(json.dumps(tl.state()))
        back = Timeline.from_state(st)
        assert back.state() == tl.state()
        assert back.stages["c"].byte_free

    def test_report_safe_under_producer_insertion(self):
        """ISSUE 5 satellite: report() must never raise while a producer
        thread is inserting new stage keys (the window feeds do exactly
        this during consumer-side reporting)."""
        tl = Timeline()
        stop = threading.Event()
        errs = []

        def producer():
            i = 0
            try:
                while not stop.is_set():
                    with tl.stage(f"s{i % 501}", nbytes=1):
                        pass
                    tl.observe(f"h{i % 97}", 1e-4)
                    i += 1
            except Exception as e:  # noqa: BLE001 — reported to the assert
                errs.append(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 1.0
            while time.monotonic() < deadline:
                rep = tl.report()
                assert isinstance(rep, dict)
                tl.state()
                tl.snapshot()
        finally:
            stop.set()
            t.join(5)
        assert not errs

    def test_reset_preserves_identity_bench_r05_shape(self):
        """Regression pin (BENCH_r05 "stream bytes: 0"): a thread holding
        a StageStats/HistogramStats across reset() must keep feeding the
        SAME objects the report reads."""
        tl = Timeline()
        with tl.stage("stream", nbytes=100):
            pass
        tl.observe("lat", 0.5)
        held_stage = tl.stages["stream"]
        held_hist = tl.hists["lat"]
        tl.reset()
        assert tl.stages["stream"] is held_stage
        assert tl.hists["lat"] is held_hist
        held_stage.bytes += 42
        held_hist.observe(0.25)
        rep = tl.report()
        assert rep["stream"]["bytes"] == 42
        assert rep["hists"]["lat"]["n"] == 1


# -- spans ------------------------------------------------------------------


class TestSpans:
    def test_nesting_and_trace_linkage(self):
        tr = observability.tracer()
        with tr.span("outer") as outer:
            with tr.span("inner", k="v") as inner:
                assert tr.context() == {"trace": inner.trace_id,
                                        "span": inner.span_id}
        spans = {s.name: s for s in tr.spans()}
        assert spans["inner"].parent_id == spans["outer"].span_id
        assert spans["inner"].trace_id == spans["outer"].trace_id
        assert spans["outer"].parent_id is None
        assert spans["inner"].attrs == {"k": "v"}
        assert spans["inner"].duration_s >= 0.0

    def test_disabled_tracer_records_nothing(self):
        tr = observability.tracer()
        tr.enabled = False
        with tr.span("x") as sp:
            assert sp is None
        assert tr.context() is None
        assert tr.spans() == []

    def test_activate_adopts_cross_thread_context(self):
        tr = observability.tracer()
        with tr.span("driver"):
            ctx = tr.context()
        out = {}

        def worker():
            with tr.activate(ctx), tr.span("remote-leg") as sp:
                out["span"] = sp

        t = threading.Thread(target=worker)
        t.start()
        t.join(5)
        assert out["span"].trace_id == ctx["trace"]
        assert out["span"].parent_id == ctx["span"]

    def test_export_chrome_is_perfetto_shaped(self, tmp_path):
        tr = observability.tracer()
        with tr.span("a"):
            with tr.span("b"):
                pass
        path = tr.export_chrome(str(tmp_path / "t.json"))
        doc = json.load(open(path))
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {e["name"] for e in evs} == {"a", "b"}
        for e in evs:
            assert {"ts", "dur", "pid", "tid"} <= set(e)
        meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        assert meta and meta[0]["name"] == "process_name"

    def test_export_chrome_dedupes_harvested_spans(self):
        tr = observability.tracer()
        with tr.span("a"):
            pass
        doc = tr.export_chrome(extra=tr.span_dicts())
        assert len([e for e in doc["traceEvents"] if e.get("ph") == "X"]) == 1

    def test_span_buffer_is_bounded(self):
        tr = observability.Tracer(max_spans=8)
        for i in range(100):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.spans()) == 8
        assert tr.spans()[-1].name == "s99"


# -- pool propagation + fleet harvest ---------------------------------------


def _touch_process_timeline(tag="t"):
    """Worker-side probe: records on the process timeline like real
    worker entry points do (module-level so every backend can ship it)."""
    from blit.observability import process_timeline

    with process_timeline().stage(f"probe.{tag}", nbytes=10):
        pass
    return observability.tracer().context() is not None


class TestPoolPropagation:
    def test_thread_backend_spans_parent_onto_driver(self):
        tr = observability.tracer()
        with WorkerPool(["a", "b"], backend="thread") as pool:
            with tr.span("fanout") as root:
                res = pool.run_on([1, 2], _touch_process_timeline,
                                  [("a",), ("b",)])
        assert res == [True, True]  # ambient ctx visible worker-side
        pool_spans = [s for s in tr.spans()
                      if s.name == "pool._touch_process_timeline"]
        assert len(pool_spans) == 2
        assert all(s.parent_id == root.span_id for s in pool_spans)
        assert {s.attrs["worker"] for s in pool_spans} == {1, 2}

    def test_harvest_merges_thread_workers_once(self):
        with WorkerPool(["a", "b"], backend="thread") as pool:
            pool.run_on([1, 2], _touch_process_timeline,
                        [("m1",), ("m1",)])
            report = pool.harvest_telemetry()
        host = observability.hostname()
        assert list(report["hosts"]) == [host]
        entry = report["hosts"][host]
        # Both thread workers answer from the driver process: dedupe by
        # (host, pid) counts the snapshot once, not three times.
        assert len(entry["workers"]) == 1
        assert entry["stages"]["probe.m1"]["calls"] == 2
        assert "faults" in entry
        assert report["fleet"]["probe.m1"]["calls"] == 2
        assert "health" in report

    def test_harvest_captures_dead_host_as_error(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        faults.install(faults.FaultRule("remote.call", "fail", times=-1,
                                        match="bad"))
        try:
            pool = WorkerPool(
                ["bad"], backend="remote", transport=local_transport,
                agent_env=agent_env_with_repo(),
            )
            try:
                report = pool.harvest_telemetry(timeout=60)
            finally:
                pool.shutdown()
        finally:
            faults.clear()
        assert "bad" in report.get("errors", {})
        # The driver's own snapshot still reports.
        assert observability.hostname() in report["hosts"]


class TestRemoteFanOutAcceptance:
    """ISSUE 5 acceptance: a multi-worker reduce_to_file run produces a
    Perfetto-loadable trace whose worker spans parent onto the driver
    span, and one merged per-host fleet report with every worker's stage
    table and fault counters."""

    def test_multiworker_reduce_trace_and_fleet_report(self, tmp_path):
        from blit.workers import reduce_raw

        faults.reset_counters()  # the host entry merges driver counters too
        tr = observability.tracer()
        argtuples = []
        for i in range(2):
            raw = str(tmp_path / f"in{i}.raw")
            synth_raw(raw, nblocks=1, obsnchan=2, ntime_per_block=11 * 64,
                      seed=i)
            argtuples.append((raw, str(tmp_path / f"out{i}.fil")))
        # One transient injected read failure per agent process: the
        # harvested report must carry the workers' fault counters.
        env = agent_env_with_repo()
        env["BLIT_FAULTS"] = "guppi.read:fail:1"
        pool = WorkerPool(
            ["hA", "hB"], backend="remote", transport=local_transport,
            agent_env=env,
        )
        try:
            with tr.span("driver-reduce") as root:
                pool.run_on([1, 2], reduce_raw, argtuples,
                            kwargs={"nfft": 64})
            report = pool.harvest_telemetry(timeout=120)
        finally:
            pool.shutdown()

        # (b) one merged per-host fleet report: every worker's stage
        # table (both agent pids under this host) and fault counters.
        host = observability.hostname()
        entry = report["hosts"][host]
        pids = {w["pid"] for w in entry["workers"]}
        assert len(pids) == 3  # 2 agents + the driver
        assert {w["worker"] for w in entry["workers"]} >= {1, 2}
        for stage in ("ingest", "stream", "device", "write"):
            assert entry["stages"][stage]["calls"] >= 2, stage
        assert entry["faults"].get("fault.guppi.read.fail", 0) == 2
        assert entry["faults"].get("retry.io", 0) == 2
        assert report["fleet"]["ingest"]["bytes"] > 0

        # (a) Perfetto-loadable trace whose worker spans parent onto the
        # driver span (via the per-worker pool dispatch spans).
        trace_path = str(tmp_path / "trace.json")
        tr.export_chrome(trace_path, extra=report["spans"])
        doc = json.load(open(trace_path))
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        by_id = {e["args"]["span"]: e for e in evs}
        pool_spans = [e for e in evs if e["name"] == "pool.reduce_raw"]
        agent_spans = [e for e in evs if e["name"] == "agent.reduce_raw"]
        reduce_spans = [e for e in evs if e["name"] == "reduce.to_file"]
        assert len(pool_spans) == 2 and len(agent_spans) == 2
        assert len(reduce_spans) >= 2
        for sp in pool_spans:
            assert sp["args"]["parent"] == root.span_id
        for sp in agent_spans:
            parent = by_id[sp["args"]["parent"]]
            assert parent["name"] == "pool.reduce_raw"
            assert sp["args"]["trace"] == root.trace_id
        for sp in reduce_spans:
            assert by_id[sp["args"]["parent"]]["name"] == "agent.reduce_raw"


class TestMergeFleet:
    def test_per_host_keying_and_fault_sums(self):
        def snap(host, pid, calls, nfaults):
            tl = _tl(stages=[("ingest", calls, 1.0, 100 * calls)])
            return {"host": host, "pid": pid, "worker": pid,
                    "timeline": tl.state(),
                    "faults": {"retry.io": nfaults}, "spans": []}

        report = merge_fleet([snap("h1", 1, 2, 1), snap("h1", 2, 3, 2),
                              snap("h2", 1, 5, 0), None,
                              snap("h1", 1, 99, 99)])  # dup (host,pid)
        assert set(report["hosts"]) == {"h1", "h2"}
        assert report["hosts"]["h1"]["stages"]["ingest"]["calls"] == 5
        assert report["hosts"]["h1"]["faults"]["retry.io"] == 3
        assert report["hosts"]["h2"]["stages"]["ingest"]["calls"] == 5
        assert report["fleet"]["ingest"]["calls"] == 10
        assert report["faults"]["retry.io"] == 3

    def test_renders(self):
        report = merge_fleet([telemetry_snapshot()])
        text = render_fleet_text(report)
        assert "fleet:" in text
        prom = render_prometheus(report)
        assert "# TYPE blit_stage_seconds_total counter" in prom

    def test_duplicate_pid_keeps_richest_snapshot(self):
        """reset=True harvests on the thread backend: whichever worker's
        snapshot call ran first drained the process telemetry, so the
        later (empty) duplicates must not shadow the populated one."""
        rich = {"host": "h", "pid": 1, "worker": 2,
                "timeline": _tl(stages=[("ingest", 4, 1.0, 400)]).state(),
                "faults": {}, "spans": [{"name": "x", "trace": "t",
                                         "span": "s", "t0": 0.0,
                                         "duration_s": 0.1}]}
        empty = {"host": "h", "pid": 1, "worker": 1,
                 "timeline": Timeline().state(), "faults": {}, "spans": []}
        for order in ([empty, rich], [rich, empty]):
            report = merge_fleet(order)
            assert report["hosts"]["h"]["stages"]["ingest"]["calls"] == 4
            assert len(report["spans"]) == 1

    def test_thread_harvest_reset_keeps_the_run(self):
        with WorkerPool(["a", "b"], backend="thread") as pool:
            pool.run_on([1, 2], _touch_process_timeline, [("r",), ("r",)])
            report = pool.harvest_telemetry(reset=True)
        host = observability.hostname()
        assert report["hosts"][host]["stages"]["probe.r"]["calls"] == 2


# -- flight recorder --------------------------------------------------------


class TestFlightRecorder:
    def test_stall_watchdog_dumps_and_trace_view_renders(
            self, tmp_path, monkeypatch, capsys):
        """ISSUE 5 acceptance: a forced stall leaves a dump that
        `python -m blit trace-view` renders with the tripped watchdog
        and the last events before the trip."""
        from blit.__main__ import main
        from blit.pipeline import BufferRotation

        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = observability.flight_recorder()
        monkeypatch.setattr(rec, "min_interval_s", 0.0)
        rec.event("fault", "drill.before-the-trip", n=1)

        def wedged(rot):
            rot.acquire()
            time.sleep(1.2)  # wedged past the watchdog, then exits

        rot = BufferRotation(2, wedged, name="blit-drill-feed",
                             stall_timeout_s=0.2)
        with pytest.raises(RuntimeError, match="stall watchdog"):
            for _ in rot.slots():
                pass
        dumps = sorted(tmp_path.glob("blit-flight-*.json"))
        assert len(dumps) == 1
        doc = json.load(open(dumps[0]))
        assert "producer stalled" in doc["reason"]
        assert any(e["name"] == "drill.before-the-trip"
                   for e in doc["events"])

        assert main(["trace-view", str(dumps[0])]) == 0
        out = capsys.readouterr().out
        assert "blit-drill-feed: producer stalled" in out
        assert "drill.before-the-trip" in out
        assert "stall watchdog" in out

    def test_breaker_trip_dumps(self, tmp_path, monkeypatch):
        from blit.config import SiteConfig

        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setattr(observability.flight_recorder(),
                            "min_interval_s", 0.0)
        faults.install(faults.FaultRule("remote.call", "fail", times=-1))
        try:
            pool = WorkerPool(
                ["h0"], backend="remote", transport=local_transport,
                agent_env=agent_env_with_repo(),
                config=SiteConfig(call_retries=0, breaker_threshold=1,
                                  retry_jitter=0.0),
            )
            try:
                with pytest.raises(Exception):
                    pool.run_on([1], _touch_process_timeline, [()])
            finally:
                pool.shutdown()
        finally:
            faults.clear()
        dumps = list(tmp_path.glob("blit-flight-*.json"))
        assert dumps, "breaker trip / agent death left no flight dump"
        reasons = [json.load(open(d))["reason"] for d in dumps]
        assert any("died" in r or "breaker" in r for r in reasons)

    def test_dump_rate_limited_and_forceable(self, tmp_path, monkeypatch):
        # Rate limiting is per REASON class (ISSUE 15 satellite): a
        # repeat of one reason is suppressed, a different reason is
        # not, and force always overrides.
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        rec = observability.FlightRecorder(min_interval_s=60.0)
        assert rec.dump("first: a") is not None
        assert rec.dump("first: b — suppressed repeat") is None
        assert rec.dump("first: c", force=True) is not None

    def test_ring_is_bounded(self):
        rec = observability.FlightRecorder(capacity=16)
        for i in range(100):
            rec.event("fault", f"e{i}")
        evs = rec.events()
        assert len(evs) == 16 and evs[-1]["name"] == "e99"

    def test_disable_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("BLIT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("BLIT_FLIGHT_DISABLE", "1")
        rec = observability.FlightRecorder(min_interval_s=0.0)
        assert rec.dump("nope") is None
        assert not list(tmp_path.glob("*.json"))

    def test_render_flight_dump_tail(self):
        doc = {"reason": "r", "t": 0, "host": "h", "pid": 1, "worker": 0,
               "events": [{"t": 0, "kind": "stage", "name": f"e{i}", "s": 1}
                          for i in range(50)],
               "faults": {"retry.io": 2}, "timeline": {}}
        out = render_flight_dump(doc, tail=5)
        assert "e49" in out and "e40" not in out and "retry.io" in out


# -- JSON logging (ISSUE 5 satellite) ---------------------------------------


class TestJsonLogging:
    def test_json_lines_records(self, blit_logger_restored):
        buf = StringIO()
        configure_logging(level=logging.INFO, worker=7, json_lines=True,
                          stream=buf)
        logging.getLogger("blit.test").info("hello %s", "fleet")
        logging.getLogger("blit.test").warning("deg raded")
        lines = [ln for ln in buf.getvalue().splitlines() if ln]
        assert len(lines) == 2
        recs = [json.loads(ln) for ln in lines]
        for rec in recs:
            assert set(rec) >= {"ts", "level", "host", "worker", "name",
                                "msg"}
            assert rec["worker"] == 7
            assert rec["host"] == observability.hostname()
        assert recs[0]["msg"] == "hello fleet"
        assert recs[1]["level"] == "WARNING"
        # configure_logging(worker=) also stamps span identity.
        with observability.span("w") as sp:
            pass
        assert sp.worker == 7
        configure_logging(worker=0)  # restore module-global worker id

    def test_worker_startup_threading(self, monkeypatch):
        """The pool stamps each remote agent's env with its worker id and
        the driver's BLIT_LOG_JSON flag rides along (agent.main reads
        both) — worker startup is wired, not just the formatter."""
        monkeypatch.setenv("BLIT_LOG_JSON", "1")
        pool = WorkerPool(["x", "y"], backend="remote",
                          transport=local_transport)
        try:
            envs = [w.remote._env for w in pool.workers]
            assert [e["BLIT_WORKER_ID"] for e in envs] == ["1", "2"]
            assert all(e.get("BLIT_LOG_JSON") == "1" for e in envs)
        finally:
            pool.shutdown()

    def test_ssh_transport_carries_stamp_in_remote_command(self,
                                                           monkeypatch):
        """sshd does not forward client env vars: over the production ssh
        transport the identity stamp must ride the remote command line
        (`env K=V python3 -m blit.agent`)."""
        from blit.parallel.remote import ssh_command

        cmd = ssh_command("blc17", remote_env={"BLIT_WORKER_ID": "3"})
        i = cmd.index("env")
        assert cmd[i:i + 2] == ["env", "BLIT_WORKER_ID=3"]
        assert cmd[-3:] == ["python3", "-m", "blit.agent"]
        # The pool routes the stamp through the transport when it accepts
        # remote_env (the default ssh_command does).
        monkeypatch.delenv("BLIT_LOG_JSON", raising=False)
        seen = {}

        def transport(host, remote_env=None):
            seen[host] = remote_env
            return local_agent_command()

        pool = WorkerPool(["hx", "hy"], backend="remote",
                          transport=transport)
        try:
            assert seen == {"hx": {"BLIT_WORKER_ID": "1"},
                            "hy": {"BLIT_WORKER_ID": "2"}}
        finally:
            pool.shutdown()


# -- CLI --------------------------------------------------------------------


class TestTelemetryCli:
    def test_demo_json_report_and_trace(self, tmp_path, capsys):
        from blit.__main__ import main

        trace = str(tmp_path / "trace.json")
        rc = main(["telemetry", "--demo", "--workers", "2",
                   "--nfft", "64", "--format", "json",
                   "--trace-out", trace])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        host = observability.hostname()
        assert host in report["hosts"]
        assert report["hosts"][host]["stages"]["ingest"]["calls"] >= 2
        doc = json.load(open(trace))
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert "telemetry-demo" in names and "reduce.to_file" in names

    def test_prom_exposition(self, capsys):
        from blit.__main__ import main

        with observability.process_timeline().stage("probe.cli", nbytes=1):
            pass
        rc = main(["telemetry", "--format", "prom"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "# TYPE blit_stage_calls_total counter" in out
        assert 'stage="probe.cli"' in out

    def test_from_file_render(self, tmp_path, capsys):
        from blit.__main__ import main

        report = merge_fleet([telemetry_snapshot()])
        p = tmp_path / "report.json"
        p.write_text(json.dumps(report))
        assert main(["telemetry", "--from", str(p)]) == 0
        assert "fleet:" in capsys.readouterr().out

    def test_trace_out_works_without_demo(self, tmp_path, capsys):
        from blit.__main__ import main

        with observability.span("cli-leg"):
            pass
        trace = tmp_path / "t.json"
        assert main(["telemetry", "--trace-out", str(trace)]) == 0
        doc = json.loads(trace.read_text())
        assert any(e.get("name") == "cli-leg" for e in doc["traceEvents"])


# -- scheduler histogram satellite ------------------------------------------


class TestSchedulerBoundedWaits:
    def test_wait_percentiles_shape_and_bounded_memory(self):
        from blit.serve.scheduler import Scheduler

        s = Scheduler(max_concurrency=2)
        for _ in range(300):
            s.submit(lambda: None).result(timeout=10)
        s.close()
        pct = s.wait_percentiles()
        assert set(pct) == {"p50", "p99", "n"}  # report shape kept
        assert pct["n"] == 300
        assert 0.0 <= pct["p50"] <= pct["p99"]
        # Bounded: the histogram is 64 counters, not a 300-entry list.
        assert len(s.wait_hist.counts) == 64
        assert not hasattr(s, "wait_samples")


# -- retry backoff histogram ------------------------------------------------


class TestRetryBackoffHistogram:
    def test_backoff_observes_process_timeline(self):
        h = observability.process_timeline().hists["retry.backoff_s"]
        n0 = h.n
        policy = faults.RetryPolicy(attempts=3, base_s=0.01, jitter=0.0,
                                    sleep=lambda s: None)
        policy.backoff(0)
        policy.backoff(1)
        assert h.n == n0 + 2
        assert h.vmax >= 0.01
