"""The fleet wire + PeerServer (blit/serve/http.py; ISSUE 14):
product round-trips byte-identical over HTTP, the Overloaded→503
mapping honoring the jittered ``Retry-After``, DeadlineExpired→504,
deadline propagation ON the wire, /healthz (incl. draining), /metrics
parseability, /warm cache-warming, and the wire codecs."""

import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from blit.monitor import parse_prometheus  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.serve import (  # noqa: E402
    DeadlineExpired,
    Overloaded,
    PeerServer,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.serve.cache import fingerprint_for  # noqa: E402
from blit.serve.http import (  # noqa: E402
    WIRE_CTYPE,
    WIRE_HEADER,
    decode_product,
    decode_product_wire,
    encode_product,
    http_json,
    http_request,
    request_from_wire,
    retry_after_from,
    wire_request,
)
from blit.testing import synth_raw  # noqa: E402

NFFT = 128
NTIME = (8 + 3) * NFFT


@pytest.fixture
def raw(tmp_path):
    p = str(tmp_path / "a.raw")
    synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=NTIME,
              tone_chan=1)
    return p


@pytest.fixture
def peer(tmp_path):
    tl = Timeline()
    service = ProductService(
        cache=ProductCache(str(tmp_path / "cache"), ram_bytes=1 << 24,
                           timeline=tl),
        scheduler=Scheduler(max_concurrency=2, queue_depth=8,
                            timeline=tl, retry_seed=3),
        timeline=tl,
    )
    server = PeerServer(service, name="p0",
                        lease_dir=str(tmp_path / "leases"), proc=0,
                        beat_interval_s=0.05).start()
    yield server
    server.close()
    service.close(5)


class TestWireCodecs:
    def test_product_roundtrip_is_byte_exact(self):
        hdr = {"nchans": 4, "tsamp": 1e-5, "src": "unit"}
        data = np.arange(24, dtype=np.float32).reshape(2, 3, 4) * 0.37
        h2, d2 = decode_product(encode_product(hdr, data))
        assert h2 == hdr
        assert d2.dtype == np.float32
        assert d2.tobytes() == data.tobytes()
        assert not d2.flags.writeable  # the frozen-result contract

    def test_request_roundtrip(self, raw):
        req = ProductRequest(raw=raw, nfft=256, nint=2, fqav_by=2)
        doc = wire_request(req, priority=2, client="c1", deadline_s=3.5)
        req2, priority, client, deadline = request_from_wire(doc)
        assert (priority, client, deadline) == (2, "c1", 3.5)
        assert req2.nfft == 256 and req2.nint == 2 and req2.fqav_by == 2

    def test_stream_requests_refuse_the_wire(self, raw):
        req = ProductRequest(raw=raw, kind="stream", out="/tmp/x.fil")
        with pytest.raises(ValueError, match="stream"):
            wire_request(req)


class TestPeerServer:
    def test_product_over_http_matches_direct(self, peer, raw):
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        status, _, body = http_json("POST", peer.url, "/product",
                                    wire_request(req), timeout=120)
        assert status == 200
        _, via_http = decode_product(body)
        _, direct = peer.service.get(req, timeout=120)
        assert np.array_equal(via_http, direct)

    def test_overloaded_maps_to_503_with_jittered_retry_after(
            self, peer, raw, monkeypatch):
        def refuse(*a, **kw):
            raise Overloaded("queue full", retry_after_s=0.321)

        # submit is the peer handler's seam (it needs the ticket for
        # the ISSUE 15 access record) — and where admission refuses.
        monkeypatch.setattr(peer.service, "submit", refuse)
        status, headers, body = http_json(
            "POST", peer.url, "/product",
            wire_request(ProductRequest(raw=raw, nfft=NFFT)), timeout=30)
        assert status == 503
        # The satellite's contract: the jittered hint rides the HTTP
        # header AND the body, exactly.
        assert headers["retry-after"] == "0.321"
        assert body["retry_after_s"] == 0.321
        assert retry_after_from(headers, body) == 0.321

    def test_deadline_expired_maps_to_504(self, peer, raw, monkeypatch):
        def expire(*a, **kw):
            raise DeadlineExpired("dead on arrival")

        monkeypatch.setattr(peer.service, "submit", expire)
        status, _, body = http_json(
            "POST", peer.url, "/product",
            wire_request(ProductRequest(raw=raw, nfft=NFFT)), timeout=30)
        assert status == 504
        assert body["etype"] == "DeadlineExpired"

    def test_deadline_rides_the_wire_into_the_scheduler(
            self, peer, raw, monkeypatch):
        seen = {}
        real = peer.service.submit

        def spy(req, **kw):
            seen.update(kw)
            return real(req, **kw)

        monkeypatch.setattr(peer.service, "submit", spy)
        http_json("POST", peer.url, "/product",
                  wire_request(ProductRequest(raw=raw, nfft=NFFT),
                               deadline_s=7.5), timeout=120)
        assert seen["deadline_s"] == 7.5

    def test_healthz_ok_then_draining(self, peer):
        status, _, body = http_json("GET", peer.url, "/healthz")
        assert status == 200 and body["ok"] and body["name"] == "p0"
        peer.service._draining = True
        _, _, degraded = http_json("GET", peer.url, "/healthz")
        assert not degraded["ok"]
        assert "draining" in degraded["reasons"]

    def test_metrics_parse_as_prometheus(self, peer, raw):
        peer.service.get(ProductRequest(raw=raw, nfft=NFFT), timeout=120)
        status, _, text = http_json("GET", peer.url, "/metrics")
        assert status == 200
        samples = parse_prometheus(text)
        assert samples  # non-empty, every line parseable

    def test_warm_populates_the_cache(self, peer, raw):
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        fp = fingerprint_for(req.reducer(), raw)
        status, _, body = http_json("POST", peer.url, "/warm",
                                    {"recipes": [req.recipe()]},
                                    timeout=30)
        assert status == 202 and body["accepted"] == 1
        deadline = time.monotonic() + 60
        while not peer.service.cache.contains(fp):
            assert time.monotonic() < deadline, "warm never landed"
            time.sleep(0.05)

    def test_lease_beats_land(self, peer, tmp_path):
        from blit.recover import lease_age_s

        time.sleep(0.2)
        age = lease_age_s(str(tmp_path / "leases"), 0)
        assert age is not None and age < 5.0

    def test_stats_surface(self, peer, raw):
        peer.service.get(ProductRequest(raw=raw, nfft=NFFT), timeout=120)
        peer.service.get(ProductRequest(raw=raw, nfft=NFFT), timeout=120)
        status, _, s = http_json("GET", peer.url, "/stats")
        assert status == 200
        assert s["name"] == "p0"
        assert s["cache"]["hit.ram"] >= 1
        assert s["hot"], "hot-entry tracking must surface"

    def test_unknown_route_404s(self, peer):
        status, _, _ = http_json("GET", peer.url, "/nope")
        assert status == 404

    def test_drain_endpoint_refuses_new_work(self, peer, raw):
        status, _, body = http_json("POST", peer.url, "/drain", {})
        assert status == 200 and body["draining"]
        deadline = time.monotonic() + 10
        while not peer.service.draining():
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # In-flight finished, new work refused at the door with a 503.
        deadline = time.monotonic() + 10
        while True:
            status, _, _ = http_json(
                "POST", peer.url, "/product",
                wire_request(ProductRequest(raw=raw, nfft=NFFT)),
                timeout=30)
            if status == 503:
                break
            assert time.monotonic() < deadline
            time.sleep(0.05)


class TestConcurrentHTTP:
    def test_parallel_identical_requests_coalesce_on_the_peer(
            self, peer, raw):
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        wire = wire_request(req)
        results = []
        errors = []

        def hit():
            try:
                status, _, body = http_json("POST", peer.url, "/product",
                                            wire, timeout=120)
                assert status == 200
                results.append(decode_product(body)[1].tobytes())
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=hit) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(set(results)) == 1  # byte-identical for every caller
        # Single-flight + cache: at most one reduction was scheduled.
        assert peer.service.counts["scheduled"] == 1


class TestBinaryWireNegotiation:
    BIN_ACCEPT = {"Accept": f"{WIRE_CTYPE}, application/json",
                  "Content-Type": "application/json"}

    def post(self, peer, req, headers=None):
        import json as _json

        return http_request(
            "POST", peer.url, "/product",
            body=_json.dumps(wire_request(req)).encode(),
            headers=headers or {"Content-Type": "application/json"},
            timeout=120)

    def test_binary_accept_negotiates_binary(self, peer, raw):
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        status, hdrs, payload = self.post(peer, req, self.BIN_ACCEPT)
        assert status == 200
        assert hdrs["content-type"].startswith(WIRE_CTYPE)
        assert hdrs[WIRE_HEADER.lower()] == "binary"
        _, via_wire = decode_product_wire(payload)
        _, direct = peer.service.get(req, timeout=120)
        assert via_wire.dtype == direct.dtype
        assert via_wire.tobytes() == direct.tobytes()

    def test_legacy_client_untouched(self, peer, raw):
        # No binary Accept -> the exact JSON+base64 wire as before,
        # now self-labelling via X-Blit-Wire: json.
        import json as _json

        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        status, hdrs, payload = self.post(peer, req)
        assert status == 200
        assert hdrs["content-type"].startswith("application/json")
        assert hdrs[WIRE_HEADER.lower()] == "json"
        _, via_json = decode_product(_json.loads(payload))
        _, direct = peer.service.get(req, timeout=120)
        assert via_json.tobytes() == direct.tobytes()

    def test_both_wires_byte_identical(self, peer, raw):
        import json as _json

        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        _, _, pj = self.post(peer, req)
        _, hb, pb = self.post(peer, req, self.BIN_ACCEPT)
        hj_h, dj = decode_product(_json.loads(pj))
        hb_h, db = decode_product_wire(pb)
        assert hj_h == hb_h
        assert dj.dtype == db.dtype and dj.shape == db.shape
        assert dj.tobytes() == db.tobytes()

    def test_second_binary_hit_serves_from_wire_tier(self, peer, raw):
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        _, _, p1 = self.post(peer, req, self.BIN_ACCEPT)
        before = peer.service.cache.stats().get("hit.wire", 0)
        _, _, p2 = self.post(peer, req, self.BIN_ACCEPT)
        assert p1 == p2  # the retained body IS the first response
        assert peer.service.cache.stats()["hit.wire"] > before

    def test_deflate_negotiated_when_enabled(self, peer, raw):
        peer._wire_deflate = True
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        hdrs_in = dict(self.BIN_ACCEPT)
        hdrs_in["Accept-Encoding"] = "deflate"
        status, hdrs, payload = self.post(peer, req, hdrs_in)
        assert status == 200
        assert hdrs.get("content-encoding") == "deflate"
        _, d = decode_product_wire(payload, encoding="deflate")
        _, direct = peer.service.get(req, timeout=120)
        assert d.tobytes() == direct.tobytes()
