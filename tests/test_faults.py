"""Deterministic fault injection + recovery policy (ISSUE 2 tentpole):
the injection registry, seeded-jitter retry (no test sleeps real backoff
time — sleeps and clocks are injectable), transparent transient-I/O
recovery in the guppi/fbh5 layers, the WorkerPool re-dispatch path, and
the per-host circuit breaker."""

import os
import threading
import time

import numpy as np
import pytest

from blit import faults, workers
from blit.agent import ping
from blit.config import SiteConfig
from blit.faults import CircuitBreaker, FaultRule, InjectedFault, RetryPolicy
from blit.io.guppi import GuppiRaw
from blit.parallel import pool as poolmod
from blit.parallel.pool import WorkerError, WorkerPool
from blit.parallel.remote import (
    RemoteError,
    agent_env_with_repo,
    local_agent_command,
)
from blit.testing import synth_fil, synth_raw


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(RetryPolicy(attempts=3, base_s=0.0, jitter=0.0))
    yield
    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(None)


def local_transport(host):
    return local_agent_command()


class TestRegistry:
    def test_fail_rule_fires_exactly_times(self):
        faults.install(FaultRule("p", "fail", times=2))
        for _ in range(2):
            with pytest.raises(InjectedFault):
                faults.fire("p")
        assert faults.fire("p") is None  # exhausted
        assert faults.counters()["fault.p.fail"] == 2

    def test_after_offsets_the_firing_window(self):
        faults.install(FaultRule("p", "fail", times=1, after=2))
        assert faults.fire("p") is None
        assert faults.fire("p") is None
        with pytest.raises(InjectedFault):
            faults.fire("p")  # 3rd matching hit
        assert faults.fire("p") is None

    def test_match_filters_by_key_substring(self):
        faults.install(FaultRule("p", "fail", times=-1, match="ant2"))
        assert faults.fire("p", key="/data/ant1.raw") is None
        with pytest.raises(InjectedFault):
            faults.fire("p", key="/data/ant2.raw")
        assert faults.fire("p") is None  # no key, match rule skips

    def test_delay_uses_injectable_sleep(self):
        rec = []
        faults.install(
            FaultRule("p", "delay", times=1, delay_s=7.5, sleep=rec.append)
        )
        assert faults.fire("p") is None
        assert rec == [7.5]

    def test_destructive_rule_returned_to_caller(self):
        faults.install(FaultRule("p", "truncate", times=1, amount=3))
        act = faults.fire("p")
        assert act.mode == "truncate" and act.amount == 3
        assert faults.fire("p") is None

    def test_parse_spec_grammar(self):
        rules = faults.parse_spec(
            "guppi.read:fail:2:match=ant1;"
            "remote.call:delay:times=-1:delay=0.25;"
            "fbh5.write:truncate:1:after=4:amount=8"
        )
        assert [r.point for r in rules] == [
            "guppi.read", "remote.call", "fbh5.write"
        ]
        assert rules[0].times == 2 and rules[0].match == "ant1"
        assert rules[1].times == -1 and rules[1].delay_s == 0.25
        assert rules[2].after == 4 and rules[2].amount == 8
        with pytest.raises(ValueError, match="point:mode"):
            faults.parse_spec("lonely")
        with pytest.raises(ValueError, match="unknown fault mode"):
            faults.parse_spec("p:explode")

    def test_hit_counting_is_thread_safe(self):
        faults.install(FaultRule("p", "fail", times=50))
        raised = []

        def worker():
            for _ in range(20):
                try:
                    faults.fire("p")
                except InjectedFault:
                    raised.append(1)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert len(raised) == 50  # exactly `times`, no lost updates


class TestRetryPolicy:
    def test_seeded_jitter_is_deterministic_and_bounded(self):
        a = RetryPolicy(base_s=0.1, max_s=2.0, jitter=0.5, seed=7)
        b = RetryPolicy(base_s=0.1, max_s=2.0, jitter=0.5, seed=7)
        for k in range(6):
            d = a.delay_s(k)
            assert d == b.delay_s(k)  # pure function of (seed, attempt)
            nominal = min(2.0, 0.1 * 2.0 ** k)
            assert 0.5 * nominal <= d <= 1.5 * nominal

    def test_transient_failures_retry_then_succeed(self):
        rec = []
        policy = RetryPolicy(attempts=3, base_s=0.5, jitter=0.0,
                             sleep=rec.append)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise OSError("nfs weather")
            return 42

        assert faults.retry_call(flaky, policy=policy) == 42
        assert rec == [0.5, 1.0]  # exponential, recorded not slept
        assert faults.counters()["retry.io"] == 2

    def test_non_transient_never_retries(self):
        policy = RetryPolicy(attempts=5, base_s=0.0)
        for exc in (FileNotFoundError("gone"), PermissionError("no"),
                    ValueError("logic")):
            calls = [0]

            def bad(exc=exc):
                calls[0] += 1
                raise exc

            with pytest.raises(type(exc)):
                faults.retry_call(bad, policy=policy)
            assert calls[0] == 1

    def test_attempts_bound_exhaustion(self):
        rec = []
        policy = RetryPolicy(attempts=4, base_s=0.1, jitter=0.0,
                             sleep=rec.append)

        def always():
            raise OSError("down")

        with pytest.raises(OSError, match="down"):
            faults.retry_call(always, policy=policy)
        assert len(rec) == 3  # attempts - 1 backoffs


class TestGuppiIORecovery:
    @pytest.fixture
    def raw(self, tmp_path):
        p = str(tmp_path / "ant0.raw")
        synth_raw(p, nblocks=2, obsnchan=4, ntime_per_block=64, seed=1)
        return p

    def test_transient_read_fault_is_invisible(self, raw):
        from blit.parallel.scan import _gapless

        clean = np.array(_gapless(GuppiRaw(raw), 96, skip=8))
        faults.install(FaultRule("guppi.read", "fail", times=2))
        got = _gapless(GuppiRaw(raw), 96, skip=8)
        np.testing.assert_array_equal(got, clean)
        assert faults.counters()["retry.io"] >= 2

    def test_transient_open_fault_is_invisible(self, raw):
        faults.install(FaultRule("guppi.open", "fail", times=1))
        assert GuppiRaw(raw).nblocks == 2
        assert faults.counters()["retry.io"] >= 1

    def test_retry_exhaustion_raises(self, raw):
        faults.set_io_policy(RetryPolicy(attempts=2, base_s=0.0))
        faults.install(FaultRule("guppi.read", "fail", times=-1))
        r = GuppiRaw(raw)
        dst = np.empty((4, 16, 2, 2), np.int8)
        with pytest.raises(InjectedFault):
            r.read_block_into(0, dst, 0, 16)

    def test_truncate_injection_shortens_the_read(self, raw):
        r = GuppiRaw(raw)
        dst = np.empty((4, 32, 2, 2), np.int8)
        faults.install(FaultRule("guppi.read", "truncate", times=1, amount=10))
        assert r.read_block_into(0, dst, 0, 32) == 22
        assert r.read_block_into(0, dst, 0, 32) == 32  # rule exhausted

    def test_truncate_surfaces_as_short_gapless(self, raw):
        from blit.parallel.scan import _gapless

        faults.install(FaultRule("guppi.read", "truncate", times=1))
        v = _gapless(GuppiRaw(raw), 96, skip=0)
        assert v.shape[1] < 96  # callers' length checks turn this hard

    def test_read_block_honors_destructive_rules(self, raw):
        # The whole-block path must apply truncate/corrupt too — a drill
        # must never count a fault as fired while delivering clean data.
        r = GuppiRaw(raw)
        clean = np.array(r.read_block(0))
        faults.install(FaultRule("guppi.read", "truncate", times=1,
                                 amount=10))
        assert r.read_block(0).shape[1] == clean.shape[1] - 10
        faults.clear()
        faults.install(FaultRule("guppi.read", "corrupt", times=1))
        bad = r.read_block(0)
        assert not np.array_equal(bad, clean)
        np.testing.assert_array_equal(bad[1:], clean[1:])

    def test_corrupt_injection_flips_frame_bytes(self, raw):
        r = GuppiRaw(raw)
        clean = np.array(r.read_block(0))
        faults.install(FaultRule("guppi.read", "corrupt", times=1))
        dst = np.zeros((4, 64, 2, 2), np.int8)
        r.read_block_into(0, dst, 0, 64)
        assert not np.array_equal(dst, clean)
        np.testing.assert_array_equal(dst[1:], clean[1:])  # channel 0 only

    def test_workers_read_retries_transient(self, tmp_path):
        p = str(tmp_path / "x.fil")
        _, data = synth_fil(p, nsamps=8, nchans=32)
        faults.install(FaultRule("workers.read", "fail", times=1))
        out = workers.get_data(p, (slice(None), slice(None), slice(None)))
        np.testing.assert_array_equal(out, data)
        assert faults.counters()["retry.io"] >= 1


class TestFBH5WriteRecovery:
    def test_transient_write_fault_is_invisible(self, tmp_path):
        from blit.io.fbh5 import FBH5Writer, read_fbh5_data
        from blit.testing import make_fil_header

        hdr = make_fil_header(nchans=8, nifs=1)
        slabs = [np.random.default_rng(s).standard_normal(
            (4, 1, 8)).astype(np.float32) for s in range(3)]

        def write(path):
            with FBH5Writer(path, hdr, nifs=1, nchans=8) as w:
                for s in slabs:
                    w.append(s)

        clean = str(tmp_path / "clean.h5")
        write(clean)
        faults.install(FaultRule("fbh5.write", "fail", times=2))
        faulty = str(tmp_path / "faulty.h5")
        write(faulty)
        np.testing.assert_array_equal(
            read_fbh5_data(faulty), read_fbh5_data(clean)
        )
        assert faults.counters()["retry.io"] >= 2


class TestCircuitBreaker:
    def test_trips_after_consecutive_failures_and_recloses(self):
        now = [0.0]
        br = CircuitBreaker(threshold=3, cooldown_s=60.0,
                            clock=lambda: now[0])
        assert br.allow() and not br.record_failure()
        assert br.allow() and not br.record_failure()
        assert br.allow()
        assert br.record_failure()  # third consecutive: trips
        assert br.snapshot() == {
            "state": "open", "consecutive_failures": 3, "trips": 1,
        }
        assert not br.allow()  # fail fast inside cooldown
        now[0] = 61.0
        assert br.allow()       # the half-open probe
        assert not br.allow()   # only ONE probe
        br.record_success()
        assert br.snapshot()["state"] == "closed"

    def test_half_open_failure_reopens(self):
        now = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=10.0,
                            clock=lambda: now[0])
        br.record_failure()
        now[0] = 11.0
        assert br.allow()
        assert br.record_failure()  # probe failed: open again
        assert not br.allow()
        assert br.trips == 2

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(threshold=3)
        br.record_failure()
        br.record_failure()
        br.record_success()
        assert not br.record_failure()  # streak restarted
        assert br.snapshot()["state"] == "closed"


def _pool_config(**kw):
    """Fast deterministic recovery knobs: zero backoff, seeded jitter."""
    defaults = dict(call_retries=1, call_backoff_s=0.0,
                    call_backoff_max_s=0.0, retry_jitter=0.0, retry_seed=0,
                    breaker_threshold=2, breaker_cooldown_s=60.0)
    defaults.update(kw)
    return SiteConfig(**defaults)


class TestPoolRecovery:
    def test_injected_agent_death_is_retried_through_respawn(self):
        faults.install(FaultRule("remote.call", "fail", times=1))
        pool = WorkerPool(
            ["h0"], backend="remote", transport=local_transport,
            agent_env=agent_env_with_repo(), config=_pool_config(),
        )
        try:
            assert pool.run_on([1], ping, [()]) == ["pong"]
        finally:
            pool.shutdown()
        assert faults.counters()["retry.remote"] == 1
        assert pool.health()[0]["state"] == "closed"

    def test_persistent_failure_trips_breaker_then_fails_fast(self):
        rule = FaultRule("remote.call", "fail", times=-1, match="h0")
        faults.install(rule)
        pool = WorkerPool(
            ["h0", "h1"], backend="remote", transport=local_transport,
            agent_env=agent_env_with_repo(), config=_pool_config(),
        )
        try:
            res = pool.broadcast(ping, on_error="capture")
            assert isinstance(res[0], WorkerError)
            assert res[0].error.etype == "AgentDied"
            assert res[1] == "pong"  # the healthy host is untouched
            # call_retries=1 + threshold=2: the breaker tripped during the
            # first fan-out.
            health = {h["host"]: h for h in pool.health()}
            assert health["h0"]["state"] == "open"
            assert health["h1"]["state"] == "closed"
            assert faults.counters()["breaker.trip"] == 1
            fired_before = rule.fired
            res = pool.broadcast(ping, on_error="capture")
            # Degraded host fails FAST: reported, not hammered — the
            # transport was never touched again.
            assert isinstance(res[0], WorkerError)
            assert res[0].error.etype == "HostDegraded"
            assert rule.fired == fired_before
            assert faults.counters()["breaker.fastfail"] == 1
        finally:
            pool.shutdown()

    def test_breaker_probe_recloses_after_cooldown(self):
        rule = FaultRule("remote.call", "fail", times=2)
        faults.install(rule)
        pool = WorkerPool(
            ["h0"], backend="remote", transport=local_transport,
            agent_env=agent_env_with_repo(),
            config=_pool_config(call_retries=0),
        )
        try:
            for _ in range(2):  # two failures trip the breaker
                with pytest.raises(RemoteError):
                    pool.run_on([1], ping, [()])
            assert pool.health()[0]["state"] == "open"
            # Advance the (injectable) clock past the cooldown: the next
            # call is the half-open probe, succeeds, and re-closes.
            br = pool.workers[0].breaker
            base = time.monotonic()
            br.clock = lambda: base + br.cooldown_s + 1
            assert pool.run_on([1], ping, [()]) == ["pong"]
            assert pool.health()[0]["state"] == "closed"
        finally:
            pool.shutdown()

    def test_degraded_run_report_includes_fault_counters(self):
        from blit.observability import Timeline

        faults.install(FaultRule("remote.call", "fail", times=-1))
        pool = WorkerPool(
            ["h0"], backend="remote", transport=local_transport,
            agent_env=agent_env_with_repo(), config=_pool_config(),
        )
        try:
            pool.broadcast(ping, on_error="capture")
        finally:
            pool.shutdown()
        rep = Timeline().report(include_faults=True)
        assert rep["faults"]["breaker.trip"] == 1
        assert rep["faults"]["retry.remote"] == 1


class TestFanInCancellation:
    """A first-worker failure under on_error="raise" must not leak the
    rest of the fan-out as orphaned background work (ISSUE 2 satellite).
    Queued-future cancellation is inherently racy to observe through a
    live executor, so the pin is structural: stub futures, remote-backend
    pool with the local transport."""

    def _pool(self):
        return WorkerPool(
            ["a", "b", "c"], backend="remote", transport=local_transport,
            agent_env=agent_env_with_repo(),
        )

    def _stub_futures(self, pool, exc):
        from concurrent.futures import Future

        f1, f2, f3 = Future(), Future(), Future()
        f1.set_exception(exc)
        futs = iter([f1, f2, f3])
        pool._submit = lambda *a, **kw: next(futs)
        return f1, f2, f3

    def test_run_on_raise_cancels_not_yet_started_futures(self):
        pool = self._pool()
        try:
            _f1, f2, f3 = self._stub_futures(pool, RuntimeError("boom"))
            with pytest.raises(RuntimeError, match="boom"):
                pool.run_on([1, 2, 3], ping, [(), (), ()])
            assert f2.cancelled() and f3.cancelled()
        finally:
            pool.shutdown()

    def test_broadcast_raise_cancels_not_yet_started_futures(self):
        pool = self._pool()
        try:
            _f1, f2, f3 = self._stub_futures(pool, RuntimeError("boom"))
            with pytest.raises(RuntimeError, match="boom"):
                pool.broadcast(ping)
            assert f2.cancelled() and f3.cancelled()
        finally:
            pool.shutdown()

    def test_capture_mode_still_waits_everyone(self):
        pool = self._pool()
        try:
            from concurrent.futures import Future

            f1, f2, f3 = Future(), Future(), Future()
            f1.set_exception(RuntimeError("boom"))
            f2.set_result("ok2")
            f3.set_result("ok3")
            futs = iter([f1, f2, f3])
            pool._submit = lambda *a, **kw: next(futs)
            res = pool.broadcast(ping, on_error="capture")
            assert isinstance(res[0], WorkerError)
            assert res[1:] == ["ok2", "ok3"]
            assert not f2.cancelled() and not f3.cancelled()
        finally:
            pool.shutdown()


class TestGlobalPoolThreadSafety:
    def test_racing_setup_workers_builds_exactly_one_pool(self, monkeypatch):
        poolmod.reset_pool()
        built = []
        orig = poolmod.WorkerPool

        class Counting(orig):
            def __init__(self, *a, **kw):
                built.append(self)
                super().__init__(*a, **kw)

        monkeypatch.setattr(poolmod, "WorkerPool", Counting)
        results = []
        barrier = threading.Barrier(8)

        def go():
            barrier.wait()
            results.append(poolmod.setup_workers(["a"], backend="local"))

        threads = [threading.Thread(target=go) for _ in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        try:
            assert len(built) == 1  # no second pool built-and-leaked
            assert len(results) == 8
            assert all(r is results[0] for r in results)
            assert poolmod.current_pool() is results[0]
        finally:
            poolmod.reset_pool()
        assert poolmod.current_pool() is None


class TestAsyncSinkFaults:
    """ISSUE 4 satellite: the write-behind output plane's injection
    points.  A failure on the SINK THREAD must surface as a clean
    consumer-side re-raise — no orphaned daemon, no valid-looking
    truncated product, and a resumable partial where the writer is
    resumable."""

    def _raw(self, tmp_path):
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=3, obsnchan=2, ntime_per_block=1024)
        return p

    def _no_sink_threads(self):
        import time as _t

        deadline = _t.monotonic() + 5.0
        while _t.monotonic() < deadline:
            if not any(t.name in ("blit-sink", "blit-readback")
                       and t.is_alive() for t in threading.enumerate()):
                return True
            _t.sleep(0.02)
        return False

    def test_sink_write_failure_reraises_and_drops_partial(self, tmp_path):
        from blit.pipeline import RawReducer

        raw = self._raw(tmp_path)
        out = str(tmp_path / "x.h5")
        faults.install(FaultRule("sink.write", "fail", times=1, after=1))
        with pytest.raises(InjectedFault):
            RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_to_file(
                raw, out)
        # Atomic-publish writers must leave NOTHING: no final product, no
        # .partial sibling (abort ran on the consumer thread after join).
        assert not os.path.exists(out)
        assert not os.path.exists(out + ".partial")
        assert faults.counters()["fault.sink.write.fail"] == 1
        assert self._no_sink_threads()

    def test_sink_flush_failure_reraises_at_barrier(self, tmp_path):
        from blit.pipeline import RawReducer

        raw = self._raw(tmp_path)
        out = str(tmp_path / "x.fil")
        # Every append succeeds; the close-time flush barrier fails.
        faults.install(FaultRule("sink.flush", "fail", times=1))
        with pytest.raises(InjectedFault):
            RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_to_file(
                raw, out)
        assert not os.path.exists(out)  # never renamed complete
        assert faults.counters()["fault.sink.flush.fail"] == 1
        assert self._no_sink_threads()

    def test_sink_failure_keeps_resumable_partial(self, tmp_path):
        from blit.io.sigproc import read_fil_data
        from blit.pipeline import RawReducer, ReductionCursor

        raw = self._raw(tmp_path)
        out = str(tmp_path / "x.fil")
        faults.install(FaultRule("sink.write", "fail", times=-1, after=1))
        with pytest.raises(InjectedFault):
            RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_resumable(
                raw, out)
        assert self._no_sink_threads()
        # The resumable writer's crash artifacts survive the sink abort:
        # product prefix + cursor = the resume point.
        cur = ReductionCursor.load(out)
        assert cur is not None and cur.frames_done == 4
        assert os.path.exists(out)
        faults.clear()
        RawReducer(nfft=64, nint=2, chunk_frames=4).reduce_resumable(
            raw, out)
        _, got = read_fil_data(out)
        _, want = RawReducer(nfft=64, nint=2, chunk_frames=4).reduce(raw)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_sink_points_ride_the_drill_grammar(self):
        rules = faults.parse_spec(
            "sink.write:fail:2:match=x.h5;sink.flush:delay:delay=0.5")
        assert rules[0].point == "sink.write" and rules[0].times == 2
        assert rules[1].point == "sink.flush" and rules[1].delay_s == 0.5
