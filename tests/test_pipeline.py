"""End-to-end RAW → filterbank pipeline tests (blit/pipeline.py): streaming
chunking vs whole-file golden reduction, overlap handling, product output."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.io.guppi import GuppiRaw  # noqa: E402
from blit.io.sigproc import read_fil_data  # noqa: E402
from blit.ops.channelize import channelize_np, pfb_coeffs  # noqa: E402
from blit.pipeline import RawReducer, reducer_for_product  # noqa: E402
from blit.testing import synth_raw  # noqa: E402


def whole_file_reference(raw_path, nfft, ntap, nint, stokes="I"):
    """Golden: concatenate the overlap-trimmed stream and reduce in one shot
    with the NumPy reference implementation."""
    raw = GuppiRaw(raw_path)
    stream = np.concatenate(
        [blk for _, blk in raw.iter_blocks(drop_overlap=True)], axis=1
    )
    frames = stream.shape[1] // nfft - ntap + 1
    frames = (frames // nint) * nint
    usable = (frames + ntap - 1) * nfft
    h = pfb_coeffs(ntap, nfft)
    return channelize_np(
        stream[:, :usable], h, nfft=nfft, ntap=ntap, nint=nint, stokes=stokes
    )


class TestStreaming:
    @pytest.mark.parametrize("overlap", [0, 64])
    def test_streaming_matches_whole_file(self, tmp_path, overlap):
        # Chunked streaming with PFB state carry must equal the one-shot
        # reduction of the gap-free stream — block/chunk boundaries invisible.
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=4, obsnchan=4, ntime_per_block=1024 + overlap,
                  overlap=overlap, tone_chan=2)
        red = RawReducer(nfft=128, nint=2, chunk_frames=4)
        hdr, data = red.reduce(p)
        want = whole_file_reference(p, nfft=128, ntap=4, nint=2)
        assert data.shape == want.shape
        np.testing.assert_allclose(data, want, rtol=1e-4, atol=0.5)
        rel = np.abs(data - want).max() / want.max()
        assert rel < 1e-4

    @pytest.mark.parametrize("overlap", [0, 64])
    def test_drain_checksum_matches_stream(self, tmp_path, overlap):
        # The device-sink path must reduce exactly the frames the host-sink
        # path yields (same chunker underneath).
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=4, obsnchan=4, ntime_per_block=1024 + overlap,
                  overlap=overlap, tone_chan=1)
        red = RawReducer(nfft=128, nint=2, chunk_frames=4)
        slabs = list(red.stream(GuppiRaw(p)))
        want = sum(float(s.sum()) for s in slabs)
        red2 = RawReducer(nfft=128, nint=2, chunk_frames=4)
        got = red2.drain(GuppiRaw(p))
        assert red2.stats.output_frames == red.stats.output_frames
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_chunk_frames_rounds_to_nint(self):
        red = RawReducer(nfft=64, nint=6, chunk_frames=8)
        assert red.chunk_frames % 6 == 0

    @pytest.mark.parametrize("depth", [2, 3, 4])
    def test_prefetch_depth_invariant(self, tmp_path, depth):
        # The rotation depth changes pipelining only — never the product.
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=4, obsnchan=2, ntime_per_block=1024,
                  overlap=32, tone_chan=1)
        base = RawReducer(nfft=64, nint=2, chunk_frames=4, prefetch_depth=2)
        _, want = base.reduce(p)
        red = RawReducer(nfft=64, nint=2, chunk_frames=4,
                         prefetch_depth=depth)
        _, got = red.reduce(p)
        np.testing.assert_array_equal(got, want)
        drained = RawReducer(nfft=64, nint=2, chunk_frames=4,
                             prefetch_depth=depth).drain(GuppiRaw(p))
        np.testing.assert_allclose(drained, float(want.sum()), rtol=1e-5)

    def test_abandoned_stream_stops_producer(self, tmp_path):
        # Breaking out of a stream must not leak a blocked ingest thread.
        import threading

        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=8, obsnchan=2, ntime_per_block=1024)
        red = RawReducer(nfft=64, nint=1, chunk_frames=2)
        it = red.stream(GuppiRaw(p))
        next(it)
        it.close()  # abandon mid-stream
        for _ in range(50):
            if not any(t.name == "blit-ingest" and t.is_alive()
                       for t in threading.enumerate()):
                break
            import time

            time.sleep(0.05)
        assert not any(t.name == "blit-ingest" and t.is_alive()
                       for t in threading.enumerate())

    def test_stats_track_input_bytes(self, tmp_path):
        p = str(tmp_path / "x.raw")
        _, blocks = synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=512)
        red = RawReducer(nfft=64, nint=1)
        red.reduce(p)
        assert red.stats.input_bytes == sum(b.nbytes for b in blocks)
        assert red.stats.wall_seconds > 0
        assert red.stats.gbps > 0

    def test_every_timed_stage_carries_bytes(self, tmp_path):
        # VERDICT r5 weak #3: the dominant stage of the streaming leg
        # reported zero bytes (BENCH_r05 stream.s=350, bytes=0), so the
        # stage table couldn't be sanity-summed against end-to-end GB/s.
        # Invariant, pinned for every reducer stage: nonzero seconds ⇒
        # nonzero bytes, unless the stage is explicitly byte-free.
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=3, obsnchan=2, ntime_per_block=1024)
        red = RawReducer(nfft=64, nint=2, chunk_frames=4)
        red.reduce(p)
        assert red.timeline.stages["stream"].bytes > 0
        for name, st in red.timeline.stages.items():
            if st.seconds > 0:
                assert st.bytes > 0 or st.byte_free, (
                    f"stage {name!r} spent {st.seconds}s moving 0 bytes "
                    "without declaring byte_free"
                )

    def test_stream_stage_counts_gross_chunk_bytes(self, tmp_path):
        # The stream stage moves every gross chunk byte it hands
        # downstream (net file bytes + the re-dispatched PFB tails).
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024)
        red = RawReducer(nfft=64, nint=1, chunk_frames=4)
        gross = 0
        for c in red._chunks(GuppiRaw(p)):
            gross += c.view.nbytes
            c.release()
        assert red.timeline.stages["stream"].bytes == gross > 0


class TestProducts:
    def test_reduce_to_fil_roundtrip(self, tmp_path):
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024, tone_chan=1)
        out = str(tmp_path / "x.rawspec.0002.fil")
        red = RawReducer(nfft=64, nint=4, stokes="I")
        hdr = red.reduce_to_file(p, out)
        rhdr, data = read_fil_data(out)
        assert rhdr["nchans"] == 2 * 64
        assert rhdr["nifs"] == 1
        assert data.shape[0] == hdr["nsamps"]
        # The injected tone (chan 1, freq 0.25) must dominate its fine channel.
        spec = np.asarray(data).sum(axis=0)[0]
        assert spec.argmax() == 64 + 32 + 16  # coarse 1, fftshift(0.25*64)=48

    def test_reduce_to_fbh5_roundtrip(self, tmp_path):
        h5py = pytest.importorskip("h5py")  # noqa: F841
        from blit.io.fbh5 import read_fbh5_data, read_fbh5_header

        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024)
        out = str(tmp_path / "x.rawspec.0002.h5")
        red = RawReducer(nfft=64, nint=4)
        red.reduce_to_file(p, out)
        hdr = read_fbh5_header(out)
        data = read_fbh5_data(out)
        assert hdr["nchans"] == 128 and data.ndim == 3

    def test_header_frequency_axis(self, tmp_path):
        p = str(tmp_path / "x.raw")
        synth_raw(p, nblocks=1, obsnchan=4, ntime_per_block=512, obsbw=-187.5)
        red = RawReducer(nfft=64, nint=1)
        hdr, _ = red.reduce(p)
        assert hdr["foff"] == pytest.approx(-187.5 / 4 / 64)
        freqs = hdr["fch1"] + hdr["foff"] * np.arange(hdr["nchans"])
        assert freqs.mean() == pytest.approx(8437.5, abs=abs(hdr["foff"]))

    def test_product_presets(self):
        red = reducer_for_product("0001")
        assert (red.nfft, red.nint) == (8, 128)


class TestEdgeCases:
    def test_empty_raw_file_raises(self, tmp_path):
        p = tmp_path / "empty.raw"
        p.write_bytes(b"")
        with pytest.raises(ValueError, match="empty"):
            RawReducer(nfft=64).reduce(str(p))

    def test_hires_default_chunk_is_hbm_sized(self):
        red = RawReducer(nfft=1 << 20, nint=1)
        assert red.chunk_frames <= 8  # budget-scaled, not the small-nfft 64
        red2 = RawReducer(nfft=1024, nint=1)
        assert red2.chunk_frames == 64
