"""FleetFrontDoor (blit/serve/fleet.py; ISSUE 14 tentpole): ring
routing with cross-host dedupe, replica failover byte-identity, lease
ejection + rejoin, hedged reads off the live p99, the pinned
deadline-expired-at-the-door acceptance, cache-warm replication,
aggregated /healthz, and graceful drain with hot-entry hints."""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.faults import FaultRule  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.serve import (  # noqa: E402
    DeadlineExpired,
    FleetFrontDoor,
    FrontDoorServer,
    Overloaded,
    PeerServer,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.serve.cache import fingerprint_for  # noqa: E402
from blit.serve.http import (  # noqa: E402
    decode_product,
    http_json,
    wire_request,
)
from blit.testing import synth_raw  # noqa: E402

NFFT = 128
NTIME = (8 + 3) * NFFT
TTL = 0.6


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


class Fleet:
    """Three in-process peers + a door driven by EXPLICIT observe()
    ticks (no background thread) — deterministic liveness for tests."""

    def __init__(self, tmp_path, npeers=3, **door_kw):
        self.lease_dir = str(tmp_path / "leases")
        self.servers = []
        peers = {}
        for i in range(npeers):
            tl = Timeline()
            svc = ProductService(
                cache=ProductCache(str(tmp_path / f"cache{i}"),
                                   ram_bytes=1 << 24, timeline=tl),
                scheduler=Scheduler(max_concurrency=2, queue_depth=8,
                                    timeline=tl, retry_seed=i),
                timeline=tl)
            ps = PeerServer(svc, name=f"peer{i}",
                            lease_dir=self.lease_dir, proc=i,
                            beat_interval_s=0.05).start()
            self.servers.append(ps)
            peers[f"peer{i}"] = ps.url
        kw = dict(peer_ttl_s=TTL, poll_s=0.05, health_poll_s=0.2,
                  hedge_floor_s=5.0, request_timeout_s=60.0)
        kw.update(door_kw)
        self.timeline = Timeline()
        self.door = FleetFrontDoor(peers, lease_dir=self.lease_dir,
                                   timeline=self.timeline, **kw)
        # Warm the lease watches (3 beats arm the TTL).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            self.door.observe()
            if all(p.watch.seen for p in self.door._peers.values()):
                break
            time.sleep(0.05)

    def kill(self, name):
        """Die unannounced: socket closed, beats stop — the SIGKILL
        shape, in-process."""
        i = int(name.replace("peer", ""))
        self.servers[i].close()

    def wait_ejected(self, name, budget=10.0):
        deadline = time.monotonic() + budget
        while name in self.door.ring:
            assert time.monotonic() < deadline, "never ejected"
            self.door.observe()
            time.sleep(0.05)

    def close(self):
        self.door.close()
        for s in self.servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — some die mid-test
                pass
            s.service.close(5)


@pytest.fixture
def fleet(tmp_path):
    f = Fleet(tmp_path)
    yield f
    f.close()


def make_req(tmp_path, i=0):
    p = str(tmp_path / f"r{i}.raw")
    synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=NTIME, seed=i)
    return ProductRequest(raw=p, nfft=NFFT, nint=1)


def owner_of(fleet, req):
    fp = fingerprint_for(req.reducer(), req.raw_source)
    return fp, fleet.door.ring.owners(fp)


class TestRouting:
    def test_same_request_routes_to_one_owner(self, fleet, tmp_path):
        req = make_req(tmp_path)
        fp, owners = owner_of(fleet, req)
        h1, d1 = fleet.door.get(req)
        h2, d2 = fleet.door.get(req)
        assert np.array_equal(d1, d2)
        by_peer = {n: p.requests for n, p in fleet.door._peers.items()}
        assert by_peer[owners[0]] == 2  # both landed on the OWNER
        assert sum(by_peer.values()) == 2
        # ... where the peer served the second from its cache.
        i = int(owners[0].replace("peer", ""))
        assert fleet.servers[i].service.counts["cache_hits"] >= 1

    def test_member_order_cannot_split_the_cache(self, fleet, tmp_path):
        # Cross-host dedupe is free because fingerprints are
        # order-insensitive (the tentpole's routing claim).
        a = str(tmp_path / "m0.raw")
        b = str(tmp_path / "m1.raw")
        synth_raw(a, nblocks=1, obsnchan=2, ntime_per_block=NTIME)
        synth_raw(b, nblocks=1, obsnchan=2, ntime_per_block=NTIME,
                  seed=5)
        r1 = ProductRequest(raw=(a, b), nfft=NFFT)
        r2 = ProductRequest(raw=(b, a), nfft=NFFT)
        fp1, _ = owner_of(fleet, r1)
        fp2, _ = owner_of(fleet, r2)
        assert fp1 == fp2


class TestFailover:
    def test_dead_owner_fails_over_byte_identical(self, fleet,
                                                  tmp_path):
        req = make_req(tmp_path, 1)
        _, owners = owner_of(fleet, req)
        _, oracle = fleet.door.get(req)  # computed on the owner
        fleet.kill(owners[0])  # socket refused; lease still un-stale
        h, d = fleet.door.get(req)  # immediate failover to the replica
        assert np.array_equal(d, oracle)
        assert fleet.door._peers[owners[0]].failures >= 1
        stats = fleet.door.stats()
        assert stats["counters"]["fleet.failover"] >= 1

    def test_all_peers_overloaded_raises_overloaded(self, fleet,
                                                    tmp_path,
                                                    monkeypatch):
        req = make_req(tmp_path, 2)
        for s in fleet.servers:
            def refuse(*a, **kw):
                raise Overloaded("full", retry_after_s=0.2)

            # submit is the peer handler's seam (ISSUE 15: it needs
            # the ticket) — and where admission refuses.
            monkeypatch.setattr(s.service, "submit", refuse)
        with pytest.raises(Overloaded):
            fleet.door.get(req)


class TestEjectionRejoin:
    def test_stale_lease_ejects_and_reroutes(self, fleet, tmp_path):
        req = make_req(tmp_path, 3)
        fp, owners = owner_of(fleet, req)
        _, oracle = fleet.door.get(req)
        victim = owners[0]
        fleet.kill(victim)
        time.sleep(TTL * 1.5)
        fleet.wait_ejected(victim)
        assert victim not in fleet.door.ring.peers()
        # The key range re-routed: the replica owns it now and serves
        # byte-identically.
        new_owners = fleet.door.ring.owners(fp)
        assert victim not in new_owners
        _, d = fleet.door.get(req)
        assert np.array_equal(d, oracle)
        stats = fleet.door.stats()
        assert stats["counters"]["fleet.eject"] == 1
        assert stats["hists"]["fleet.detect_s"]["n"] == 1

    def test_fresh_beats_rejoin_the_ring(self, fleet, tmp_path):
        from blit.recover import Lease

        victim = "peer2"
        fleet.kill(victim)
        time.sleep(TTL * 1.5)
        fleet.wait_ejected(victim)
        # The peer comes back: beats resume (a new process would beat
        # the same proc slot), the door rejoins it.
        lease = Lease(fleet.lease_dir, 2)
        deadline = time.monotonic() + 10
        while victim not in fleet.door.ring:
            assert time.monotonic() < deadline, "never rejoined"
            lease.beat()
            fleet.door.observe()
            time.sleep(0.05)
        assert fleet.door.stats()["counters"]["fleet.rejoin"] == 1


class TestHedgedReads:
    def test_slow_owner_hedges_to_replica_first_wins(self, tmp_path):
        fleet = Fleet(tmp_path, hedge_floor_s=0.1)
        try:
            req = make_req(tmp_path, 4)
            _, owners = owner_of(fleet, req)
            fleet.door.get(req)  # warm the owner's cache
            # Make the owner SLOW (not dead): the hedge, not failover,
            # must cover it.  The in-process servers share this fault
            # registry, and a ONE-SHOT delay rule is eaten by the first
            # /product handled — the owner's — so the hedge lands clean.
            faults.install(FaultRule(point="peer.request", mode="delay",
                                     delay_s=2.0, times=1))
            t0 = time.perf_counter()
            h, d = fleet.door.get(req)
            dt = time.perf_counter() - t0
            stats = fleet.door.stats()
            assert stats["counters"]["fleet.hedge"] >= 1
            assert stats["counters"].get("fleet.hedge.win", 0) >= 1
            # The hedge cut the tail: well under the injected 2 s.
            assert dt < 1.5
        finally:
            fleet.close()

    def test_hedge_is_bounded_to_one_duplicate(self, tmp_path):
        fleet = Fleet(tmp_path, hedge_floor_s=0.05)
        try:
            req = make_req(tmp_path, 5)
            faults.install(FaultRule(point="peer.request", mode="delay",
                                     delay_s=0.5, times=-1))
            fleet.door.get(req)
            stats = fleet.door.stats()
            # One request, every peer slow: exactly ONE hedge launched
            # (<= 2x compute on the hedged slice, by construction).
            assert stats["counters"]["fleet.hedge"] == 1
            assert stats["counters"]["fleet.route"] <= 2
        finally:
            fleet.close()


class TestDeadlinePropagation:
    def test_expired_at_the_door_is_never_dispatched(self, fleet,
                                                     tmp_path):
        req = make_req(tmp_path, 6)
        before = sum(p.requests for p in fleet.door._peers.values())
        before_http = [s.counts["product"] for s in fleet.servers]
        with pytest.raises(DeadlineExpired):
            fleet.door.get(req, deadline_s=0.0)
        # The acceptance pin: no peer dispatch, no peer HTTP hit.
        assert sum(p.requests
                   for p in fleet.door._peers.values()) == before
        assert [s.counts["product"] for s in fleet.servers] == before_http
        stats = fleet.door.stats()
        assert stats["counters"]["fleet.deadline_expired"] == 1

    def test_remaining_budget_rides_the_wire(self, fleet, tmp_path,
                                             monkeypatch):
        req = make_req(tmp_path, 7)
        seen = {}
        for s in fleet.servers:
            real = s.service.submit

            def spy(r, _real=real, **kw):
                seen.setdefault("deadline_s", kw.get("deadline_s"))
                return _real(r, **kw)

            monkeypatch.setattr(s.service, "submit", spy)
        fleet.door.get(req, deadline_s=30.0)
        # The peer saw the REMAINING budget, not the original.
        assert seen["deadline_s"] is not None
        assert 0 < seen["deadline_s"] <= 30.0


class TestWarmReplication:
    def test_hot_entry_warms_the_replicas(self, tmp_path):
        fleet = Fleet(tmp_path, hot_hits=2)
        try:
            req = make_req(tmp_path, 8)
            fp, owners = owner_of(fleet, req)
            fleet.door.get(req)
            fleet.door.get(req)  # crosses hot_hits -> replicas warm
            replica = owners[1]
            i = int(replica.replace("peer", ""))
            svc = fleet.servers[i].service
            deadline = time.monotonic() + 60
            while not svc.cache.contains(fp):
                assert time.monotonic() < deadline, "replica never warmed"
                time.sleep(0.05)
            # Losing the owner now degrades hit-rate, not correctness —
            # and not even hit-rate for THIS key.
            fleet.kill(owners[0])
            time.sleep(TTL * 1.5)
            fleet.wait_ejected(owners[0])
            before = svc.counts["scheduled"]
            _, d = fleet.door.get(req)
            assert svc.counts["scheduled"] == before  # served from cache
        finally:
            fleet.close()


class TestFleetHealth:
    def test_aggregated_healthz(self, fleet):
        fleet.door.observe()
        doc = fleet.door.health()
        assert doc["ok"] and doc["status"] == "ok"
        assert doc["peers"] == 3 and doc["peers_ok"] == 3
        victim = "peer1"
        fleet.kill(victim)
        time.sleep(TTL * 1.5)
        fleet.wait_ejected(victim)
        doc = fleet.door.health()
        assert not doc["ok"] and doc["status"] == "degraded"
        assert f"peer-ejected:{victim}" in doc["reasons"]
        assert victim not in doc["ring"]

    def test_peer_degradation_folds_in(self, fleet):
        fleet.door._peers["peer0"].last_health = {
            "ok": False, "status": "degraded",
            "reasons": ["quarantine:2"]}
        doc = fleet.door.health()
        assert "peer:peer0:quarantine:2" in doc["reasons"]
        assert doc["status"] == "degraded"

    def test_empty_ring_is_down(self, fleet):
        for name in list(fleet.door._peers):
            fleet.door.ring.remove(name)
            fleet.door._peers[name].in_ring = False
        assert fleet.door.health()["status"] == "down"


class TestDoorDrain:
    def test_drain_refuses_new_and_hints_hot_entries(self, tmp_path):
        fleet = Fleet(tmp_path, hot_hits=100)  # no mid-test warms
        try:
            req = make_req(tmp_path, 9)
            fp, owners = owner_of(fleet, req)
            for _ in range(3):
                fleet.door.get(req)
            res = fleet.door.drain(timeout=10)
            assert res["hints"] >= 1
            with pytest.raises(Overloaded):
                fleet.door.get(req)
            # The hints landed as /warm submissions on the owner set.
            warmed = sum(s.counts["warm"] for s in fleet.servers)
            assert warmed >= 1
        finally:
            fleet.close()


class TestFrontDoorServer:
    def test_http_door_serves_and_aggregates(self, fleet, tmp_path):
        req = make_req(tmp_path, 10)
        with FrontDoorServer(fleet.door) as fd:
            status, _, body = http_json("POST", fd.url, "/product",
                                        wire_request(req), timeout=120)
            assert status == 200
            _, d = decode_product(body)
            _, direct = fleet.door.get(req)
            assert np.array_equal(d, direct)
            status, _, health = http_json("GET", fd.url, "/healthz")
            assert status == 200 and "peers_ok" in health
            status, _, text = http_json("GET", fd.url, "/metrics")
            assert status == 200
            from blit.monitor import parse_prometheus

            assert parse_prometheus(text)
            status, _, stats = http_json("GET", fd.url, "/stats")
            assert status == 200 and stats["ring"]

    def test_deadline_expired_maps_to_504_at_the_door(self, fleet,
                                                      tmp_path):
        req = make_req(tmp_path, 11)
        with FrontDoorServer(fleet.door) as fd:
            status, _, body = http_json(
                "POST", fd.url, "/product",
                wire_request(req, deadline_s=0.0), timeout=30)
            assert status == 504
            assert body["etype"] == "DeadlineExpired"


@pytest.mark.slow
class TestFleetCLI:
    """The REAL multi-process legs (subprocess peers + SIGKILL) — the
    CI fleet-smoke job's shape, kept out of the tier-1 budget."""

    def test_chaos_fleet_kill_drill(self, tmp_path):
        out = tmp_path / "report.json"
        res = subprocess.run(
            [sys.executable, "-m", "blit", "chaos", "--fleet",
             "--fault", "kill", "--fleet-requests", "60",
             "--fleet-distinct", "3", "--nfft", "128",
             "--lease-ttl", "1.5", "--poll", "0.1",
             "--work-dir", str(tmp_path / "work"),
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=600)
        assert res.returncode == 0, res.stdout + res.stderr
        rep = json.loads(out.read_text())
        assert rep["ok"] and rep["detected"] and rep["byte_identical"]
        assert rep["healthz"]["after_detect"] == "degraded"
        assert rep["hit_rate_recovered"]

    def test_serve_bench_fleet_smoke(self, tmp_path):
        res = subprocess.run(
            [sys.executable, "-m", "blit", "serve-bench", "--fleet",
             "--requests", "30", "--distinct", "4", "--clients", "3",
             "--peers", "3", "--nfft", "128"],
            capture_output=True, text=True, timeout=600)
        assert res.returncode == 0, res.stdout + res.stderr
        rep = json.loads(res.stdout.strip().splitlines()[-1])
        assert rep["fleet"] and rep["hit_rate"] > 0
        assert "hedge" in rep and "slo" in rep
