"""The sharded reduction plane (blit/parallel/sharded.py, ISSUE 9).

The acceptance contract: sharded-path products are BYTE-IDENTICAL to
the pool-path oracle (`reduce_scan_pool_to_files` — the reference's "64
workers doing 64 small jobs" shape) for `.fil`, `.h5` and `.hits`,
including masked-antenna and resume-replay runs, on the >= 8-device
forced-host CPU mesh the suite provisions (tests/conftest.py /
the CI mesh-smoke job's XLA_FLAGS).  Plus the plane's building blocks:
the partition-rule registry, `ShardedAccumulator`'s spec-drift check,
ICI byte accounting, the `BLIT_MESH_*` knob resolution, and the
`blit.compat.shard_map` version shim's resolution on both the oldest
and newest supported jax spellings.
"""

import filecmp
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.observability import Timeline  # noqa: E402
from blit.parallel import mesh as M  # noqa: E402
from blit.parallel.mesh import make_mesh  # noqa: E402
from blit.parallel.scan import (  # noqa: E402
    reduce_scan_mesh_to_files,
    reduce_scan_pool_to_files,
)
from blit.parallel.sharded import (  # noqa: E402
    reduce_scan_sharded_to_files,
    search_scan_sharded_to_files,
)
from blit.testing import synth_raw  # noqa: E402

NFFT, NINT, NCHAN = 64, 2, 2
WF = 4  # window_frames: several windows per scan at these shapes


def make_scan(tmp_path, nband=1, nbank=8, ntime=1024, nblocks=2):
    """One synthetic scan (the tests/test_scan_mesh.py grid): per-player
    RAW files with contiguous bank frequencies."""
    paths = []
    bank_bw = -187.5 / nbank
    for b in range(nband):
        row = []
        for k in range(nbank):
            p = str(tmp_path / f"blc{b}{k}.raw")
            synth_raw(p, nblocks=nblocks, obsnchan=NCHAN,
                      ntime_per_block=ntime, seed=b * 8 + k,
                      tone_chan=(k % NCHAN), obsbw=bank_bw,
                      obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw)
            row.append(p)
        paths.append(row)
    return paths


def run_three_ways(paths, tmp_path, **kw):
    """The same scan through the sharded plane, the pool oracle and the
    serial mesh loop, each into its own directory."""
    outs = {}
    for tag, fn in (("sharded", reduce_scan_sharded_to_files),
                    ("pool", reduce_scan_pool_to_files),
                    ("mesh", reduce_scan_mesh_to_files)):
        d = tmp_path / tag
        d.mkdir(exist_ok=True)
        outs[tag] = fn(paths, out_dir=str(d), nfft=NFFT, nint=NINT,
                       window_frames=WF, **kw)
    return outs


class TestByteIdentityGoldens:
    """THE acceptance criterion: sharded products == pool-path goldens,
    byte for byte."""

    @pytest.mark.parametrize("nband,nbank", [(1, 8), (2, 4)])
    def test_fil_products_byte_identical(self, tmp_path, nband, nbank):
        paths = make_scan(tmp_path, nband, nbank)
        outs = run_three_ways(paths, tmp_path)
        assert sorted(outs["sharded"]) == sorted(outs["pool"])
        for b in outs["sharded"]:
            sp, shdr = outs["sharded"][b]
            assert filecmp.cmp(sp, outs["pool"][b][0], shallow=False), (
                f"band {b}: sharded .fil != pool oracle"
            )
            assert filecmp.cmp(sp, outs["mesh"][b][0], shallow=False), (
                f"band {b}: sharded .fil != serial mesh loop"
            )
            assert shdr["nsamps"] == outs["pool"][b][1]["nsamps"]

    def test_h5_products_byte_identical(self, tmp_path):
        pytest.importorskip("h5py")
        from blit.io import bshuf

        if not bshuf.available():
            pytest.skip("native bitshuffle codec unbuilt")
        paths = make_scan(tmp_path, 1, 8)
        outs = run_three_ways(paths, tmp_path, compression="bitshuffle")
        for b in outs["sharded"]:
            sp = outs["sharded"][b][0]
            assert sp.endswith(".h5")
            assert filecmp.cmp(sp, outs["pool"][b][0], shallow=False), (
                f"band {b}: sharded .h5 != pool oracle"
            )

    def test_despiked_products_byte_identical(self, tmp_path):
        # The stitch epilogue differs mechanically (host despike on the
        # pool path, post-all_gather despike over ICI on the sharded
        # path) — the bytes must not.
        paths = make_scan(tmp_path, 1, 8)
        d1, d2 = tmp_path / "s", tmp_path / "p"
        d1.mkdir(), d2.mkdir()
        w1 = reduce_scan_sharded_to_files(
            paths, out_dir=str(d1), nfft=NFFT, nint=NINT,
            window_frames=WF, despike=True,
        )
        w2 = reduce_scan_pool_to_files(
            paths, out_dir=str(d2), nfft=NFFT, nint=NINT,
            window_frames=WF, despike=True,
        )
        for b in w1:
            assert filecmp.cmp(w1[b][0], w2[b][0], shallow=False)

    def test_sharded_probe_reports_collectives(self, tmp_path):
        # Telemetry contract: probe windows sample mesh.gather_s and
        # every window accounts per-chip ICI bytes on mesh.ici.
        paths = make_scan(tmp_path, 1, 8)
        (tmp_path / "out").mkdir()
        tl = Timeline()
        reduce_scan_sharded_to_files(
            paths, out_dir=str(tmp_path / "out"), nfft=NFFT, nint=NINT,
            window_frames=WF, probe_windows=2, timeline=tl,
        )
        assert tl.stages["mesh.ici"].calls > 0
        assert tl.stages["mesh.ici"].bytes > 0
        assert tl.hists["mesh.gather_s"].n == 2  # the probe windows
        assert tl.hists["mesh.gather_ici_bytes"].n == \
            tl.stages["mesh.ici"].calls


class TestResumeReplay:
    def test_crash_resume_byte_identical_to_uninterrupted(
            self, tmp_path, monkeypatch):
        # The mesh-writer resume discipline on the SHARDED plane: crash
        # after the 3rd window's dispatch, leave cursors, resume, and
        # byte-match both the uninterrupted sharded run AND the pool
        # oracle.
        paths = make_scan(tmp_path, 1, 8, nblocks=4)
        gold = tmp_path / "gold"
        gold.mkdir()
        gw = reduce_scan_sharded_to_files(
            paths, out_dir=str(gold), nfft=NFFT, nint=NINT,
            window_frames=WF, resume=False,
        )
        pool = tmp_path / "pool"
        pool.mkdir()
        pw = reduce_scan_pool_to_files(
            paths, out_dir=str(pool), nfft=NFFT, nint=NINT,
            window_frames=WF,
        )

        res = tmp_path / "res"
        res.mkdir()
        real = M.band_reduce
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 3:
                raise RuntimeError("synthetic crash")
            return real(*a, **kw)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError, match="synthetic crash"):
            reduce_scan_sharded_to_files(
                paths, out_dir=str(res), nfft=NFFT, nint=NINT,
                window_frames=WF, resume=True,
            )
        monkeypatch.setattr(M, "band_reduce", real)
        assert len(calls) == 3, "the injected crash did not fire"
        assert [p for p in os.listdir(res) if p.endswith(".cursor")], (
            "no cursor sidecar after the crash"
        )

        rw = reduce_scan_sharded_to_files(
            paths, out_dir=str(res), nfft=NFFT, nint=NINT,
            window_frames=WF, resume=True,
        )
        assert not [p for p in os.listdir(res) if p.endswith(".cursor")]
        for b in rw:
            assert filecmp.cmp(rw[b][0], gw[b][0], shallow=False), (
                f"band {b}: resumed sharded product != uninterrupted"
            )
            assert filecmp.cmp(rw[b][0], pw[b][0], shallow=False), (
                f"band {b}: resumed sharded product != pool oracle"
            )


class TestSearchHitsParity:
    def test_hits_byte_identical_to_pool_reducers(self, tmp_path):
        # The sharded search plane: every chip searches its own
        # frequency slice; each per-player .hits must be byte-identical
        # to the pool path's own DedopplerReducer.search_to_file at the
        # matching dispatch shape (chunk_frames == window_frames).
        from blit.search import DedopplerReducer

        nband, nbank = 1, 8
        paths = make_scan(tmp_path, nband, nbank)
        wspec, wf = 4, 16
        sd = tmp_path / "sharded"
        sd.mkdir()
        written = search_scan_sharded_to_files(
            paths, out_dir=str(sd), nfft=NFFT, nint=NINT,
            window_spectra=wspec, window_frames=wf, snr_threshold=4.0,
        )
        assert sorted(written) == [(0, k) for k in range(nbank)]
        pd = tmp_path / "pool"
        pd.mkdir()
        for (b, k), (spath, shdr) in written.items():
            red = DedopplerReducer(
                nfft=NFFT, nint=NINT, window_spectra=wspec,
                snr_threshold=4.0, chunk_frames=wf,
            )
            out = str(pd / f"band{b}bank{k}.hits")
            red.search_to_file(paths[b][k], out)
            assert filecmp.cmp(spath, out, shallow=False), (
                f"player ({b},{k}): sharded .hits != pool oracle"
            )
            assert shdr["search_windows"] > 0


class _StubWindow:
    """A hand-fed window for beamform_accumulate goldens: the consumer
    contract (arrays/ntime/index/release) with no producer thread."""

    def __init__(self, index, arrays, ntime):
        self.index, self.arrays, self.ntime = index, arrays, ntime
        self.masked = ()

    def release(self):
        pass


class TestMaskedAntennaParity:
    """ISSUE 9 satellite: a zero-weight seat under the sharded
    accumulator path produces the same bytes as the pool path's masked
    product (the zero-filled golden)."""

    NANT, W, TOTAL, START = 4, 128, 896, 48

    @pytest.fixture()
    def ant_files(self, tmp_path):
        paths = []
        for a in range(self.NANT):
            p = str(tmp_path / f"ant{a}.raw")
            synth_raw(p, nblocks=2, obsnchan=4, ntime_per_block=480,
                      seed=200 + a, tone_chan=a % 4)
            paths.append(p)
        return paths

    def test_masked_accumulate_matches_zero_filled_golden(
            self, ant_files):
        from blit import faults
        from blit.faults import FaultRule
        from blit.parallel.antenna import AntennaStream, load_antennas_mesh
        from blit.parallel.beamform import (
            antenna_sharding,
            beamform_accumulate,
            weight_sharding,
        )

        mesh = make_mesh(1, 4)
        rng = np.random.default_rng(5)
        w = (rng.standard_normal((3, self.NANT, 4))
             + 1j * rng.standard_normal((3, self.NANT, 4))
             ).astype(np.complex64)
        ws = weight_sharding(mesh)
        wput = (jax.device_put(w.real.astype(np.float32), ws),
                jax.device_put(w.imag.astype(np.float32), ws))

        faults.clear()
        faults.reset_counters()
        try:
            faults.install(FaultRule("guppi.read", "truncate", times=1,
                                     after=2, match="ant2"))
            feed = AntennaStream(
                ant_files, mesh=mesh, window_samples=self.W,
                start_sample=self.START, max_samples=self.TOTAL,
                on_antenna_error="mask",
            )
            per_window = []

            def spy(f):
                for win in f:
                    per_window.append(win.masked)
                    yield win

            got = np.asarray(beamform_accumulate(spy(feed), wput,
                                                 mesh=mesh))
            assert feed.masked_antennas == {2}
            wmask = next(i for i, m in enumerate(per_window) if m)
            assert 0 < wmask < feed.nwindows  # genuinely mid-stream
        finally:
            faults.clear()
            faults.reset_counters()

        # The pool path's masked product: the SAME accumulate program
        # over stub windows sliced from planes with antenna 2 zeroed
        # from the mask boundary on — identical window shapes, identical
        # fold order, so the bytes must match exactly.
        _, (vr, vi) = load_antennas_mesh(
            ant_files, mesh=mesh, start_sample=self.START,
            max_samples=self.TOTAL,
        )
        zr, zi = np.asarray(vr).copy(), np.asarray(vi).copy()
        zr[2, :, wmask * self.W:] = 0
        zi[2, :, wmask * self.W:] = 0
        sh = antenna_sharding(mesh)
        stubs = [
            _StubWindow(i, (
                jax.device_put(zr[:, :, s:s + self.W], sh),
                jax.device_put(zi[:, :, s:s + self.W], sh),
            ), self.W)
            for i, s in enumerate(range(0, self.TOTAL, self.W))
        ]
        golden = np.asarray(beamform_accumulate(iter(stubs), wput,
                                                mesh=mesh))
        np.testing.assert_array_equal(got, golden)


class TestPartitionRules:
    def test_registry_roles_resolve(self):
        from jax.sharding import PartitionSpec as P

        assert M.partition_rule("voltages") == P("band", "bank")
        assert M.partition_rule("replicated") == P()
        # A spec passes through untouched.
        spec = P("band", None)
        assert M.partition_rule(spec) is spec

    def test_unknown_role_lists_known(self):
        with pytest.raises(KeyError, match="voltages"):
            M.partition_rule("no_such_role")

    def test_sharding_for_builds_namedsharding(self):
        mesh = make_mesh(1, 8)
        s = M.sharding_for(mesh, "filterbank_sharded")
        assert s.mesh.shape == {"band": 1, "bank": 8}
        assert s.spec == M.PARTITION_RULES["filterbank_sharded"]

    def test_ici_byte_models(self):
        # all_gather: each chip receives the other n-1 shards.
        assert M.gather_ici_bytes(100, 8) == 700
        assert M.gather_ici_bytes(100, 1) == 0
        # ring all-reduce: 2 * (n-1)/n * nbytes.
        assert M.psum_ici_bytes(800, 2) == 800
        assert M.psum_ici_bytes(800, 1) == 0

    def test_record_ici_accounting(self):
        tl = Timeline()
        M.record_ici(tl, "gather", 1024, 0.5)
        M.record_ici(tl, "gather", 1024)  # untimed: bytes only
        assert tl.stages["mesh.ici"].calls == 2
        assert tl.stages["mesh.ici"].bytes == 2048
        assert tl.hists["mesh.gather_s"].n == 1
        assert tl.hists["mesh.gather_ici_bytes"].n == 2


class TestShardedAccumulator:
    def test_fold_before_init_raises(self):
        acc = M.ShardedAccumulator(make_mesh(1, 8), "beamform_acc")
        with pytest.raises(RuntimeError, match="before init"):
            acc.fold(lambda v: v)

    def test_fold_preserving_rule_passes(self):
        mesh = make_mesh(1, 8)
        acc = M.ShardedAccumulator(mesh, "replicated")
        sh = M.sharding_for(mesh, "replicated")
        acc.init(jax.device_put(np.zeros((8, 4), np.float32), sh))
        add = jax.jit(lambda a, p: a + p, donate_argnums=0)
        out = acc.fold(add,
                       jax.device_put(np.ones((8, 4), np.float32), sh))
        assert np.asarray(out).sum() == 32.0

    def test_spec_drift_fails_loudly(self):
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(1, 8)
        acc = M.ShardedAccumulator(mesh, "replicated")
        acc.init(jax.device_put(np.zeros((8, 4), np.float32),
                                M.sharding_for(mesh, "replicated")))

        def reshard(a):
            return jax.device_put(
                np.asarray(a), jax.sharding.NamedSharding(mesh, P("bank"))
            )

        with pytest.raises(ValueError, match="drifted"):
            acc.fold(reshard)


class TestMeshDefaults:
    def test_env_overrides(self, monkeypatch):
        from blit.config import mesh_defaults

        monkeypatch.setenv("BLIT_MESH_SHARDED", "1")
        monkeypatch.setenv("BLIT_MESH_PROBE", "5")
        monkeypatch.setenv("BLIT_MESH_PREFETCH", "3")
        monkeypatch.setenv("BLIT_MESH_OUT_DEPTH", "4")
        d = mesh_defaults()
        assert d == {"sharded": True, "probe_windows": 5,
                     "prefetch_depth": 3, "out_depth": 4}
        monkeypatch.setenv("BLIT_MESH_SHARDED", "0")
        assert mesh_defaults()["sharded"] is False

    def test_defaults_without_env(self, monkeypatch):
        from blit.config import SiteConfig, mesh_defaults

        for k in ("BLIT_MESH_SHARDED", "BLIT_MESH_PROBE",
                  "BLIT_MESH_PREFETCH", "BLIT_MESH_OUT_DEPTH"):
            monkeypatch.delenv(k, raising=False)
        d = mesh_defaults(SiteConfig())
        assert d == {"sharded": False, "probe_windows": 2,
                     "prefetch_depth": None, "out_depth": None}


class TestCompatShardMapShim:
    """ISSUE 9 satellite: the blit.compat.shard_map version shim
    RESOLVES on both supported jax spellings — the newest
    (jax.shard_map, check_vma) and the oldest
    (jax.experimental.shard_map.shard_map, check_rep)."""

    def test_newest_spelling_routes_check_vma(self, monkeypatch):
        from blit import compat

        seen = {}

        def fake(f, *, mesh, in_specs, out_specs, check_vma):
            seen.update(mesh=mesh, check_vma=check_vma)
            return lambda *a: "new-api"

        monkeypatch.setattr(jax, "shard_map", fake, raising=False)
        got = compat.shard_map(lambda x: x, mesh="m", in_specs=None,
                               out_specs=None, check_vma=False)()
        assert got == "new-api"
        assert seen == {"mesh": "m", "check_vma": False}

    def test_oldest_spelling_routes_check_rep(self, monkeypatch):
        import sys
        import types

        from blit import compat

        seen = {}

        def fake(f, *, mesh, in_specs, out_specs, check_rep):
            seen.update(mesh=mesh, check_rep=check_rep)
            return lambda *a: "old-api"

        # Oldest jax: no jax.shard_map attribute, the API lives at
        # jax.experimental.shard_map.shard_map with check_rep.
        monkeypatch.delattr(jax, "shard_map", raising=False)
        mod = types.ModuleType("jax.experimental.shard_map")
        mod.shard_map = fake
        monkeypatch.setitem(sys.modules, "jax.experimental.shard_map", mod)
        got = compat.shard_map(lambda x: x, mesh="m", in_specs=None,
                               out_specs=None, check_vma=True)()
        assert got == "old-api"
        assert seen == {"mesh": "m", "check_rep": True}

    def test_live_resolution_executes_a_collective(self):
        # Whatever THIS jax provides, the shim must produce a working
        # shard_map: an 8-way psum over the bank axis.
        from jax.sharding import PartitionSpec as P

        from blit.compat import shard_map

        mesh = make_mesh(1, 8)
        x = jax.device_put(
            np.arange(8, dtype=np.float32).reshape(8, 1),
            jax.sharding.NamedSharding(mesh, P("bank", None)),
        )
        out = shard_map(
            lambda b: jax.lax.psum(b, "bank"), mesh=mesh,
            in_specs=P("bank", None), out_specs=P(None, None),
            check_vma=False,
        )(x)
        np.testing.assert_array_equal(np.asarray(out), [[28.0]])


class TestGbtWrappers:
    def test_lazy_wrappers_resolve(self):
        # The deployment surface (blit.gbt) exposes the sharded plane
        # and its pool oracle without importing jax at module import.
        from blit import gbt

        for name in ("reduce_scan_sharded_to_files",
                     "reduce_scan_pool_to_files",
                     "search_scan_sharded_to_files"):
            assert callable(getattr(gbt, name)), name


class TestScanCLI:
    def _tree(self, tmp_path):
        from blit.testing import build_observation_tree

        root = str(tmp_path / "datax")
        build_observation_tree(
            root, kind="raw", players=((0, 0), (0, 1)), nchans=2,
            nfiles=2, raw_ntime=512,
        )
        return root

    def _run(self, capsys, *args):
        from blit.__main__ import main

        rc = main(list(args))
        return rc, capsys.readouterr().out

    def test_scan_sharded_matches_pool_flag(self, tmp_path, capsys):
        root = self._tree(tmp_path)
        d1, d2 = tmp_path / "s", tmp_path / "p"
        d1.mkdir(), d2.mkdir()
        rc1, txt1 = self._run(
            capsys, "scan", root, "AGBT22B_999_01", "0011", "-o", str(d1),
            "--nfft", "64", "--nint", "2", "--window-frames", "4",
            "--sharded",
        )
        rc2, txt2 = self._run(
            capsys, "scan", root, "AGBT22B_999_01", "0011", "-o", str(d2),
            "--nfft", "64", "--nint", "2", "--window-frames", "4",
            "--pool",
        )
        assert rc1 == rc2 == 0
        assert filecmp.cmp(str(d1 / "band0.fil"), str(d2 / "band0.fil"),
                           shallow=False)
        s1 = json.loads(txt1.strip().splitlines()[-1])
        s2 = json.loads(txt2.strip().splitlines()[-1])
        assert s1["parallel"] == "sharded"
        assert s2["parallel"] == "pool"

    def test_scan_sharded_env_default(self, tmp_path, capsys, monkeypatch):
        # BLIT_MESH_SHARDED=1 flips the default path without a flag.
        root = self._tree(tmp_path)
        monkeypatch.setenv("BLIT_MESH_SHARDED", "1")
        (tmp_path / "o").mkdir()
        rc, txt = self._run(
            capsys, "scan", root, "AGBT22B_999_01", "0011",
            "-o", str(tmp_path / "o"), "--nfft", "64", "--nint", "2",
            "--window-frames", "4",
        )
        assert rc == 0
        assert json.loads(txt.strip().splitlines()[-1])["parallel"] == \
            "sharded"

    def test_scan_search_sharded_vs_pool(self, tmp_path, capsys):
        root = self._tree(tmp_path)
        d1, d2 = tmp_path / "s", tmp_path / "p"
        d1.mkdir(), d2.mkdir()
        common = ("scan", root, "AGBT22B_999_01", "0011",
                  "--nfft", "64", "--nint", "2", "--window-frames", "16",
                  "--search", "--window-spectra", "4", "--snr", "4")
        rc1, txt1 = self._run(capsys, *common, "-o", str(d1), "--sharded")
        rc2, txt2 = self._run(capsys, *common, "-o", str(d2), "--pool")
        assert rc1 == rc2 == 0
        hits1 = sorted(p.name for p in d1.glob("*.hits"))
        hits2 = sorted(p.name for p in d2.glob("*.hits"))
        assert hits1 == hits2 and hits1
        for name in hits1:
            assert filecmp.cmp(str(d1 / name), str(d2 / name),
                               shallow=False), name


class TestSearchResumeReplay:
    def test_search_crash_resume_byte_identical(self, tmp_path,
                                                monkeypatch):
        # The SearchCursor twin of TestResumeReplay (ISSUE 12): crash
        # the sharded SEARCH after the 3rd window's channelize, leave
        # per-player cursors (window_claims ledger included), resume at
        # the pod-agreed window, and byte-match both the uninterrupted
        # sharded run AND the pool oracle.
        from blit.search import DedopplerReducer
        from blit.search.dedoppler import SearchCursor

        nband, nbank = 1, 8
        paths = make_scan(tmp_path, nband, nbank, nblocks=4)
        wspec, wf = 4, 8
        kw = dict(nfft=NFFT, nint=NINT, window_spectra=wspec,
                  window_frames=wf, snr_threshold=4.0)
        gold = tmp_path / "gold"
        gold.mkdir()
        gw = search_scan_sharded_to_files(paths, out_dir=str(gold), **kw)

        res = tmp_path / "res"
        res.mkdir()
        real = M.band_reduce
        calls = []

        def flaky(*a, **k):
            calls.append(1)
            if len(calls) == 3:
                raise RuntimeError("synthetic crash")
            return real(*a, **k)

        monkeypatch.setattr(M, "band_reduce", flaky)
        with pytest.raises(RuntimeError, match="synthetic crash"):
            search_scan_sharded_to_files(paths, out_dir=str(res),
                                         resume=True, **kw)
        monkeypatch.setattr(M, "band_reduce", real)
        cursors = [p for p in os.listdir(res) if p.endswith(".cursor")]
        assert len(cursors) == nbank, "every player keeps a cursor"
        cur = SearchCursor.load(str(res / "band0bank0.hits"))
        assert cur is not None and cur.window_claims is not None

        rw = search_scan_sharded_to_files(paths, out_dir=str(res),
                                          resume=True, **kw)
        assert not [p for p in os.listdir(res) if p.endswith(".cursor")]
        pd = tmp_path / "poolhits"
        pd.mkdir()
        for (b, k), (spath, shdr) in rw.items():
            assert filecmp.cmp(spath, gw[(b, k)][0], shallow=False), (
                f"player ({b},{k}): resumed != uninterrupted")
            red = DedopplerReducer(nfft=NFFT, nint=NINT,
                                   window_spectra=wspec,
                                   snr_threshold=4.0, chunk_frames=wf)
            opath = str(pd / f"band{b}bank{k}.hits")
            red.search_to_file(paths[b][k], opath)
            assert filecmp.cmp(spath, opath, shallow=False), (
                f"player ({b},{k}): resumed != pool oracle")
            assert shdr["search_windows"] > 0

    def test_search_resume_restart_at_earlier_agreed_window(
            self, tmp_path):
        # The pod-minimum restart on the RAGGED product: hand-roll one
        # player's cursor BACK two windows (as if a peer had claimed
        # less) and check the resumed product still finishes exact —
        # the window_claims ledger truncation.
        from blit.search.dedoppler import SearchCursor

        nband, nbank = 1, 8
        paths = make_scan(tmp_path, nband, nbank, nblocks=4)
        wspec, wf = 4, 8
        kw = dict(nfft=NFFT, nint=NINT, window_spectra=wspec,
                  window_frames=wf, snr_threshold=4.0)
        gold = tmp_path / "gold"
        gold.mkdir()
        gw = search_scan_sharded_to_files(paths, out_dir=str(gold), **kw)

        res = tmp_path / "res"
        res.mkdir()
        with pytest.raises(RuntimeError):
            _crash_search_after(paths, res, kw, nwindows=3)
        # Roll ONE player back: the pod-wide agreement must restart
        # every player at the minimum.
        target = str(res / "band0bank3.hits")
        cur = SearchCursor.load(target)
        assert cur.windows_done >= 2
        back = cur.windows_done - 1
        off, hits = cur.claim_at(back)
        cur.windows_done, cur.byte_offset, cur.hits_done = back, off, hits
        cur.window_claims = [e for e in cur.window_claims
                             if e[0] <= back]
        cur.save(target)
        with open(target, "r+b") as f:
            f.truncate(off)

        rw = search_scan_sharded_to_files(paths, out_dir=str(res),
                                          resume=True, **kw)
        for (b, k), (spath, _) in rw.items():
            assert filecmp.cmp(spath, gw[(b, k)][0], shallow=False), (
                f"player ({b},{k}): agreed-restart resume != golden")


def _crash_search_after(paths, outdir, kw, nwindows):
    """Run the sharded search with a band_reduce that crashes after
    ``nwindows`` scan windows (monkeypatch-free helper for reuse)."""
    real = M.band_reduce
    calls = []

    def flaky(*a, **k):
        calls.append(1)
        if len(calls) == nwindows:
            raise RuntimeError("synthetic crash")
        return real(*a, **k)

    M.band_reduce = flaky
    try:
        search_scan_sharded_to_files(paths, out_dir=str(outdir),
                                     resume=True, **kw)
    finally:
        M.band_reduce = real
