"""Two-tier content-addressed product cache (blit/serve/cache.py; ISSUE 3):
fingerprint stability (incl. member-order insensitivity — the cache-key
contract), RAM-tier LRU byte budgeting, disk-tier atomic publish +
corrupt-entry eviction, publish fault drills, and the concurrent-access
torn-entry guarantees (ISSUE 3 satellite)."""

import json
import os
import threading

import numpy as np
import pytest

from blit import faults
from blit.observability import Timeline
from blit.serve.cache import (
    ProductCache,
    reduction_fingerprint,
)
from blit.testing import make_fil_header, make_spectra


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


@pytest.fixture
def raw_files(tmp_path):
    paths = []
    for i in range(3):
        p = str(tmp_path / f"m.{i:04d}.raw")
        with open(p, "wb") as f:
            f.write(bytes([i]) * (100 + i))
        paths.append(p)
    return paths


def entry(nsamps=4, nchans=32, seed=0):
    hdr = make_fil_header(nchans=nchans)
    data = make_spectra(nsamps, 1, nchans, seed=seed)
    return hdr, data


class TestFingerprint:
    def test_member_order_insensitive(self, raw_files):
        a = reduction_fingerprint(raw_files, nfft=256, nint=2)
        b = reduction_fingerprint(list(reversed(raw_files)), nfft=256, nint=2)
        assert a == b

    def test_single_path_equals_singleton_list(self, raw_files):
        assert reduction_fingerprint(
            raw_files[0], nfft=64, nint=1
        ) == reduction_fingerprint([raw_files[0]], nfft=64, nint=1)

    def test_every_reducer_knob_is_key_material(self, raw_files):
        base = dict(nfft=256, nint=2, ntap=4, stokes="I", window="hamming",
                    fqav_by=1, dtype="float32", fft_method="auto")
        fp0 = reduction_fingerprint(raw_files, **base)
        for k, v in [("nfft", 512), ("nint", 4), ("ntap", 8),
                     ("stokes", "IQUV"), ("window", "hann"), ("fqav_by", 2),
                     ("dtype", "bfloat16"), ("fft_method", "direct")]:
            assert reduction_fingerprint(
                raw_files, **{**base, k: v}
            ) != fp0, f"changing {k} must change the key"

    def test_changed_bytes_change_the_key(self, raw_files):
        fp0 = reduction_fingerprint(raw_files, nfft=256, nint=2)
        with open(raw_files[1], "ab") as f:
            f.write(b"x")  # size change
        assert reduction_fingerprint(raw_files, nfft=256, nint=2) != fp0

    def test_missing_member_raises(self, tmp_path):
        with pytest.raises(OSError):
            reduction_fingerprint(str(tmp_path / "nope.raw"), nfft=64, nint=1)

    def test_fingerprint_for_pulls_reducer_knobs(self, raw_files):
        jax = pytest.importorskip("jax")  # noqa: F841 — RawReducer needs it
        from blit.pipeline import RawReducer
        from blit.serve.cache import fingerprint_for

        red = RawReducer(nfft=128, nint=2, stokes="I", fqav_by=2)
        assert fingerprint_for(red, raw_files) == reduction_fingerprint(
            raw_files, nfft=128, nint=2, ntap=red.ntap, stokes="I",
            window=red.window, fqav_by=2, dtype=red.dtype,
            fft_method=red.fft_method,
        )


class TestRamTier:
    def test_hit_miss_and_promotion_counters(self):
        tl = Timeline()
        c = ProductCache(None, ram_bytes=1 << 20, timeline=tl)
        assert c.get("f" * 64) is None
        hdr, data = entry()
        served = c.put("f" * 64, hdr, data)
        assert not served.flags.writeable
        got = c.get("f" * 64)
        assert got is not None and got[2] == "ram"
        np.testing.assert_array_equal(got[1], data)
        assert c.stats()["hit.ram"] == 1 and c.stats()["miss"] == 1
        assert tl.stages["cache.hit.ram"].calls == 1
        assert tl.stages["cache.miss"].calls == 1

    def test_lru_eviction_by_byte_budget(self):
        hdr, data = entry(nsamps=4, nchans=32)  # 512 B each
        c = ProductCache(None, ram_bytes=2 * data.nbytes)
        c.put("a" * 64, hdr, data)
        c.put("b" * 64, hdr, make_spectra(4, 1, 32, seed=1))
        assert c.get("a" * 64) is not None  # refresh a: b is now LRU
        c.put("c" * 64, hdr, make_spectra(4, 1, 32, seed=2))
        assert c.get("b" * 64) is None  # evicted
        assert c.get("a" * 64) is not None
        assert c.get("c" * 64) is not None
        assert c.stats()["evict.ram"] == 1

    def test_oversized_entry_skips_ram(self, tmp_path):
        hdr, data = entry(nsamps=64, nchans=64)
        c = ProductCache(str(tmp_path / "cache"), ram_bytes=16)
        c.put("a" * 64, hdr, data)
        assert c.stats()["ram_entries"] == 0
        got = c.get("a" * 64)  # still served, from disk
        assert got is not None and got[2] == "disk"

    def test_later_caller_mutation_cannot_tear_the_entry(self):
        hdr, data = entry()
        c = ProductCache(None, ram_bytes=1 << 20)
        mine = data.copy()
        c.put("a" * 64, hdr, mine)
        mine[:] = -1.0  # publisher keeps writing its own buffer
        np.testing.assert_array_equal(c.get("a" * 64)[1], data)

    def test_hitter_header_mutation_cannot_tear_the_entry(self):
        # Regression: get() must copy the header out — the array is
        # frozen, but a by-reference dict would let one caller's edit
        # corrupt the entry for every later hitter.
        hdr, data = entry()
        c = ProductCache(None, ram_bytes=1 << 20)
        c.put("a" * 64, hdr, data)
        got_hdr, _, _ = c.get("a" * 64)
        got_hdr["source_name"] = "TAMPERED"
        assert c.get("a" * 64)[0]["source_name"] == hdr["source_name"]


class TestDiskTier:
    def test_spill_and_reload_across_instances(self, tmp_path):
        hdr, data = entry(nsamps=8)
        root = str(tmp_path / "cache")
        c1 = ProductCache(root, ram_bytes=1 << 20)
        c1.put("a" * 64, hdr, data)
        # Fresh instance (fresh process stand-in): disk hit, then promoted.
        c2 = ProductCache(root, ram_bytes=1 << 20)
        got = c2.get("a" * 64)
        assert got is not None and got[2] == "disk"
        np.testing.assert_array_equal(got[1], data)
        assert got[0]["source_name"] == hdr["source_name"]
        assert c2.get("a" * 64)[2] == "ram"  # promoted
        assert c2.index() == ["a" * 64]

    def test_publish_is_atomic_no_temp_debris(self, tmp_path):
        root = str(tmp_path / "cache")
        c = ProductCache(root, ram_bytes=1 << 20)
        hdr, data = entry()
        c.put("a" * 64, hdr, data)
        assert sorted(os.listdir(root)) == [
            "a" * 64 + ".h5", "a" * 64 + ".json"
        ]

    def test_corrupt_entry_evicted_not_served(self, tmp_path):
        root = str(tmp_path / "cache")
        c = ProductCache(root, ram_bytes=0)  # force disk reads
        hdr, data = entry()
        c.put("a" * 64, hdr, data)
        # Scribble over the product: the resume_target_ok probe must
        # catch it, evict BOTH files, and report a miss — never raise,
        # never serve garbage.
        with open(c.data_path("a" * 64), "r+b") as f:
            f.truncate(100)
        assert c.get("a" * 64) is None
        assert c.stats()["evict.corrupt"] == 1
        assert not os.path.exists(c.data_path("a" * 64))
        assert not os.path.exists(c.meta_path("a" * 64))

    def test_sidecar_is_the_completeness_marker(self, tmp_path):
        root = str(tmp_path / "cache")
        c = ProductCache(root, ram_bytes=0)
        hdr, data = entry()
        c.put("a" * 64, hdr, data)
        os.unlink(c.meta_path("a" * 64))  # crash between data and sidecar
        assert c.get("a" * 64) is None  # incomplete: a miss, not an error
        assert c.index() == []

    def test_claimed_rows_beyond_file_evicted(self, tmp_path):
        root = str(tmp_path / "cache")
        c = ProductCache(root, ram_bytes=0)
        hdr, data = entry(nsamps=4)
        c.put("a" * 64, hdr, data)
        meta = json.load(open(c.meta_path("a" * 64)))
        meta["nsamps"] = 400  # sidecar claims more than the data holds
        json.dump(meta, open(c.meta_path("a" * 64), "w"))
        assert c.get("a" * 64) is None
        assert c.stats()["evict.corrupt"] == 1

    def test_publish_fault_downgrades_to_ram_only(self, tmp_path):
        faults.install(faults.FaultRule("cache.publish", "fail", times=1))
        root = str(tmp_path / "cache")
        c = ProductCache(root, ram_bytes=1 << 20)
        hdr, data = entry()
        served = c.put("a" * 64, hdr, data)
        # The result in hand is still served (RAM) and no debris landed.
        np.testing.assert_array_equal(served, data)
        assert c.get("a" * 64)[2] == "ram"
        assert os.listdir(root) == []
        assert c.stats()["publish.error"] == 1
        assert faults.counters()["fault.cache.publish.fail"] == 1

    def test_disk_byte_budget_evicts_oldest(self, tmp_path):
        root = str(tmp_path / "cache")
        hdr, data = entry(nsamps=8, nchans=64)
        c = ProductCache(root, ram_bytes=0, disk_bytes=3 * data.nbytes)
        for i, fp in enumerate(["a" * 64, "b" * 64, "c" * 64]):
            c.put(fp, hdr, make_spectra(8, 1, 64, seed=i))
            os.utime(c.data_path(fp), ns=(i * 10**9, i * 10**9))
        c.put("d" * 64, hdr, make_spectra(8, 1, 64, seed=3))
        assert "a" * 64 not in c.index()  # oldest went first
        assert c.stats()["evict.disk"] >= 1


class TestConcurrentAccess:
    """ISSUE 3 satellite: the cache under thread pressure."""

    def test_hammering_readers_never_see_a_torn_entry(self, tmp_path):
        # A tiny RAM budget forces constant eviction while 8 threads mix
        # gets and puts over 4 distinct products: every successful get
        # must return EXACTLY the bytes published under that key.
        nkeys = 4
        hdr = make_fil_header(nchans=32)
        expect = {
            f"{k}" * 64: make_spectra(4, 1, 32, seed=k) for k in range(nkeys)
        }
        c = ProductCache(str(tmp_path / "cache"),
                         ram_bytes=2 * next(iter(expect.values())).nbytes)
        errors = []

        def worker(tid):
            rng = np.random.default_rng(tid)
            for _ in range(60):
                fp = f"{rng.integers(nkeys)}" * 64
                got = c.get(fp)
                if got is None:
                    c.put(fp, hdr, expect[fp].copy())
                    continue
                if got[1].tobytes() != expect[fp].tobytes():
                    errors.append(fp)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert errors == []

    def test_concurrent_same_key_publishes_converge(self, tmp_path):
        # Many threads publishing the SAME key concurrently (the lost
        # single-flight race) must leave one complete, readable entry.
        hdr, data = entry(nsamps=8)
        c = ProductCache(str(tmp_path / "cache"), ram_bytes=1 << 20)
        threads = [
            threading.Thread(
                target=lambda: c.put("a" * 64, hdr, data.copy())
            )
            for _ in range(8)
        ]
        [t.start() for t in threads]
        [t.join() for t in threads]
        c2 = ProductCache(c.root, ram_bytes=1 << 20)
        got = c2.get("a" * 64)
        assert got is not None
        np.testing.assert_array_equal(got[1], data)
        assert sorted(os.listdir(c.root)) == [
            "a" * 64 + ".h5", "a" * 64 + ".json"
        ]


class TestColdTier:
    """Object-store-style cold tier behind the hot disk (ISSUE 19
    tentpole #2): demotion on capacity eviction, manifest/CRC-verified
    promotion on a cold hit (byte-identical to the published product),
    rotted entries evicted — never promoted — and the ``tier ∈ {ram,
    wire, disk, cold, derive}`` reporting surface."""

    def make(self, tmp_path, **kw):
        kw.setdefault("ram_bytes", 1 << 20)
        return ProductCache(str(tmp_path / "hot"),
                            cold_dir=str(tmp_path / "cold"), **kw)

    def publish_one(self, c, seed=1, nsamps=8):
        hdr, data = entry(nsamps=nsamps, seed=seed)
        fp = f"{seed:02x}" * 32
        c.put(fp, hdr, data)
        return fp, hdr, data

    def test_cold_hit_promotes_byte_identical(self, tmp_path):
        c = self.make(tmp_path)
        fp, _, data = self.publish_one(c)
        hot_bytes = open(c.data_path(fp), "rb").read()
        assert c._demote(fp)
        assert not os.path.exists(c.data_path(fp))
        # A fresh process (empty RAM tier) must find the entry cold,
        # verify it against the manifest, and promote it back hot.
        c2 = ProductCache(c.root, ram_bytes=1 << 20,
                          cold_dir=c.cold_dir)
        got = c2.get(fp)
        assert got is not None
        hdr2, data2, tier = got
        assert tier == "cold"
        np.testing.assert_array_equal(data2, data)
        assert c2.counts["hit.cold"] == 1
        assert c2.counts["promote.cold"] == 1
        # Promotion is the EXACT published bytes, and the cold copy is
        # retired once the hot tier holds them again.
        assert open(c2.data_path(fp), "rb").read() == hot_bytes
        assert not os.path.exists(c2.cold_data_path(fp))
        # The next ask is a plain RAM hit — cold served once.
        assert c2.get(fp)[2] == "ram"

    def test_contains_sees_cold_entries(self, tmp_path):
        c = self.make(tmp_path)
        fp, _, _ = self.publish_one(c)
        c._demote(fp)
        assert c.contains(fp)
        assert not c.contains("9" * 64)

    def test_capacity_eviction_demotes_instead_of_deleting(self, tmp_path):
        c = self.make(tmp_path, disk_bytes=1)  # one entry at most
        fp, _, data = self.publish_one(c, seed=2)
        self.publish_one(c, seed=5)  # over budget: seed=2 demotes
        assert c.counts["demote.cold"] >= 1
        assert fp in c.cold_index()
        # The demoted entry still serves — as a cold hit.
        c2 = ProductCache(c.root, ram_bytes=1 << 20,
                          cold_dir=c.cold_dir)
        got = c2.get(fp)
        assert got is not None and got[2] == "cold"
        np.testing.assert_array_equal(got[1], data)

    def test_rotted_cold_entry_is_evicted_not_promoted(self, tmp_path):
        c = self.make(tmp_path)
        fp, _, _ = self.publish_one(c, seed=3)
        c._demote(fp)
        with open(c.cold_data_path(fp), "r+b") as f:
            f.seek(128)
            f.write(b"\xff" * 16)
        c2 = ProductCache(c.root, ram_bytes=1 << 20,
                          cold_dir=c.cold_dir)
        assert c2.get(fp) is None  # a miss, never garbage
        assert not os.path.exists(c2.cold_data_path(fp))
        assert not os.path.exists(c2.cold_meta_path(fp))
        assert c2.counts["miss"] == 1

    def test_ram_only_cache_ignores_cold_dir(self, tmp_path):
        c = ProductCache(None, cold_dir=str(tmp_path / "cold"))
        assert c.cold_dir is None
        assert c.cold_index() == []

    def test_hit_rate_counts_cold_hits(self, tmp_path):
        c = self.make(tmp_path)
        fp, _, _ = self.publish_one(c, seed=4)
        c._demote(fp)
        c2 = ProductCache(c.root, ram_bytes=1 << 20,
                          cold_dir=c.cold_dir)
        c2.get(fp)
        c2.get("8" * 64)
        assert c2.hit_rate == 0.5
