"""Native C++ codec tests: bitshuffle+LZ4 (vs the NumPy bit-transpose
model), FBH5 direct-chunk round-trips, and the threaded GUPPI reader.

All skip cleanly when blit/native is unbuilt (`make -C blit/native`)."""

import numpy as np
import pytest

from blit.io import bshuf

pytestmark = pytest.mark.skipif(
    not bshuf.available(), reason="native libs not built (make -C blit/native)"
)


class TestBitshuffleCore:
    @pytest.mark.parametrize("dtype", [np.float32, np.int8, np.uint16, np.float64])
    def test_shuffle_matches_numpy_model(self, dtype):
        rng = np.random.default_rng(0)
        a = rng.integers(0, 200, 1024).astype(dtype)
        np.testing.assert_array_equal(bshuf.bitshuffle(a), bshuf.bitshuffle_np(a))

    def test_shuffle_roundtrip(self):
        rng = np.random.default_rng(1)
        a = rng.standard_normal(4096).astype(np.float32)
        back = bshuf.bitunshuffle(bshuf.bitshuffle(a), np.float32, a.size)
        np.testing.assert_array_equal(back, a)

    def test_non_multiple_of_8_raises(self):
        with pytest.raises(ValueError):
            bshuf.bitshuffle(np.zeros(7, np.float32))

    # Sweep the kernel dispatch seams: the AVX2 fast path (elem 1/2/4,
    # >= 512 elements), the u64-SWAR path (elem 8; short inputs), and the
    # sub-chunk tails each path hands off (lengths not multiples of the
    # 512-element staging chunk or the 8-position block step).
    @pytest.mark.parametrize("esize", [1, 2, 4, 8])
    @pytest.mark.parametrize("n", [8, 64, 512, 520, 1000, 4104, 1 << 14])
    def test_shuffle_matches_model_across_paths(self, esize, n):
        dtype = {1: np.uint8, 2: np.uint16, 4: np.float32, 8: np.float64}[esize]
        rng = np.random.default_rng(esize * 1000 + n)
        # Full byte alphabet incl. 0xFF (all-bits-set catches SWAR
        # mask/carry bugs); compare as raw bytes — float views would let
        # NaN-payload scrambles and 0.0 sign flips pass assert_array_equal.
        a = (rng.integers(0, 256, n * esize, dtype=np.uint16)
             .astype(np.uint8).view(dtype)[:n].copy())
        np.testing.assert_array_equal(bshuf.bitshuffle(a).view(np.uint8),
                                      bshuf.bitshuffle_np(a).view(np.uint8))
        back = bshuf.bitunshuffle(bshuf.bitshuffle(a), dtype, a.size)
        np.testing.assert_array_equal(back.view(np.uint8), a.view(np.uint8))

    @pytest.mark.parametrize("n", [8, 500, 2048, 2051, 10000, 99999])
    @pytest.mark.parametrize("dtype", [np.float32, np.int8, np.uint16])
    def test_chunk_codec_fuzz(self, n, dtype):
        # Chunk codec round trip across block boundaries, partial last
        # blocks, and the raw sub-8-element leftover framing.
        rng = np.random.default_rng(n)
        a = (rng.integers(-100, 100, n).astype(dtype)
             if dtype != np.float32
             else (rng.standard_normal(n) * 50).astype(np.float32))
        comp = bshuf.compress_chunk(a)
        back = bshuf.decompress_chunk(comp, dtype, n)
        np.testing.assert_array_equal(back.view(np.uint8), a.view(np.uint8))

    @pytest.mark.parametrize("n", [8, 131, 1000, 4096, 100_000])
    def test_chunk_codec_roundtrip(self, n):
        rng = np.random.default_rng(n)
        a = (rng.standard_normal(n) * 100).astype(np.float32)
        payload = bshuf.compress_chunk(a)
        np.testing.assert_array_equal(
            bshuf.decompress_chunk(payload, np.float32, n), a
        )

    def test_wire_format_header(self):
        # [u64 BE nbytes][u32 BE block bytes] prefix per the filter spec.
        a = np.arange(1024, dtype=np.float32)
        p = bshuf.compress_chunk(a)
        assert int.from_bytes(p[:8], "big") == a.nbytes
        blk = int.from_bytes(p[8:12], "big")
        assert blk == bshuf.default_block_size(4) * 4

    def test_compression_ratio_on_smooth_data(self):
        a = np.arange(65536, dtype=np.float32)
        assert len(bshuf.compress_chunk(a)) < 0.2 * a.nbytes

    def test_size_mismatch_rejected(self):
        a = np.arange(64, dtype=np.float32)
        p = bshuf.compress_chunk(a)
        with pytest.raises(ValueError):
            bshuf.decompress_chunk(p, np.float32, 128)


class TestFBH5Bitshuffle:
    def make(self, tmp_path, shape=(20, 2, 64), chunks=None):
        from blit.io.fbh5 import write_fbh5

        rng = np.random.default_rng(2)
        data = rng.standard_normal(shape).astype(np.float32)
        hdr = {"fch1": 8000.0, "foff": -0.1, "nchans": shape[2],
               "nifs": shape[1], "tsamp": 1.0, "nbits": 32}
        p = str(tmp_path / "x.h5")
        write_fbh5(p, hdr, data, compression="bitshuffle", chunks=chunks)
        return p, data

    def test_full_read_roundtrip(self, tmp_path):
        from blit.io.fbh5 import read_fbh5_data

        p, data = self.make(tmp_path)
        np.testing.assert_array_equal(read_fbh5_data(p), data)

    def test_edge_chunks_roundtrip(self, tmp_path):
        from blit.io.fbh5 import read_fbh5_data

        # 20 rows with 16-row chunks → padded edge chunk.
        p, data = self.make(tmp_path, shape=(20, 2, 100), chunks=(16, 2, 100))
        np.testing.assert_array_equal(read_fbh5_data(p), data)

    @pytest.mark.parametrize("idxs", [
        (slice(3, 11), slice(None), slice(10, 50)),
        (slice(None), slice(0, 1), slice(None, None, 4)),
        (5, slice(None), slice(None)),
        (-1, slice(None), slice(None)),
        (slice(17, 20), slice(None), slice(90, 100)),
    ])
    def test_hyperslab_reads(self, tmp_path, idxs):
        from blit.io.fbh5 import read_fbh5_data

        p, data = self.make(tmp_path, shape=(20, 2, 100), chunks=(8, 1, 32))
        np.testing.assert_array_equal(read_fbh5_data(p, idxs), data[idxs])

    def test_filter_id_in_pipeline(self, tmp_path):
        import h5py

        from blit.io.fbh5 import BITSHUFFLE_FILTER_ID

        p, _ = self.make(tmp_path)
        with h5py.File(p, "r") as h5:
            plist = h5["data"].id.get_create_plist()
            codes = [plist.get_filter(i)[0] for i in range(plist.get_nfilters())]
        assert BITSHUFFLE_FILTER_ID in codes

    def test_worker_functions_read_bitshuffle(self, tmp_path):
        # The reference's worker read path must work on compressed products.
        from blit import workers

        p, data = self.make(tmp_path)
        hdr = workers.get_header(p)
        assert hdr["nchans"] == 64
        out = workers.get_data(p, (slice(None), slice(None), slice(None)),
                               fqav_by=4)
        # rtol covers f32 group-sum reordering: fqav's default sum runs as
        # one BLAS pass (blit/ops/fqav.py), not np.sum's pairwise order.
        np.testing.assert_allclose(
            out, data.reshape(20, 2, 16, 4).sum(axis=-1), rtol=1e-5
        )


class TestGuppiPread:
    def test_threaded_read_matches_file(self, tmp_path):
        from blit.io.native import guppi_pread

        rng = np.random.default_rng(3)
        blob = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
        p = tmp_path / "x.bin"
        p.write_bytes(blob)
        out = guppi_pread(str(p), 4096, 1 << 19, nthreads=4)
        assert out.tobytes() == blob[4096 : 4096 + (1 << 19)]

    def test_short_read_errors(self, tmp_path):
        from blit.io.native import guppi_pread

        p = tmp_path / "small.bin"
        p.write_bytes(b"abc")
        with pytest.raises(OSError):
            guppi_pread(str(p), 0, 100)


class TestNativeGuppiRaw:
    """Parity of the native threaded reader against the memmap path."""

    def _raw(self, tmp_path, **kw):
        from blit.testing import synth_raw

        p = str(tmp_path / "n.raw")
        synth_raw(p, nblocks=3, obsnchan=4, ntime_per_block=256, **kw)
        return p

    def test_read_block_native_matches_memmap(self, tmp_path):
        from blit.io.guppi import GuppiRaw
        from blit.io.native import guppi_lib

        if guppi_lib() is None:
            pytest.skip("native reader unbuilt")
        p = self._raw(tmp_path, directio=True)
        a, b = GuppiRaw(p, native=True), GuppiRaw(p, native=False)
        assert a.native and not b.native
        for i in range(a.nblocks):
            np.testing.assert_array_equal(a.read_block(i), b.read_block(i))

    @pytest.mark.parametrize("native", [True, False])
    def test_read_block_into_ring_slice(self, tmp_path, native):
        from blit.io.guppi import GuppiRaw
        from blit.io.native import guppi_lib

        if native and guppi_lib() is None:
            pytest.skip("native reader unbuilt")
        p = self._raw(tmp_path, overlap=32)
        raw = GuppiRaw(p, native=native)
        want = raw.read_block(1)
        # Land samples [16, 16+128) at time offset 40 of a wider ring.
        ring = np.full((4, 512, 2, 2), -100, np.int8)
        n = raw.read_block_into(1, ring[:, 40:], t0=16, ntime_keep=128)
        assert n == 128
        np.testing.assert_array_equal(ring[:, 40:168], want[:, 16:144])
        assert (ring[:, :40] == -100).all() and (ring[:, 168:] == -100).all()

    def test_read_block_into_bounds_checked(self, tmp_path):
        from blit.io.guppi import GuppiRaw

        p = self._raw(tmp_path)
        raw = GuppiRaw(p)
        ring = np.empty((4, 64, 2, 2), np.int8)
        with pytest.raises(ValueError, match="outside block"):
            raw.read_block_into(0, ring, t0=200, ntime_keep=100)
        with pytest.raises(ValueError):
            raw.read_block_into(0, np.empty((3, 64, 2, 2), np.int8))

    @pytest.mark.parametrize("native", [True, False])
    def test_stream_identical_across_readers(self, tmp_path, native):
        pytest.importorskip("jax")
        from blit.io.guppi import GuppiRaw
        from blit.io.native import guppi_lib
        from blit.pipeline import RawReducer

        if native and guppi_lib() is None:
            pytest.skip("native reader unbuilt")
        p = self._raw(tmp_path, overlap=64, tone_chan=2)
        red = RawReducer(nfft=32, nint=2, chunk_frames=4)
        slabs = list(red.stream(GuppiRaw(p, native=native)))
        red2 = RawReducer(nfft=32, nint=2, chunk_frames=4)
        slabs2 = list(red2.stream(GuppiRaw(p, native=not native)))
        assert len(slabs) == len(slabs2)
        for s1, s2 in zip(slabs, slabs2):
            np.testing.assert_array_equal(s1, s2)
