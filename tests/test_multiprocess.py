"""Real 2-process pod execution (blit/parallel/multihost.py + scan.py).

The reference drives 64 hosts from one process over ssh (src/gbt.jl:28-42);
blit's TPU analog is ``jax.distributed`` with each process feeding only its
own banks' files.  These tests run that analog for real: two OS processes,
a localhost coordinator, gloo CPU collectives, disjoint ``local_players``,
per-process file locality, and a cross-process ``band_reduce`` stitch whose
product must match the single-process golden.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.parallel.mesh import make_mesh  # noqa: E402
from blit.parallel.scan import load_scan_mesh  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NBAND, NBANK, NFFT, NINT, NCHAN = 2, 4, 32, 2, 2
CHILD = os.path.join(os.path.dirname(__file__), "_mh_child.py")
PSUM_CHILD = os.path.join(os.path.dirname(__file__), "_mh_psum_child.py")
RESUME_CHILD = os.path.join(os.path.dirname(__file__), "_mh_resume_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _golden(tmp_path):
    """Single-process reduction of the identical synthetic scan (same seeds
    and headers as the children write) on this process's 8-device mesh."""
    bank_bw = -187.5 / NBANK
    paths = []
    for b in range(NBAND):
        row = []
        for k in range(NBANK):
            p = str(tmp_path / f"golden_blc{b}{k}.raw")
            synth_raw(p, nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
                      seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
                      obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw)
            row.append(p)
        paths.append(row)
    hdr, out = load_scan_mesh(paths, nfft=NFFT, nint=NINT, despike=False,
                              mesh=make_mesh(NBAND, NBANK))
    return hdr, np.asarray(out)


def _run_pod(outdir, extra_args=(), child=CHILD):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(CHILD))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    ).rstrip(os.pathsep)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    procs = [
        subprocess.Popen(
            [sys.executable, child, str(pid), "2", str(port), outdir,
             *extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("pod child timed out (coordinator / gloo stall)")
        outs.append((p.returncode, out, err))
    return outs


@pytest.mark.flaky(reruns=1)
def test_two_process_pod_matches_single_process(tmp_path):
    # ISSUE 5 satellite: this pod test is known to stall under load (the
    # localhost coordinator / gloo bring-up races the 240 s child budget
    # on saturated runners) — ONE auto-rerun via pytest-rerunfailures,
    # scoped to this test only, absorbs the transient without masking a
    # real regression (a deterministic failure still fails both runs).
    # The marker is inert where the plugin isn't installed.
    outdir = str(tmp_path / "pod")
    os.makedirs(outdir)
    outs = _run_pod(outdir)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-OK" in out, (
            f"pod child failed (rc={rc}):\n{err[-3000:]}"
        )

    reports = []
    for pid in range(2):
        with open(os.path.join(outdir, f"proc{pid}.json")) as f:
            reports.append(json.load(f))

    # Disjoint, complete player ownership across the two processes.
    locals_ = [set(map(tuple, r["local"])) for r in reports]
    assert locals_[0] and locals_[1]
    assert not (locals_[0] & locals_[1]), "local_players overlap"
    assert locals_[0] | locals_[1] == {
        (b, k) for b in range(NBAND) for k in range(NBANK)
    }

    # Every band row produced by the pod matches the single-process golden.
    ghdr, golden = _golden(tmp_path)
    seen_bands = set()
    for pid, r in enumerate(reports):
        assert r["nchans"] == ghdr["nchans"]
        assert r["nsamps"] == ghdr["nsamps"]
        for band in r["bands"]:
            row = np.load(os.path.join(outdir, f"band{band}_proc{pid}.npy"))
            np.testing.assert_allclose(
                row, golden[band], rtol=1e-5, atol=1e-3
            )
            seen_bands.add(band)
    assert seen_bands == set(range(NBAND))
    # The band-0 header agrees wherever band 0 was local.
    for r in reports:
        if 0 in [b for b, _ in map(tuple, r["local"])]:
            assert r["fch1"] == pytest.approx(ghdr["fch1"])
            assert r["foff"] == pytest.approx(ghdr["foff"])


def test_pod_player_failure_raises_on_every_process(tmp_path):
    # One player's file missing on its owning host: the owner AND the peer
    # must both raise promptly (symmetric agreement), not error-vs-hang.
    outdir = str(tmp_path / "podfail")
    os.makedirs(outdir)
    outs = _run_pod(outdir, extra_args=("1,2",))
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-SYMMETRIC-ERROR" in out, (
            f"pod child did not fail symmetrically (rc={rc}):\n"
            f"{out[-500:]}\n{err[-2000:]}"
        )


def test_two_process_psum_products_match_golden(tmp_path):
    # VERDICT r3 item 6: the psum collectives (beamform config 4, FX
    # correlator config 5) executed under jax.distributed with 2 gloo
    # processes — the configuration where a wrong sharding becomes a
    # cross-process deadlock.  Each child asserts its addressable shards
    # against the NumPy goldens; any mismatch or hang fails here.
    outs = _run_pod(str(tmp_path), child=PSUM_CHILD)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-PSUM-OK" in out, (
            f"psum pod child failed (rc={rc}):\n{err[-3000:]}"
        )


def test_two_process_resumable_mesh_writer(tmp_path):
    # The resume restart offset is agreed POD-WIDE (window-aligned MIN over
    # every process's cursors) — this runs crash → cursors → resume →
    # byte-identical product under real jax.distributed with 2 processes.
    outs = _run_pod(str(tmp_path), child=RESUME_CHILD)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-RESUME-OK" in out, (
            f"resume pod child failed (rc={rc}):\n{err[-3000:]}"
        )
