"""Real 2-process pod execution (blit/parallel/multihost.py + scan.py).

The reference drives 64 hosts from one process over ssh (src/gbt.jl:28-42);
blit's TPU analog is ``jax.distributed`` with each process feeding only its
own banks' files.  These tests run that analog for real: two OS processes,
a localhost coordinator, gloo CPU collectives, disjoint ``local_players``,
per-process file locality, and a cross-process ``band_reduce`` stitch whose
product must match the single-process golden.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.parallel.mesh import make_mesh  # noqa: E402
from blit.parallel.scan import load_scan_mesh  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NBAND, NBANK, NFFT, NINT, NCHAN = 2, 4, 32, 2, 2
CHILD = os.path.join(os.path.dirname(__file__), "_mh_child.py")
PSUM_CHILD = os.path.join(os.path.dirname(__file__), "_mh_psum_child.py")
RESUME_CHILD = os.path.join(os.path.dirname(__file__), "_mh_resume_child.py")
SHARDED_CHILD = os.path.join(os.path.dirname(__file__),
                             "_mh_sharded_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _golden(tmp_path):
    """Single-process reduction of the identical synthetic scan (same seeds
    and headers as the children write) on this process's 8-device mesh."""
    bank_bw = -187.5 / NBANK
    paths = []
    for b in range(NBAND):
        row = []
        for k in range(NBANK):
            p = str(tmp_path / f"golden_blc{b}{k}.raw")
            synth_raw(p, nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
                      seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
                      obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw)
            row.append(p)
        paths.append(row)
    hdr, out = load_scan_mesh(paths, nfft=NFFT, nint=NINT, despike=False,
                              mesh=make_mesh(NBAND, NBANK))
    return hdr, np.asarray(out)


# Deflaked pod execution (ISSUE 8 satellite).  The old shape gave each
# child ONE 240 s budget covering BOTH distributed bring-up (coordinator
# + gloo handshakes — legitimately slow on saturated CI runners) and the
# actual reduction, and papered over the races with a blanket
# @pytest.mark.flaky(reruns=1).  Now the child drops a readiness marker
# the moment init_multihost returns (blit.testing.signal_ready), and the
# parent runs TWO separately-budgeted phases:
#
#   1. readiness barrier — wait for every child's marker.  Bring-up load
#      spikes extend only this phase; a child that DIES during bring-up
#      fails immediately with its stderr (no timeout wait).
#   2. work — communicate() from the barrier, so the reduction gets its
#      full budget regardless of how slow bring-up was.
#
# Budgets are env-tunable for slower rigs (BLIT_POD_READY_TIMEOUT_S /
# BLIT_POD_WORK_TIMEOUT_S); a deterministic failure still fails — only
# the load-dependent bring-up race is absorbed, so the rerun marker (and
# its plugin dependency) is gone.  Defaults are sized so the designed
# worst case (barrier + both sequential communicates; in practice the
# children run concurrently, so the second communicate returns almost
# immediately after the first) stays inside the tier-1 job's outer
# 870 s wall with room for the rest of the suite — the per-test
# backstop below must be REACHABLE in CI, not just on paper.
_READY_TIMEOUT_S = float(os.environ.get("BLIT_POD_READY_TIMEOUT_S", 240))
_WORK_TIMEOUT_S = float(os.environ.get("BLIT_POD_WORK_TIMEOUT_S", 240))
# Per-test backstop (pytest-timeout, inert without the plugin): sized
# ABOVE the phases' own worst case — barrier + two sequential
# communicate() budgets — so the tailored failure messages and child
# kill/cleanup above always run first, and raising the env budgets on a
# slow rig raises this backstop with them.
_TEST_TIMEOUT_S = int(_READY_TIMEOUT_S + 2 * _WORK_TIMEOUT_S + 60)


def _child_err(outdir, pid):
    try:
        with open(os.path.join(outdir, f"child{pid}.err")) as f:
            return f.read()
    except OSError:
        return "<no stderr captured>"


def _kill_pod(procs):
    """Kill AND reap every child: without the wait() a killed child
    stays a zombie for the rest of the pytest session (and its
    ResourceWarning noise lands in the very CI logs the deflake is
    meant to keep readable)."""
    for q in procs:
        q.kill()
    for q in procs:
        try:
            q.wait(timeout=10)
        except Exception:  # noqa: BLE001 — already failing the test
            pass


def _await_ready(procs, outdir, timeout_s):
    """Block until every child wrote its readiness marker; fail with the
    dead child's stderr (from its redirect file) if one exits during
    bring-up."""
    import time

    deadline = time.monotonic() + timeout_s
    pending = {pid: os.path.join(outdir, f".ready{pid}")
               for pid in range(len(procs))}
    while pending:
        for pid in list(pending):
            if os.path.exists(pending[pid]):
                del pending[pid]
                continue
            p = procs[pid]
            if p.poll() is not None:
                if p.returncode == 0 and os.path.exists(pending[pid]):
                    # Fast child: it wrote its marker and exited cleanly
                    # between our marker check and poll() — ready, not
                    # dead.  (Without this recheck, a sub-second child
                    # reintroduces exactly the flake this barrier fixes.)
                    del pending[pid]
                    continue
                _kill_pod(procs)
                pytest.fail(
                    f"pod child {pid} died during bring-up "
                    f"(rc={p.returncode}):\n"
                    f"{_child_err(outdir, pid)[-3000:]}"
                )
        if pending and time.monotonic() > deadline:
            _kill_pod(procs)
            pytest.fail(
                f"pod children {sorted(pending)} not ready within "
                f"{timeout_s:.0f}s (coordinator / gloo bring-up stall; "
                "raise BLIT_POD_READY_TIMEOUT_S on slower rigs)"
            )
        if pending:
            time.sleep(0.1)


def _run_pod(outdir, extra_args=(), child=CHILD):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(CHILD))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    ).rstrip(os.pathsep)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    # Child output goes to FILES, not pipes: the readiness barrier waits
    # up to _READY_TIMEOUT_S without reading child output, and a chatty
    # distributed bring-up (gloo retries, XLA logging under CI load) can
    # fill a ~64 KiB pipe and deadlock the child BEFORE it signals ready
    # — the exact wedge this barrier exists to absorb.
    procs, logs = [], []
    try:
        for pid in range(2):
            fo = open(os.path.join(outdir, f"child{pid}.out"), "w+")
            fe = open(os.path.join(outdir, f"child{pid}.err"), "w+")
            logs.append((fo, fe))
            procs.append(subprocess.Popen(
                [sys.executable, child, str(pid), "2", str(port), outdir,
                 *extra_args],
                env=env, stdout=fo, stderr=fe, text=True,
            ))
        _await_ready(procs, outdir, _READY_TIMEOUT_S)
        outs = []
        for p, (fo, fe) in zip(procs, logs):
            try:
                p.communicate(timeout=_WORK_TIMEOUT_S)  # output is on disk
            except subprocess.TimeoutExpired:
                _kill_pod(procs)
                pytest.fail("pod child hung AFTER distributed bring-up "
                            "completed (collective deadlock?)")
            finally:
                for f in (fo, fe):
                    f.flush()
                    f.seek(0)
            outs.append((p.returncode, fo.read(), fe.read()))
        return outs
    finally:
        # Every exit path — barrier pytest.fail, communicate timeout,
        # happy return — closes the redirect files exactly once.
        for fo, fe in logs:
            for f in (fo, fe):
                try:
                    f.close()
                except OSError:
                    pass


@pytest.mark.timeout(_TEST_TIMEOUT_S)
def test_two_process_pod_matches_single_process(tmp_path):
    outdir = str(tmp_path / "pod")
    os.makedirs(outdir)
    outs = _run_pod(outdir)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-OK" in out, (
            f"pod child failed (rc={rc}):\n{err[-3000:]}"
        )

    reports = []
    for pid in range(2):
        with open(os.path.join(outdir, f"proc{pid}.json")) as f:
            reports.append(json.load(f))

    # Disjoint, complete player ownership across the two processes.
    locals_ = [set(map(tuple, r["local"])) for r in reports]
    assert locals_[0] and locals_[1]
    assert not (locals_[0] & locals_[1]), "local_players overlap"
    assert locals_[0] | locals_[1] == {
        (b, k) for b in range(NBAND) for k in range(NBANK)
    }

    # Every band row produced by the pod matches the single-process golden.
    ghdr, golden = _golden(tmp_path)
    seen_bands = set()
    for pid, r in enumerate(reports):
        assert r["nchans"] == ghdr["nchans"]
        assert r["nsamps"] == ghdr["nsamps"]
        for band in r["bands"]:
            row = np.load(os.path.join(outdir, f"band{band}_proc{pid}.npy"))
            np.testing.assert_allclose(
                row, golden[band], rtol=1e-5, atol=1e-3
            )
            seen_bands.add(band)
    assert seen_bands == set(range(NBAND))
    # The band-0 header agrees wherever band 0 was local.
    for r in reports:
        if 0 in [b for b, _ in map(tuple, r["local"])]:
            assert r["fch1"] == pytest.approx(ghdr["fch1"])
            assert r["foff"] == pytest.approx(ghdr["foff"])


@pytest.mark.timeout(_TEST_TIMEOUT_S)
def test_pod_player_failure_raises_on_every_process(tmp_path):
    # One player's file missing on its owning host: the owner AND the peer
    # must both raise promptly (symmetric agreement), not error-vs-hang.
    outdir = str(tmp_path / "podfail")
    os.makedirs(outdir)
    outs = _run_pod(outdir, extra_args=("1,2",))
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-SYMMETRIC-ERROR" in out, (
            f"pod child did not fail symmetrically (rc={rc}):\n"
            f"{out[-500:]}\n{err[-2000:]}"
        )


@pytest.mark.timeout(_TEST_TIMEOUT_S)
def test_two_process_psum_products_match_golden(tmp_path):
    # VERDICT r3 item 6: the psum collectives (beamform config 4, FX
    # correlator config 5) executed under jax.distributed with 2 gloo
    # processes — the configuration where a wrong sharding becomes a
    # cross-process deadlock.  Each child asserts its addressable shards
    # against the NumPy goldens; any mismatch or hang fails here.
    outs = _run_pod(str(tmp_path), child=PSUM_CHILD)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-PSUM-OK" in out, (
            f"psum pod child failed (rc={rc}):\n{err[-3000:]}"
        )


@pytest.mark.timeout(_TEST_TIMEOUT_S)
def test_two_process_sharded_scan_matches_pool_oracle(tmp_path):
    # ISSUE 9: the fully-threaded sharded reduction plane under REAL
    # jax.distributed — per-shard pinned feeds, addressable-shard-only
    # readback, write-behind sinks — with each process feeding only its
    # own players' files.  The pod's per-band .fil products must be
    # BYTE-IDENTICAL to the single-process pool-path oracle over the
    # identical synthetic scan (same seeds, same window_frames).
    outdir = str(tmp_path / "podsharded")
    os.makedirs(outdir)
    outs = _run_pod(outdir, child=SHARDED_CHILD)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-SHARDED-OK" in out, (
            f"sharded pod child failed (rc={rc}):\n{err[-3000:]}"
        )

    reports = []
    for pid in range(2):
        with open(os.path.join(outdir, f"proc{pid}.json")) as f:
            reports.append(json.load(f))
    # Disjoint band ownership covering the whole scan.
    bands = [set(r["bands"]) for r in reports]
    assert not (bands[0] & bands[1]) and bands[0] | bands[1] == {0, 1}

    # Pool oracle: the identical scan reduced single-process.
    from blit.parallel.scan import reduce_scan_pool_to_files

    bank_bw = -187.5 / NBANK
    paths = []
    for b in range(NBAND):
        row = []
        for k in range(NBANK):
            p = str(tmp_path / f"oracle_blc{b}{k}.raw")
            synth_raw(p, nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
                      seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
                      obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw)
            row.append(p)
        paths.append(row)
    gold = str(tmp_path / "oracle")
    os.makedirs(gold)
    gw = reduce_scan_pool_to_files(
        paths, out_dir=gold, nfft=NFFT, nint=NINT, despike=False,
        window_frames=4,
    )
    import filecmp

    for band in range(NBAND):
        pod = os.path.join(outdir, "products", f"band{band}.fil")
        assert filecmp.cmp(pod, gw[band][0], shallow=False), (
            f"pod band {band} product != pool oracle bytes"
        )


@pytest.mark.timeout(_TEST_TIMEOUT_S)
def test_two_process_resumable_mesh_writer(tmp_path):
    # The resume restart offset is agreed POD-WIDE (window-aligned MIN over
    # every process's cursors) — this runs crash → cursors → resume →
    # byte-identical product under real jax.distributed with 2 processes.
    outs = _run_pod(str(tmp_path), child=RESUME_CHILD)
    for rc, out, err in outs:
        assert rc == 0 and "CHILD-RESUME-OK" in out, (
            f"resume pod child failed (rc={rc}):\n{err[-3000:]}"
        )
