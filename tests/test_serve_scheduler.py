"""Priority scheduler + admission control (blit/serve/scheduler.py;
ISSUE 3): deterministic overload rejection (never a hang), priority and
fair-share dispatch order, the health-aware concurrency budget (a tripped
breaker measurably shrinks admitted concurrency — acceptance criterion),
queued-job cancellation, and the dispatch fault-injection point."""

import threading
import time

import pytest

from blit import faults
from blit.faults import FaultRule, InjectedFault
from blit.observability import Timeline
from blit.parallel.pool import WorkerPool
from blit.serve.scheduler import Cancelled, Overloaded, Scheduler


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


class Gate:
    """A job body that blocks until released, recording its run order."""

    def __init__(self):
        self.release = threading.Event()
        self.order = []
        self.started = threading.Event()

    def job(self, tag):
        def run():
            self.started.set()
            assert self.release.wait(10), "gate never released"
            self.order.append(tag)
            return tag

        return run

    def instant(self, tag):
        def run():
            self.order.append(tag)
            return tag

        return run


def wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.005)


class TestDispatchOrder:
    def test_priorities_dispatch_lowest_first(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=16)
        blocker = s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        jobs = [s.submit(g.instant(p), priority=p) for p in (2, 0, 1)]
        g.release.set()
        for j in jobs:
            j.result(timeout=10)
        blocker.result(timeout=10)
        assert g.order == ["blocker", 0, 1, 2]

    def test_fair_share_round_robin_across_clients(self):
        # One caller fanning out a burst must not starve another: with
        # alice's 4 jobs queued ahead of bob's 1, bob still runs second.
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=16)
        blocker = s.submit(g.job("blocker"), client="alice")
        wait_for(g.started.is_set)
        jobs = [s.submit(g.instant(f"alice{i}"), client="alice")
                for i in range(4)]
        jobs.append(s.submit(g.instant("bob0"), client="bob"))
        g.release.set()
        for j in jobs:
            j.result(timeout=10)
        blocker.result(timeout=10)
        assert g.order[0] == "blocker"
        # Round-robin: alice0, bob0, then alice's remaining backlog.
        assert g.order[1:3] == ["alice0", "bob0"]
        assert g.order[3:] == ["alice1", "alice2", "alice3"]

    def test_concurrency_budget_is_respected(self):
        g = Gate()
        s = Scheduler(max_concurrency=2, queue_depth=16)
        jobs = [s.submit(g.job(i)) for i in range(4)]
        wait_for(lambda: s.running() == 2)
        assert s.depth() == 2  # the rest stay queued
        g.release.set()
        for j in jobs:
            j.result(timeout=10)
        assert s.running() == 0


class TestAdmissionControl:
    def test_full_queue_rejects_with_overloaded_not_a_hang(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=2)
        s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        s.submit(g.instant("q1"))
        s.submit(g.instant("q2"))
        t0 = time.monotonic()
        with pytest.raises(Overloaded) as ei:
            s.submit(g.instant("q3"))
        assert time.monotonic() - t0 < 1.0  # immediate, not a hang
        assert ei.value.retry_after_s > 0
        assert s.counts["rejected"] == 1
        g.release.set()
        s.close()

    def test_queue_bound_is_per_priority(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=1)
        s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        s.submit(g.instant("a"), priority=1)
        with pytest.raises(Overloaded):
            s.submit(g.instant("b"), priority=1)
        s.submit(g.instant("c"), priority=0)  # other priority: own bound
        g.release.set()
        s.close()

    def test_unmeetable_deadline_rejected_at_the_door(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=16)
        # Seed the service-time estimator with one real completion.
        s.submit(lambda: time.sleep(0.05)).result(timeout=10)
        assert s.est_wait_s(1) == 0.0  # empty queue: no wait
        s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        for i in range(4):
            s.submit(g.instant(i))
        est = s.est_wait_s(1)
        assert est > 0.0
        with pytest.raises(Overloaded) as ei:
            s.submit(g.instant("late"), deadline_s=est / 100)
        assert ei.value.retry_after_s > 0
        # A patient caller is still admitted.
        s.submit(g.instant("patient"), deadline_s=60.0)
        g.release.set()
        s.close()

    def test_degraded_host_shrinks_admitted_concurrency(self):
        # Acceptance criterion: a tripped breaker (HostDegraded, PR 2)
        # must measurably shrink the concurrency budget — half the hosts
        # degraded halves the admitted parallelism.
        pool = WorkerPool(["h0", "h1"], backend="local")
        s = Scheduler(max_concurrency=2, queue_depth=16, pool=pool)
        assert s.effective_budget() == 2
        g = Gate()
        br = pool.workers[0].breaker
        for _ in range(br.threshold):
            br.record_failure()
        assert pool.health()[0]["state"] == "open"
        assert s.effective_budget() == 1
        jobs = [s.submit(g.job(i)) for i in range(2)]
        wait_for(lambda: s.running() == 1)
        time.sleep(0.05)
        assert s.running() == 1  # second job held back by the shrunk budget
        assert s.depth() == 1
        # Recovery: the breaker re-closing restores the budget and the
        # held job dispatches on the next completion.
        br.record_success()
        assert s.effective_budget() == 2
        g.release.set()
        for j in jobs:
            j.result(timeout=10)
        s.close()
        pool.shutdown()

    def test_fully_degraded_pool_still_probes_one_job(self):
        pool = WorkerPool(["h0", "h1"], backend="local")
        for w in pool.workers:
            for _ in range(w.breaker.threshold):
                w.breaker.record_failure()
        s = Scheduler(max_concurrency=4, pool=pool)
        assert s.effective_budget() == 1  # floor: never wedge the queue
        pool.shutdown()


class TestWaitEstimatorRegimes:
    """ISSUE 11 satellite (the ROADMAP item-3 carve-out): admission uses
    the REAL wait_hist p99 once enough samples exist, with the EWMA
    model below the sample floor."""

    def test_below_floor_uses_the_ewma_model(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=16,
                      wait_est_floor=1000)
        s.submit(lambda: time.sleep(0.05)).result(timeout=10)
        s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        for i in range(4):
            s.submit(g.instant(i))
        # EWMA regime: the estimate is backlog x service time — it
        # scales with the queue depth, unlike a static p99.
        est4 = s.est_wait_s(1)
        assert est4 == pytest.approx(5 * s._svc_ewma, rel=1e-6)
        s.submit(g.instant("more"))
        assert s.est_wait_s(1) > est4
        g.release.set()
        s.close()

    def test_at_floor_the_real_p99_estimates(self):
        # Seed the wait histogram with KNOWN waits via the injectable
        # clock: 100 recorded queue waits around 2s (p99 ~ 2s), then a
        # trivial EWMA — the regimes disagree wildly, and the estimate
        # must follow the histogram.
        s = Scheduler(max_concurrency=1, wait_est_floor=32)
        for _ in range(100):
            s.wait_hist.observe(2.0)
        s._svc_ewma = 0.001
        s._svc_n = 1
        with s._lock:
            s._queued[1] = 3  # synthetic backlog (ahead > 0)
        est = s.est_wait_s(1)
        p99 = s.wait_hist.percentile(0.99)
        assert est == pytest.approx(p99)
        assert est > 1.0  # nowhere near the EWMA model's ~0.003
        # An EMPTY scheduler predicts no wait whatever the history says.
        with s._lock:
            s._queued[1] = 0
        assert s.est_wait_s(1) == 0.0
        # Deadline admission now rejects on the observed tail.
        with s._lock:
            s._queued[1] = 3
        with pytest.raises(Overloaded):
            s.submit(lambda: None, deadline_s=0.5)
        with s._lock:
            s._queued[1] = 0
        s.close(timeout=1)

    def test_floor_boundary(self):
        s = Scheduler(max_concurrency=1, wait_est_floor=4)
        for _ in range(3):
            s.wait_hist.observe(5.0)
        s._svc_ewma = 0.01
        s._svc_n = 1
        with s._lock:
            s._queued[1] = 2
        below = s.est_wait_s(1)  # n=3 < floor: EWMA model
        assert below < 1.0
        s.wait_hist.observe(5.0)  # n=4 == floor: histogram p99
        assert s.est_wait_s(1) > 1.0
        with s._lock:
            s._queued[1] = 0
        s.close(timeout=1)


class TestLoadShed:
    """Scheduler.shed — the SLO breach action (ISSUE 11)."""

    def test_shed_scales_budget_and_queue_depth(self):
        s = Scheduler(max_concurrency=4, queue_depth=8)
        assert s.effective_budget() == 4
        s.shed(0.5)
        assert s.shed_level() == 0.5
        assert s.effective_budget() == 2
        assert s._shed_queue_depth() == 4
        s.shed(0.0)
        assert s.effective_budget() == 4
        # Clamped: a hook can never shed to zero admission.
        s.shed(5.0)
        assert s.shed_level() == 0.9
        assert s.effective_budget() >= 1
        assert s._shed_queue_depth() >= 1
        s.close()

    def test_shed_queue_bound_rejects_at_the_tightened_door(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=4)
        s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        s.shed(0.5)  # admitted depth: 2
        s.submit(g.instant("a"))
        s.submit(g.instant("b"))
        with pytest.raises(Overloaded, match="shedding"):
            s.submit(g.instant("c"))
        s.shed(0.0)
        s.submit(g.instant("c"))  # released: full depth again
        g.release.set()
        s.close()

    def test_shed_is_gauged(self):
        tl = Timeline()
        s = Scheduler(max_concurrency=2, timeline=tl)
        s.shed(0.5)
        assert tl.gauges["sched.shed"].last == 0.5
        assert tl.stages["sched.shed_change"].calls == 1
        s.shed(0.5)  # unchanged: no extra change event
        assert tl.stages["sched.shed_change"].calls == 1
        s.close()


class TestCancellation:
    def test_cancel_queued_job_releases_its_slot(self):
        g = Gate()
        s = Scheduler(max_concurrency=1, queue_depth=1)
        s.submit(g.job("blocker"))
        wait_for(g.started.is_set)
        queued = s.submit(g.instant("queued"))
        with pytest.raises(Overloaded):
            s.submit(g.instant("refused"))
        assert s.cancel(queued)
        assert queued.state == "cancelled"
        with pytest.raises(Cancelled):
            queued.result(timeout=1)
        replacement = s.submit(g.instant("replacement"))  # slot released
        g.release.set()
        assert replacement.result(timeout=10) == "replacement"
        assert "queued" not in g.order  # never dispatched
        s.close()

    def test_running_job_cannot_be_cancelled(self):
        g = Gate()
        s = Scheduler(max_concurrency=1)
        j = s.submit(g.job("r"))
        wait_for(g.started.is_set)
        assert not s.cancel(j)
        g.release.set()
        assert j.result(timeout=10) == "r"
        s.close()


class TestFailuresAndDrills:
    def test_job_exception_delivered_via_result(self):
        s = Scheduler(max_concurrency=1)

        def boom():
            raise ValueError("bad request")

        j = s.submit(boom)
        with pytest.raises(ValueError, match="bad request"):
            j.result(timeout=10)
        assert s.counts["failed"] == 1
        s.close()

    def test_dispatch_fault_injection_point(self):
        # BLIT_FAULTS drills reach the serving layer: a sched.dispatch
        # fail rule kills the dispatched job, keyed by client identity.
        faults.install(FaultRule("sched.dispatch", "fail", times=1,
                                 match="victim"))
        s = Scheduler(max_concurrency=2)
        ok = s.submit(lambda: "fine", client="bystander")
        bad = s.submit(lambda: "never", client="victim")
        assert ok.result(timeout=10) == "fine"
        with pytest.raises(InjectedFault):
            bad.result(timeout=10)
        assert faults.counters()["fault.sched.dispatch.fail"] == 1
        s.close()

    def test_result_timeout_is_builtin_timeout_error(self):
        g = Gate()
        s = Scheduler(max_concurrency=1)
        j = s.submit(g.job("slow"))
        wait_for(g.started.is_set)
        with pytest.raises(TimeoutError):
            j.result(timeout=0.01)
        g.release.set()
        j.result(timeout=10)
        s.close()


class TestObservability:
    def test_wait_gauges_and_percentiles(self):
        tl = Timeline()
        s = Scheduler(max_concurrency=1, timeline=tl)
        for i in range(5):
            s.submit(lambda: None).result(timeout=10)
        s.close()
        pct = s.wait_percentiles()
        assert pct["n"] == 5
        assert 0.0 <= pct["p50"] <= pct["p99"]
        rep = tl.report()
        assert "gauges" in rep
        assert rep["gauges"]["sched.wait_s"]["n"] == 5
        assert rep["gauges"]["sched.queue_depth"]["n"] == 5
        assert tl.stages["sched.run"].byte_free  # no byte-invariant breach

    def test_closed_scheduler_refuses_work(self):
        s = Scheduler(max_concurrency=1)
        s.close()
        with pytest.raises(RuntimeError, match="closed"):
            s.submit(lambda: None)


class TestCapacityHolds:
    """Session-length capacity holds (ISSUE 12 satellite): live jobs
    pin a concurrency slot but never poison the bounded-job EWMA or
    the deadline estimator's work-ahead count."""

    def test_hold_pins_capacity_and_reports(self):
        tl = Timeline()
        s = Scheduler(max_concurrency=2, timeline=tl)
        g = Gate()
        j = s.submit(g.job("live"), hold=True)
        wait_for(lambda: s.held() == 1)
        assert s.running() == 1
        assert tl.report()["gauges"]["sched.held"]["last"] == 1.0
        g.release.set()
        j.result(timeout=10)
        wait_for(lambda: s.held() == 0)
        s.close()

    def test_held_job_excluded_from_ewma(self):
        s = Scheduler(max_concurrency=2)
        g = Gate()
        j = s.submit(g.job("live"), hold=True)
        wait_for(g.started.is_set)
        time.sleep(0.05)  # the session "runs long"
        g.release.set()
        j.result(timeout=10)
        wait_for(lambda: s.held() == 0)
        assert s._svc_ewma == 0.0, (
            "a session's duration must not become the bounded-job "
            "service model")
        # A bounded job still seeds the EWMA normally.
        s.submit(lambda: None).result(timeout=10)
        wait_for(lambda: s.running() == 0)
        assert s._svc_n == 1
        s.close()

    def test_deadline_admission_ignores_held_sessions(self):
        s = Scheduler(max_concurrency=2, wait_est_floor=1 << 30)
        # Seed a nonzero EWMA with one bounded job.
        s.submit(lambda: time.sleep(0.05)).result(timeout=10)
        wait_for(lambda: s.running() == 0)
        assert s._svc_ewma > 0
        g = Gate()
        s.submit(g.job("live"), hold=True)
        wait_for(lambda: s.held() == 1)
        # Work-ahead excludes the session: a fresh tight deadline must
        # still be admitted (the old math counted the unbounded job).
        assert s.est_wait_s(priority=1) == 0.0
        j = s.submit(lambda: "ok", deadline_s=0.001)
        assert j.result(timeout=10) == "ok"
        g.release.set()
        s.close()
