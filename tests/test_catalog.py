"""Archive catalog (blit/serve/catalog.py; ISSUE 19 tentpole #1): the
session/scan/product index built from the inventory crawl — lookup
document shapes, by-(session, scan, player) resolution into member
paths, mtime-invalidated incremental rescan (sessions appearing
mid-flight), the bounded TTL'd negative-lookup cache, malformed player
dirs rejected by the corrected PLAYER_RE, and door/peer catalog
agreement over the real fleet wire (addressed asks byte-identical to
explicit-member asks)."""

import os
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from blit.config import DEFAULT  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.serve import (  # noqa: E402
    PeerServer,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.serve.cache import fingerprint_for  # noqa: E402
from blit.serve.catalog import (  # noqa: E402
    CatalogIndex,
    CatalogMiss,
    catalog_fingerprint,
)
from blit.serve.fleet import FleetFrontDoor  # noqa: E402
from blit.testing import build_observation_tree  # noqa: E402

SESSION = "AGBT25A_999_01"
NFFT = 64
RAW_NTIME = 6 * NFFT  # x2 blocks/file = 12 PFB frames' worth


@pytest.fixture
def archive(tmp_path):
    root = str(tmp_path / "archive")
    build_observation_tree(root, SESSION, scans=("0001", "0002"),
                           players=((0, 0), (0, 1)), kind="raw",
                           nchans=2, raw_ntime=RAW_NTIME, nfiles=2)
    return root


def make_index(root, **kw):
    kw.setdefault("rescan_s", 0.0)
    return CatalogIndex(root, **kw)


class TestCatalogFingerprint:
    def test_stable_and_query_keyed(self):
        assert catalog_fingerprint("") == catalog_fingerprint("")
        assert catalog_fingerprint("a") != catalog_fingerprint("b")

    def test_never_collides_with_product_space(self, archive):
        # A catalog ask hashes a namespaced string, never file bytes —
        # even a query spelling a real path keys differently than any
        # product fingerprint shape (64 hex chars is all they share).
        fp = catalog_fingerprint(f"{SESSION}/0001")
        assert len(fp) == 64
        assert fp != catalog_fingerprint(f"{SESSION}/0002")


class TestLookupShapes:
    def test_all_sessions_document(self, archive):
        doc = make_index(archive).lookup()
        assert doc["sessions"][SESSION]["scans"] == 2
        assert doc["sessions"][SESSION]["files"] == 8  # 2 scans x 2 players x 2 members

    def test_session_document_lists_scans(self, archive):
        doc = make_index(archive).lookup(SESSION)
        assert sorted(doc["scans"]) == ["0001", "0002"]
        sc = doc["scans"]["0001"]
        assert sc["bands"] == [0] and sc["banks"] == [0, 1]
        assert sc["sequences"] == 2
        assert "members" not in sc  # membership only on the scan ask

    def test_scan_document_carries_members(self, archive):
        doc = make_index(archive).lookup(SESSION, "0001")
        members = doc["members"]
        assert sorted(members) == ["00", "01"]
        for paths in members.values():
            assert len(paths) == 2
            assert all(os.path.exists(p) for p in paths)

    def test_scan_keys_are_zero_padded_strings(self, archive):
        # The naming grammar's scan field is a STRING ("0001"), and the
        # catalog must key exactly like the wire query partition does.
        idx = make_index(archive)
        idx.lookup(SESSION, "0001")
        with pytest.raises(CatalogMiss):
            idx.lookup(SESSION, "1")


class TestResolve:
    def test_resolves_unique_player_sequence(self, archive):
        idx = make_index(archive)
        members = idx.resolve(SESSION, "0001", band=0, bank=1)
        assert len(members) == 2
        assert members == sorted(members)
        assert all("blc01" in os.path.basename(p) for p in members)

    def test_ambiguous_without_player_is_loud(self, archive):
        with pytest.raises(CatalogMiss, match="2 RAW sequences"):
            make_index(archive).resolve(SESSION, "0001")

    def test_absent_player_is_a_miss(self, archive):
        with pytest.raises(CatalogMiss, match="no RAW sequence"):
            make_index(archive).resolve(SESSION, "0001", band=3, bank=7)


class TestMalformedPlayers:
    def test_malformed_player_dirs_never_index(self, archive):
        # The corrected PLAYER_RE admits BLP[0-7][0-7] only — a dir
        # named outside the grammar must be skipped by the crawl even
        # when its files parse.
        for bad in ("BLP99", "BLPXY", "BLP0", "GPU00"):
            d = os.path.join(archive, SESSION, "GUPPI", bad)
            os.makedirs(d)
            with open(os.path.join(
                    d, "blc00_guppi_59897_21221_HD_84406_0001.0000.raw"),
                    "wb") as f:
                f.write(b"not a recording")
        doc = make_index(archive).lookup(SESSION, "0001")
        assert sorted(doc["members"]) == ["00", "01"]
        assert doc["bands"] == [0] and doc["banks"] == [0, 1]


class TestRescan:
    def test_session_appearing_mid_flight(self, archive):
        idx = make_index(archive)
        with pytest.raises(CatalogMiss):
            idx.lookup("AGBT25A_999_02")
        build_observation_tree(archive, "AGBT25A_999_02",
                               scans=("0003",), players=((1, 0),),
                               kind="raw", nchans=2,
                               raw_ntime=RAW_NTIME, nfiles=1)
        doc = idx.lookup("AGBT25A_999_02", "0003")
        assert sorted(doc["members"]) == ["10"]

    def test_new_scan_invalidates_only_its_session(self, archive):
        idx = make_index(archive)
        idx.lookup(SESSION)
        base = idx.stats()["rescans"]
        build_observation_tree(archive, SESSION, scans=("0009",),
                               players=((0, 0),), kind="raw", nchans=2,
                               raw_ntime=RAW_NTIME, nfiles=1)
        doc = idx.lookup(SESSION)
        assert "0009" in doc["scans"]
        assert idx.stats()["rescans"] == base + 1

    def test_unchanged_tree_is_never_recrawled(self, archive):
        idx = make_index(archive)
        idx.lookup(SESSION)
        base = idx.stats()["rescans"]
        for _ in range(5):
            idx.lookup(SESSION, "0002")
        assert idx.stats()["rescans"] == base


class TestNegativeCache:
    def test_repeat_miss_skips_the_tree(self, archive):
        idx = make_index(archive, negative_ttl_s=30.0)
        with pytest.raises(CatalogMiss):
            idx.lookup(SESSION, "9999")
        refreshes = idx.stats()["refreshes"]
        with pytest.raises(CatalogMiss, match="negative-cached"):
            idx.lookup(SESSION, "9999")
        assert idx.stats()["refreshes"] == refreshes
        assert idx.stats()["neg_hits"] == 1

    def test_expiry_rechecks_and_finds_late_data(self, archive):
        idx = make_index(archive, negative_ttl_s=0.05)
        with pytest.raises(CatalogMiss):
            idx.lookup(SESSION, "0042")
        build_observation_tree(archive, SESSION, scans=("0042",),
                              players=((0, 0),), kind="raw", nchans=2,
                              raw_ntime=RAW_NTIME, nfiles=1)
        # Inside the TTL the miss is still served from the cache...
        with pytest.raises(CatalogMiss, match="negative-cached"):
            idx.lookup(SESSION, "0042")
        time.sleep(0.06)
        # ...and past it the rescan finds the late-landing scan.
        doc = idx.lookup(SESSION, "0042")
        assert sorted(doc["members"]) == ["00"]

    def test_bounded_by_negative_max(self, archive):
        idx = make_index(archive, negative_ttl_s=30.0, negative_max=4)
        for i in range(10):
            with pytest.raises(CatalogMiss):
                idx.lookup(SESSION, f"9{i:03d}")
        assert idx.stats()["negative_entries"] == 4


class TestServeSurface:
    def test_serve_shapes_ride_the_product_result(self, archive):
        idx = make_index(archive)
        hdr, data = idx.serve("")
        assert hdr["kind"] == "catalog" and SESSION in hdr["sessions"]
        assert data.shape == (0, 1, 0) and not data.flags.writeable
        hdr, _ = idx.serve(f"{SESSION}/0001")
        assert sorted(hdr["members"]) == ["00", "01"]

    def test_serve_miss_raises(self, archive):
        with pytest.raises(CatalogMiss):
            make_index(archive).serve("NOPE")


class TestFleetAgreement:
    """Door and peer each crawl the SAME root independently; the wire
    must agree — addressed product asks byte-identical to explicit
    member-path asks, and catalog documents identical modulo the
    serving generation."""

    @pytest.fixture
    def fleet(self, tmp_path, archive):
        config = DEFAULT.with_(catalog_root=archive)
        lease_dir = str(tmp_path / "leases")
        tl = Timeline()
        svc = ProductService(
            cache=ProductCache(str(tmp_path / "cache0"),
                               ram_bytes=1 << 24, timeline=tl),
            scheduler=Scheduler(max_concurrency=2, queue_depth=8,
                                timeline=tl, retry_seed=0),
            timeline=tl, config=config)
        ps = PeerServer(svc, name="peer0", lease_dir=lease_dir, proc=0,
                        beat_interval_s=0.05).start()
        door = FleetFrontDoor({"peer0": ps.url}, lease_dir=lease_dir,
                              timeline=Timeline(), peer_ttl_s=0.6,
                              poll_s=0.05, hedge_floor_s=5.0,
                              request_timeout_s=60.0, config=config)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            door.observe()
            if all(p.watch.seen for p in door._peers.values()):
                break
            time.sleep(0.05)
        yield door, ps
        door.close()
        ps.close()
        svc.close(5)

    def test_addressed_equals_explicit_member_ask(self, fleet):
        door, _ = fleet
        addressed = ProductRequest(raw="", session=SESSION,
                                   scan="0001", band=0, bank=1,
                                   nfft=NFFT, nint=1)
        _, d1 = door.get(addressed, client="t")
        members = door.catalog.resolve(SESSION, "0001", band=0, bank=1)
        explicit = ProductRequest(raw=tuple(members), nfft=NFFT, nint=1)
        _, d2 = door.get(explicit, client="t")
        assert d1.dtype == d2.dtype and d1.shape == d2.shape
        assert d1.tobytes() == d2.tobytes()
        # Same fingerprint by construction: resolution happened at the
        # door, BEFORE routing — one owner, one cache entry.
        fp1 = fingerprint_for(addressed.reducer()
                              if addressed.session is None else
                              explicit.reducer(), explicit.raw_source)
        assert door._peers["peer0"].breaker.failures == 0
        svc_cache = fleet[1].service.cache
        assert svc_cache.counts.get("miss", 0) == 1
        assert fp1 in svc_cache._ram

    def test_catalog_documents_agree_across_the_wire(self, fleet):
        door, ps = fleet
        hdr, data = door.get(ProductRequest(kind="catalog",
                                            raw=f"{SESSION}/0001"),
                             client="t")
        local = CatalogIndex(ps.service.catalog.root, rescan_s=0.0)
        want, _ = local.serve(f"{SESSION}/0001")
        for k in ("kind", "query", "session", "scan", "members",
                  "bands", "banks", "src"):
            assert hdr[k] == want[k]
        assert data.size == 0

    def test_unknown_scan_is_a_clean_miss_not_a_breaker_trip(self, fleet):
        door, _ = fleet
        with pytest.raises(CatalogMiss):
            door.get(ProductRequest(kind="catalog",
                                    raw=f"{SESSION}/8888"), client="t")
        assert door._peers["peer0"].breaker.failures == 0

    def test_addressed_miss_is_terminal_at_the_door(self, fleet):
        door, _ = fleet
        with pytest.raises((CatalogMiss, Exception)) as ei:
            door.get(ProductRequest(raw="", session="NO_SUCH",
                                    scan="0001", nfft=NFFT, nint=1),
                     client="t")
        assert "NO_SUCH" in str(ei.value)
