"""fqav parity tests, including the reference's own two unit tests
(test/runtests.jl:4-7) translated to the (fch1, foff, nchans) triple form."""

import numpy as np
import pytest

from blit.ops import fqav, fqav_range


def test_reference_range_tests():
    # Julia: @test GBT.fqav(1:4, 4) === 2.5:4.0:2.5  (start 2.5, step 4, len 1)
    assert fqav_range(1.0, 1.0, 4, 4) == (2.5, 4.0, 1)
    # Julia: @test GBT.fqav(1:2:15, 4) === 4.0:8.0:12.0  (start 4, step 8, len 2)
    assert fqav_range(1.0, 2.0, 8, 4) == (4.0, 8.0, 2)


def test_range_identity():
    assert fqav_range(10.0, -0.5, 64, 1) == (10.0, -0.5, 64)
    assert fqav_range(10.0, -0.5, 64, 0) == (10.0, -0.5, 64)


def test_range_negative_foff():
    fch1, foff, n = fqav_range(100.0, -1.0, 8, 2)
    assert (fch1, foff, n) == (99.5, -2.0, 4)


def test_array_sum_default():
    a = np.arange(12.0).reshape(1, 1, 12)
    out = fqav(a, 4)
    assert out.shape == (1, 1, 3)
    np.testing.assert_allclose(out[0, 0], [0 + 1 + 2 + 3, 4 + 5 + 6 + 7, 8 + 9 + 10 + 11])


def test_array_mean_and_max():
    a = np.arange(8.0).reshape(1, 1, 8)
    np.testing.assert_allclose(fqav(a, 2, f=np.mean)[0, 0], [0.5, 2.5, 4.5, 6.5])
    np.testing.assert_allclose(fqav(a, 2, f=np.max)[0, 0], [1, 3, 5, 7])


def test_array_identity_n1():
    a = np.random.default_rng(0).normal(size=(5, 2, 8))
    assert fqav(a, 1) is a
    assert fqav(a, 0) is a


def test_array_divisibility_error():
    a = np.zeros((2, 2, 10))
    with pytest.raises(ValueError):
        fqav(a, 3)


def test_array_3d_grouping_matches_reference_layout():
    # Channel is the fastest-varying axis in both layouts; averaging groups
    # consecutive channels.  Check against an explicit loop.
    rng = np.random.default_rng(1)
    a = rng.normal(size=(4, 2, 16))
    out = fqav(a, 4)
    expect = np.zeros((4, 2, 4))
    for c in range(4):
        expect[:, :, c] = a[:, :, 4 * c : 4 * c + 4].sum(axis=-1)
    np.testing.assert_allclose(out, expect)


def test_array_jax():
    import jax.numpy as jnp

    a = jnp.arange(12.0).reshape(1, 1, 12)
    out = fqav(a, 3, f=jnp.sum)
    np.testing.assert_allclose(np.asarray(out)[0, 0], [3, 12, 21, 30])
