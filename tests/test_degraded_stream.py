"""Degraded-antenna continuation in the streaming collectives (ISSUE 2
tentpole): injected transient read faults retry to byte-identical
results; a HARD mid-stream antenna failure masks that antenna
(zero-weight, flagged in the result metadata) instead of aborting the
scan; producer stalls are bounded by a watchdog; producer exceptions
propagate promptly."""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.faults import FaultRule, InjectedFault, RetryPolicy  # noqa: E402
from blit.ops.channelize import pfb_coeffs  # noqa: E402
from blit.parallel.antenna import (  # noqa: E402
    AntennaStream,
    CorrelatorStream,
    load_antennas_mesh,
)
from blit.parallel.beamform import (  # noqa: E402
    antenna_sharding,
    beamform,
    beamform_stream,
    weight_sharding,
)
from blit.parallel.correlator import correlate_stream  # noqa: E402
from blit.parallel.mesh import make_mesh  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NANT, NCHAN, NPOL = 4, 4, 2
KEPT = 960          # gap-free samples per recording
START = 48          # every test re-enters mid-recording
TOTAL = 896         # samples consumed from START
W = 128             # beamform window (7 windows)
NINT = 4
NFFT, NTAP, WF = 16, 4, 8


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    # Deterministic, sleepless backoff for every injected-transient test.
    faults.set_io_policy(RetryPolicy(attempts=3, base_s=0.0, jitter=0.0))
    yield
    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(None)


@pytest.fixture(scope="module")
def ant_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("degraded_ants")
    paths = []
    for a in range(NANT):
        p = str(d / f"ant{a}.raw")
        synth_raw(p, nblocks=2, obsnchan=NCHAN, ntime_per_block=KEPT // 2,
                  seed=200 + a, tone_chan=a % NCHAN)
        paths.append(p)
    return paths


@pytest.fixture(scope="module")
def weights():
    rng = np.random.default_rng(5)
    return (rng.standard_normal((3, NANT, NCHAN))
            + 1j * rng.standard_normal((3, NANT, NCHAN))).astype(np.complex64)


def put_weights(w, mesh):
    ws = weight_sharding(mesh)
    return (jax.device_put(w.real.astype(np.float32), ws),
            jax.device_put(w.imag.astype(np.float32), ws))


def run_stream(feed, wput, mesh, windows=None):
    """Drive beamform_stream, recording each window's masked tuple."""
    def spy(f):
        for win in f:
            if windows is not None:
                windows.append(win.masked)
            yield win

    return np.concatenate(
        list(beamform_stream(spy(feed), wput, mesh=mesh, nint=NINT)), axis=2
    )


class TestTransientRetryTransparency:
    def test_stream_with_injected_read_faults_is_byte_identical(
            self, ant_files, weights):
        # THE acceptance scenario: transient read faults (flaky NFS) on
        # one antenna retry inside the producer and the streamed beam
        # powers come out byte-identical to the fault-free run.
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        _, vp = load_antennas_mesh(ant_files, mesh=mesh,
                                   start_sample=START, max_samples=TOTAL)
        one = np.asarray(beamform(vp, wput, mesh=mesh, nint=NINT))
        faults.install(FaultRule("guppi.read", "fail", times=2, match="ant1"))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL)
        got = run_stream(feed, wput, mesh)
        np.testing.assert_array_equal(got, one)
        assert feed.masked_antennas == set()  # recovered, nothing degraded
        assert faults.counters()["retry.io"] >= 2
        assert faults.counters()["fault.guppi.read.fail"] == 2


class TestDegradedBeamform:
    def test_hard_midstream_failure_masks_antenna_not_abort(
            self, ant_files, weights):
        # A truncate fault is HARD (short read — never retried): the
        # stream must complete with antenna 2 zero-weighted from the
        # failing window on, flagged in the metadata, and the output
        # byte-identical to a one-shot beamform over planes with that
        # antenna zeroed from the same window boundary.
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        faults.install(
            FaultRule("guppi.read", "truncate", times=1, after=2,
                      match="ant2")
        )
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL,
                             on_antenna_error="mask")
        per_window = []
        got = run_stream(feed, wput, mesh, windows=per_window)

        assert feed.masked_antennas == {2}
        assert feed.header["_masked_antennas"] == [2]
        wmask = next(i for i, m in enumerate(per_window) if m)
        assert 0 < wmask < feed.nwindows - 1  # genuinely mid-stream
        assert all(m == (2,) for m in per_window[wmask:])

        _, (vr, vi) = load_antennas_mesh(ant_files, mesh=mesh,
                                         start_sample=START,
                                         max_samples=TOTAL)
        zr = np.asarray(vr).copy()
        zi = np.asarray(vi).copy()
        zr[2, :, wmask * W:] = 0
        zi[2, :, wmask * W:] = 0
        sh = antenna_sharding(mesh)
        golden = np.asarray(beamform(
            (jax.device_put(zr, sh), jax.device_put(zi, sh)), wput,
            mesh=mesh, nint=NINT,
        ))
        np.testing.assert_array_equal(got, golden)

        # A degraded run SAYS so: feed timeline + global fault counters.
        rep = feed.timeline.report(include_faults=True)
        assert rep["antenna.masked"]["calls"] == 1
        assert rep["faults"]["mask.antenna"] == 1

    def test_retry_exhaustion_masks_under_mask_mode(self, ant_files,
                                                    weights):
        # Persistent transient failure (dead mount): retries exhaust,
        # then the mask policy converts the hard failure into degraded
        # continuation from window 0.
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        faults.install(FaultRule("guppi.read", "fail", times=-1,
                                 match="ant3"))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL,
                             on_antenna_error="mask")
        got = run_stream(feed, wput, mesh)
        assert feed.masked_antennas == {3}
        faults.clear()  # disarm before reading the golden's planes
        _, (vr, vi) = load_antennas_mesh(ant_files, mesh=mesh,
                                         start_sample=START,
                                         max_samples=TOTAL)
        zr = np.asarray(vr).copy()
        zi = np.asarray(vi).copy()
        zr[3] = 0
        zi[3] = 0
        sh = antenna_sharding(mesh)
        golden = np.asarray(beamform(
            (jax.device_put(zr, sh), jax.device_put(zi, sh)), wput,
            mesh=mesh, nint=NINT,
        ))
        np.testing.assert_array_equal(got, golden)

    def test_default_policy_still_raises(self, ant_files, weights):
        # on_antenna_error="raise" (the default) preserves the loud
        # behavior: hard failures abort promptly (no rotation deadlock).
        mesh = make_mesh(1, 4)
        wput = put_weights(weights, mesh)
        faults.set_io_policy(RetryPolicy(attempts=2, base_s=0.0))
        faults.install(FaultRule("guppi.read", "fail", times=-1,
                                 match="ant0"))
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL)
        t0 = time.monotonic()
        with pytest.raises(InjectedFault):
            for win in feed:
                win.release()
        assert time.monotonic() - t0 < 30

    def test_bad_policy_name_rejected(self, ant_files):
        mesh = make_mesh(1, 4)
        with pytest.raises(ValueError, match="on_antenna_error"):
            AntennaStream(ant_files, mesh=mesh, window_samples=W,
                          on_antenna_error="ignore")


class TestDegradedCorrelator:
    def test_hard_midstream_failure_masks_antenna(self, ant_files):
        import jax.numpy as jnp

        mesh = make_mesh(2, 2)
        coeffs = jnp.asarray(pfb_coeffs(NTAP, NFFT).astype(np.float32))

        def stream(**kw):
            feed = CorrelatorStream(ant_files, mesh=mesh, nfft=NFFT,
                                    ntap=NTAP, window_frames=WF,
                                    start_sample=START, **kw)
            from blit.observability import Timeline

            tl = Timeline()
            visr, visi = correlate_stream(feed, coeffs, mesh=mesh,
                                          nfft=NFFT, ntap=NTAP, timeline=tl)
            return feed, tl, np.asarray(visr), np.asarray(visi)

        _, _, cr, ci = stream()
        faults.install(
            FaultRule("guppi.read", "truncate", times=1, after=3,
                      match="ant2")
        )
        feed, tl, gr, gi = stream(on_antenna_error="mask")

        # Completed degraded, flagged in the metadata + driver tables.
        assert feed.masked_antennas == {2}
        assert feed.header["_masked_antennas"] == [2]
        assert tl.stages["masked_antennas"].calls >= 1
        assert np.isfinite(gr).all() and np.isfinite(gi).all()
        # Baselines not involving the masked antenna are untouched —
        # byte-identical to the fault-free stream (pairwise cross
        # products never read antenna 2's spectra).
        keep = np.array([0, 1, 3])
        np.testing.assert_array_equal(gr[np.ix_(keep, keep)],
                                      cr[np.ix_(keep, keep)])
        np.testing.assert_array_equal(gi[np.ix_(keep, keep)],
                                      ci[np.ix_(keep, keep)])
        # The masked antenna's visibilities lost the post-mask windows.
        assert not np.array_equal(gr[2, 2], cr[2, 2])


class TestStallWatchdog:
    def test_wedged_producer_bounds_the_hang(self, ant_files):
        # A wedged read (injected delay far beyond the watchdog) must
        # surface as a prompt RuntimeError, not an unbounded hang.
        mesh = make_mesh(1, 4)
        faults.install(
            FaultRule("antenna.produce", "delay", times=1, delay_s=1.0)
        )
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL,
                             stall_timeout_s=0.2)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="stalled"):
            for win in feed:
                win.release()
        assert time.monotonic() - t0 < 10

    def test_healthy_stream_unaffected_by_watchdog(self, ant_files):
        mesh = make_mesh(1, 4)
        feed = AntennaStream(ant_files, mesh=mesh, window_samples=W,
                             start_sample=START, max_samples=TOTAL,
                             stall_timeout_s=5.0)
        n = 0
        for win in feed:
            win.release()
            n += 1
        assert n == feed.nwindows
