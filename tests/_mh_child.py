"""Child process for the 2-process pod test (tests/test_multiprocess.py).

Run as: ``python tests/_mh_child.py <pid> <nproc> <port> <outdir>``.

Each child initializes the JAX distributed runtime against a localhost
coordinator (CPU backend, gloo collectives), synthesizes RAW files for ONLY
the (band, bank) players whose virtual chips it owns, and runs the full
``load_scan_mesh`` reduction — the data-feed locality of the reference's
one-worker-per-host deployment (src/gbt.jl:28-42) on the TPU-pod analog.
Results (local player set, per-band stitched rows) land in ``outdir`` for
the parent test to validate against the single-process golden.
"""

import json
import os
import sys


def main() -> None:
    pid, nproc, port, outdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    # Optional sabotage: "b,k" names one player whose file is NOT written —
    # the owner must fail to open it and EVERY process must raise (the
    # symmetric-error contract that keeps a pod misconfiguration from
    # hanging the peers inside the collectives).
    sabotage = None
    if len(sys.argv) > 5 and sys.argv[5]:
        sabotage = tuple(int(x) for x in sys.argv[5].split(","))
    import jax

    jax.config.update("jax_platforms", "cpu")

    from blit.parallel.multihost import init_multihost, local_players

    active = init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cpu_collectives="gloo",
    )
    assert active, "expected an active multi-process runtime"
    assert jax.process_count() == nproc, jax.process_count()

    # Bring-up barrier marker: the parent times the WORK phase from here,
    # not from fork — coordinator/gloo bring-up legitimately runs long on
    # loaded CI machines (tests/test_multiprocess.py).
    from blit.testing import signal_ready

    signal_ready(outdir, pid)

    import numpy as np

    from blit.parallel.mesh import make_mesh
    from blit.parallel.scan import load_scan_mesh
    from blit.testing import synth_raw

    NBAND, NBANK, NFFT, NINT, NCHAN = 2, 4, 32, 2, 2
    mesh = make_mesh(NBAND, NBANK)
    local = sorted(local_players(mesh))

    # Write ONLY this process's players' files, into a private directory:
    # the grid entries for non-local players name files that do not exist
    # here, proving load_scan_mesh never touches them.
    priv = os.path.join(outdir, f"proc{pid}")
    os.makedirs(priv, exist_ok=True)
    bank_bw = -187.5 / NBANK
    paths = [
        [os.path.join(priv, f"blc{b}{k}.raw") for k in range(NBANK)]
        for b in range(NBAND)
    ]
    for b, k in local:
        if (b, k) == sabotage:
            continue
        synth_raw(
            paths[b][k], nblocks=2, obsnchan=NCHAN, ntime_per_block=512,
            seed=b * 8 + k, tone_chan=k % NCHAN, obsbw=bank_bw,
            obsfreq=8000.0 + b * 500.0 + (k + 0.5) * bank_bw,
        )

    if sabotage is not None:
        try:
            load_scan_mesh(paths, nfft=NFFT, nint=NINT, despike=False,
                           mesh=mesh)
        except ValueError as e:
            assert "failed to open" in str(e), e
            print(f"CHILD-SYMMETRIC-ERROR:{pid}", flush=True)
            return
        raise AssertionError("sabotaged pod did not raise")

    hdr, out = load_scan_mesh(
        paths, nfft=NFFT, nint=NINT, despike=False, mesh=mesh
    )
    assert hdr["nchans"] == NBANK * NCHAN * NFFT, hdr

    rows = {}
    for s in out.addressable_shards:
        if s.replica_id == 0:
            band = int(s.index[0].start or 0)
            rows[band] = np.asarray(s.data)[0]
    for band, row in rows.items():
        np.save(os.path.join(outdir, f"band{band}_proc{pid}.npy"), row)
    with open(os.path.join(outdir, f"proc{pid}.json"), "w") as f:
        json.dump(
            {
                "local": [list(x) for x in local],
                "bands": sorted(rows),
                "nsamps": int(hdr["nsamps"]),
                "fch1": hdr["fch1"],
                "foff": hdr["foff"],
                "nchans": int(hdr["nchans"]),
            },
            f,
        )
    print("CHILD-OK", flush=True)


if __name__ == "__main__":
    main()
