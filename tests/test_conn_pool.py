"""The fleet's keep-alive :class:`ConnectionPool` (ISSUE 16): sockets
reused across requests, a mid-flight reset (``BLIT_FAULTS``-style
``pool.reuse`` injection) evicts the pooled socket and redials fresh so
the caller never sees the stale connection, bodies never bleed across
concurrent requests, and the idle set stays bounded."""

import json
import threading

import pytest

pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.faults import FaultRule  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.serve.http import (  # noqa: E402
    ConnectionPool,
    _make_server,
    http_json,
    http_request,
)


@pytest.fixture
def echo_server():
    """A keep-alive server that echoes the request body (and tags the
    serving path) — the bleed/byte-exactness oracle."""

    def router(method, path, doc, headers):
        body = json.dumps({"path": path, "doc": doc})
        if path.startswith("/bytes/"):
            # Raw binary body, length from the path: byte-exactness.
            n = int(path.rsplit("/", 1)[1])
            return 200, bytes(range(256)) * (n // 256 + 1), \
                "application/octet-stream", {}
        return 200, body, "application/json", {}

    server = _make_server(router, 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    yield url
    server.shutdown()
    server.close_all_connections()
    server.server_close()


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()


class TestReuse:
    def test_second_request_reuses_the_socket(self, echo_server):
        tl = Timeline()
        pool = ConnectionPool(max_per_peer=4, timeline=tl)
        try:
            for i in range(3):
                st, _, doc = http_json("POST", echo_server, "/e",
                                       {"i": i}, pool=pool)
                assert st == 200 and doc["doc"] == {"i": i}
            rep = tl.report()
            assert rep["fleet.pool.open"]["calls"] == 1
            assert rep["fleet.pool.reuse"]["calls"] == 2
            assert sum(pool.stats().values()) == 1
        finally:
            pool.close()

    def test_idle_set_is_bounded(self, echo_server):
        pool = ConnectionPool(max_per_peer=2, timeline=Timeline())
        try:
            n = 6
            barrier = threading.Barrier(n)
            errs = []

            def worker():
                try:
                    barrier.wait(timeout=10)
                    st, _, _ = http_json("GET", echo_server, "/x",
                                         pool=pool)
                    assert st == 200
                except Exception as e:  # noqa: BLE001 — collected
                    errs.append(repr(e))

            ts = [threading.Thread(target=worker) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            assert sum(pool.stats().values()) <= 2
        finally:
            pool.close()

    def test_close_empties_the_pool(self, echo_server):
        pool = ConnectionPool(timeline=Timeline())
        http_json("GET", echo_server, "/x", pool=pool)
        assert sum(pool.stats().values()) == 1
        pool.close()
        assert sum(pool.stats().values()) == 0
        # A closed-then-reused pool still serves (fresh dial).
        st, _, _ = http_json("GET", echo_server, "/x", pool=pool)
        assert st == 200
        pool.close()


class TestFaults:
    def test_reset_on_reuse_evicts_and_redials(self, echo_server):
        # The BLIT_FAULTS drill: the pooled socket dies between
        # requests (peer restarted, LB idle-timeout).  The pool must
        # absorb exactly that — evict, redial fresh, serve — without
        # surfacing the reset to the caller.
        tl = Timeline()
        pool = ConnectionPool(max_per_peer=4, timeline=tl)
        try:
            http_json("GET", echo_server, "/warmup", pool=pool)
            faults.install(FaultRule(point="pool.reuse",
                                     exc=ConnectionResetError))
            st, _, doc = http_json("POST", echo_server, "/after",
                                   {"ok": 1}, pool=pool)
            assert st == 200 and doc["doc"] == {"ok": 1}
            rep = tl.report()
            assert rep["fleet.pool.evict"]["calls"] == 1
            assert rep["fleet.pool.open"]["calls"] == 2  # warmup+redial
        finally:
            pool.close()

    def test_fresh_dial_failure_propagates(self):
        # Only the REUSED leg retries: a dead peer stays an error the
        # breaker/failover layer above must see (PR-13 semantics).
        pool = ConnectionPool(timeline=Timeline())
        try:
            with pytest.raises(OSError):
                http_request("GET", "http://127.0.0.1:9", "/x",
                             timeout=0.5, pool=pool)
        finally:
            pool.close()


class TestEvictPeer:
    def test_drained_then_removed_peer_sockets_are_severed(
            self, echo_server):
        # The ISSUE 17 satellite: a peer that is drained and REMOVED
        # from the ring leaves pooled keep-alive sockets behind;
        # evict_peer must sever exactly those so no later request is
        # written to a departed peer's dead socket.
        tl = Timeline()
        pool = ConnectionPool(max_per_peer=4, timeline=tl)
        try:
            http_json("GET", echo_server, "/x", pool=pool)
            assert sum(pool.stats().values()) == 1
            n = pool.evict_peer(echo_server)
            assert n == 1
            assert sum(pool.stats().values()) == 0
            rep = tl.report()
            assert rep["fleet.pool.evict"]["calls"] == 1
            # The pool still serves the (rejoined) peer: a FRESH dial,
            # never the severed socket.
            st, _, _ = http_json("GET", echo_server, "/y", pool=pool)
            assert st == 200
            assert tl.report()["fleet.pool.open"]["calls"] == 2
        finally:
            pool.close()

    def test_evict_unknown_peer_is_a_noop(self):
        pool = ConnectionPool(timeline=Timeline())
        try:
            assert pool.evict_peer("http://127.0.0.1:1") == 0
        finally:
            pool.close()


class TestNoBodyBleed:
    def test_concurrent_distinct_bodies(self, echo_server):
        # Many threads hammer one pool with distinct payloads; every
        # response must match ITS request — a pooled socket handed to
        # two requests at once (or a stale buffered body) would
        # scramble this.
        pool = ConnectionPool(max_per_peer=3, timeline=Timeline())
        errs = []

        def worker(wid):
            try:
                for i in range(8):
                    st, _, doc = http_json(
                        "POST", echo_server, f"/w{wid}",
                        {"wid": wid, "i": i}, pool=pool)
                    assert st == 200
                    assert doc["path"] == f"/w{wid}"
                    assert doc["doc"] == {"wid": wid, "i": i}
            except Exception as e:  # noqa: BLE001 — collected
                errs.append(repr(e))

        try:
            ts = [threading.Thread(target=worker, args=(w,))
                  for w in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
        finally:
            pool.close()

    def test_binary_bodies_byte_exact_over_reused_socket(
            self, echo_server):
        # The transport/codec split satellite: http_request must
        # round-trip non-JSON bodies byte-exact — including over a
        # REUSED socket, where a length bug would bleed into the next
        # response.
        pool = ConnectionPool(timeline=Timeline())
        try:
            for n in (256, 1024, 512):
                st, hdrs, payload = http_request(
                    "GET", echo_server, f"/bytes/{n}", pool=pool)
                assert st == 200
                want = bytes(range(256)) * (n // 256 + 1)
                assert payload == want
        finally:
            pool.close()
