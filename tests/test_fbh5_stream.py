"""Streaming FBH5 writes (VERDICT r3 item 5): slab-by-slab, time-resizable
``.h5`` products at bounded host memory, identical payload to the
in-memory writer, with ``.partial`` atomicity — BL's native product
format (src/gbtworkerfunctions.jl:141-155) without materializing it."""

import os

import h5py
import numpy as np
import pytest

from blit.io.fbh5 import (
    FBH5Writer,
    read_fbh5_data,
    read_fbh5_header,
    write_fbh5,
)

HDR = {"fch1": 8000.0, "foff": -0.1, "tsamp": 1.0, "nbits": 32,
       "source_name": "SYNTH"}


def make_data(nsamps=37, nifs=2, nchans=64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((nsamps, nifs, nchans)).astype(np.float32)


def stream_write(path, data, slab_sizes, **kw):
    with FBH5Writer(path, HDR, nifs=data.shape[1], nchans=data.shape[2],
                    **kw) as w:
        pos = 0
        for k in slab_sizes:
            w.append(data[pos:pos + k])
            pos += k
        assert pos == data.shape[0]
    return w


class TestStreamedPayload:
    @pytest.mark.parametrize("compression", [None, "gzip", "bitshuffle"])
    def test_matches_in_memory_write(self, tmp_path, compression):
        data = make_data()
        mem = str(tmp_path / "mem.h5")
        st = str(tmp_path / "stream.h5")
        chunks = (8, data.shape[1], data.shape[2])
        write_fbh5(mem, HDR, data, compression=compression, chunks=chunks)
        # Ragged slabs that straddle chunk boundaries both ways.
        stream_write(st, data, [5, 11, 1, 13, 7], compression=compression,
                     chunks=chunks)
        np.testing.assert_array_equal(read_fbh5_data(st), data)
        hm, hs = read_fbh5_header(mem), read_fbh5_header(st)
        assert hm == hs  # includes nsamps and data_size

    def test_bitshuffle_chunks_byte_identical(self, tmp_path):
        # The streamed file's ENCODED chunks equal the in-memory writer's:
        # same codec, same padding convention, chunk for chunk.
        data = make_data(nsamps=20, nchans=100)
        mem = str(tmp_path / "mem.h5")
        st = str(tmp_path / "stream.h5")
        chunks = (8, 2, 100)
        write_fbh5(mem, HDR, data, compression="bitshuffle", chunks=chunks)
        stream_write(st, data, [3, 9, 8], compression="bitshuffle",
                     chunks=chunks)
        with h5py.File(mem) as a, h5py.File(st) as b:
            for t0 in range(0, 20, 8):
                pa = a["data"].id.read_direct_chunk((t0, 0, 0))[1]
                pb = b["data"].id.read_direct_chunk((t0, 0, 0))[1]
                assert pa == pb

    def test_single_append_whole_product(self, tmp_path):
        data = make_data(nsamps=16)
        p = str(tmp_path / "x.h5")
        stream_write(p, data, [16], compression="bitshuffle")
        np.testing.assert_array_equal(read_fbh5_data(p), data)

    def test_empty_product(self, tmp_path):
        p = str(tmp_path / "x.h5")
        stream_write(p, make_data(nsamps=0), [], compression="bitshuffle")
        assert read_fbh5_header(p)["nsamps"] == 0


class TestBoundedMemory:
    def test_buffer_never_exceeds_one_chunk_row(self, tmp_path):
        # The streaming writer's residency bound: one chunk row of pending
        # spectra, however the appends arrive.
        data = make_data(nsamps=100)
        p = str(tmp_path / "x.h5")
        w = FBH5Writer(p, HDR, nifs=2, nchans=64, compression="bitshuffle",
                       chunks=(16, 2, 64))
        try:
            pos = 0
            for k in (1, 33, 2, 50, 14):
                w.append(data[pos:pos + k])
                pos += k
                assert w._buffered < 16  # full rows always flushed
                assert w._buf.shape == (16, 2, 64)
        finally:
            w.close()
        np.testing.assert_array_equal(read_fbh5_data(p), data)


class TestAtomicity:
    def test_crash_leaves_no_product(self, tmp_path):
        p = str(tmp_path / "x.h5")
        with pytest.raises(RuntimeError, match="boom"):
            with FBH5Writer(p, HDR, nifs=2, nchans=64) as w:
                w.append(make_data(nsamps=4))
                raise RuntimeError("boom")
        assert not os.path.exists(p)
        assert not os.path.exists(p + ".partial")

    def test_partial_invisible_until_close(self, tmp_path):
        p = str(tmp_path / "x.h5")
        w = FBH5Writer(p, HDR, nifs=2, nchans=64)
        try:
            w.append(make_data(nsamps=4))
            assert not os.path.exists(p)
            assert os.path.exists(p + ".partial")
        finally:
            w.close()
        assert os.path.exists(p) and not os.path.exists(p + ".partial")

    def test_bad_slab_shape_rejected(self, tmp_path):
        p = str(tmp_path / "x.h5")
        with pytest.raises(ValueError, match="slab shape"):
            with FBH5Writer(p, HDR, nifs=2, nchans=64) as w:
                w.append(np.zeros((4, 2, 32), np.float32))
        assert not os.path.exists(p + ".partial")


class TestReducerH5Streaming:
    def test_reduce_to_file_h5_matches_reduce(self, tmp_path):
        jax = pytest.importorskip("jax")
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        raw = str(tmp_path / "x.raw")
        synth_raw(raw, nblocks=3, obsnchan=2, ntime_per_block=512)
        red = RawReducer(nfft=64, nint=2)
        hdr_mem, data = red.reduce(raw)
        out = str(tmp_path / "x.h5")
        hdr = red.reduce_to_file(raw, out)
        np.testing.assert_array_equal(read_fbh5_data(out), data)
        assert hdr["nsamps"] == data.shape[0] == read_fbh5_header(out)["nsamps"]

    def test_reduce_to_file_h5_bitshuffle(self, tmp_path):
        jax = pytest.importorskip("jax")
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        raw = str(tmp_path / "x.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512)
        red = RawReducer(nfft=32)
        _, data = red.reduce(raw)
        out = str(tmp_path / "x.h5")
        red.reduce_to_file(raw, out, compression="bitshuffle")
        np.testing.assert_array_equal(read_fbh5_data(out), data)

    def test_fil_rejects_compression(self, tmp_path):
        jax = pytest.importorskip("jax")
        from blit.pipeline import RawReducer
        from blit.testing import synth_raw

        raw = str(tmp_path / "x.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=256)
        with pytest.raises(ValueError, match="uncompressed"):
            RawReducer(nfft=32).reduce_to_file(
                raw, str(tmp_path / "x.fil"), compression="gzip"
            )


class TestConstructionGuards:
    def test_bitshuffle_rejects_channel_split_chunks(self, tmp_path):
        # The streaming encoder writes one chunk per time row; channel-split
        # chunks would silently drop data, so construction refuses them.
        p = str(tmp_path / "x.h5")
        with pytest.raises(ValueError, match="whole-spectrum"):
            FBH5Writer(p, HDR, nifs=2, nchans=1024,
                       compression="bitshuffle", chunks=(16, 2, 512))
        assert not os.path.exists(p + ".partial")

    def test_unknown_compression_rejected(self, tmp_path):
        p = str(tmp_path / "x.h5")
        with pytest.raises(ValueError, match="unknown compression"):
            FBH5Writer(p, HDR, nifs=2, nchans=64, compression="lzma")

    def test_plain_writer_skips_chunk_buffer(self, tmp_path):
        # Only the bitshuffle path needs the pending chunk-row buffer; a
        # plain/gzip writer of a wide product must not allocate it.
        p = str(tmp_path / "x.h5")
        with FBH5Writer(p, HDR, nifs=1, nchans=1 << 20) as w:
            assert w._buf is None
            w.append(np.zeros((1, 1, 1 << 20), np.float32))


class TestChunkClamp:
    """HDF5 refuses chunks of 4 GiB or more; defaults must clamp (ADVICE
    r4: the hi-res preset's unclamped 16-row default was 16 GiB and made
    the flagship .h5 product unwritable via the public APIs)."""

    def test_default_chunks_clamped_under_limit(self):
        from blit.io.fbh5 import H5_CHUNK_LIMIT, default_chunks

        # hi-res bank product: 64 coarse channels x 2^20 fine = 256 MiB/row.
        c = default_chunks(1, 64 << 20, 4)
        assert c == (15, 1, 64 << 20)
        assert c[0] * c[1] * c[2] * 4 <= H5_CHUNK_LIMIT
        # IQUV hi-res: 1 GiB rows -> 3.
        assert default_chunks(4, 64 << 20, 4)[0] == 3
        # Small products keep BL's conventional 16 rows.
        assert default_chunks(4, 64, 4) == (16, 4, 64)

    def test_default_chunks_splits_channels_past_limit(self):
        from blit.io.fbh5 import H5_CHUNK_LIMIT, default_chunks

        # Full-band IQUV mesh product: one spectrum is 8 GiB.
        rows, nifs, cchunk = default_chunks(4, 512 << 20, 4)
        assert rows == 1 and nifs == 4 and cchunk < 512 << 20
        assert rows * nifs * cchunk * 4 <= H5_CHUNK_LIMIT
        with pytest.raises(ValueError, match="whole-spectrum"):
            default_chunks(4, 512 << 20, 4, whole_spectrum=True)

    def test_hires_writer_opens_with_default_chunks(self, tmp_path):
        # The ADVICE repro: writer open at the hi-res shape must succeed.
        p = str(tmp_path / "hires.h5")
        w = FBH5Writer(p, HDR, nifs=1, nchans=64 << 20)
        try:
            assert w.chunks[0] * w.chunks[1] * w.chunks[2] * 4 < 2**32
        finally:
            w.abort()
