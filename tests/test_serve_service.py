"""ProductService front door (blit/serve/service.py; ISSUE 3 acceptance):
the single-flight proof (>= 8 concurrent identical requests -> exactly ONE
reduction, byte-identical results for every caller), the cache hot path
never touching the GUPPI read injection point, failure isolation (no
poisoned single-flight groups), cancellation releasing queue slots, and
the ``serve-bench`` CLI leg."""

import json
import threading

import pytest

pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.faults import FaultRule, InjectedFault  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.serve import (  # noqa: E402
    Cancelled,
    Overloaded,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.testing import synth_raw  # noqa: E402

NFFT = 128
NTIME = (8 + 3) * NFFT  # 8 PFB frames at ntap=4


@pytest.fixture(autouse=True)
def clean_faults():
    from blit.faults import RetryPolicy

    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(RetryPolicy(attempts=3, base_s=0.0, jitter=0.0))
    yield
    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(None)


@pytest.fixture
def raw(tmp_path):
    p = str(tmp_path / "a.raw")
    synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=NTIME, tone_chan=1)
    return p


def make_service(tmp_path, *, concurrency=4, queue_depth=16, ram_bytes=1 << 24,
                 disk=True, pool=None):
    tl = Timeline()
    return ProductService(
        cache=ProductCache(str(tmp_path / "cache") if disk else None,
                           ram_bytes=ram_bytes, timeline=tl),
        scheduler=Scheduler(max_concurrency=concurrency,
                            queue_depth=queue_depth, pool=pool, timeline=tl),
        timeline=tl,
    )


class TestSingleFlight:
    def test_concurrent_identical_requests_run_one_reduction(
        self, tmp_path, raw
    ):
        # Acceptance criterion: >= 8 concurrent identical requests ->
        # exactly one reduction runs (proven via the fault-registry hit
        # counter on guppi.open — one open per reduction; the delay rule
        # holds the flight open until every caller has submitted) and all
        # callers receive byte-identical results.
        faults.install(FaultRule("guppi.open", "delay", times=-1,
                                 delay_s=1.0))
        svc = make_service(tmp_path)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        barrier = threading.Barrier(8)
        results, errors = [], []

        def caller(cid):
            try:
                barrier.wait(10)
                hdr, data = svc.get(req, timeout=60, client=f"c{cid}")
                results.append(data)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=caller, args=(c,))
                   for c in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert errors == []
        assert len(results) == 8
        counters = faults.counters()
        assert counters["fault.guppi.open.delay"] == 1  # ONE reduction
        ref = results[0].tobytes()
        assert all(r.tobytes() == ref for r in results)
        assert svc.counts["coalesced"] == 7
        assert svc.counts["scheduled"] == 1
        svc.close()

    def test_failed_flight_does_not_poison_the_group(self, tmp_path, raw):
        # The first reduction dies on a transient injected fault (times=3
        # exhausts the io retry policy's attempts, so the failure escapes
        # the transparent retry layer); every waiter on THAT flight gets
        # the error, but the next identical request starts a fresh flight
        # and succeeds.
        faults.install(FaultRule("guppi.open", "fail", times=3))
        svc = make_service(tmp_path)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        with pytest.raises(InjectedFault):
            svc.get(req, timeout=60)
        hdr, data = svc.get(req, timeout=60)  # fresh flight, no stale error
        assert data.shape[0] > 0
        assert svc.counts["scheduled"] == 2
        svc.close()


class TestCacheHotPath:
    def test_hit_never_touches_the_guppi_read_point(self, tmp_path, raw):
        # Acceptance criterion: after warming, a repeat request is served
        # entirely from the cache — an armed guppi.read FAIL rule proves
        # the hot path cannot even reach the GUPPI layer.
        svc = make_service(tmp_path)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        hdr, warm = svc.get(req, timeout=60)
        rule = FaultRule("guppi.read", "fail", times=-1)
        faults.install(rule)
        hdr2, hot = svc.get(req, timeout=60)
        assert rule.hits == 0  # the injection point was never visited
        assert hot.tobytes() == warm.tobytes()
        assert svc.counts["cache_hits"] == 1
        svc.close()

    def test_disk_tier_survives_a_new_service(self, tmp_path, raw):
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        svc1 = make_service(tmp_path)
        hdr, warm = svc1.get(req, timeout=60)
        svc1.close()
        # New service over the same cache dir (process restart stand-in):
        # the product comes off disk; GUPPI stays cold.
        svc2 = make_service(tmp_path)
        rule = FaultRule("guppi.read", "fail", times=-1)
        faults.install(rule)
        ticket = svc2.submit(req)
        assert ticket.source == "disk"
        hdr2, data = svc2.result(ticket, timeout=10)
        assert rule.hits == 0
        assert data.tobytes() == warm.tobytes()
        svc2.close()

    def test_member_order_does_not_refetch(self, tmp_path):
        from blit.testing import synth_raw_sequence

        paths, _ = synth_raw_sequence(
            str(tmp_path / "seq"), nfiles=2, blocks_per_file=1,
            obsnchan=2, ntime_per_block=NTIME,
        )
        svc = make_service(tmp_path)
        hdr, warm = svc.get(ProductRequest(raw=paths, nfft=NFFT, nint=1),
                            timeout=60)
        # Same members, reversed glob order: same fingerprint, cache hit.
        t = svc.submit(ProductRequest(raw=list(reversed(paths)),
                                      nfft=NFFT, nint=1))
        assert t.source in ("ram", "disk")
        svc.close()


class TestOverloadAndCancel:
    def _blocked_service(self, tmp_path, blocker_raw, queue_depth=1):
        """A budget-1 service whose single slot is held by a delayed
        reduction of ``blocker_raw``."""
        faults.install(FaultRule("guppi.open", "delay", times=-1,
                                 delay_s=1.5, match=blocker_raw))
        svc = make_service(tmp_path, concurrency=1, queue_depth=queue_depth)
        blocker = svc.submit(
            ProductRequest(raw=blocker_raw, nfft=NFFT, nint=1))
        import time
        deadline = time.monotonic() + 5
        while svc.scheduler.running() == 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        return svc, blocker

    def test_excess_submissions_get_overloaded_not_a_hang(
        self, tmp_path, raw
    ):
        # Acceptance criterion: budget 1 + full queue -> Overloaded.
        import time

        b = str(tmp_path / "blocker.raw")
        synth_raw(b, nblocks=1, obsnchan=2, ntime_per_block=NTIME, seed=7)
        svc, blocker = self._blocked_service(tmp_path, b)
        queued = svc.submit(ProductRequest(raw=raw, nfft=NFFT, nint=1))
        other = str(tmp_path / "other.raw")
        synth_raw(other, nblocks=1, obsnchan=2, ntime_per_block=NTIME,
                  seed=8)
        t0 = time.monotonic()
        with pytest.raises(Overloaded) as ei:
            svc.submit(ProductRequest(raw=other, nfft=NFFT, nint=1))
        assert time.monotonic() - t0 < 1.0  # rejected at the door
        assert ei.value.retry_after_s > 0
        assert svc.counts["rejected"] == 1
        svc.result(blocker, timeout=60)
        svc.result(queued, timeout=60)
        svc.close()

    def test_cancel_releases_the_queue_slot(self, tmp_path, raw):
        b = str(tmp_path / "blocker.raw")
        synth_raw(b, nblocks=1, obsnchan=2, ntime_per_block=NTIME, seed=7)
        svc, blocker = self._blocked_service(tmp_path, b)
        queued = svc.submit(ProductRequest(raw=raw, nfft=NFFT, nint=1))
        assert svc.cancel(queued)
        with pytest.raises(Cancelled):
            svc.result(queued, timeout=1)
        # The released slot admits new work where it would have Overloaded.
        replacement = svc.submit(ProductRequest(raw=raw, nfft=NFFT, nint=1))
        hdr, data = svc.result(replacement, timeout=60)
        assert data.shape[0] > 0
        svc.result(blocker, timeout=60)
        svc.close()

    def test_coalesced_ticket_cancel_keeps_the_flight(self, tmp_path, raw):
        b = str(tmp_path / "blocker.raw")
        synth_raw(b, nblocks=1, obsnchan=2, ntime_per_block=NTIME, seed=7)
        svc, blocker = self._blocked_service(tmp_path, b, queue_depth=4)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        first = svc.submit(req)
        rider = svc.submit(req)
        assert rider.source == "coalesced"
        assert svc.cancel(rider)  # one rider leaves ...
        hdr, data = svc.result(first, timeout=60)  # ... flight completes
        assert data.shape[0] > 0
        with pytest.raises(Cancelled):
            svc.result(rider, timeout=1)
        svc.result(blocker, timeout=60)
        svc.close()

    def test_result_timeout_is_builtin(self, tmp_path, raw):
        faults.install(FaultRule("guppi.open", "delay", times=-1,
                                 delay_s=1.0))
        svc = make_service(tmp_path)
        t = svc.submit(ProductRequest(raw=raw, nfft=NFFT, nint=1))
        with pytest.raises(TimeoutError):
            svc.result(t, timeout=0.01)
        hdr, data = svc.result(t, timeout=60)  # still completes after
        assert data.shape[0] > 0
        svc.close()

    def test_missing_raw_rejected_at_submit(self, tmp_path):
        svc = make_service(tmp_path)
        with pytest.raises(OSError):
            svc.submit(ProductRequest(raw=str(tmp_path / "nope.raw"),
                                      nfft=NFFT, nint=1))
        svc.close()

    def test_closed_scheduler_does_not_leak_a_flight(self, tmp_path, raw):
        # Regression: a non-Overloaded admission failure (here: the
        # scheduler is closed) must drop the flight from the single-flight
        # table — a leaked jobless flight would make every later identical
        # request coalesce onto it and hang forever.
        svc = make_service(tmp_path)
        svc.scheduler.close()
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        with pytest.raises(RuntimeError, match="closed"):
            svc.submit(req)
        with pytest.raises(RuntimeError, match="closed"):
            svc.submit(req)  # NOT a coalesced hang
        assert svc.counts["coalesced"] == 0
        assert not svc._flights


class TestRequestValidation:
    def test_product_and_explicit_nfft_conflict(self):
        with pytest.raises(ValueError, match="not both"):
            ProductRequest(raw="x.raw", product="0000", nfft=2048)

    def test_list_raw_becomes_hashable_tuple(self):
        r = ProductRequest(raw=["b.raw", "a.raw"], nfft=64)
        assert isinstance(r.raw, tuple)
        hash(r)  # frozen dataclass stays hashable
        assert r.raw_source == ["b.raw", "a.raw"]


class TestServeBenchCLI:
    def test_serve_bench_runs_and_reports(self, capsys):
        # Acceptance criterion: `python -m blit serve-bench` runs on CPU
        # and reports hit-rate, coalesce count, and p50/p99 queue wait.
        from blit.__main__ import main

        rc = main([
            "serve-bench", "--requests", "12", "--distinct", "3",
            "--clients", "3", "--concurrency", "2", "--nfft", "128",
            "--disk-cache",
        ])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["requests"] == 12
        assert 0.0 <= out["hit_rate"] <= 1.0
        assert out["hit_rate"] > 0  # zipfian replay re-asks hot products
        assert "coalesced" in out
        assert out["queue_wait_p99_s"] >= out["queue_wait_p50_s"] >= 0.0
        assert out["errors"] == []


class TestStatsAndObservability:
    def test_stats_shape(self, tmp_path, raw):
        svc = make_service(tmp_path)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        svc.get(req, timeout=60)
        svc.get(req, timeout=60)
        st = svc.stats()
        assert st["requests"] == 2
        assert st["cache_hits"] == 1
        assert st["hit_rate"] == 0.5
        assert st["budget"] >= 1
        assert {"p50", "p99", "n"} <= set(st["queue_wait"])
        # Queue gauges landed on the shared timeline.
        rep = svc.timeline.report()
        assert "gauges" in rep and "sched.wait_s" in rep["gauges"]
        svc.close()

    def test_served_arrays_are_read_only(self, tmp_path, raw):
        svc = make_service(tmp_path)
        hdr, data = svc.get(ProductRequest(raw=raw, nfft=NFFT, nint=1),
                            timeout=60)
        assert not data.flags.writeable
        with pytest.raises(ValueError):
            data[0, 0, 0] = 1.0
        svc.close()


class TestLiveAdmission:
    """kind='stream' live jobs (ISSUE 12 satellite): admitted under a
    session-length capacity hold, never cached/coalesced, product on
    disk byte-identical to the batch path, held capacity reported."""

    def test_stream_request_validation(self, raw):
        with pytest.raises(ValueError, match="out="):
            ProductRequest(raw=raw, nfft=NFFT, kind="stream")
        with pytest.raises(ValueError, match="kind='stream'"):
            ProductRequest(raw=raw, nfft=NFFT, out="/tmp/x.fil")
        r = ProductRequest(raw=raw, nfft=NFFT, kind="stream",
                           out="/tmp/x.fil", session_s=300.0,
                           replay_rate=10.0)
        assert r.session_s == 300.0

    def test_live_session_holds_capacity_and_matches_batch(
            self, tmp_path, raw):
        import os

        from blit.pipeline import RawReducer

        oracle = str(tmp_path / "oracle.fil")
        RawReducer(nfft=NFFT, nint=1, tune_online=False).reduce_to_file(
            raw, oracle)
        out = str(tmp_path / "live.fil")
        svc = make_service(tmp_path, concurrency=2)
        req = ProductRequest(raw=raw, nfft=NFFT, kind="stream", out=out,
                             session_s=5.0, replay_rate=10000.0)
        t = svc.submit(req, client="recorder")
        # While (or after) the session runs, stats reports the hold
        # machinery; the ticket resolves with the product ON DISK.
        hdr, data = svc.result(t, timeout=60)
        assert data.shape[0] == 0  # live products live on disk
        assert "held" in svc.stats()
        # result() resolves from the job body; the scheduler's own
        # finally releases the hold a beat later — wait for it.
        import time as _t

        deadline = _t.monotonic() + 5
        while svc.scheduler.held() and _t.monotonic() < deadline:
            _t.sleep(0.01)
        assert svc.scheduler.held() == 0  # released at session end
        with open(out, "rb") as fg, open(oracle, "rb") as fo:
            assert fg.read() == fo.read()
        assert not os.path.exists(out + ".stream-cursor")
        # Never cached: an identical bounded request still reduces.
        st = svc.stats()
        assert st["cache"]["hit.ram"] + st["cache"]["hit.disk"] == 0
        svc.close()

    def test_duplicate_live_session_rejected(self, tmp_path, raw):
        # Two live consumers of ONE product path would interleave
        # appends on the same file and rejoin sidecar: the second ask
        # must be rejected while the first session is in flight.
        svc = make_service(tmp_path, concurrency=2)
        out = str(tmp_path / "dup.fil")
        # The session ends via the tail's idle timeout (the recording
        # is complete and nothing writes a done marker).
        req = ProductRequest(raw=raw, nfft=NFFT, kind="stream", out=out,
                             session_s=9.0, idle_timeout_s=2.0)
        t1 = svc.submit(req, client="a")
        with pytest.raises(Overloaded, match="already in flight"):
            svc.submit(ProductRequest(raw=raw, nfft=NFFT, kind="stream",
                                      out=out, idle_timeout_s=2.0),
                       client="b")
        assert svc.stats()["held_declared_s"] == 9.0
        hdr, _ = svc.result(t1, timeout=60)
        assert hdr.get("nsamps") is not None
        st = svc.stats()
        assert st["held_declared_s"] == 0
        assert st["rejected"] == 1
        svc.close()
