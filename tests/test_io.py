"""Round-trip tests for the three codecs (SIGPROC .fil, FBH5, GUPPI RAW).

The reference has zero I/O tests (SURVEY.md §4); these validate blit's
writers against its readers and the header-normalization semantics of
src/gbtworkerfunctions.jl:131-155.
"""

import numpy as np
import pytest

from blit import testing
from blit.io import (
    GuppiRaw,
    is_hdf5,
    read_fbh5_data,
    read_fbh5_header,
    read_fil_data,
    read_fil_header,
    write_fil,
)
from blit.io.guppi import block_ntime


# ---------- SIGPROC ----------

def test_fil_roundtrip(tmp_path):
    p = str(tmp_path / "x.fil")
    hdr, data = testing.synth_fil(p, nsamps=8, nifs=2, nchans=32)
    rhdr, rdata = read_fil_data(p)
    assert rdata.shape == (8, 2, 32)
    np.testing.assert_array_equal(np.asarray(rdata), data)
    assert rhdr["source_name"] == "SYNTH"
    assert rhdr["nchans"] == 32 and rhdr["nifs"] == 2
    assert rhdr["nsamps"] == 8  # computed from file size
    assert rhdr["fch1"] == pytest.approx(hdr["fch1"])


def test_fil_not_sigproc(tmp_path):
    p = tmp_path / "bad.fil"
    p.write_bytes(b"\x00" * 100)
    with pytest.raises(ValueError):
        read_fil_header(str(p))


def test_fil_mmap_vs_read(tmp_path):
    p = str(tmp_path / "x.fil")
    testing.synth_fil(p, nsamps=4, nchans=16)
    _, a = read_fil_data(p, mmap=True)
    _, b = read_fil_data(p, mmap=False)
    np.testing.assert_array_equal(np.asarray(a), b)
    assert isinstance(a, np.memmap) and not isinstance(b, np.memmap)


def test_fil_writer_validates_slabs(tmp_path):
    # SIGPROC derives nsamps from file size, so a mis-shaped or mis-typed
    # slab would write a valid-looking corrupt product nothing downstream
    # detects (ADVICE r4) — append must validate shape and coerce dtype.
    from blit.io.sigproc import FilWriter

    hdr = testing.make_fil_header(nchans=16)
    p = str(tmp_path / "x.fil")
    with FilWriter(p, hdr, nifs=2, nchans=16) as w:
        with pytest.raises(ValueError, match="slab shape"):
            w.append(np.zeros((3, 2, 8), np.float32))  # wrong nchans
        with pytest.raises(ValueError, match="slab shape"):
            w.append(np.zeros((3, 16), np.float32))  # wrong ndim
        w.append(np.arange(3 * 2 * 16, dtype=np.float64).reshape(3, 2, 16))
    _, data = read_fil_data(p)
    assert data.dtype == np.float32  # float64 slab coerced, not raw-written
    np.testing.assert_array_equal(
        np.asarray(data).ravel(), np.arange(3 * 2 * 16, dtype=np.float32)
    )
    # Cross-kind coercion would silently wrap sample values (300.0 -> 44):
    # refused, same-kind only.
    hdr8 = testing.make_fil_header(nchans=16)
    with FilWriter(str(tmp_path / "u8.fil"), hdr8, nifs=1, nchans=16,
                   dtype=np.uint8) as w:
        with pytest.raises(TypeError):
            w.append(np.full((1, 1, 16), 300.0, np.float32))
        w.append(np.zeros((1, 1, 16), np.uint8))


def test_fil_uint8_dtype(tmp_path):
    p = str(tmp_path / "u8.fil")
    hdr = testing.make_fil_header(nchans=8)
    data = np.arange(2 * 1 * 8, dtype=np.uint8).reshape(2, 1, 8)
    write_fil(p, hdr, data)
    rhdr, rdata = read_fil_data(p)
    assert rdata.dtype == np.uint8 and rhdr["nbits"] == 8
    np.testing.assert_array_equal(np.asarray(rdata), data)


# ---------- FBH5 ----------

def test_fbh5_roundtrip_and_header_normalization(tmp_path):
    p = str(tmp_path / "x.h5")
    hdr, data = testing.synth_fbh5(p, nsamps=8, nifs=2, nchans=32)
    assert is_hdf5(p)
    rhdr = read_fbh5_header(p)
    # normalization parity (src/gbtworkerfunctions.jl:141-155): no
    # DIMENSION_LABELS, data_size & nsamps computed, key-sorted
    assert "DIMENSION_LABELS" not in rhdr
    assert rhdr["data_size"] == data.nbytes
    assert rhdr["nsamps"] == 8
    assert list(rhdr) == sorted(rhdr)
    assert rhdr["source_name"] == "SYNTH"
    rdata = read_fbh5_data(p)
    np.testing.assert_array_equal(rdata, data)


def test_fbh5_missing_nfpc_computed(tmp_path):
    # The reference crashes on FBH5 files lacking an nfpc attr (latent bug,
    # SURVEY.md §2.1 #16); blit computes it from foff.
    from blit.config import nfpc_from_foff
    from blit.io import write_fbh5

    p = str(tmp_path / "x.h5")
    hdr = testing.make_fil_header(nchans=64)
    data = testing.make_spectra(4, 1, 64)
    write_fbh5(p, hdr, data)  # hdr has no nfpc key
    rhdr = read_fbh5_header(p)
    assert rhdr["nfpc"] == nfpc_from_foff(hdr["foff"])


def test_fbh5_hyperslab(tmp_path):
    p = str(tmp_path / "x.h5")
    _, data = testing.synth_fbh5(p, nsamps=16, nifs=2, nchans=32)
    sl = (slice(2, 6), slice(0, 1), slice(8, 24))
    out = read_fbh5_data(p, sl)
    np.testing.assert_array_equal(out, data[sl])
    with pytest.raises(ValueError):
        read_fbh5_data(p, (slice(None),))


def test_fbh5_gzip(tmp_path):
    p = str(tmp_path / "z.h5")
    _, data = testing.synth_fbh5(p, nsamps=8, nchans=64, compression="gzip")
    np.testing.assert_array_equal(read_fbh5_data(p), data)


# ---------- GUPPI RAW ----------

def test_raw_roundtrip(tmp_path):
    p = str(tmp_path / "x.0000.raw")
    hdr, blocks = testing.synth_raw(p, nblocks=3, obsnchan=16, ntime_per_block=128)
    g = GuppiRaw(p)
    assert g.nblocks == 3
    h0 = g.header(0)
    assert h0["OBSNCHAN"] == 16 and h0["NPOL"] == 4 and h0["NBITS"] == 8
    assert h0["SRC_NAME"] == "SYNTH"
    assert block_ntime(h0) == 128
    for i in range(3):
        np.testing.assert_array_equal(np.asarray(g.read_block(i)), blocks[i])


def test_raw_directio_padding(tmp_path):
    p = str(tmp_path / "d.0000.raw")
    _, blocks = testing.synth_raw(p, nblocks=2, obsnchan=8, ntime_per_block=64, directio=True)
    g = GuppiRaw(p)
    assert g.nblocks == 2
    assert g.header(0)["DIRECTIO"] == 1
    np.testing.assert_array_equal(np.asarray(g.read_block(1)), blocks[1])


def test_raw_overlap_concatenation(tmp_path):
    p = str(tmp_path / "o.0000.raw")
    hdr, blocks = testing.synth_raw(
        p, nblocks=3, obsnchan=4, ntime_per_block=64, overlap=16
    )
    g = GuppiRaw(p)
    # blocks share their trailing/leading `overlap` samples
    np.testing.assert_array_equal(blocks[0][:, -16:], blocks[1][:, :16])
    # drop_overlap gives a gap-free stream
    parts = [b for _, b in g.iter_blocks(drop_overlap=True)]
    stream = np.concatenate(parts, axis=1)
    assert stream.shape[1] == 3 * 64 - 2 * 16
    # pktidx advances by (ntime - overlap)
    assert g.header(1)["PKTIDX"] - g.header(0)["PKTIDX"] == 48


def test_raw_complex_view(tmp_path):
    p = str(tmp_path / "c.0000.raw")
    _, blocks = testing.synth_raw(p, nblocks=1, obsnchan=4, ntime_per_block=32)
    g = GuppiRaw(p)
    c = g.read_block_complex(0)
    assert c.shape == (4, 32, 2) and c.dtype == np.complex64
    np.testing.assert_array_equal(c.real, blocks[0][..., 0].astype(np.float32))


def test_raw_tone_visible_in_spectrum(tmp_path):
    # An injected tone must dominate its coarse channel's power — the
    # fixture end-to-end sanity the pipeline tests build on.
    p = str(tmp_path / "t.0000.raw")
    testing.synth_raw(p, nblocks=1, obsnchan=8, ntime_per_block=4096, tone_chan=3)
    g = GuppiRaw(p)
    c = g.read_block_complex(0)
    power = (np.abs(c) ** 2).mean(axis=(1, 2))
    assert power[3] > 2 * power[np.arange(8) != 3].max()


def test_raw_truncated_trailing_block(tmp_path):
    p = str(tmp_path / "trunc.raw")
    testing.synth_raw(p, nblocks=2, obsnchan=8, ntime_per_block=64)
    size = p and __import__("os").path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - 100)
    g = GuppiRaw(p)
    assert g.nblocks == 1  # partial final block dropped, no crash
