"""Test harness: run JAX on a virtual 8-device CPU mesh.

Must set the XLA flags *before* jax is imported anywhere, so this executes at
conftest import time.  This fakes the 8-bank (and 2x4 band,bank) topology the
same way SURVEY.md §4 prescribes for testing the multi-chip path without
multi-chip hardware.
"""

import os

# Force CPU for tests even when the session env points JAX at real hardware
# (e.g. JAX_PLATFORMS=axon under the TPU tunnel): the suite runs on the
# virtual 8-device mesh; benchmarks (bench.py) use the real chip.  The
# sitecustomize may have imported jax already, so the env var alone is not
# enough — update the live config too (backends are not initialized yet at
# conftest-import time, so this still takes effect).
# Remember what the session pointed JAX at before we force CPU, so hardware
# smoke tests (test_tpu_smoke.py) can target the real chip via subprocess.
os.environ.setdefault("BLIT_HW_PLATFORMS", os.environ.get("JAX_PLATFORMS", ""))
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Hermetic ingest-plane env (ISSUE 8): the suite must not pick up a real
# per-rig profile from ~/.cache/blit/tune OR from a BLIT_TUNE_DIR the
# shell happens to export (reducer knob defaults are asserted by tests),
# nor write into either, and a shell-exported staging budget (the
# hostmem.py A/B lever) must not reshape SlabPool behavior under test.
# An empty per-session dir keeps the tuning machinery ENABLED — tests
# that exercise it point BLIT_TUNE_DIR at their own tmp_path via
# monkeypatch.
import atexit
import shutil
import tempfile

os.environ["BLIT_TUNE_DIR"] = tempfile.mkdtemp(prefix="blit-tune-test-")
atexit.register(shutil.rmtree, os.environ["BLIT_TUNE_DIR"],
                ignore_errors=True)
os.environ.pop("BLIT_STAGING_BYTES", None)

import sys

if "jax" in sys.modules:  # sitecustomize already imported jax
    import jax

    jax.config.update("jax_platforms", "cpu")


import logging

import pytest


def pytest_sessionfinish(session, exitstatus):
    """Publish the run's merged telemetry report (ISSUE 5 CI satellite):
    when BLIT_TELEMETRY_OUT is set (the tier-1 CI job points it at a
    workspace file uploaded as an artifact), the whole suite's process
    timeline, fault counters and spans land there as one fleet report."""
    if os.environ.get("BLIT_TELEMETRY_OUT"):
        from blit import observability

        observability.maybe_write_report()


@pytest.fixture
def blit_logger_restored():
    """Snapshot + restore the 'blit' logger around tests that call
    configure_logging (which sets propagate=False — that must not leak into
    caplog-based tests)."""
    root = logging.getLogger("blit")
    handlers, propagate, level = list(root.handlers), root.propagate, root.level
    yield
    root.handlers = handlers
    root.propagate = propagate
    root.setLevel(level)
