"""Test harness: run JAX on a virtual 8-device CPU mesh.

Must set the XLA flags *before* jax is imported anywhere, so this executes at
conftest import time.  This fakes the 8-bank (and 2x4 band,bank) topology the
same way SURVEY.md §4 prescribes for testing the multi-chip path without
multi-chip hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
