"""The asynchronous output plane (ISSUE 4): overlapped device→host
readback (OutputRotation), write-behind product sinks (AsyncSink), the
shared fold bookkeeping (FoldInFlight) — and the contract that matters
above all: products through the async plane are BYTE-IDENTICAL to the
synchronous path's, crash/resume semantics included."""

import os
import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit import faults  # noqa: E402
from blit.faults import FaultRule, RetryPolicy  # noqa: E402
from blit.io.fbh5 import read_fbh5_data, read_fbh5_header  # noqa: E402
from blit.io.sigproc import read_fil_data  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.outplane import AsyncSink, FoldInFlight, OutputRotation  # noqa: E402
from blit.pipeline import RawReducer, ReductionCursor  # noqa: E402
from blit.testing import synth_raw  # noqa: E402


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(RetryPolicy(attempts=3, base_s=0.0, jitter=0.0))
    yield
    faults.clear()
    faults.reset_counters()
    faults.set_io_policy(None)


def no_plane_threads():
    """No output-plane thread may outlive its driver."""
    names = ("blit-readback", "blit-sink", "blit-bf-readback")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        alive = [t.name for t in threading.enumerate()
                 if t.name in names and t.is_alive()]
        if not alive:
            return True
        time.sleep(0.02)
    return False


# -- OutputRotation ---------------------------------------------------------


class TestOutputRotation:
    def test_order_and_values_preserved(self):
        tl = Timeline()
        rot = OutputRotation(depth=2, timeline=tl)
        try:
            got = []
            for i in range(7):
                out = jnp.full((4, 3), float(i))
                got.extend(rot.put(out, nbytes=out.nbytes))
            for slab in rot.drain():
                got.append(slab)
            assert len(got) == 7
            for i, slab in enumerate(got):
                np.testing.assert_array_equal(
                    slab.data, np.full((4, 3), float(i), np.float32))
                slab.release()
        finally:
            rot.close()
        assert tl.stages["readback"].calls == 7
        assert tl.stages["readback"].bytes == 7 * 4 * 3 * 4
        assert tl.stages["device"].bytes == 7 * 4 * 3 * 4

    def test_ring_mode_reuses_bounded_slabs(self):
        rot = OutputRotation(depth=2, reuse=True)
        try:
            seen_ids = set()
            for i in range(10):
                out = jnp.full((8,), float(i))
                for slab in rot.put(out):
                    seen_ids.add(id(slab.data))
                    np.testing.assert_array_equal(
                        slab.data, np.full((8,), held_val(slab)))
                    slab.release()
            for slab in rot.drain():
                seen_ids.add(id(slab.data))
                slab.release()
            # At most depth+1 distinct resident slab buffers ever existed
            # (CPU fetches alias the jax buffer, so the recycling ring is
            # the path exercised here).
            assert len(seen_ids) <= 3
        finally:
            rot.close()

    def test_late_release_retires_slab_to_staging_pool(self):
        # A slab still held by a consumer when close() sweeps the ring
        # (the AsyncSink write-behind tail pattern) must retire to the
        # process staging pool on release — not feed the GC and make the
        # next stream re-pay allocation + first-touch faults.
        from blit import hostmem

        pool = hostmem.slab_pool()
        rot = OutputRotation(depth=2, reuse=True)
        held = []
        try:
            for slab in rot.put(jnp.full((4099,), 7.0)):
                held.append(slab)
            for slab in rot.drain():
                held.append(slab)
        finally:
            rot.close()
        assert held  # the ring path ran (CPU fetch copies into a slab)
        before = pool.stats()["free_bytes"]
        for slab in held:
            slab.release()
        assert pool.stats()["free_bytes"] >= before + 4099 * 4

    def test_on_consumed_fires_before_emission(self):
        events = []
        rot = OutputRotation(depth=1)
        try:
            out = jnp.zeros((4,))
            # depth=1: put blocks until the readback completes, so the
            # finished slab comes back from put() itself.
            done = rot.put(out, on_consumed=lambda: events.append("consumed"))
            for slab in done:
                events.append("slab")
                slab.release()
            for slab in rot.drain():
                events.append("slab")
                slab.release()
        finally:
            rot.close()
        assert events == ["consumed", "slab"]

    def test_readback_error_reraises_in_consumer(self):
        rot = OutputRotation(depth=1)

        class Dead:
            def block_until_ready(self):
                raise RuntimeError("device fell over")

        try:
            with pytest.raises(RuntimeError, match="device fell over"):
                rot.put(Dead())
                list(rot.drain())
        finally:
            rot.close()
        assert no_plane_threads()

    def test_close_is_idempotent_and_joins(self):
        rot = OutputRotation(depth=1)
        rot.put(jnp.zeros((2,)))
        list(rot.drain())
        rot.close()
        rot.close()
        assert no_plane_threads()


def held_val(slab):
    return float(slab.data.flat[0])


# -- AsyncSink --------------------------------------------------------------


class _ListWriter:
    """Recording writer with the slab-writer contract."""

    def __init__(self):
        self.slabs = []
        self.closed = False
        self.aborted = False
        self.flushes = 0
        self.path = "/fake/list.fil"

    def append(self, slab):
        self.slabs.append(np.array(slab, copy=True))

    def flush(self):
        self.flushes += 1

    def close(self):
        self.closed = True

    def abort(self):
        self.aborted = True

    @property
    def nsamps(self):
        return sum(s.shape[0] for s in self.slabs)


class TestAsyncSink:
    def test_writes_in_order_and_finalizes(self):
        tl = Timeline()
        w = _ListWriter()
        sink = AsyncSink(w, depth=2, timeline=tl)
        for i in range(6):
            sink.append(np.full((2, 1, 4), float(i), np.float32))
        sink.close()
        assert w.closed and not w.aborted
        assert len(w.slabs) == 6
        for i, s in enumerate(w.slabs):
            np.testing.assert_array_equal(s, np.full((2, 1, 4), float(i)))
        assert tl.stages["write"].calls == 6
        assert tl.stages["write"].bytes == 6 * 2 * 4 * 4
        assert sink.nsamps == 12
        assert no_plane_threads()

    def test_flush_is_a_barrier(self):
        w = _ListWriter()
        sink = AsyncSink(w, depth=4)
        for i in range(3):
            sink.append(np.zeros((1, 1, 4), np.float32))
        sink.flush()
        assert len(w.slabs) == 3  # every prior append applied
        assert w.flushes == 1     # writer's own flush hook ran
        sink.close()
        assert no_plane_threads()

    def test_release_fires_after_write(self):
        w = _ListWriter()
        released = []
        sink = AsyncSink(w, depth=2)
        sink.append(np.zeros((1, 1, 4), np.float32),
                    release=lambda: released.append(len(w.slabs)))
        sink.flush()
        # The release saw the write already applied (FIFO on one thread).
        assert released == [1]
        sink.close()

    def test_writer_stall_watchdog(self):
        class Wedged(_ListWriter):
            def append(self, slab):
                time.sleep(3600)

        # Distinct thread name: the wedged daemon is abandoned (sleeping),
        # and must not trip later tests' no_plane_threads() sweeps.
        sink = AsyncSink(Wedged(), depth=1, stall_timeout_s=0.3,
                         name="blit-sink-wedged")
        sink.append(np.zeros((1, 1, 4), np.float32))
        with pytest.raises(RuntimeError, match="stall"):
            # Queue full behind the wedged append -> watchdog, not a hang.
            for _ in range(10):
                sink.append(np.zeros((1, 1, 4), np.float32))
        # Bounded teardown: the wedged daemon is abandoned, not joined.
        t0 = time.monotonic()
        sink.abort(join_timeout_s=0.2)
        assert time.monotonic() - t0 < 5.0


# -- FoldInFlight -----------------------------------------------------------


class _FakeWin:
    def __init__(self, log, i):
        self.log, self.i = log, i

    def release(self):
        self.log.append(self.i)


class TestFoldInFlight:
    def test_lag_release_order(self):
        tl = Timeline()
        fl = FoldInFlight(tl, depth=1)
        log = []
        for i in range(4):
            fl.make_room()
            fl.admit(_FakeWin(log, i), jnp.zeros((2,)))
        assert log == [0, 1, 2]  # lag-1: last window still admitted
        fl.drain(synced=True)
        assert log == [0, 1, 2, 3]
        # synced drain did not run a device wait for the tail
        assert tl.stages["device"].calls == 3


# -- async-vs-sync equivalence (ISSUE 4 satellite) --------------------------


def _synth(tmp_path, **kw):
    p = str(tmp_path / "x.raw")
    kw.setdefault("nblocks", 3)
    kw.setdefault("obsnchan", 2)
    kw.setdefault("ntime_per_block", 1024)
    kw.setdefault("tone_chan", 1)
    synth_raw(p, **kw)
    return p


class TestAsyncSyncEquivalence:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("fqav_by", [1, 4])
    def test_fil_products_byte_identical(self, tmp_path, dtype, fqav_by):
        raw = _synth(tmp_path)
        kw = dict(nfft=64, nint=2, chunk_frames=4, dtype=dtype,
                  fqav_by=fqav_by)
        out_a = str(tmp_path / "a.fil")
        out_s = str(tmp_path / "s.fil")
        RawReducer(**kw).reduce_to_file(raw, out_a)
        RawReducer(**kw, async_output=False).reduce_to_file(raw, out_s)
        with open(out_a, "rb") as fa, open(out_s, "rb") as fs:
            assert fa.read() == fs.read()  # whole file, header included
        assert no_plane_threads()

    @pytest.mark.parametrize("fqav_by", [1, 4])
    def test_h5_products_identical(self, tmp_path, fqav_by):
        raw = _synth(tmp_path)
        kw = dict(nfft=64, nint=2, chunk_frames=4, fqav_by=fqav_by)
        out_a = str(tmp_path / "a.h5")
        out_s = str(tmp_path / "s.h5")
        ha = RawReducer(**kw).reduce_to_file(raw, out_a)
        hs = RawReducer(**kw, async_output=False).reduce_to_file(raw, out_s)
        np.testing.assert_array_equal(read_fbh5_data(out_a),
                                      read_fbh5_data(out_s))
        assert read_fbh5_header(out_a) == read_fbh5_header(out_s)
        assert ha["nsamps"] == hs["nsamps"]

    def test_stream_slabs_identical(self, tmp_path):
        raw = _synth(tmp_path)
        kw = dict(nfft=64, nint=2, chunk_frames=4)
        _, da = RawReducer(**kw).reduce(raw)
        _, ds = RawReducer(**kw, async_output=False).reduce(raw)
        np.testing.assert_array_equal(da, ds)

    def test_skip_frames_replay_identical(self, tmp_path):
        # The resume path's exact-replay contract through the new plane.
        raw = _synth(tmp_path)
        from blit.io.guppi import GuppiRaw

        kw = dict(nfft=64, nint=2, chunk_frames=4)
        full = np.concatenate(
            list(RawReducer(**kw).stream(GuppiRaw(raw))), axis=0)
        tail_a = np.concatenate(
            list(RawReducer(**kw).stream(GuppiRaw(raw), skip_frames=8)),
            axis=0)
        tail_s = np.concatenate(
            list(RawReducer(**kw, async_output=False).stream(
                GuppiRaw(raw), skip_frames=8)), axis=0)
        np.testing.assert_array_equal(tail_a, tail_s)
        np.testing.assert_array_equal(tail_a, full[8 // 2:])

    def test_resume_mid_file_through_async_plane(self, tmp_path):
        # Crash the write-behind sink mid-product, resume, compare with
        # an uninterrupted synchronous run: decoded payloads identical.
        raw = _synth(tmp_path, nblocks=4)
        kw = dict(nfft=64, nint=2, chunk_frames=4)
        out = str(tmp_path / "r.fil")
        faults.install(FaultRule(point="sink.write", mode="fail", after=2,
                                 times=-1))
        try:
            with pytest.raises(faults.InjectedFault):
                RawReducer(**kw).reduce_resumable(raw, out)
        finally:
            faults.clear()
        cur = ReductionCursor.load(out)
        assert cur is not None and cur.frames_done == 8  # two slabs landed
        RawReducer(**kw).reduce_resumable(raw, out)
        _, got = read_fil_data(out)
        want_out = str(tmp_path / "w.fil")
        RawReducer(**kw, async_output=False).reduce_to_file(raw, want_out)
        _, want = read_fil_data(want_out)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert not os.path.exists(ReductionCursor.path_for(out))
        assert no_plane_threads()

    def test_env_kill_switch(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BLIT_SYNC_OUTPUT", "1")
        assert RawReducer(nfft=64).async_output is False


class TestMaskedStreamThroughPlane:
    def test_masked_antenna_stream_matches_zero_weight(self, tmp_path):
        # on_antenna_error="mask" windows ride the same OutputRotation:
        # the degraded stream's slabs must equal a clean stream whose
        # failed antenna is zero-weighted from the failing window on.
        from blit.parallel.antenna import AntennaStream
        from blit.parallel.beamform import beamform_stream, delay_weights_planar
        from blit.parallel.mesh import make_mesh

        nant, nsamp = 4, 512
        paths = []
        for a in range(nant):
            p = str(tmp_path / f"ant{a}.raw")
            synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=nsamp // 2,
                      seed=a)
            paths.append(p)
        mesh = make_mesh(1, 4)
        w = delay_weights_planar(
            jnp.zeros((2, nant)), jnp.asarray([1e9, 2e9]))

        def powers(ps, **feed_kw):
            feed = AntennaStream(ps, mesh=mesh, window_samples=128,
                                 max_samples=nsamp, **feed_kw)
            slabs = list(beamform_stream(feed, w, mesh=mesh, nint=64))
            return np.concatenate(slabs, axis=2), feed

        # Fail antenna 2's reads from its second window on.
        faults.install(FaultRule(point="guppi.read", mode="fail", after=2,
                                 times=-1, match="ant2"))
        faults.set_io_policy(RetryPolicy(attempts=1))
        try:
            got, feed = powers(paths, on_antenna_error="mask")
        finally:
            faults.clear()
        assert feed.masked_antennas == {2}
        assert feed.timeline.stages["antenna.masked"].calls >= 1
        assert got.shape[2] == nsamp // 64
        # Clean slabs for the unmasked prefix; finite everywhere after.
        clean, _ = powers(paths)
        np.testing.assert_array_equal(got[..., :2, :], clean[..., :2, :])
        assert np.isfinite(got).all()
        assert not np.array_equal(got[..., 2:, :], clean[..., 2:, :])
        assert no_plane_threads()


# -- the ingest rig's byte accounting (ISSUE 4 satellite) -------------------


class TestRigAccounting:
    def test_timeline_reset_preserves_stage_identity(self):
        tl = Timeline()
        with tl.stage("stream", nbytes=10):
            pass
        tl.gauge("depth", 3.0)
        held = tl.stages["stream"]  # a concurrent thread's captured ref
        tl.reset()
        assert tl.stages["stream"] is held  # identity preserved...
        assert held.bytes == 0 and held.seconds == 0.0  # ...and zeroed
        assert tl.gauges["depth"].n == 0
        held.bytes += 7  # late update from the holder
        assert tl.stages["stream"].bytes == 7  # ...lands in the report
        # clear() is exactly the footgun reset() exists to avoid:
        tl.stages.clear()
        held.bytes += 5
        assert tl.stages["stream"].bytes == 0  # orphaned — the r05 bug

    def test_rig_sequence_keeps_stream_bytes(self, tmp_path):
        # BENCH_r05 reported "stream": {"s": 350.3, "bytes": 0} — the rig
        # lost the stream-stage byte counter across its warmup/clear/
        # drain sequence (seed-era _chunks never counted them; clear()
        # would orphan them today).  Pin the exact rig sequence from
        # bench.py::_run_ingest: warmup chunk passes, Timeline.reset(),
        # timed drain — the dominant stage must carry its bytes.
        from blit.io.guppi import GuppiRaw

        raw = _synth(tmp_path)
        red = RawReducer(nfft=64, nint=1, chunk_frames=4)
        g = GuppiRaw(raw)
        for _ in range(2):
            for c in red._chunks(g):
                c.release()
        red.timeline.reset()
        red.drain(g)
        st = red.timeline.stages
        assert st["stream"].bytes == st["device"].bytes > 0
        for name, s in st.items():
            if s.seconds > 0:
                assert s.bytes > 0 or s.byte_free, name


# -- overlap gauge + product-path stage table -------------------------------


class TestOverlapObservability:
    def test_product_run_times_readback_and_write(self, tmp_path):
        raw = _synth(tmp_path)
        red = RawReducer(nfft=64, nint=2, chunk_frames=4)
        red.reduce_to_file(raw, str(tmp_path / "p.fil"))
        st = red.timeline.stages
        assert st["readback"].calls > 0 and st["readback"].bytes > 0
        assert st["write"].calls > 0 and st["write"].bytes > 0
        assert st["write"].bytes == st["readback"].bytes
        assert st["dispatch"].byte_free
        # The gauge landed (value is rig-dependent; presence is the pin).
        assert "overlap.stream" in red.timeline.gauges
        rep = red.timeline.report()
        assert rep["gauges"]["overlap.stream"]["n"] == 1

    def test_overlap_efficiency_math(self):
        tl = Timeline()
        tl.stages["stream"].seconds = 2.0
        tl.stages["device"].seconds = 1.0
        tl.stages["readback"].seconds = 2.0
        tl.stages["write"].seconds = 1.0
        assert tl.overlap_efficiency() == pytest.approx(2.0)
        assert tl.gauges["overlap.stream"].last == pytest.approx(2.0)
        assert Timeline().overlap_efficiency() == 0.0


class TestIngestBenchCLI:
    def test_ingest_bench_prints_stage_table(self, capsys):
        import json

        from blit.__main__ import main

        rc = main(["ingest-bench", "--nfft", "128", "--chunks", "2",
                   "--chunk-frames", "4", "--nchan", "2", "--blocks", "2",
                   "--sync-compare"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["file_bytes"] > 0
        legs = {leg["async_output"]: leg for leg in rep["legs"]}
        assert set(legs) == {True, False}
        a = legs[True]
        assert {"readback", "write", "dispatch"} <= set(a["stages"])
        assert a["stages"]["write"]["bytes"] == a["stages"]["readback"]["bytes"] > 0
        assert a["product_bytes"] == legs[False]["product_bytes"]
        assert "async_speedup" in rep
        # ISSUE 8 satellites: stage TAILS from the telemetry hists (not
        # just means), the byte-identity bit, and tuning provenance in
        # the ingest_config block.
        q = a["stage_quantiles"]
        for h in ("out.chunk_latency_s", "out.readback_lag_s",
                  "out.write_s"):
            assert {"p50", "p99", "n"} <= set(q[h]), h
        assert rep["products_identical"] is True
        tuning = rep["ingest_config"]["tuning"]
        assert set(tuning["sources"]) == {"chunk_frames",
                                          "prefetch_depth", "out_depth"}

    def test_ingest_bench_narrowed_product(self, capsys):
        # --nbits 8: the async leg narrows ON DEVICE before D2H; the
        # sync leg quantizes host-side — products must stay identical
        # and 4x smaller than f32.
        import json

        from blit.__main__ import main

        rc = main(["ingest-bench", "--nfft", "128", "--chunks", "2",
                   "--chunk-frames", "4", "--nchan", "2", "--blocks", "2",
                   "--sync-compare", "--nbits", "8",
                   "--quant-scale", "0.05"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["products_identical"] is True
        a = {leg["async_output"]: leg for leg in rep["legs"]}[True]
        # The readback stage moved the NARROW bytes (uint8 product).
        assert a["stages"]["readback"]["bytes"] == \
            a["stages"]["write"]["bytes"]
        assert a["stages"]["write"]["bytes"] < rep["file_bytes"]
