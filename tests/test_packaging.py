"""Packaging contract (VERDICT r4 missing item 1): pyproject.toml is the
blit analog of the reference's Project.toml (/root/reference/
Project.toml:1-24 — name/version, dependency pins, compat bounds) and the
``blit`` console script is the deployment surface on worker hosts
(docs/WORKFLOWS.md "Deploying to worker hosts")."""

import os
import subprocess
import sys

import pytest

# stdlib from 3.11; pyproject declares >=3.10 support, where this file
# must not break collection.
tomllib = pytest.importorskip("tomllib")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def project():
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)["project"]


class TestMetadata:
    def test_name_and_dynamic_version(self, project):
        import blit

        assert project["name"] == "blit"
        assert "version" in project["dynamic"]
        # The dynamic version resolves from blit/version.py (single source).
        assert isinstance(blit.__version__, str) and blit.__version__

    def test_dependencies_are_compat_bounded(self, project):
        # The reference pins compat bounds for every dep
        # (Project.toml [compat]); blit's core deps carry both a floor
        # and a ceiling.
        deps = {d.split(">=")[0]: d for d in project["dependencies"]}
        assert set(deps) == {"numpy", "h5py", "jax"}
        for spec in deps.values():
            assert ">=" in spec and "<" in spec, f"unbounded dep: {spec}"

    def test_console_script_entry_point(self, project):
        # The entry point must reference a real callable.
        assert project["scripts"]["blit"] == "blit.__main__:main"
        from blit.__main__ import main

        assert callable(main)


class TestPublicSurface:
    """The serving layer's public names are part of the package contract
    (ISSUE 3 satellite): pinned here so a refactor that drops or renames
    them fails loudly."""

    SERVE_EXPORTS = (
        "ProductService",
        "ProductRequest",
        "ProductCache",
        "Scheduler",
        "Overloaded",
        "FleetFrontDoor",
    )

    def test_top_level_reexports_serve_layer(self):
        import blit
        import blit.serve

        for name in self.SERVE_EXPORTS:
            assert getattr(blit, name) is getattr(blit.serve, name), name
            assert name in blit.__all__

    def test_serve_module_surface(self):
        import blit.serve

        expected = {
            "Cancelled", "DeadlineExpired", "FleetError",
            "FleetFrontDoor", "FrontDoorServer", "HashRing", "Job",
            "Overloaded", "PeerServer", "ProductCache", "ProductRequest",
            "ProductService", "Scheduler", "Ticket",
            "fingerprint_for", "reduction_fingerprint",
        }
        assert set(blit.serve.__all__) == expected
        for name in expected:
            assert callable(getattr(blit.serve, name)), name

    SEARCH_EXPORTS = ("DedopplerReducer", "Hit")

    STREAM_EXPORTS = ("stream_reduce", "stream_search")

    def test_top_level_reexports_stream_plane(self):
        # The streaming ingest plane's front door (ISSUE 7): pinned like
        # the serve/search layers' so a refactor that drops it fails
        # loudly.
        import blit
        import blit.stream

        for name in self.STREAM_EXPORTS:
            assert getattr(blit, name) is getattr(blit.stream, name), name
            assert name in blit.__all__

    def test_stream_module_surface(self):
        import blit.stream

        expected = {
            "ChunkSource", "FileTailSource", "LiveRawStream",
            "PacketAssembler", "PacketFramer", "PacketReplaySource",
            "PacketSource", "QueueSource", "ReplaySource",
            "SessionSupervisor", "StreamChunk", "StreamCursor",
            "chunks_of", "packets_of", "source_from_spec",
            "stream_reduce", "stream_search",
        }
        assert set(blit.stream.__all__) == expected
        for name in expected:
            assert callable(getattr(blit.stream, name)), name

    def test_top_level_reexports_search_plane(self):
        # The search plane's front door (ISSUE 6 satellite): pinned like
        # the serve layer's so a refactor that drops it fails loudly.
        import blit
        import blit.search

        for name in self.SEARCH_EXPORTS:
            assert getattr(blit, name) is getattr(blit.search, name), name
            assert name in blit.__all__

    def test_search_module_surface(self):
        import blit.search

        expected = {
            "DedopplerReducer", "SearchCursor", "Hit", "hit_from_record",
            "hits_from_array", "hits_from_packed", "hits_to_array",
        }
        assert set(blit.search.__all__) == expected
        for name in expected:
            assert callable(getattr(blit.search, name)), name

    def test_serve_package_ships(self):
        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            tool = tomllib.load(f)["tool"]["setuptools"]
        assert "blit.serve" in tool["packages"]
        assert "blit.search" in tool["packages"]
        assert "blit.stream" in tool["packages"]

    def test_unknown_attribute_still_raises(self):
        import blit

        with pytest.raises(AttributeError):
            blit.definitely_not_a_thing  # noqa: B018 — the access IS the test


class TestLintConfig:
    """The ruff CI job (ISSUE 3 satellite) must keep its checked-in
    config: job present in the workflow, config present in pyproject."""

    def test_ruff_config_checked_in(self):
        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            tool = tomllib.load(f)["tool"]
        assert "F" in tool["ruff"]["lint"]["select"]
        assert "E9" in tool["ruff"]["lint"]["select"]

    def test_ci_runs_ruff(self):
        with open(os.path.join(REPO, ".github", "workflows", "ci.yml")) as f:
            ci = f.read()
        assert "ruff check" in ci


class TestInstalledSurface:
    def test_module_invocation(self):
        # `python -m blit --help` works from any cwd (the console script
        # is this plus the pip-generated shim).
        out = subprocess.run(
            [sys.executable, "-m", "blit", "--help"],
            capture_output=True, text=True, cwd="/",
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert out.returncode == 0
        assert "reduce" in out.stdout and "scan" in out.stdout

    def test_agent_module_importable(self):
        # The remote transport spawns `python -m blit.agent` on workers;
        # the module must resolve in an installed/PYTHONPATH environment.
        out = subprocess.run(
            [sys.executable, "-c", "import blit.agent, blit.workers"],
            capture_output=True, text=True, cwd="/",
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert out.returncode == 0, out.stderr

    def test_native_sources_ship_as_package_data(self):
        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            tool = tomllib.load(f)["tool"]["setuptools"]
        assert "blit.native" in tool["packages"]
        data = tool["package-data"]["blit.native"]
        assert "Makefile" in data and "*.cc" in data and "build/*.so" in data
