"""ReductionCursor torn-write crash drills (ISSUE 12 satellite).

The ``.fil`` resume path's crash states, mirroring the PR 7
SearchCursor drills (tests/test_dedoppler.py TestSearchCursorDrills):
the fsync-before-claim ordering's only legal torn state (durable rows
beyond the claim), a torn partial row, a claim exactly at EOF (the
clean crash — must RESUME), and a claim past EOF (crash-corrupted —
POSIX truncate would NUL-hole-extend; must restart fresh, the
``resume_fil_ok`` guard).  Every drill finishes byte-identical to an
uninterrupted reduction — the supervisor's resume contract is now
pinned on BOTH cursor types."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.pipeline import (  # noqa: E402
    RawReducer,
    ReductionCursor,
    resume_fil_ok,
)
from blit.testing import synth_raw  # noqa: E402

NFFT, CF = 32, 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


def _kw():
    return dict(nfft=NFFT, chunk_frames=CF, tune_online=False)


def _bytes(path):
    with open(path, "rb") as f:
        return f.read()


class TestReductionCursorDrills:
    def _interrupted(self, tmp_path):
        """A reference product plus an 'interrupted' resumable twin:
        crash (injected sink failure) after two durable appends,
        returning ``(raw, ref_path, out_path, row_bytes)``."""
        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=512,
                  seed=2)
        ref = str(tmp_path / "ref.fil")
        RawReducer(**_kw()).reduce_to_file(raw, ref)
        out = str(tmp_path / "res.fil")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            RawReducer(**_kw()).reduce_resumable(raw, out)
        faults.clear()
        cur = ReductionCursor.load(out)
        assert cur is not None and cur.frames_done > 0
        from blit.io.guppi import open_raw

        hdr = RawReducer(**_kw()).header_for(open_raw(raw))
        row_bytes = hdr["nchans"] * hdr["nifs"] * 4
        return raw, ref, out, row_bytes

    def test_unclaimed_tail_truncated_and_replayed(self, tmp_path):
        # Durable rows past the claim (the crash window between fsync
        # and cursor save): resume truncates and re-reduces them,
        # finishing byte-identical.
        raw, ref, out, row_bytes = self._interrupted(tmp_path)
        with open(out, "ab") as f:
            f.write(np.full(row_bytes // 4, 7.0, np.float32).tobytes())
        RawReducer(**_kw()).reduce_resumable(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert not os.path.exists(ReductionCursor.path_for(out))

    def test_torn_row_tail_truncated(self, tmp_path):
        # A crash mid-write leaves HALF a row past the claim: resume
        # truncates it rather than splicing garbage mid-product.
        raw, ref, out, row_bytes = self._interrupted(tmp_path)
        with open(out, "ab") as f:
            f.write(b"\x01" * (row_bytes // 2))
        RawReducer(**_kw()).reduce_resumable(raw, out)
        assert _bytes(out) == _bytes(ref)

    def test_claim_exactly_at_eof_resumes(self, tmp_path):
        # The clean crash state: claim == file length must RESUME (the
        # guard is a strict can-the-file-hold-the-claim check), not
        # restart — pinned by watching how many frames re-reduce.
        raw, ref, out, _ = self._interrupted(tmp_path)
        claimed = ReductionCursor.load(out).frames_done
        red = RawReducer(**_kw())
        red.reduce_resumable(raw, out)
        assert _bytes(out) == _bytes(ref)
        # Resumed, not restarted: this run produced only the remainder.
        assert red.stats.output_frames > 0
        ref_frames = RawReducer(**_kw()).reduce(raw)[1].shape[0]
        assert red.stats.output_frames == ref_frames - claimed

    def test_claim_past_eof_starts_fresh(self, tmp_path):
        # One row short of the claim is already corrupt: truncate would
        # EXTEND a NUL hole into the product — must start fresh (the
        # new resume_fil_ok guard) and still finish byte-identical.
        raw, ref, out, row_bytes = self._interrupted(tmp_path)
        size = os.path.getsize(out)
        with open(out, "r+b") as f:
            f.truncate(size - row_bytes)
        red = RawReducer(**_kw())
        red.reduce_resumable(raw, out)
        assert _bytes(out) == _bytes(ref)
        ref_frames = RawReducer(**_kw()).reduce(raw)[1].shape[0]
        # Fresh start: EVERY frame was re-reduced.
        assert red.stats.output_frames == ref_frames


class TestResumeFilOk:
    def test_holds_claim(self, tmp_path):
        from blit.io.sigproc import write_fil

        p = str(tmp_path / "x.fil")
        hdr = {"nchans": 4, "nifs": 1, "nbits": 32, "tsamp": 1.0,
               "fch1": 1000.0, "foff": -0.1}
        write_fil(p, hdr, np.zeros((3, 1, 4), np.float32))
        assert resume_fil_ok(p, 1, 4, 3)
        assert not resume_fil_ok(p, 1, 4, 4)
        assert not resume_fil_ok(str(tmp_path / "missing.fil"), 1, 4, 0)

    def test_unparseable_header_fails_closed(self, tmp_path):
        p = str(tmp_path / "junk.fil")
        with open(p, "wb") as f:
            f.write(b"not a sigproc header")
        assert not resume_fil_ok(p, 1, 4, 0)
