"""Distributed RAW → filterbank reduction through the orchestration API
(gbt.reduce_raw → workers.reduce_raw → pipeline), per BASELINE configs 1-2."""

import pytest

jax = pytest.importorskip("jax")

from blit import gbt, workers  # noqa: E402
from blit.io.sigproc import read_fil_data  # noqa: E402
from blit.parallel.pool import WorkerPool  # noqa: E402
from blit.testing import synth_raw  # noqa: E402


def test_worker_reduce_raw_inline(tmp_path):
    p = str(tmp_path / "a.raw")
    synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024, tone_chan=0)
    hdr, data = workers.reduce_raw(p, nfft=64, nint=4)
    assert data.shape[-1] == 2 * 64
    assert hdr["nchans"] == 128


def test_worker_reduce_raw_product_preset(tmp_path):
    p = str(tmp_path / "a.raw")
    synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=2048)
    hdr, data = workers.reduce_raw(p, product="0001")  # nfft=8, nint=128
    assert hdr["nchans"] == 16


def test_gbt_reduce_raw_fanout(tmp_path):
    paths = []
    for k in range(3):
        p = str(tmp_path / f"bank{k}.raw")
        synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024, seed=k,
                  tone_chan=k % 2)
        paths.append(p)
    outs = [p.replace(".raw", ".fil") for p in paths]
    with WorkerPool(["h0", "h1", "h2"]) as pool:
        hdrs = gbt.reduce_raw([1, 2, 3], paths, outs, pool=pool,
                              nfft=64, nint=2, stokes="XXYY")
    for out, hdr in zip(outs, hdrs):
        rhdr, data = read_fil_data(out)
        assert rhdr["nifs"] == 2
        assert data.shape[0] == hdr["nsamps"]


def test_gbt_reduce_raw_size_asserts(tmp_path):
    with WorkerPool(["h0"]) as pool:
        with pytest.raises(ValueError, match="same size"):
            gbt.reduce_raw([1, 2], ["a.raw"], pool=pool)
        with pytest.raises(ValueError, match="out_paths"):
            gbt.reduce_raw([1], ["a.raw"], out_paths=["x", "y"], pool=pool)


def test_product_with_explicit_nfft_rejected(tmp_path):
    with pytest.raises(ValueError, match="not both"):
        workers.reduce_raw("x.raw", product="0000", nint=16)
