"""Data-integrity plane (ISSUE 13 tentpole): ingest digest masking,
product manifests, serve-cache content verification, fsck + quarantine
+ repair, the background scrubber, and the degraded /healthz surface."""

import filecmp
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit import faults, integrity  # noqa: E402
from blit.io.guppi import GuppiRaw, write_raw  # noqa: E402
from blit.observability import Timeline  # noqa: E402
from blit.pipeline import RawReducer  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NFFT = 32


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


@pytest.fixture(autouse=True)
def _isolate_quarantine_watch():
    """The quarantine watch registry is process-wide by design (a serve
    process watches the caches it opened); restore it after each test so
    a drill's leftover quarantine cannot degrade /healthz for unrelated
    test files (test_monitor's clean-process assertions)."""
    with integrity._WATCH_LOCK:
        saved = set(integrity._WATCHED_QUARANTINES)
    yield
    with integrity._WATCH_LOCK:
        integrity._WATCHED_QUARANTINES.clear()
        integrity._WATCHED_QUARANTINES.update(saved)


def _kw(cf=4):
    return dict(nfft=NFFT, chunk_frames=cf, tune_online=False)


def _flip_byte(path, back=9):
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) - back)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0x20]))


class TestIngestDigests:
    """RAW digest sidecars: verified blocks deliver; corrupt ones mask."""

    def _setup(self, tmp_path, nblocks=4, per_block=512):
        d = tmp_path / "in"
        d.mkdir()
        raw = str(d / "t.raw")
        synth_raw(raw, nblocks=nblocks, obsnchan=2,
                  ntime_per_block=per_block, seed=1)
        return raw

    def test_sidecar_roundtrip_clean(self, tmp_path):
        raw = self._setup(tmp_path)
        ref = str(tmp_path / "ref.fil")
        RawReducer(**_kw()).reduce_to_file(raw, ref)
        integrity.write_raw_digests(raw)
        out = str(tmp_path / "out.fil")
        rdr = GuppiRaw(raw)
        RawReducer(**_kw()).reduce_to_file(rdr, out)
        # Clean bytes under an armed sidecar: zero masks, identical
        # product — verification must never change a healthy reduction.
        assert rdr.bad_blocks == set()
        assert filecmp.cmp(out, ref, shallow=False)
        assert "integrity.bad_block" not in faults.counters()

    def _zero_oracle(self, tmp_path, raw, victim):
        """The same recording (same basename) with ``victim`` zeroed."""
        rdr = GuppiRaw(raw, native=False)
        blocks = [np.array(rdr.read_block(i))
                  for i in range(rdr.nblocks)]
        blocks[victim][:] = 0
        od = tmp_path / "oracle_in"
        od.mkdir()
        opath = str(od / os.path.basename(raw))
        write_raw(opath, dict(rdr.header(0)), blocks)
        oracle = str(tmp_path / "oracle.fil")
        RawReducer(**_kw()).reduce_to_file(opath, oracle)
        return oracle

    def test_disk_rot_masked_to_zero_oracle(self, tmp_path):
        # A flipped byte ON DISK inside block 1's payload: the block
        # fails its sidecar digest and the product is byte-identical to
        # the zero-filled oracle (the acceptance golden).
        raw = self._setup(tmp_path)
        integrity.write_raw_digests(raw)
        oracle = self._zero_oracle(tmp_path, raw, victim=1)
        rdr0 = GuppiRaw(raw, native=False)
        off = rdr0._data_offsets[1] + 100
        with open(raw, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0x01]))
        out = str(tmp_path / "out.fil")
        rdr = GuppiRaw(raw)
        hdr = RawReducer(**_kw()).reduce_to_file(rdr, out)
        assert rdr.bad_blocks == {1}
        assert hdr["_masked_blocks"] == [1]
        assert faults.counters()["integrity.bad_block"] == 1
        assert filecmp.cmp(out, oracle, shallow=False)

    def test_seeded_corrupt_fault_masked_to_zero_oracle(self, tmp_path):
        # The seeded ``corrupt`` fault mode (in-flight flip of the
        # DELIVERED frame, disk clean): detected per delivery, masked,
        # byte-identical to the zero-filled oracle.  Single-chunk
        # geometry (chunk spans the recording) makes delivery k ==
        # block k, so after=2 targets exactly block 2.
        raw = self._setup(tmp_path)
        integrity.write_raw_digests(raw)
        kw = dict(nfft=NFFT, chunk_frames=4 * 512 // NFFT - 3,
                  tune_online=False)
        rdr0 = GuppiRaw(raw, native=False)
        blocks = [np.array(rdr0.read_block(i)) for i in range(4)]
        blocks[2][:] = 0
        od = tmp_path / "oin"
        od.mkdir()
        opath = str(od / "t.raw")
        write_raw(opath, dict(rdr0.header(0)), blocks)
        oracle = str(tmp_path / "oracle.fil")
        RawReducer(**kw).reduce_to_file(opath, oracle)
        faults.install(faults.FaultRule(point="guppi.read",
                                        mode="corrupt", after=2, times=1))
        out = str(tmp_path / "out.fil")
        rdr = GuppiRaw(raw)
        hdr = RawReducer(**kw).reduce_to_file(rdr, out)
        assert rdr.bad_blocks == {2}
        assert hdr["_masked_blocks"] == [2]
        assert filecmp.cmp(out, oracle, shallow=False)

    def test_malformed_sidecar_refused_loudly(self, tmp_path):
        raw = self._setup(tmp_path)
        with open(integrity.raw_digests_path(raw), "w") as f:
            f.write('{"kind": "blit.digests", "blocks": [truncated')
        with pytest.raises(integrity.IntegrityError):
            GuppiRaw(raw)

    def test_verify_disabled_by_env(self, tmp_path, monkeypatch):
        raw = self._setup(tmp_path)
        integrity.write_raw_digests(raw)
        monkeypatch.setenv("BLIT_VERIFY_INGEST", "0")
        rdr = GuppiRaw(raw)
        assert rdr._block_digests is None


class TestManifests:
    def test_fil_manifest_published_and_verifies(self, tmp_path):
        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512, seed=2)
        out = str(tmp_path / "p.fil")
        RawReducer(**_kw()).reduce_to_file(raw, out)
        doc, problems = integrity.verify_product(out)
        assert doc is not None and doc["complete"] and not problems
        assert doc["format"] == "fil" and doc["rows"] > 0
        assert doc["windows"], "per-window claim ledger missing"

    def test_single_flipped_byte_detected(self, tmp_path):
        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512, seed=2)
        out = str(tmp_path / "p.fil")
        RawReducer(**_kw()).reduce_to_file(raw, out)
        _flip_byte(out)
        _doc, problems = integrity.verify_product(out)
        assert problems and "digest mismatch" in problems[0]

    def test_h5_manifest_whole_file_digest(self, tmp_path):
        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512, seed=2)
        out = str(tmp_path / "p.h5")
        RawReducer(**_kw()).reduce_to_file(raw, out)
        doc, problems = integrity.verify_product(out)
        assert doc is not None and doc["complete"] and not problems
        _flip_byte(out, back=5)
        _doc, problems = integrity.verify_product(out)
        assert problems

    def test_hits_manifest(self, tmp_path):
        from blit.search import DedopplerReducer

        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512,
                  seed=2, tone_chan=0)
        out = str(tmp_path / "p.hits")
        DedopplerReducer(nfft=NFFT, chunk_frames=8, window_spectra=4,
                         snr_threshold=2.0).search_to_file(raw, out)
        doc, problems = integrity.verify_product(out)
        assert doc is not None and doc["complete"] and not problems
        _flip_byte(out, back=3)
        _doc, problems = integrity.verify_product(out)
        assert problems


class TestSigprocPayloadGuard:
    """The ISSUE 13 satellite closing the blit/io/sigproc.py gap: a .fil
    whose payload is not a whole number of header-described spectra is
    REFUSED at read-back, never silently mis-shaped."""

    def test_truncated_payload_refused(self, tmp_path):
        from blit.io.sigproc import read_fil_data, write_fil

        p = str(tmp_path / "x.fil")
        hdr = {"nchans": 4, "nifs": 1, "nbits": 32, "tsamp": 1.0,
               "fch1": 1000.0, "foff": -0.1}
        write_fil(p, hdr, np.arange(12, dtype=np.float32).reshape(3, 1, 4))
        read_fil_data(p)  # whole spectra: fine
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) - 6)  # torn mid-row
        with pytest.raises(ValueError, match="whole number"):
            read_fil_data(p)

    def test_resume_probe_fails_closed_on_torn_row(self, tmp_path):
        from blit.io.sigproc import write_fil
        from blit.pipeline import resume_fil_ok

        p = str(tmp_path / "x.fil")
        hdr = {"nchans": 4, "nifs": 1, "nbits": 32, "tsamp": 1.0,
               "fch1": 1000.0, "foff": -0.1}
        write_fil(p, hdr, np.zeros((3, 1, 4), np.float32))
        assert resume_fil_ok(p, 1, 4, 3)


class TestCacheIntegrity:
    def _publish(self, tmp_path):
        from blit.serve.cache import ProductCache, fingerprint_for
        from blit.serve.service import ProductRequest

        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512, seed=3)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        reducer = req.reducer()
        fp = fingerprint_for(reducer, raw)
        header, data = reducer.reduce(raw)
        cdir = str(tmp_path / "cache")
        cache = ProductCache(cdir, ram_bytes=0)
        cache.put(fp, header, data, recipe=req.recipe())
        return cache, cdir, fp, raw

    def test_meta_carries_digest_and_recipe(self, tmp_path):
        cache, cdir, fp, _raw = self._publish(tmp_path)
        meta = json.load(open(os.path.join(cdir, f"{fp}.json")))
        assert integrity.parse_crc(meta["crc32"]) is not None
        assert meta["recipe"]["nfft"] == NFFT
        assert cache.get(fp) is not None  # verified load serves

    def test_flipped_entry_evicted_as_corrupt_on_load(self, tmp_path):
        cache, cdir, fp, _raw = self._publish(tmp_path)
        _flip_byte(os.path.join(cdir, f"{fp}.h5"))
        assert cache.get(fp) is None
        assert cache.stats()["evict.corrupt"] >= 1
        assert faults.counters().get("integrity.cache.corrupt", 0) >= 1

    def test_scrubber_quarantines_and_health_degrades(self, tmp_path):
        from blit import monitor

        cache, cdir, fp, _raw = self._publish(tmp_path)
        tl = Timeline()
        sc = integrity.Scrubber(cache, timeline=tl, interval_s=999)
        assert sc.scrub_once()["ok"]
        _flip_byte(os.path.join(cdir, f"{fp}.h5"), back=30)
        r = sc.scrub_once()
        assert r is not None and not r["ok"]
        rep = tl.report()
        assert "integrity.scrub.corrupt" in rep
        assert "integrity.verify_s" in rep.get("hists", {})
        # The corrupt entry moved to .quarantine and stopped serving.
        qdir = os.path.join(cdir, integrity.QUARANTINE_DIR)
        assert os.listdir(qdir)
        assert cache.get(fp) is None
        # /healthz says degraded while the quarantine is non-empty.
        pub = monitor.MetricsPublisher(interval_s=999)
        try:
            h = pub.health()
            assert h["status"] == "degraded"
            assert any(r.startswith("integrity:") for r in h["reasons"])
        finally:
            pub.close()
            # Triage: clear the quarantine so later tests see a clean
            # health surface (the watch registry is process-wide).
            for n in os.listdir(qdir):
                os.unlink(os.path.join(qdir, n))
        assert not integrity.quarantine_health()


class TestScrubKnobs:
    def test_interval_zero_disables(self, monkeypatch):
        from blit.config import scrub_defaults

        for v in ("0", "", "none", "-1"):
            monkeypatch.setenv("BLIT_SCRUB_INTERVAL", v)
            assert scrub_defaults()["enabled"] is False, v
        monkeypatch.setenv("BLIT_SCRUB_INTERVAL", "0.5")
        d = scrub_defaults()
        assert d["enabled"] and d["interval_s"] == 0.5

    def test_vanished_entry_is_not_corrupt(self, tmp_path):
        # An entry evicted between index() and verify (a routine LRU
        # race) must not page operators via integrity.scrub.corrupt.
        from blit.serve.cache import ProductCache

        class _Racy(ProductCache):
            def index(self):
                return ["gone" * 16]

        cache = _Racy(str(tmp_path / "c"), ram_bytes=0)
        tl = Timeline()
        sc = integrity.Scrubber(cache, timeline=tl, interval_s=999)
        assert sc.scrub_once() is None
        assert sc.corrupt == 0
        assert "integrity.scrub.corrupt" not in tl.report()


class TestMonitorSurface:
    def test_integrity_counters_ride_metrics_and_top(self):
        """ISSUE 13 satellite: integrity.* counters and the
        integrity.verify_s histogram ride the PR 10 monitor plane —
        blit_fault_total / blit_latency_* on /metrics, fault rows on
        `blit top`, and (via local_fleet_report) the
        telemetry-report.json CI artifact."""
        from blit.monitor import parse_prometheus, render_top
        from blit.observability import (
            local_fleet_report,
            render_prometheus,
        )

        integrity.incr("integrity.bad_block")
        integrity.observe_verify(0.003)
        rep = local_fleet_report()
        assert rep["faults"].get("integrity.bad_block", 0) >= 1
        text = render_prometheus(rep)
        samples = parse_prometheus(text)
        assert any(n == "blit_fault_total"
                   and labels.get("counter") == "integrity.bad_block"
                   for n, labels, _v in samples)
        assert any(labels.get("name") == "integrity.verify_s"
                   for _n, labels, _v in samples)
        assert "integrity.bad_block" in render_top(rep)


class TestFsck:
    def _tree(self, tmp_path):
        from blit.serve.cache import ProductCache, fingerprint_for
        from blit.serve.service import ProductRequest

        tree = tmp_path / "tree"
        (tree / "products").mkdir(parents=True)
        raw = str(tmp_path / "drill.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512, seed=4)
        product = str(tree / "products" / "drill.fil")
        RawReducer(**_kw()).reduce_to_file(raw, product)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        reducer = req.reducer()
        fp = fingerprint_for(reducer, raw)
        header, data = reducer.reduce(raw)
        cdir = str(tree / "cache")
        ProductCache(cdir, ram_bytes=0).put(fp, header, data,
                                            recipe=req.recipe())
        return str(tree), product, cdir, fp, raw

    def test_clean_tree(self, tmp_path):
        tree, *_ = self._tree(tmp_path)
        rep = integrity.fsck(tree)
        assert rep["clean"] and rep["checked"] == 2 and rep["ok"] == 2

    def test_flips_detected_quarantined_and_repaired(self, tmp_path):
        tree, product, cdir, fp, raw = self._tree(tmp_path)
        _flip_byte(product)
        _flip_byte(os.path.join(cdir, f"{fp}.h5"))
        rep = integrity.fsck(tree)
        assert not rep["clean"]
        bad_paths = " ".join(b["path"] for b in rep["bad"])
        assert "drill.fil" in bad_paths and f"{fp}.h5" in bad_paths
        assert all(b["quarantined"] for b in rep["bad"])
        # The corrupt artifacts are OUT of the tree (contained).
        assert not os.path.exists(product)
        # Operator re-reduces the product; --repair re-derives the
        # cache entry from its recorded recipe and retires the corpses.
        RawReducer(**_kw()).reduce_to_file(raw, product)
        rep = integrity.fsck(tree, repair=True)
        assert rep["clean"] and len(rep["repaired"]) >= 2, rep
        rep2 = integrity.fsck(tree)
        assert rep2["clean"] and rep2["checked"] == 2

    def test_raw_member_sidecar_verified_report_only(self, tmp_path):
        # A digest-armed RAW member inside the tree: fsck re-derives
        # its block digests; rot is REPORTED (exit != 0) but the member
        # is never quarantined — it is the read-only source of truth.
        tree = tmp_path / "tree"
        tree.mkdir()
        raw = str(tree / "m.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512,
                  seed=6)
        integrity.write_raw_digests(raw)
        rep = integrity.fsck(str(tree))
        assert rep["clean"] and rep["checked"] == 1
        rdr = GuppiRaw(raw, native=False)
        with open(raw, "r+b") as f:
            f.seek(rdr._data_offsets[1] + 50)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0x02]))
        rep = integrity.fsck(str(tree))
        assert not rep["clean"]
        assert rep["bad"][0]["kind"] == "raw"
        assert "block 1" in rep["bad"][0]["problems"][0]
        assert os.path.exists(raw)  # never moved

    def test_torn_cache_meta_fails_closed(self, tmp_path):
        tree, _product, cdir, fp, _raw = self._tree(tmp_path)
        with open(os.path.join(cdir, f"{fp}.json"), "w") as f:
            f.write('{"fingerprint": "trunca')
        rep = integrity.fsck(tree)
        assert not rep["clean"]

    def test_cli_roundtrip(self, tmp_path):
        from blit.__main__ import main

        tree, product, _cdir, _fp, _raw = self._tree(tmp_path)
        out = str(tmp_path / "fsck.json")
        assert main(["fsck", tree, "--json-out", out]) == 0
        _flip_byte(product)
        assert main(["fsck", tree, "--json-out", out]) == 1
        rep = json.load(open(out))
        assert rep["bad"] and not rep["clean"]


class TestChaosCorruptCLI:
    def test_corrupt_leg(self, tmp_path):
        from blit.__main__ import main

        out = str(tmp_path / "report.json")
        rc = main(["chaos", "--fault", "corrupt",
                   "--work-dir", str(tmp_path / "work"),
                   "--json-out", out])
        assert rc == 0
        rep = json.load(open(out))
        assert rep["recovered"] is True
        assert rep["byte_identical"] is True
        assert rep["integrity"]["integrity.bad_block"] >= 1
        assert rep["masked_blocks"] == [rep["victim_block"]]
