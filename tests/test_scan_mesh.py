"""End-to-end mesh scan loading (blit/parallel/scan.py): RAW files for all
(band, bank) players → sharded reduction → stitched band, on the virtual
8-device mesh, vs the host pipeline golden."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.parallel.scan import load_scan_mesh  # noqa: E402
from blit.pipeline import RawReducer  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NFFT, NINT = 64, 2


def make_scan(tmp_path, nband=1, nbank=8, nchan=2, ntime=1024, nblocks=2):
    """One synthetic scan: per-player RAW files with contiguous bank
    frequencies (bank k centered obsbw/nbank apart)."""
    paths = []
    band_bw = -187.5  # GBT sign convention: descending frequency
    bank_bw = band_bw / nbank
    for b in range(nband):
        row = []
        for k in range(nbank):
            p = str(tmp_path / f"blc{b}{k}.raw")
            # output_header: band center obsfreq spans obsbw; for contiguity
            # bank k center must step by bank_bw from the band edge.
            obsfreq = 8000.0 + b * 500.0 + (k + 0.5) * bank_bw
            synth_raw(p, nblocks=nblocks, obsnchan=nchan,
                      ntime_per_block=ntime, seed=b * 8 + k,
                      tone_chan=(k % nchan), obsbw=bank_bw)
            row.append(p)
        paths.append(row)
    return paths


class TestLoadScanMesh:
    @pytest.mark.parametrize("nband,nbank", [(1, 8), (2, 4)])
    def test_matches_host_pipeline(self, tmp_path, nband, nbank):
        paths = make_scan(tmp_path, nband, nbank)
        hdr, out = load_scan_mesh(paths, nfft=NFFT, nint=NINT, despike=False)
        got = np.asarray(out)
        assert got.shape[0] == nband
        assert hdr["nchans"] == nbank * 2 * NFFT == got.shape[-1]
        # Host golden: per-bank RawReducer + channel concat, trimmed to the
        # common frame count.
        frames = got.shape[1]
        for b in range(nband):
            banks = []
            for k in range(nbank):
                red = RawReducer(nfft=NFFT, nint=NINT)
                _, d = red.reduce(paths[b][k])
                banks.append(d[:frames])
            want = np.concatenate(banks, axis=-1)
            np.testing.assert_allclose(got[b], want, rtol=1e-4, atol=0.5)

    def test_despike_epilogue(self, tmp_path):
        paths = make_scan(tmp_path)
        _, out = load_scan_mesh(paths, nfft=NFFT, nint=NINT, despike=True)
        got = np.asarray(out)
        np.testing.assert_array_equal(
            got[..., NFFT // 2 :: NFFT], got[..., NFFT // 2 - 1 :: NFFT]
        )

    def test_max_frames_caps_output(self, tmp_path):
        paths = make_scan(tmp_path, nblocks=4)
        _, out = load_scan_mesh(paths, nfft=NFFT, nint=NINT, max_frames=4)
        assert np.asarray(out).shape[1] == 4 // NINT

    def test_header_band_span(self, tmp_path):
        paths = make_scan(tmp_path)
        hdr, _ = load_scan_mesh(paths, nfft=NFFT, nint=NINT)
        # 8 contiguous banks of -187.5/8 MHz each: full span 187.5 MHz.
        span = abs(hdr["foff"]) * hdr["nchans"]
        assert span == pytest.approx(187.5)

    def test_multifile_sequence_stems(self, tmp_path):
        # Each player recorded as a 2-file .NNNN.raw sequence, passed as a
        # bare stem: the mesh reduction must equal the same recording in
        # one file per player (gap-free stitch across file boundaries).
        from blit.io.guppi import write_raw
        from blit.testing import make_raw_header, synth_raw_sequence

        nbank, bank_bw = 4, -187.5 / 4
        stems, monos = [], []
        for k in range(nbank):
            stem = str(tmp_path / f"seq{k}")
            paths, stream = synth_raw_sequence(
                stem, nfiles=2, blocks_per_file=1, obsnchan=2,
                ntime_per_block=512, seed=k, tone_chan=k % 2,
                obsbw=bank_bw, obsfreq=8000.0 + (k + 0.5) * bank_bw,
            )
            mono = str(tmp_path / f"mono{k}.raw")
            write_raw(mono, make_raw_header(
                obsnchan=2, obsbw=bank_bw,
                obsfreq=8000.0 + (k + 0.5) * bank_bw), [stream])
            stems.append(stem)
            monos.append(mono)
        _, out_seq = load_scan_mesh([stems], nfft=NFFT, nint=NINT,
                                    despike=False)
        _, out_mono = load_scan_mesh([monos], nfft=NFFT, nint=NINT,
                                     despike=False)
        np.testing.assert_array_equal(np.asarray(out_seq),
                                      np.asarray(out_mono))

    def test_ragged_rejected(self, tmp_path):
        paths = make_scan(tmp_path, 1, 8)
        with pytest.raises(ValueError, match="rectangular"):
            load_scan_mesh([paths[0], paths[0][:4]], nfft=NFFT)

    def test_short_scan_rejected(self, tmp_path):
        paths = make_scan(tmp_path, nblocks=1, ntime=128)
        with pytest.raises(ValueError, match="too short"):
            load_scan_mesh(paths, nfft=256)


class TestReviewRegressions:
    def test_single_pol_raw_supported(self, tmp_path):
        # npol from the file header, not assumed 2 (no silent broadcast).
        paths = [[None] * 8]
        for k in range(8):
            p = str(tmp_path / f"p{k}.raw")
            synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=1024,
                      seed=k, npol=1, obsbw=-187.5 / 8)
            paths[0][k] = p
        hdr, out = load_scan_mesh(paths, nfft=NFFT, nint=NINT, despike=False)
        got = np.asarray(out)
        assert got.shape[-1] == 8 * 2 * NFFT
        red = RawReducer(nfft=NFFT, nint=NINT)
        _, want0 = red.reduce(paths[0][0])
        np.testing.assert_allclose(got[0, :, :, :2 * NFFT],
                                   want0[: got.shape[1]], rtol=1e-4, atol=0.5)

    def test_dft_use_pallas_works_on_cpu(self):
        # interpret-mode plumbing: the public flag is safe off-TPU.
        from blit.ops import dft as D
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        xr = jnp.asarray(rng.standard_normal((2, 256)).astype(np.float32))
        xi = jnp.asarray(rng.standard_normal((2, 256)).astype(np.float32))
        yr, yi = D.dft(xr, xi, use_pallas=True)
        wr, wi = D.dft_np(np.asarray(xr), np.asarray(xi))
        assert np.abs(np.asarray(yr) - wr).max() < 1e-2

    def test_pick_tile_bounds_vmem(self):
        from blit.ops.pallas_dft import _pick_tile

        assert _pick_tile(1280, 512) == 256  # divisor, lane-aligned
        assert _pick_tile(96, 512) == 96     # small extents stay whole
        assert _pick_tile(1024, 512) == 512
        assert _pick_tile(997, 512) == 1     # prime: degenerate but bounded


class TestWindowChunkRows:
    def test_coprime_window_rows_warn(self, caplog):
        import logging

        from blit.parallel.scan import _bitshuffle_window_chunk_rows

        with caplog.at_level(logging.WARNING, logger="blit.scan"):
            assert _bitshuffle_window_chunk_rows(16, 5) == 1
        assert "collapse" in caplog.text  # ADVICE r5: no silent 1-row chunks

    def test_dividing_window_rows_stay_silent(self, caplog):
        import logging

        from blit.parallel.scan import _bitshuffle_window_chunk_rows

        with caplog.at_level(logging.WARNING, logger="blit.scan"):
            assert _bitshuffle_window_chunk_rows(16, 8) == 8   # divides
            assert _bitshuffle_window_chunk_rows(16, 32) == 16  # multiple
            assert _bitshuffle_window_chunk_rows(16, 16) == 16
        assert "collapse" not in caplog.text
