"""Fused detect+untwist kernel (blit/ops/pallas_detect.py), interpret mode."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops import channelize as ch  # noqa: E402
from blit.ops import dft as D  # noqa: E402
from blit.ops.pallas_detect import (  # noqa: E402
    detect_untwist_i,
    tail2_detect,
    tail2_detect_i,
)


class TestDetectUntwist:
    # (8, 32, 4) with tile_mid=16 spans mid=32 over TWO grid tiles — the
    # j index-map path the production 2^20 shape (mid=128, 8 tiles) uses;
    # tile_mid=2 forces 16 tiles over the same shape.
    @pytest.mark.parametrize("factors,tile_mid", [
        ((8, 4), 16), ((8, 4, 4), 16), ((16,), 16),
        ((8, 32, 4), 16), ((8, 32, 4), 2),
    ])
    def test_matches_untwist_then_detect(self, factors, tile_mid):
        rng = np.random.default_rng(0)
        n = int(np.prod(factors))
        nchan, npol, nframes = 2, 2, 3
        sr = rng.standard_normal((nchan, npol, nframes, n)).astype(np.float32)
        si = rng.standard_normal((nchan, npol, nframes, n)).astype(np.float32)
        got = np.asarray(detect_untwist_i(
            jnp.asarray(sr), jnp.asarray(si), factors, tile_mid=tile_mid,
            interpret=True))
        nat_r = np.asarray(D.untwist(jnp.asarray(sr), factors))
        nat_i = np.asarray(D.untwist(jnp.asarray(si), factors))
        want = (nat_r**2 + nat_i**2).sum(axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)

    def test_channelize_fused_detect_matches(self):
        rng = np.random.default_rng(4)
        nfft, ntap = 8192, 4
        v = rng.integers(-40, 40, (2, 7 * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        a = np.asarray(ch.channelize(
            jnp.asarray(v), h, nfft=nfft, nint=2, fft_method="matmul",
            pfb_kernel="fused1", detect_kernel="pallas"))
        b = np.asarray(ch.channelize(
            jnp.asarray(v), h, nfft=nfft, nint=2, fft_method="matmul",
            pfb_kernel="xla"))
        np.testing.assert_allclose(a, b, rtol=1e-4,
                                   atol=1e-2 * np.abs(b).max())

    def test_vmem_gate(self):
        from blit.ops import pallas_detect as pd

        assert pd.fits((128, 128, 64))  # the hi-res production shape
        assert pd.fits((128, 128, 1024))  # 2^24: fits by shrinking tile_mid
        # f1 and flast are untiled, so a square 1M split cannot fit.
        assert not pd.fits((1024, 1024))
        sr = jnp.zeros((1, 2, 1, 1024 * 1024), jnp.bfloat16)
        with pytest.raises(ValueError, match="VMEM"):
            detect_untwist_i(sr, sr, (1024, 1024), interpret=True)

    def test_guards(self):
        v = jnp.zeros((1, 7 * 8192, 2, 2), jnp.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, 8192))
        with pytest.raises(ValueError, match="detect_kernel"):
            ch.channelize(v, h, nfft=8192, fft_method="matmul",
                          pfb_kernel="xla", detect_kernel="pallas")
        with pytest.raises(ValueError, match="detect_kernel"):
            ch.channelize(v, h, nfft=8192, fft_method="matmul",
                          pfb_kernel="fused1", stokes="IQUV",
                          detect_kernel="pallas")


class TestTail2Detect:
    """Fully-fused tail+detect (tail2_detect_i): DFT levels 2+3, inner
    untwist, Stokes-I detection and the product transpose in one pass."""

    # (16, 8, 8) with tile_f1=8 spans f1=16 over TWO grid tiles — the j
    # index-map path the production (128, 128, 64) shape uses.  (Tiles
    # must be 8-divisible or full-f1: mosaic's sublane constraint, which
    # interpret mode does not enforce but the fit gate must.)
    @pytest.mark.parametrize("factors,tile_f1", [
        ((8, 32, 4), 16), ((8, 4, 4), 16), ((16, 8, 8), 8),
    ])
    def test_matches_tail_then_detect(self, factors, tile_f1):
        rng = np.random.default_rng(0)
        f1, f2, f3 = factors
        m = f2 * f3
        nchan, npol, nframes = 2, 2, 3
        ur = rng.standard_normal((nchan, npol, nframes, f1, m))
        ui = rng.standard_normal((nchan, npol, nframes, f1, m))
        ur = ur.astype(np.float32)
        ui = ui.astype(np.float32)
        got = np.asarray(tail2_detect_i(
            jnp.asarray(ur), jnp.asarray(ui), f2, f3, tile_f1=tile_f1,
            interpret=True))
        sr, si = D.dft_tail(jnp.asarray(ur), jnp.asarray(ui), factors)
        want = np.asarray((sr**2 + si**2).sum(axis=1))  # (chan, frame, n)
        want = want.transpose(1, 0, 2)  # frame-major product layout
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-5,
                                   atol=1e-4 * np.abs(want).max())

    @pytest.mark.parametrize("stokes", ["XX", "YY", "XXYY", "full", "IQUV"])
    def test_all_products_match_detect(self, stokes):
        from blit.ops.channelize import detect_stokes_planar

        rng = np.random.default_rng(2)
        f1, f2, f3 = 8, 32, 4
        m = f2 * f3
        nchan, npol, nframes = 2, 2, 3
        ur = rng.standard_normal((nchan, npol, nframes, f1, m))
        ui = rng.standard_normal((nchan, npol, nframes, f1, m))
        ur = ur.astype(np.float32)
        ui = ui.astype(np.float32)
        got = np.asarray(tail2_detect(
            jnp.asarray(ur), jnp.asarray(ui), f2, f3, stokes=stokes,
            interpret=True))
        sr, si = D.dft_tail(jnp.asarray(ur), jnp.asarray(ui), (f1, f2, f3))
        # dft_tail emits (nchan, npol, nframes, n) — detect's expected
        # (..., npol, nframes, n) layout — giving (nchan, nif, nframes, n).
        want = np.asarray(detect_stokes_planar(sr, si, stokes))
        want = want.transpose(2, 1, 0, 3)  # (nframes, nif, nchan, n)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-5,
                                   atol=1e-4 * np.abs(want).max())

    def test_single_pol_guard(self):
        ur = jnp.zeros((1, 1, 1, 8, 128), jnp.float32)
        with pytest.raises(ValueError, match="2 pols"):
            tail2_detect(ur, ur, 32, 4, stokes="IQUV", interpret=True)

    def test_bfloat16_input(self):
        rng = np.random.default_rng(1)
        f1, f2, f3 = 8, 32, 4
        ur = rng.standard_normal((1, 2, 2, f1, f2 * f3)).astype(np.float32)
        ui = rng.standard_normal((1, 2, 2, f1, f2 * f3)).astype(np.float32)
        ub_r = jnp.asarray(ur).astype(jnp.bfloat16)
        ub_i = jnp.asarray(ui).astype(jnp.bfloat16)
        got = np.asarray(tail2_detect_i(ub_r, ub_i, f2, f3, interpret=True))
        sr, si = D.dft_tail(jnp.asarray(ur), jnp.asarray(ui), (f1, f2, f3))
        want = np.asarray((sr**2 + si**2).sum(axis=1)).transpose(1, 0, 2)
        # bf16 inputs: ~3 decimal digits.
        np.testing.assert_allclose(got, want, rtol=0.05,
                                   atol=0.05 * np.abs(want).max())

    def test_channelize_fused_tail_detect_matches(self):
        # The only default_factors 3-factor sizes are >= 2^20; keep the
        # batch tiny so interpret mode stays fast.
        rng = np.random.default_rng(4)
        nfft, ntap = 1 << 20, 4
        v = rng.integers(-40, 40, (1, (ntap + 1) * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        a = np.asarray(ch.channelize(
            jnp.asarray(v), h, nfft=nfft, nint=2, fft_method="matmul",
            pfb_kernel="fused1", tail_kernel="pallas",
            detect_kernel="pallas"))
        b = np.asarray(ch.channelize(
            jnp.asarray(v), h, nfft=nfft, nint=2, fft_method="matmul",
            pfb_kernel="xla"))
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, rtol=1e-4,
                                   atol=1e-2 * np.abs(b).max())

    def test_channelize_fused_iquv_matches(self):
        # Full-Stokes product through the fused path ("auto" now resolves
        # to tail2_detect for every detect_stokes_planar product).
        rng = np.random.default_rng(6)
        nfft, ntap = 1 << 20, 4
        v = rng.integers(-40, 40, (1, (ntap + 1) * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        kw = dict(nfft=nfft, stokes="IQUV", fft_method="matmul")
        a = np.asarray(ch.channelize(
            jnp.asarray(v), h, pfb_kernel="fused1", tail_kernel="pallas",
            detect_kernel="pallas", **kw))
        b = np.asarray(ch.channelize(jnp.asarray(v), h, pfb_kernel="xla",
                                     **kw))
        assert a.shape == b.shape and a.shape[1] == 4
        np.testing.assert_allclose(a, b, rtol=1e-4,
                                   atol=1e-2 * np.abs(b).max())

    def test_channelize_fused_tail_detect_channel_block(self):
        # The blocked-mode assembly (lax.map + moveaxis + channel-major
        # flatten) must keep coarse channels in order.
        rng = np.random.default_rng(5)
        nfft, ntap = 1 << 20, 4
        v = rng.integers(-40, 40, (2, (ntap + 1) * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        kw = dict(nfft=nfft, fft_method="matmul", pfb_kernel="fused1",
                  tail_kernel="pallas", detect_kernel="pallas")
        a = np.asarray(ch.channelize(
            jnp.asarray(v), h, channel_block=1, **kw))
        b = np.asarray(ch.channelize(jnp.asarray(v), h, **kw))
        np.testing.assert_allclose(a, b, rtol=1e-5,
                                   atol=1e-5 * np.abs(b).max())

    def test_vmem_gate(self):
        from blit.ops import pallas_detect as pd

        # The hi-res production shape, bf16 and f32.
        assert pd.tail2_detect_fits((128, 128, 64), esize=2)
        assert pd.tail2_detect_fits((128, 128, 64), esize=4)
        assert not pd.tail2_detect_fits((128, 2048), esize=2)  # 2 factors
        assert not pd.tail2_detect_fits((1, 2048, 4096), esize=2)
        ur = jnp.zeros((1, 2, 1, 1, 2048 * 4096), jnp.bfloat16)
        with pytest.raises(ValueError, match="VMEM"):
            tail2_detect_i(ur, ur, 2048, 4096, interpret=True)

    def test_guards(self):
        v = jnp.zeros((1, 7 * 8192, 2, 2), jnp.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, 8192))
        # 8192 → two factors: the combined path is ineligible.
        with pytest.raises(ValueError, match="fused tail"):
            ch.channelize(v, h, nfft=8192, fft_method="matmul",
                          pfb_kernel="fused1", tail_kernel="pallas",
                          detect_kernel="pallas")
