"""Fused detect+untwist kernel (blit/ops/pallas_detect.py), interpret mode."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops import channelize as ch  # noqa: E402
from blit.ops import dft as D  # noqa: E402
from blit.ops.pallas_detect import detect_untwist_i  # noqa: E402


class TestDetectUntwist:
    # (8, 32, 4) with tile_mid=16 spans mid=32 over TWO grid tiles — the
    # j index-map path the production 2^20 shape (mid=128, 8 tiles) uses;
    # tile_mid=2 forces 16 tiles over the same shape.
    @pytest.mark.parametrize("factors,tile_mid", [
        ((8, 4), 16), ((8, 4, 4), 16), ((16,), 16),
        ((8, 32, 4), 16), ((8, 32, 4), 2),
    ])
    def test_matches_untwist_then_detect(self, factors, tile_mid):
        rng = np.random.default_rng(0)
        n = int(np.prod(factors))
        nchan, npol, nframes = 2, 2, 3
        sr = rng.standard_normal((nchan, npol, nframes, n)).astype(np.float32)
        si = rng.standard_normal((nchan, npol, nframes, n)).astype(np.float32)
        got = np.asarray(detect_untwist_i(
            jnp.asarray(sr), jnp.asarray(si), factors, tile_mid=tile_mid,
            interpret=True))
        nat_r = np.asarray(D.untwist(jnp.asarray(sr), factors))
        nat_i = np.asarray(D.untwist(jnp.asarray(si), factors))
        want = (nat_r**2 + nat_i**2).sum(axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)

    def test_channelize_fused_detect_matches(self):
        rng = np.random.default_rng(4)
        nfft, ntap = 8192, 4
        v = rng.integers(-40, 40, (2, 7 * nfft, 2, 2), np.int8)
        h = jnp.asarray(ch.pfb_coeffs(ntap, nfft))
        a = np.asarray(ch.channelize(
            jnp.asarray(v), h, nfft=nfft, nint=2, fft_method="matmul",
            pfb_kernel="fused1", detect_kernel="pallas"))
        b = np.asarray(ch.channelize(
            jnp.asarray(v), h, nfft=nfft, nint=2, fft_method="matmul",
            pfb_kernel="xla"))
        np.testing.assert_allclose(a, b, rtol=1e-4,
                                   atol=1e-2 * np.abs(b).max())

    def test_vmem_gate(self):
        from blit.ops import pallas_detect as pd

        assert pd.fits((128, 128, 64))  # the hi-res production shape
        assert pd.fits((128, 128, 1024))  # 2^24: fits by shrinking tile_mid
        # f1 and flast are untiled, so a square 1M split cannot fit.
        assert not pd.fits((1024, 1024))
        sr = jnp.zeros((1, 2, 1, 1024 * 1024), jnp.bfloat16)
        with pytest.raises(ValueError, match="VMEM"):
            detect_untwist_i(sr, sr, (1024, 1024), interpret=True)

    def test_guards(self):
        v = jnp.zeros((1, 7 * 8192, 2, 2), jnp.int8)
        h = jnp.asarray(ch.pfb_coeffs(4, 8192))
        with pytest.raises(ValueError, match="detect_kernel"):
            ch.channelize(v, h, nfft=8192, fft_method="matmul",
                          pfb_kernel="xla", detect_kernel="pallas")
        with pytest.raises(ValueError, match="detect_kernel"):
            ch.channelize(v, h, nfft=8192, fft_method="matmul",
                          pfb_kernel="fused1", stokes="IQUV",
                          detect_kernel="pallas")
