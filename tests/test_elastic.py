"""Elastic fleet controller (blit/serve/elastic.py; ISSUE 17
tentpole): standbys serve NOTHING until admitted, scale-out flips
membership only after the range-scoped warm handoff acks (fail-open on
the deadline, counted), sustained idle drains the coldest peer and
severs its pooled sockets with ZERO requests routed to it afterwards,
the flap guard holds membership through alternating fast-burn/idle at
the hysteresis boundary, and ``/healthz`` answers an honest
``"resizing"`` mid-flip on both the door and every publisher."""

import json
import subprocess
import sys
import time

import pytest

pytest.importorskip("jax")

from blit import monitor  # noqa: E402
from blit.monitor import (  # noqa: E402
    BurnRateEvaluator,
    MetricsPublisher,
    SLObjective,
)
from blit.observability import Timeline  # noqa: E402
from blit.serve import (  # noqa: E402
    FleetController,
    FleetFrontDoor,
    PeerServer,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.serve.cache import fingerprint_for  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NFFT = 128
NTIME = (8 + 3) * NFFT
TTL = 0.6


class ElasticFleet:
    """In-process peers + standbys + a door driven by EXPLICIT
    observe() ticks — the test_fleet_door rig grown an elastic edge."""

    def __init__(self, tmp_path, npeers=2, nstandby=1, **door_kw):
        self.lease_dir = str(tmp_path / "leases")
        self.servers = {}
        peers = {}
        names = [f"peer{i}" for i in range(npeers)]
        names += [f"standby{j}" for j in range(nstandby)]
        for i, name in enumerate(names):
            tl = Timeline()
            svc = ProductService(
                cache=ProductCache(str(tmp_path / f"cache-{name}"),
                                   ram_bytes=1 << 24, timeline=tl),
                scheduler=Scheduler(max_concurrency=2, queue_depth=8,
                                    timeline=tl, retry_seed=i),
                timeline=tl)
            ps = PeerServer(svc, name=name, lease_dir=self.lease_dir,
                            proc=i, beat_interval_s=0.05).start()
            self.servers[name] = ps
            if not name.startswith("standby"):
                peers[name] = ps.url
        kw = dict(peer_ttl_s=TTL, poll_s=0.05, health_poll_s=0.2,
                  hedge_floor_s=5.0, request_timeout_s=60.0)
        kw.update(door_kw)
        self.timeline = Timeline()
        self.door = FleetFrontDoor(peers, lease_dir=self.lease_dir,
                                   timeline=self.timeline, **kw)
        for j in range(nstandby):
            nm = f"standby{j}"
            self.door.add_standby(nm, self.servers[nm].url,
                                  proc=npeers + j)
        self.ctl = None
        # Warm the lease watches (standbys included).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            self.door.observe()
            if all(p.watch.seen for p in self.door._peers.values()):
                break
            time.sleep(0.05)

    def controller(self, evaluator=None, **kw):
        kw.setdefault("hysteresis_s", 0.0)
        kw.setdefault("warm_timeout_s", 30.0)
        kw.setdefault("min_peers", 1)
        self.ctl = FleetController(self.door, evaluator, **kw)
        return self.ctl

    def close(self):
        if self.ctl is not None:
            self.ctl.close()
        self.door.close()
        for s in self.servers.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001 — some die mid-test
                pass
            s.service.close(5)


@pytest.fixture
def efleet(tmp_path):
    f = ElasticFleet(tmp_path)
    yield f
    f.close()


def make_req(tmp_path, i=0):
    p = str(tmp_path / f"r{i}.raw")
    synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=NTIME, seed=i)
    return ProductRequest(raw=p, nfft=NFFT, nint=1)


def fp_of(req):
    return fingerprint_for(req.reducer(), req.raw_source)


def grow_until_incoming(efleet, tmp_path, joiner, want=1, cap=24):
    """Add (and serve) products until >= ``want`` of them would MOVE to
    ``joiner`` on admit — tmp_path varies per run, so the key->owner
    draw does too, and the handoff tests need a non-empty range."""
    reqs, fps = [], []
    while len(reqs) < cap:
        r = make_req(tmp_path, len(reqs))
        efleet.door.get(r)
        efleet.door.get(r)  # two hits: firmly in the door's hot map
        reqs.append(r)
        fps.append(fp_of(r))
        incoming = efleet.door.ring.incoming_keys(joiner, fps)
        if want <= len(incoming) < len(fps):
            return reqs, fps, incoming
    raise AssertionError("keyspace never gave the joiner a share")


class TestStandby:
    def test_standby_serves_nothing_until_admitted(self, efleet,
                                                   tmp_path):
        assert "standby0" not in efleet.door.ring
        for i in range(4):
            efleet.door.get(make_req(tmp_path, i))
        sb = efleet.door._peers["standby0"]
        assert sb.standby and not sb.in_ring
        assert sb.requests == 0
        assert efleet.servers["standby0"].counts["product"] == 0

    def test_standby_listed_in_health_not_a_casualty(self, efleet):
        doc = efleet.door.health()
        assert doc["ok"] and doc["status"] == "ok"
        assert "standby0" in doc.get("standbys", [])
        assert not any("standby0" in r for r in doc["reasons"])

    def test_stalled_standby_is_not_admissible(self, efleet):
        ctl = efleet.controller()
        efleet.servers["standby0"].close()  # beats stop
        time.sleep(TTL * 1.5)
        efleet.door.observe()
        assert ctl._pick_standby() is None
        assert ctl.scale_out() is None


class TestScaleOut:
    def test_warm_handoff_lands_before_the_flip(self, efleet, tmp_path):
        ctl = efleet.controller()
        reqs, fps, incoming = grow_until_incoming(
            efleet, tmp_path, "standby0")
        sb_cache = efleet.servers["standby0"].service.cache
        assert not any(sb_cache.contains(fp) for fp in incoming)
        rec = ctl.scale_out()
        assert rec["action"] == "scale-out" and rec["peer"] == "standby0"
        assert "standby0" in efleet.door.ring
        # The ack gated the flip: every incoming hot key was ALREADY
        # on the joiner when scale_out returned.
        assert rec["acked"] and rec["hinted"] == len(incoming)
        assert rec["completed"] == len(incoming)
        for fp in incoming:
            assert sb_cache.contains(fp)
        # Only the joiner's range was streamed — nothing else.
        assert rec["hinted"] < len(fps)
        c = efleet.timeline.report()
        assert c["elastic.scale_out"]["calls"] == 1
        assert "elastic.resize_s" in efleet.timeline.hists
        # The admitted peer now serves its range byte-identically.
        moved = next(r for r in reqs if fp_of(r) in set(incoming))
        before = efleet.door._peers["standby0"].requests
        efleet.door.get(moved)
        assert efleet.door._peers["standby0"].requests == before + 1

    def test_handoff_deadline_fails_open(self, efleet, tmp_path):
        # wait_s=0 burns before the joiner computes anything: the flip
        # must STILL happen (elastic capacity now beats a warm cache)
        # and the timeout must be counted.
        ctl = efleet.controller(warm_timeout_s=0.0)
        grow_until_incoming(efleet, tmp_path, "standby0")
        rec = ctl.scale_out()
        assert rec is not None and not rec["acked"]
        assert "standby0" in efleet.door.ring
        rep = efleet.timeline.report()
        assert rep["elastic.warm_timeout"]["calls"] == 1


class TestScaleIn:
    def test_sustained_idle_drains_retires_and_severs(self, efleet,
                                                      tmp_path):
        # The drained-then-removed satellite, end to end: idle ticks
        # accumulate, the coldest peer drains, leaves the ring, its
        # pooled keep-alives are severed, ZERO later requests route to
        # it, and its still-beating lease cannot rejoin it.
        reqs = [make_req(tmp_path, i) for i in range(6)]
        for r in reqs:
            efleet.door.get(r)
        ctl = efleet.controller(idle_windows=2)
        rec = None
        for _ in range(4):
            rec = ctl.observe(interval_s=30.0)
            if rec is not None:
                break
        assert rec is not None and rec["action"] == "scale-in"
        victim = rec["peer"]
        assert rec["drained"]
        assert victim not in efleet.door.ring
        p = efleet.door._peers[victim]
        assert p.retired and not p.in_ring
        # Pooled sockets for the departed peer are GONE (the stale-
        # socket satellite): no idle entry names its port.
        port = int(p.url.rsplit(":", 1)[1])
        assert not any(str(port) in k for k in efleet.door.pool.stats())
        # Zero requests to a departed peer — and no lease rejoin, even
        # though the process is alive and beating.
        before = p.requests
        for _ in range(6):
            efleet.door.observe()
            time.sleep(0.05)
        for r in reqs:
            efleet.door.get(r)
        assert p.requests == before
        assert victim not in efleet.door.ring
        rep = efleet.timeline.report()
        assert rep.get("fleet.rejoin") is None
        assert rep["elastic.scale_in"]["calls"] == 1
        assert rep["fleet.retire"]["calls"] == 1

    def test_min_peers_floor_refuses(self, efleet):
        ctl = efleet.controller(min_peers=2, idle_windows=1)
        for _ in range(4):
            assert ctl.observe(interval_s=30.0) is None
        assert ctl.scale_in() is None
        assert len(efleet.door.ring) == 2

    def test_traffic_resets_the_idle_run(self, efleet, tmp_path):
        # idle_rps=0: ANY request in the interval counts as traffic.
        ctl = efleet.controller(idle_windows=3, idle_rps=0.0)
        req = make_req(tmp_path)
        ctl.observe(interval_s=30.0)
        ctl.observe(interval_s=30.0)
        assert ctl._idle_ticks == 2
        efleet.door.get(req)  # real traffic lands mid-run
        ctl.observe(interval_s=30.0)
        assert ctl._idle_ticks == 0  # the run restarted
        assert len(efleet.door.ring) == 2


def burn_delta(bad: bool) -> Timeline:
    tl = Timeline()
    for _ in range(10):
        tl.observe("fleet.request_s", 1.0 if bad else 0.001)
    return tl


class TestHysteresisDrill:
    def test_flap_boundary_is_one_action_per_window(self, tmp_path):
        # The pinned satellite: a REAL BurnRateEvaluator fed
        # alternating fast-burn/idle intervals right at the flap
        # boundary (fast window spans one of each, so breached() stays
        # true throughout) must produce AT MOST ONE scale action per
        # hysteresis window — page -> idle -> page cannot thrash
        # membership.
        efleet = ElasticFleet(tmp_path, npeers=2, nstandby=2)
        try:
            ev = BurnRateEvaluator(
                [SLObjective("slo", "fleet.request_s", 0.5,
                             budget=0.05)],
                fast_window=2, slow_window=4, fast_burn=4.0,
                slow_burn=2.0)
            fake = [1000.0]
            ctl = efleet.controller(
                evaluator=ev, hysteresis_s=100.0, idle_windows=1,
                clock=lambda: fake[0])
            actions = []
            for i in range(10):
                ev.observe(burn_delta(bad=(i % 2 == 0)), 1.0)
                act = ctl.observe(interval_s=1.0)
                if act is not None:
                    actions.append(act)
                fake[0] += 10.0
            # 10 ticks x 10 s = exactly one hysteresis window: the
            # first page acted, everything after was suppressed.
            assert len(actions) == 1
            assert actions[0]["action"] == "scale-out"
            rep = efleet.timeline.report()
            assert rep["elastic.flap_suppressed"]["calls"] >= 8
            # The window lapses: exactly one more action fires, then
            # the guard arms again.
            fake[0] = 1000.0 + 150.0
            ev.observe(burn_delta(bad=True), 1.0)
            act = ctl.observe(interval_s=1.0)
            assert act is not None and act["action"] == "scale-out"
            ev.observe(burn_delta(bad=False), 1.0)
            assert ctl.observe(interval_s=1.0) is None  # guarded again
        finally:
            efleet.close()


class TestResizingHealth:
    def test_door_healthz_is_resizing_mid_flip(self, efleet):
        ctl = efleet.controller()
        assert efleet.door.health()["status"] == "ok"
        ctl._set_resizing("scale-out:standby0")
        doc = efleet.door.health()
        assert doc["status"] == "resizing" and not doc["ok"]
        assert "resizing:scale-out:standby0" in doc["reasons"]
        ctl._set_resizing(None)
        assert efleet.door.health()["status"] == "ok"

    def test_publisher_health_carries_the_resize(self, efleet,
                                                 tmp_path):
        # The register_health_hook satellite: EVERY publisher health
        # document in the process answers "resizing" mid-flip.
        ctl = efleet.controller()
        pub = MetricsPublisher(interval_s=999.0, timeline=Timeline(),
                               spool_dir=str(tmp_path / "spool"))
        try:
            assert pub.health()["status"] == "ok"
            ctl._set_resizing("scale-in:peer1")
            doc = pub.health()
            assert doc["status"] == "resizing" and not doc["ok"]
            assert "elastic:scale-in:peer1" in doc["reasons"]
            ctl._set_resizing(None)
            assert pub.health()["status"] == "ok"
            # close() unregisters the hook — a dead controller cannot
            # haunt later publishers.
            ctl._set_resizing("scale-out:standby0")
            ctl.close()
            efleet.ctl = None
            assert pub.health()["status"] == "ok"
        finally:
            pub.close()


class TestWarmHints:
    def test_warm_hints_are_range_scoped(self, efleet, tmp_path):
        reqs = [make_req(tmp_path, i) for i in range(4)]
        for r in reqs:
            efleet.door.get(r)
            efleet.door.get(r)
        fps = [fp_of(r) for r in reqs]
        hints = efleet.door.warm_hints(limit=10)
        assert {fp for fp, _ in hints} == set(fps)
        assert all(rec is not None for _, rec in hints)
        sub = set(fps[:2])
        scoped = efleet.door.warm_hints(in_range=lambda fp: fp in sub,
                                        limit=10)
        assert {fp for fp, _ in scoped} == sub


@pytest.mark.slow
class TestElasticCLI:
    """The REAL multi-process legs (subprocess peers, SIGTERM/SIGKILL)
    — the CI fleet-smoke job's shape, kept out of the tier-1 budget."""

    def test_serve_bench_diurnal(self, tmp_path):
        out = tmp_path / "diurnal.json"
        res = subprocess.run(
            [sys.executable, "-m", "blit", "serve-bench", "--diurnal",
             "--peers", "2", "--cycles", "2", "--requests", "24",
             "--distinct", "6", "--clients", "3", "--nfft", "128",
             "--hysteresis", "1.0", "--idle-windows", "2",
             "--out", str(out)],
            capture_output=True, text=True, timeout=600)
        assert res.returncode == 0, res.stdout + res.stderr
        rep = json.loads(out.read_text())
        assert rep["ok"] and len(rep["cycles_detail"]) == 2
        assert rep["scale_outs"] == 2 and rep["scale_ins"] == 2
        assert rep["requests_to_departed"] == 0
        assert rep["slo"]["ok"] and rep["hit_bound_ok"]

    def test_chaos_fleet_resize_drill(self, tmp_path):
        out = tmp_path / "resize.json"
        res = subprocess.run(
            [sys.executable, "-m", "blit", "chaos", "--fleet",
             "--fault", "resize", "--peers", "3",
             "--fleet-requests", "60", "--fleet-distinct", "6",
             "--nfft", "32", "--lease-ttl", "2.0",
             "--work-dir", str(tmp_path / "work"),
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=600)
        assert res.returncode == 0, res.stdout + res.stderr
        rep = json.loads(out.read_text())
        assert rep["ok"] and rep["killed_mid_handoff"]
        assert rep["resizing_status"] == "resizing"
        assert rep["flip_completed"] and rep["byte_identical"]
        assert rep["detected"] and rep["hit_rate_recovered"]
