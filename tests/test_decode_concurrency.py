"""Bitshuffle decode-pool concurrency (VERDICT r3 item 8): the GIL-free
native codec must be correct when many threads decode (and encode)
simultaneously — the property the FBH5 chunk-read pool
(blit/io/fbh5._read_bitshuffle_chunks) relies on.  The 1-core dev rig
cannot demonstrate SPEEDUP, so these tests pin CORRECTNESS under real
thread overlap and force the pool beyond one worker via cpu_count."""

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from blit.io import bshuf

pytestmark = pytest.mark.skipif(
    not bshuf.available(), reason="native bitshuffle codec not built"
)


class TestCodecThreadSafety:
    def test_parallel_roundtrips_match_serial(self):
        # 16 distinct buffers encoded+decoded on 8 threads at once; every
        # result must equal its serial twin (shared codec state or a
        # GIL-release bug would corrupt some interleaving).
        rng = np.random.default_rng(0)
        bufs = [
            rng.standard_normal(4096 + 512 * i).astype(np.float32)
            for i in range(16)
        ]
        serial = [bshuf.compress_chunk(b) for b in bufs]

        def roundtrip(b):
            payload = bshuf.compress_chunk(b)
            return payload, bshuf.decompress_chunk(
                payload, np.float32, b.size
            )

        with ThreadPoolExecutor(8) as pool:
            results = list(pool.map(roundtrip, bufs))
        for b, s, (payload, back) in zip(bufs, serial, results):
            assert payload == s  # deterministic encoding, no cross-talk
            np.testing.assert_array_equal(back, b)

    def test_parallel_decodes_of_one_payload(self):
        # Many threads decoding the SAME payload concurrently (the read
        # pool can hold several in flight for one file).
        rng = np.random.default_rng(1)
        a = rng.standard_normal(65536).astype(np.float32)
        payload = bshuf.compress_chunk(a)
        with ThreadPoolExecutor(8) as pool:
            outs = list(pool.map(
                lambda _: bshuf.decompress_chunk(payload, np.float32, a.size),
                range(32),
            ))
        for o in outs:
            np.testing.assert_array_equal(o, a)


class TestReadPoolConcurrency:
    def test_multithreaded_chunk_read_matches_data(self, tmp_path, monkeypatch):
        # Force the FBH5 decode pool past one worker (the rig has 1 core,
        # so os.cpu_count() would size it to 1 and the concurrent path
        # would never run) and read a many-chunk file back whole.
        from blit.io.fbh5 import read_fbh5_data, write_fbh5

        monkeypatch.setattr(os, "cpu_count", lambda: 4)
        rng = np.random.default_rng(2)
        data = rng.standard_normal((96, 2, 128)).astype(np.float32)
        p = str(tmp_path / "many_chunks.h5")
        write_fbh5(p, {"fch1": 1.0, "foff": -0.1}, data,
                   compression="bitshuffle", chunks=(4, 1, 32))
        # (96/4) x 2 x 4 = 192 chunks through a 4-thread decode pool.
        np.testing.assert_array_equal(read_fbh5_data(p), data)
        # Hyperslab through the same pool.
        idxs = (slice(7, 61), slice(None), slice(10, 100))
        np.testing.assert_array_equal(read_fbh5_data(p, idxs), data[idxs])

    def test_worker_error_propagates(self, tmp_path, monkeypatch):
        # A decode failure inside the pool must surface, not vanish into
        # a dropped future.
        from blit.io.fbh5 import read_fbh5_data, write_fbh5

        monkeypatch.setattr(os, "cpu_count", lambda: 4)
        rng = np.random.default_rng(3)
        data = rng.standard_normal((32, 1, 64)).astype(np.float32)
        p = str(tmp_path / "x.h5")
        write_fbh5(p, {"fch1": 1.0, "foff": -0.1}, data,
                   compression="bitshuffle", chunks=(4, 1, 64))

        import itertools

        real = bshuf.decompress_chunk
        counter = itertools.count()  # atomic under the GIL (one bytecode)

        def flaky(payload, dtype, n):
            if next(counter) == 4:
                raise ValueError("synthetic decode failure")
            return real(payload, dtype, n)

        monkeypatch.setattr(bshuf, "decompress_chunk", flaky)
        with pytest.raises(ValueError, match="synthetic decode failure"):
            read_fbh5_data(p)
