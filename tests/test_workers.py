"""Worker-function semantics parity (src/gbtworkerfunctions.jl:131-202)."""

import numpy as np
import pytest

from blit import testing, workers
from blit.config import nfpc_from_foff
from blit.ops.despike import despike


@pytest.fixture()
def fil_file(tmp_path):
    p = str(tmp_path / "x.fil")
    hdr, data = testing.synth_fil(p, nsamps=32, nifs=2, nchans=64)
    return p, hdr, data


@pytest.fixture()
def fbh5_file(tmp_path):
    p = str(tmp_path / "x.h5")
    hdr, data = testing.synth_fbh5(p, nsamps=32, nifs=2, nchans=64)
    return p, hdr, data


def test_sanitize_idxs():
    out = workers.sanitize_idxs((3, slice(None), slice(1, 5)))
    assert out == (slice(3, 4), slice(None), slice(1, 5))


def test_get_fb_header_normalized(fil_file):
    p, hdr, data = fil_file
    h = workers.get_fb_header(p)
    assert h["nfpc"] == nfpc_from_foff(hdr["foff"])
    assert "header_size" not in h and "sample_size" not in h
    assert h["data_size"] == data.nbytes
    assert h["nsamps"] == 32
    assert list(h) == sorted(h)


def test_get_header_dispatch(fil_file, fbh5_file):
    pf, _, _ = fil_file
    ph, _, _ = fbh5_file
    assert workers.get_header(pf)["nchans"] == 64
    assert workers.get_header(ph)["nchans"] == 64


def test_get_data_always_3d(fbh5_file):
    p, _, data = fbh5_file
    out = workers.get_data(p, (5, 0, slice(None)))
    assert out.shape == (1, 1, 64)  # ints became length-1 slices
    np.testing.assert_array_equal(out[0, 0], data[5, 0])


def test_get_data_fqav_fil_vs_fbh5(fil_file, fbh5_file):
    pf, _, df = fil_file
    ph, _, dh = fbh5_file
    of = workers.get_data(pf, fqav_by=8)
    oh = workers.get_data(ph, fqav_by=8)
    assert of.shape == oh.shape == (32, 2, 8)
    np.testing.assert_allclose(of, df.reshape(32, 2, 8, 8).sum(-1), rtol=1e-6)
    np.testing.assert_allclose(of, oh, rtol=1e-6)


def test_get_data_fqav_func_mean(fbh5_file):
    p, _, data = fbh5_file
    out = workers.get_data(p, fqav_by=4, fqav_func=np.mean)
    np.testing.assert_allclose(out, data.reshape(32, 2, 16, 4).mean(-1), rtol=1e-6)


def test_get_kurtosis_shape_and_transpose(fbh5_file):
    p, _, data = fbh5_file
    k = workers.get_kurtosis(p)
    assert k.shape == (64, 2)  # (nchan, nifs) — reference indexing parity
    import scipy.stats

    want = scipy.stats.kurtosis(data, axis=0, fisher=True, bias=True).T
    np.testing.assert_allclose(k, want, rtol=1e-5)


def test_get_freq_axis(fbh5_file):
    p, hdr, _ = fbh5_file
    h = workers.get_header(p)
    fch1, foff, n = workers.get_freq_axis(h, fqav_by=8)
    assert n == 8
    assert foff == pytest.approx(8 * hdr["foff"])


def test_despike():
    nfpc = 8
    data = np.ones((2, 1, 32), dtype=np.float32)
    spike = nfpc // 2
    data[:, :, spike::nfpc] = 99.0
    out = despike(data, nfpc)
    assert (out == 1.0).all()
    assert (data[:, :, spike::nfpc] == 99.0).all()  # input untouched


def test_despike_jax():
    import jax.numpy as jnp

    nfpc = 4
    data = jnp.arange(16.0).reshape(1, 1, 16)
    out = despike(data, nfpc)
    np.testing.assert_array_equal(
        np.asarray(out[0, 0]), [0, 1, 1, 3, 4, 5, 5, 7, 8, 9, 9, 11, 12, 13, 13, 15]
    )


def test_despike_invalid():
    with pytest.raises(ValueError):
        despike(np.zeros((1, 1, 10)), 4)
