"""The chaos fault modes (ISSUE 12): ``kill`` / ``hang`` in the
BLIT_FAULTS grammar, with injectable kill/sleep so nothing here
actually dies or waits."""

import pytest

from blit import faults
from blit.faults import FaultRule


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


class TestKillMode:
    def test_kill_invokes_the_injectable(self):
        hits = []
        faults.install(FaultRule("mesh.window", "kill", after=1,
                                 kill=lambda: hits.append(1)))
        faults.fire("mesh.window", key="w0")  # after=1: first hit passes
        assert hits == []
        faults.fire("mesh.window", key="w1")
        assert hits == [1]
        assert faults.counters()["fault.mesh.window.kill"] == 1

    def test_match_targets_one_window(self):
        hits = []
        faults.install(FaultRule("mesh.window", "kill", match="w3",
                                 kill=lambda: hits.append(1)))
        for w in range(3):
            faults.fire("mesh.window", key=f"w{w}")
        assert hits == []
        faults.fire("mesh.window", key="w3")
        assert hits == [1]


class TestHangMode:
    def test_hang_sleeps_hang_s_not_delay_s(self):
        slept = []
        faults.install(FaultRule("stream.chunk", "hang", hang_s=42.0,
                                 sleep=slept.append))
        faults.fire("stream.chunk", key="s#0")
        assert slept == [42.0]
        assert faults.counters()["fault.stream.chunk.hang"] == 1

    def test_default_hang_outlasts_any_watchdog(self):
        slept = []
        faults.install(FaultRule("mesh.window", "hang",
                                 sleep=slept.append))
        faults.fire("mesh.window")
        assert slept == [3600.0]


class TestSpecGrammar:
    def test_parse_kill_and_hang(self):
        rules = faults.parse_spec(
            "mesh.window:kill:after=2;stream.chunk:hang:hang=7.5")
        assert rules[0].mode == "kill" and rules[0].after == 2
        assert rules[1].mode == "hang" and rules[1].hang_s == 7.5

    def test_unknown_mode_still_refused(self):
        with pytest.raises(ValueError):
            faults.parse_spec("mesh.window:explode")
