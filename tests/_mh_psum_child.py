"""Child process for the 2-process psum-product pod tests
(tests/test_multiprocess.py) — VERDICT r3 item 6: beamform and the FX
correlator executed under ``jax.distributed`` with 2 gloo processes,
where a sharding mistake becomes a cross-process deadlock instead of a
wrong answer.

Run as: ``python tests/_mh_psum_child.py <pid> <nproc> <port> [outdir]``
(outdir accepted for harness uniformity, unused).

Each child builds the SAME tiny deterministic problem from a seeded rng,
places its addressable shards via ``make_array_from_callback``, runs both
collectives, and asserts its local shards against the NumPy goldens.
"""

import sys


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    outdir = sys.argv[4] if len(sys.argv) > 4 else None
    import jax

    jax.config.update("jax_platforms", "cpu")

    from blit.parallel.multihost import init_multihost

    active = init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cpu_collectives="gloo",
    )
    assert active and jax.process_count() == nproc

    if outdir:
        # Bring-up barrier marker (tests/test_multiprocess.py).
        from blit.testing import signal_ready

        signal_ready(outdir, pid)

    import numpy as np

    from blit.ops.channelize import pfb_coeffs
    from blit.parallel.beamform import (
        antenna_sharding,
        beamform,
        beamform_np,
        weight_sharding,
    )
    from blit.parallel.correlator import (
        correlate,
        correlate_np,
        correlator_sharding,
        visibility_sharding,
    )
    from blit.parallel.mesh import make_mesh

    # The pod harness gives each of the 2 processes 4 virtual devices; the
    # mesh must span ALL of them or one process owns no addressable shard.
    NBAND, NBANK = 2, 4
    NANT, NBEAM, NCHAN, NTIME, NPOL = 4, 3, 4, 128, 2
    NFFT, NTAP, NINT = 16, 4, 2
    mesh = make_mesh(NBAND, NBANK)
    rng = np.random.default_rng(7)

    def put(arr, sharding):
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    # --- Config 4: tied-array beamform (psum over the antenna axis) -----
    v = (rng.standard_normal((NANT, NCHAN, NTIME, NPOL))
         + 1j * rng.standard_normal((NANT, NCHAN, NTIME, NPOL))
         ).astype(np.complex64)
    w = (rng.standard_normal((NBEAM, NANT, NCHAN))
         + 1j * rng.standard_normal((NBEAM, NANT, NCHAN))
         ).astype(np.complex64)
    # Antennas sharded over BAND: with this harness's device order each
    # band row is wholly owned by one process, so only the band axis
    # crosses the gloo boundary — the antenna psum must ride it or the
    # test never exercises a cross-process collective.
    vs = antenna_sharding(mesh, axis="band")
    ws = weight_sharding(mesh, axis="band")
    power = beamform(
        (put(v.real.astype(np.float32), vs), put(v.imag.astype(np.float32), vs)),
        (put(w.real.astype(np.float32), ws), put(w.imag.astype(np.float32), ws)),
        mesh=mesh, axis="band", nint=NINT,
    )
    golden = beamform_np(v, w, nint=NINT)
    for s in power.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(s.data), golden[s.index], rtol=1e-4, atol=1e-3
        )

    # --- Config 5: FX correlator (psum over the band/time axis) --------
    cv = (rng.standard_normal((NANT, NCHAN, NTIME, NPOL))
          + 1j * rng.standard_normal((NANT, NCHAN, NTIME, NPOL))
          ).astype(np.complex64)
    coeffs = pfb_coeffs(NTAP, NFFT).astype(np.float32)
    cs = correlator_sharding(mesh)
    visr, visi = correlate(
        (put(cv.real.astype(np.float32), cs), put(cv.imag.astype(np.float32), cs)),
        jax.numpy.asarray(coeffs), mesh=mesh, nfft=NFFT, ntap=NTAP,
    )
    gvis = correlate_np(cv, coeffs, NFFT, NTAP, nsegments=NBAND)
    assert visr.sharding.is_equivalent_to(
        visibility_sharding(mesh), visr.ndim
    )
    for s in visr.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(s.data), gvis.real[s.index], rtol=1e-3, atol=1e-2
        )
    for s in visi.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(s.data), gvis.imag[s.index], rtol=1e-3, atol=1e-2
        )

    print("CHILD-PSUM-OK", flush=True)


if __name__ == "__main__":
    main()
