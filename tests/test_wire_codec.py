"""The binary product wire (ISSUE 16): ``application/x-blit-product``
round-trips byte-exact across dtypes/shapes/endianness, rejects
malformed frames with :class:`WireError`, stays bit-identical to the
legacy JSON+base64 wire, and the encoded-body cache tier serves/spills/
CRC-guards the framed bytes."""

import zlib

import numpy as np
import pytest

pytest.importorskip("jax")

from blit.observability import Timeline  # noqa: E402
from blit.serve import ProductCache  # noqa: E402
from blit.serve.http import (  # noqa: E402
    WIRE_MAGIC,
    WIRE_MAX_META,
    WireError,
    decode_product,
    decode_product_wire,
    encode_product,
    encode_product_parts,
    encode_product_wire,
    wants_binary_product,
)

HDR = {"nchans": 4, "tsamp": 1e-5, "src": "unit"}


class TestWireRoundTrip:
    @pytest.mark.parametrize("dtype", [
        np.float32, np.float64, np.int16, np.uint8, np.complex64,
    ])
    def test_dtypes_byte_exact(self, dtype):
        data = (np.arange(24).reshape(2, 3, 4) * 0.37).astype(dtype)
        h2, d2 = decode_product_wire(encode_product_wire(HDR, data))
        assert h2 == HDR
        assert d2.dtype == data.dtype
        assert d2.shape == data.shape
        assert d2.tobytes() == data.tobytes()
        assert not d2.flags.writeable  # the frozen-result contract

    def test_big_endian_carried_explicitly(self):
        # Endianness rides in the frame's dtype string (">f4"), not in
        # any ambient convention: a big-endian array decodes back
        # big-endian, byte-for-byte.
        data = np.arange(12, dtype=">f4").reshape(3, 4)
        h2, d2 = decode_product_wire(encode_product_wire(HDR, data))
        assert d2.dtype.str == ">f4"
        assert d2.tobytes() == data.tobytes()

    def test_zero_length(self):
        data = np.zeros((0, 7), dtype=np.float32)
        _, d2 = decode_product_wire(encode_product_wire(HDR, data))
        assert d2.shape == (0, 7)
        assert d2.nbytes == 0

    def test_non_c_order_input(self):
        # Fortran-order input is re-laid C-order on encode; the decoded
        # VALUES are identical even though the original buffer isn't.
        data = np.asfortranarray(
            np.arange(24, dtype=np.float32).reshape(4, 6))
        _, d2 = decode_product_wire(encode_product_wire(HDR, data))
        assert np.array_equal(d2, data)

    def test_header_numpy_scalars_become_plain_json(self):
        hdr = {"foff": np.float64(-2.9), "nbits": np.int32(32)}
        h2, _ = decode_product_wire(
            encode_product_wire(hdr, np.ones(3, np.float32)))
        assert h2 == {"foff": -2.9, "nbits": 32}

    def test_deflate_roundtrip(self):
        data = np.zeros((64, 64), dtype=np.float32)  # compressible
        body = encode_product_wire(HDR, data, deflate=True)
        assert len(body) < data.nbytes
        _, d2 = decode_product_wire(body, encoding="deflate")
        assert d2.tobytes() == data.tobytes()

    def test_parts_concatenation_equals_whole_frame(self):
        # The zero-copy server path writes (prefix, memoryview) — their
        # concatenation must be the exact frame the one-shot encoder
        # produces.
        data = np.arange(10, dtype=np.float32)
        prefix, payload = encode_product_parts(HDR, data)
        assert prefix + bytes(payload) == encode_product_wire(HDR, data)


class TestWireRejections:
    def frame(self):
        return encode_product_wire(HDR, np.ones((2, 3), np.float32))

    def test_bad_magic(self):
        buf = b"XXXX" + self.frame()[4:]
        with pytest.raises(WireError):
            decode_product_wire(buf)

    def test_truncated_prefix(self):
        with pytest.raises(WireError):
            decode_product_wire(self.frame()[:6])

    def test_truncated_payload(self):
        with pytest.raises(WireError):
            decode_product_wire(self.frame()[:-4])

    def test_trailing_garbage_rejected(self):
        with pytest.raises(WireError):
            decode_product_wire(self.frame() + b"\x00")

    def test_oversized_meta(self):
        buf = (WIRE_MAGIC
               + (WIRE_MAX_META + 1).to_bytes(4, "big") + b"{}")
        with pytest.raises(WireError):
            decode_product_wire(buf)

    def test_bad_deflate_body(self):
        with pytest.raises(WireError):
            decode_product_wire(b"not deflate at all",
                                encoding="deflate")

    def test_negotiation_predicate(self):
        assert wants_binary_product(
            "application/x-blit-product, application/json")
        assert not wants_binary_product("application/json")
        assert not wants_binary_product(None)


class TestJsonBinaryCrossCompat:
    def test_both_wires_decode_identically(self):
        # The acceptance pin: a binary-wire response must be
        # byte-identical (values, dtype, shape, header) to what the
        # legacy JSON+base64 wire delivers for the same product.
        data = (np.arange(60).reshape(3, 4, 5) * 0.11).astype(
            np.float32)
        hj, dj = decode_product(encode_product(HDR, data))
        hb, db = decode_product_wire(encode_product_wire(HDR, data))
        assert hj == hb
        assert dj.dtype == db.dtype
        assert dj.shape == db.shape
        assert dj.tobytes() == db.tobytes()


class TestWireCacheTier:
    def make(self, tmp_path, ram_bytes=1 << 20):
        return ProductCache(str(tmp_path / "c"), ram_bytes=ram_bytes,
                            timeline=Timeline())

    def body(self, seed=0, n=64):
        return encode_product_wire(
            HDR, np.full(n, seed, dtype=np.float32))

    def test_ram_hit_and_counters(self, tmp_path):
        c = self.make(tmp_path)
        c.put_wire("fp1", self.body(1))
        body, tier = c.get_wire("fp1")
        assert tier == "ram"
        assert body == self.body(1)
        s = c.stats()
        assert s["hit.wire"] == 1
        assert s["hit.ram"] >= 1

    def test_miss_returns_none_uncounted(self, tmp_path):
        c = self.make(tmp_path)
        assert c.get_wire("nope") is None
        assert c.stats().get("miss", 0) == 0  # caller's get() counts

    def test_disk_spill_and_promotion(self, tmp_path):
        c = self.make(tmp_path)
        c.put_wire("fp1", self.body(1))
        with c._lock:  # drop the RAM copy, keep the .wire file
            c._wire.pop("fp1")
            c._wire_used = 0
        body, tier = c.get_wire("fp1")
        assert tier == "disk"
        assert body == self.body(1)
        # Promoted: the next hit is RAM.
        assert c.get_wire("fp1")[1] == "ram"

    def test_corrupt_wire_file_evicted_not_served(self, tmp_path):
        c = self.make(tmp_path)
        c.put_wire("fp1", self.body(1))
        with c._lock:
            c._wire.pop("fp1")
            c._wire_used = 0
        p = c.wire_path("fp1")
        blob = bytearray(open(p, "rb").read())
        blob[len(blob) // 2] ^= 0xFF  # flip a payload bit
        open(p, "wb").write(bytes(blob))
        assert c.get_wire("fp1") is None
        assert c.stats()["evict.corrupt"] >= 1
        import os

        assert not os.path.exists(p)

    def test_crc_footer_is_crc32(self, tmp_path):
        c = self.make(tmp_path)
        c.put_wire("fp1", self.body(1))
        blob = open(c.wire_path("fp1"), "rb").read()
        body, crc = blob[:-4], int.from_bytes(blob[-4:], "big")
        assert body == self.body(1)
        assert crc == (zlib.crc32(body) & 0xFFFFFFFF)

    def test_wire_never_displaces_products(self, tmp_path):
        # The wire tier shares the RAM budget but is always the first
        # evicted and never pushes a product out.
        c = self.make(tmp_path, ram_bytes=4096)
        arr = np.zeros(512, dtype=np.float32)  # 2048 B
        c.put("prod1", dict(HDR), arr)
        big = b"x" * 3000  # cannot fit beside the product
        c.put_wire("fpw", big)
        assert c.get("prod1") is not None  # product survived
        s = c.stats()
        assert s["ram_entries"] == 1
        assert s["wire_bytes_used"] + s["ram_bytes_used"] <= 4096

    def test_clear_drops_wire_tier(self, tmp_path):
        c = self.make(tmp_path)
        c.put_wire("fp1", self.body(1))
        c.clear()
        assert c.stats()["wire_entries"] == 0
        import os

        assert not os.path.exists(c.wire_path("fp1"))
