"""CLI surface (python -m blit): reduce / inventory / info."""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit.__main__ import main  # noqa: E402
from blit.testing import build_observation_tree, synth_raw, synth_raw_sequence  # noqa: E402


def run(capsys, *argv):
    rc = main(list(argv))
    out = capsys.readouterr().out
    return rc, out


class TestReduce:
    def test_reduce_single_file(self, tmp_path, capsys):
        raw = str(tmp_path / "x.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=1024,
                  tone_chan=1)
        out = str(tmp_path / "x.fil")
        rc, txt = run(capsys, "reduce", raw, "-o", out, "--nfft", "64",
                      "--nint", "2")
        assert rc == 0
        rep = json.loads(txt)
        assert rep["output"] == out and rep["nsamps"] > 0
        from blit.io.sigproc import read_fil_data

        hdr, data = read_fil_data(out)
        assert np.asarray(data).shape == (rep["nsamps"], 1, rep["nchans"])

    def test_reduce_sequence_stem_resume(self, tmp_path, capsys):
        stem = str(tmp_path / "seq")
        synth_raw_sequence(stem, nfiles=2, blocks_per_file=1, obsnchan=2,
                           ntime_per_block=1024)
        out = str(tmp_path / "seq.fil")
        rc, txt = run(capsys, "reduce", stem, "-o", out, "--nfft", "64",
                      "--resume")
        assert rc == 0 and json.loads(txt)["nsamps"] > 0

    def test_reduce_product_preset(self, tmp_path, capsys):
        raw = str(tmp_path / "p.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=4096)
        out = str(tmp_path / "p.fil")
        rc, txt = run(capsys, "reduce", raw, "-o", out, "--product", "0001")
        assert rc == 0
        assert json.loads(txt)["nchans"] == 2 * 8  # 0001: nfft=8


def test_product_choices_mirror_presets():
    # _PRODUCTS is hardcoded so light subcommands skip the jax import;
    # this pin keeps it in lockstep with the real preset table.
    from blit.__main__ import _PRODUCTS
    from blit.pipeline import PRODUCT_PRESETS

    assert tuple(sorted(PRODUCT_PRESETS)) == _PRODUCTS


class TestInventoryInfo:
    def test_inventory_jsonl_and_sequences(self, tmp_path, capsys):
        root = str(tmp_path / "datax")
        build_observation_tree(root, kind="raw", players=((0, 0), (0, 1)))
        rc, txt = run(capsys, "inventory", root, "--file-re", r"\.raw$")
        assert rc == 0
        recs = [json.loads(l) for l in txt.strip().splitlines()]
        assert len(recs) == 2 and all(r["session"] for r in recs)
        rc, txt = run(capsys, "inventory", root, "--file-re", r"\.raw$",
                      "--sequences")
        seqs = [json.loads(l) for l in txt.strip().splitlines()]
        assert len(seqs) == 2 and all(len(s["files"]) == 1 for s in seqs)

    def test_info_raw_and_fil(self, tmp_path, capsys):
        raw = str(tmp_path / "i.raw")
        synth_raw(raw, nblocks=3, obsnchan=4, ntime_per_block=256)
        rc, txt = run(capsys, "info", raw)
        hdr = json.loads(txt)
        assert rc == 0 and hdr["OBSNCHAN"] == 4 and hdr["_nblocks"] == 3

        from blit.testing import synth_fil

        fil = str(tmp_path / "i.fil")
        synth_fil(fil, nchans=8)
        rc, txt = run(capsys, "info", fil)
        assert rc == 0 and json.loads(txt)["nchans"] == 8


class TestScanCommand:
    def test_scan_produces_per_band_products(self, tmp_path, capsys):
        root = str(tmp_path / "datax")
        build_observation_tree(
            root, kind="raw", players=((0, 0), (0, 1)), nchans=2,
            nfiles=2, raw_ntime=512,
        )
        rc, txt = run(capsys, "scan", root, "AGBT22B_999_01", "0011",
                      "-o", str(tmp_path), "--nfft", "64", "--nint", "2",
                      "--window-frames", "4")
        assert rc == 0
        rows = [r for r in (json.loads(l) for l in txt.strip().splitlines())
                if "band" in r]  # final line is the stages stats report
        assert [r["band"] for r in rows] == [0]
        from blit.io.sigproc import read_fil_data

        hdr, data = read_fil_data(rows[0]["output"])
        assert hdr["nchans"] == rows[0]["nchans"] == 2 * 2 * 64
        assert data.shape[0] == rows[0]["nsamps"] > 0

    def test_scan_default_window_is_bounded(self, tmp_path, capsys):
        # `blit scan` must NOT default to one whole-scan device window
        # (VERDICT r4 weak item 6): the default is the HBM-safe budget of
        # 8*2^20 samples' worth of frames, and the stats line reports it.
        from blit.config import default_window_frames

        assert default_window_frames(1 << 20) == 8  # hi-res preset
        assert default_window_frames(1 << 10) == 8 << 10
        assert default_window_frames(1 << 24) == 8  # floor: whole frames

        root = str(tmp_path / "datax")
        build_observation_tree(
            root, kind="raw", players=((0, 0), (0, 1)), nchans=2,
            nfiles=2, raw_ntime=512,
        )
        rc, txt = run(capsys, "scan", root, "AGBT22B_999_01", "0011",
                      "-o", str(tmp_path), "--nfft", "64", "--nint", "2")
        assert rc == 0
        stats = json.loads(txt.strip().splitlines()[-1])
        # The stats line reports the EFFECTIVE window: default rounded to
        # a multiple of nint (the library's rounding).
        assert stats["window_frames"] == \
            (default_window_frames(64) // 2) * 2

    def test_scan_stats_line_reports_stages(self, tmp_path, capsys):
        # The mesh writer is observable (VERDICT r4 weak item 4): the CLI
        # prints per-stage throughput like `blit reduce` does.
        root = str(tmp_path / "datax")
        build_observation_tree(
            root, kind="raw", players=((0, 0), (0, 1)), nchans=2,
            nfiles=2, raw_ntime=512,
        )
        rc, txt = run(capsys, "scan", root, "AGBT22B_999_01", "0011",
                      "-o", str(tmp_path), "--nfft", "64", "--nint", "2",
                      "--window-frames", "4")
        assert rc == 0
        stats = json.loads(txt.strip().splitlines()[-1])["stages"]
        for stage in ("read", "dispatch", "device", "readback", "write"):
            assert stats[stage]["calls"] > 0, stage
        assert stats["read"]["bytes"] > 0
        assert stats["write"]["bytes"] > 0
        assert stats["readback"]["bytes"] == stats["write"]["bytes"]

    def test_scan_resume_bitshuffle_h5(self, tmp_path, capsys):
        # `blit scan --resume --compression bitshuffle` (VERDICT r4 item 3
        # done-criterion): resumable native-format products from the CLI.
        pytest.importorskip("blit.io.bshuf").available() or pytest.skip(
            "native codec unbuilt")
        from blit.io.fbh5 import read_fbh5_data

        root = str(tmp_path / "datax")
        build_observation_tree(
            root, kind="raw", players=((0, 0), (0, 1)), nchans=2,
            nfiles=2, raw_ntime=512,
        )
        args = ("scan", root, "AGBT22B_999_01", "0011",
                "-o", str(tmp_path), "--nfft", "64", "--nint", "2",
                "--window-frames", "4", "--compression", "bitshuffle",
                "--resume")
        rc, txt = run(capsys, *args)
        assert rc == 0
        rows = [json.loads(l) for l in txt.strip().splitlines()]
        out = rows[0]["output"]
        assert out.endswith(".h5")
        data = read_fbh5_data(out)
        assert data.shape[0] == rows[0]["nsamps"] > 0
        assert not (tmp_path / "band0.h5.cursor").exists()
        # Idempotent re-run (completed product, no cursor): full re-reduce
        # to the same payload.
        rc2, txt2 = run(capsys, *args)
        assert rc2 == 0
        np.testing.assert_array_equal(read_fbh5_data(out), data)
