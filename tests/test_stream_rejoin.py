"""Live-session rejoin (ISSUE 12): StreamCursor + the resume legs of
``stream_reduce`` / ``stream_search``.

The contract: a consumer that crashes mid-session and restarts with
``resume=True`` re-attaches to the still-recording session and finishes
a product BYTE-IDENTICAL to a never-restarted consumer — including
re-masking seats the pre-crash watermark masked, even when their data
exists on disk by the time the rejoin re-reads the session."""

import os

import pytest

jax = pytest.importorskip("jax")

from blit import faults  # noqa: E402
from blit.io.guppi import open_raw  # noqa: E402
from blit.pipeline import RawReducer  # noqa: E402
from blit.stream import (  # noqa: E402
    QueueSource,
    ReplaySource,
    StreamCursor,
    chunks_of,
    stream_reduce,
    stream_search,
)
from blit.testing import synth_raw  # noqa: E402

NFFT, CF = 32, 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


def _recording(tmp_path, name="r.raw", nblocks=4, seed=1):
    p = str(tmp_path / name)
    synth_raw(p, nblocks=nblocks, obsnchan=2, ntime_per_block=512,
              seed=seed)
    return p


def _bytes(path):
    with open(path, "rb") as f:
        return f.read()


def _kw():
    return dict(nfft=NFFT, chunk_frames=CF, tune_online=False)


class TestStreamCursor:
    def test_save_load_round_trip(self, tmp_path):
        out = str(tmp_path / "x.fil")
        cur = StreamCursor(path="sess.raw", kind="filterbank", nfft=NFFT,
                           frames_done=12, masked_chunks=[1, 3])
        cur.save(out)
        back = StreamCursor.load(out)
        assert back == cur
        assert StreamCursor.path_for(out).endswith(".stream-cursor")

    def test_matches_binds_session_and_knobs(self, tmp_path):
        red = RawReducer(**_kw())
        cur = StreamCursor.fresh(red, "sess.raw", "filterbank")
        assert cur.matches(red, "sess.raw", "filterbank")
        assert not cur.matches(red, "other.raw", "filterbank")
        assert not cur.matches(red, "sess.raw", "hits")
        other = RawReducer(nfft=NFFT * 2, chunk_frames=CF,
                           tune_online=False)
        assert not cur.matches(other, "sess.raw", "filterbank")

    def test_hits_claim_ledger(self):
        class _R:
            nfft, ntap, nint = NFFT, 4, 1
            stokes, window, fqav_by, dtype = "I", "hamming", 1, "float32"
            nbits = 32
            window_spectra, top_k = 4, 4
            snr_threshold, max_drift_bins = 2.0, None

        cur = StreamCursor.fresh(_R(), "s.raw", "hits")
        cur.window_claims = [[1, 100, 2], [2, 150, 3]]
        cur.windows_done, cur.byte_offset, cur.hits_done = 2, 150, 3
        assert cur.claim_at(2) == (150, 3)
        assert cur.claim_at(1) == (100, 2)
        assert cur.claim_at(5) is None
        # A trimmed ledger (bounded per-append I/O) resolves only what
        # it still holds — older windows mean a fresh restart, never a
        # wrong offset.
        del cur.window_claims[0]
        assert cur.claim_at(1) is None


class TestFilterbankRejoin:
    def test_crash_and_rejoin_byte_identical_to_batch(self, tmp_path):
        raw = _recording(tmp_path)
        oracle = str(tmp_path / "o.fil")
        RawReducer(**_kw()).reduce_to_file(raw, oracle)
        out = str(tmp_path / "s.fil")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            stream_reduce(ReplaySource(raw, rate=10000), out,
                          resume=True, **_kw())
        faults.clear()
        cur = StreamCursor.load(out)
        assert cur is not None and cur.frames_done > 0
        claimed = cur.frames_done
        hdr = stream_reduce(ReplaySource(raw, rate=10000), out,
                            resume=True, **_kw())
        assert hdr["nsamps"] * 1 >= claimed
        assert _bytes(out) == _bytes(oracle)
        assert StreamCursor.load(out) is None  # completeness marker

    def test_identity_mismatch_restarts_fresh(self, tmp_path):
        raw = _recording(tmp_path)
        out = str(tmp_path / "s.fil")
        # A cursor from a DIFFERENT config must not be spliced into.
        stale = StreamCursor(path=raw, kind="filterbank", nfft=NFFT * 2,
                             frames_done=8)
        stale.save(out)
        with open(out, "wb") as f:
            f.write(b"junk")
        oracle = str(tmp_path / "o.fil")
        RawReducer(**_kw()).reduce_to_file(raw, oracle)
        stream_reduce(ReplaySource(raw, rate=10000), out, resume=True,
                      **_kw())
        assert _bytes(out) == _bytes(oracle)

    def test_claim_past_eof_restarts_fresh(self, tmp_path):
        # The resume_fil_ok guard on the stream path: a cursor claiming
        # more bytes than the product holds would NUL-hole-extend under
        # truncate — must restart fresh instead.
        raw = _recording(tmp_path)
        oracle = str(tmp_path / "o.fil")
        RawReducer(**_kw()).reduce_to_file(raw, oracle)
        out = str(tmp_path / "s.fil")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            stream_reduce(ReplaySource(raw, rate=10000), out,
                          resume=True, **_kw())
        faults.clear()
        size = os.path.getsize(out)
        with open(out, "r+b") as f:
            f.truncate(size - 64)  # eat claimed bytes
        stream_reduce(ReplaySource(raw, rate=10000), out, resume=True,
                      **_kw())
        assert _bytes(out) == _bytes(oracle)

    def test_clean_run_with_resume_leaves_no_sidecar(self, tmp_path):
        raw = _recording(tmp_path)
        out = str(tmp_path / "s.fil")
        oracle = str(tmp_path / "o.fil")
        RawReducer(**_kw()).reduce_to_file(raw, oracle)
        stream_reduce(ReplaySource(raw, rate=10000), out, resume=True,
                      **_kw())
        assert _bytes(out) == _bytes(oracle)
        assert not os.path.exists(StreamCursor.path_for(out))


class TestMaskStateRejoin:
    def _queue(self, raw, seqs, total):
        src = QueueSource(path=raw)
        chunks = chunks_of(open_raw(raw))
        for c in chunks:
            if c.seq in seqs:
                src.push(c)
        src.finish(total)
        return src, len(chunks)

    def test_premasked_seat_stays_masked_when_data_appears(
            self, tmp_path):
        # Run A (never restarted): chunk 1 never arrives — masked.
        # Run B: crash after the mask was claimed, then rejoin against a
        # session where chunk 1's data NOW exists.  The rejoin must
        # re-mask seat 1 (zero weight) and count the data late —
        # producing run A's exact bytes.
        raw = _recording(tmp_path, nblocks=4)
        total = len(chunks_of(open_raw(raw)))
        seqs_missing_1 = {s for s in range(total)} - {1}

        oracle = str(tmp_path / "never_restarted.fil")
        src, _ = self._queue(raw, seqs_missing_1, total)
        hdr_a = stream_reduce(src, oracle, lateness_s=0.01, **_kw())
        assert hdr_a["stream_masked_chunks"] == 1

        out = str(tmp_path / "rejoined.fil")
        src, _ = self._queue(raw, seqs_missing_1, total)
        faults.install_spec("sink.write:fail:after=4")
        with pytest.raises(OSError):
            stream_reduce(src, out, lateness_s=0.01, resume=True,
                          **_kw())
        faults.clear()
        cur = StreamCursor.load(out)
        assert cur is not None
        assert cur.masked_chunks == [1], (
            "the mask must ride the durable claim")

        # The rejoin session has EVERY chunk (the recorder caught up).
        src, _ = self._queue(raw, set(range(total)), total)
        hdr_b = stream_reduce(src, out, lateness_s=5.0, resume=True,
                              **_kw())
        assert hdr_b["stream_masked_chunks"] == 1
        assert hdr_b["stream_late_chunks"] >= 1  # seat-1 data dropped
        assert _bytes(out) == _bytes(oracle)


class TestHitsRejoin:
    def _search_kw(self):
        return dict(nfft=NFFT, window_spectra=4, top_k=4,
                    snr_threshold=2.0, chunk_frames=CF)

    def test_crash_and_rejoin_byte_identical_to_batch(self, tmp_path):
        from blit.search import DedopplerReducer

        raw = _recording(tmp_path, nblocks=4, seed=7)
        oracle = str(tmp_path / "o.hits")
        DedopplerReducer(**self._search_kw()).search_to_file(raw, oracle)
        out = str(tmp_path / "s.hits")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            stream_search(ReplaySource(raw, rate=10000), out,
                          resume=True, **self._search_kw())
        faults.clear()
        cur = StreamCursor.load(out)
        assert cur is not None and cur.windows_done > 0
        hdr = stream_search(ReplaySource(raw, rate=10000), out,
                            resume=True, **self._search_kw())
        assert hdr["search_windows"] > cur.windows_done
        assert _bytes(out) == _bytes(oracle)
        assert StreamCursor.load(out) is None


class TestCLIResume:
    def test_stream_resume_flag_smoke(self, tmp_path, capsys):
        import json

        from blit.__main__ import main

        raw = _recording(tmp_path)
        out = str(tmp_path / "cli.fil")
        oracle = str(tmp_path / "o.fil")
        RawReducer(**_kw()).reduce_to_file(raw, oracle)
        rc = main(["stream", raw, "-o", out, "--nfft", str(NFFT),
                   "--replay-rate", "10000", "--resume"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["nsamps"] is not None
        assert _bytes(out) == _bytes(oracle)
        assert not os.path.exists(StreamCursor.path_for(out))
