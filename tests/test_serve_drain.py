"""Graceful drain + admission satellites (ISSUE 14): seeded jitter on
``Overloaded.retry_after_s`` (the thundering-herd fix), dispatch-time
deadline expiry (an already-dead request is NEVER computed — the
acceptance pin), ``Scheduler.drain``, and ``ProductService.drain``
releasing ``kind="stream"`` capacity holds instead of leaking them on
interpreter exit."""

import os
import signal
import threading
import time

import pytest

pytest.importorskip("jax")

from blit.observability import Timeline  # noqa: E402
from blit.serve import (  # noqa: E402
    Cancelled,
    DeadlineExpired,
    Overloaded,
    ProductCache,
    ProductRequest,
    ProductService,
    Scheduler,
)
from blit.serve.http import install_drain_handler  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NFFT = 128
NTIME = (8 + 3) * NFFT


@pytest.fixture
def raw(tmp_path):
    p = str(tmp_path / "a.raw")
    synth_raw(p, nblocks=2, obsnchan=2, ntime_per_block=NTIME,
              tone_chan=1)
    return p


def _blocked_scheduler(**kw):
    """A scheduler whose single slot is pinned by a job waiting on the
    returned event."""
    sched = Scheduler(max_concurrency=1, **kw)
    gate = threading.Event()
    running = threading.Event()

    def blocker():
        running.set()
        gate.wait(30)

    sched.submit(blocker, client="blocker")
    assert running.wait(5)
    return sched, gate


class TestRetryAfterJitter:
    def test_seeded_jitter_is_deterministic_and_spread(self):
        def rejections(seed):
            sched, gate = _blocked_scheduler(queue_depth=1,
                                             retry_seed=seed)
            sched.submit(lambda: None, client="q")  # fills the queue
            out = []
            for _ in range(4):
                with pytest.raises(Overloaded) as ei:
                    sched.submit(lambda: None, client="q")
                out.append(ei.value.retry_after_s)
            gate.set()
            sched.close(5)
            return out

        a = rejections(7)
        b = rejections(7)
        c = rejections(8)
        # Deterministic across runs with the same seed (the RetryPolicy
        # discipline), different across seeds, and SPREAD across
        # consecutive rejections — the herd does not return in lockstep.
        assert a == b
        assert a != c
        assert len(set(a)) > 1
        # Bounded: est=0 -> base 0.1s, jitter +/-50%.
        assert all(0.05 <= v <= 0.15 for v in a)

    def test_jitter_disabled_keeps_raw_estimate(self):
        sched, gate = _blocked_scheduler(queue_depth=1, retry_jitter=0.0)
        sched.submit(lambda: None, client="q")
        vals = set()
        for _ in range(3):
            with pytest.raises(Overloaded) as ei:
                sched.submit(lambda: None, client="q")
            vals.add(ei.value.retry_after_s)
        gate.set()
        sched.close(5)
        assert vals == {0.1}


class TestDispatchTimeDeadlineExpiry:
    def test_expired_in_queue_is_never_computed(self):
        clock = [0.0]
        sched = Scheduler(max_concurrency=1, clock=lambda: clock[0])
        gate = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            gate.wait(30)

        sched.submit(blocker, client="blocker")
        assert started.wait(5)
        ran = threading.Event()
        job = sched.submit(ran.set, client="late", deadline_s=5.0)
        clock[0] = 10.0  # the deadline burns while queued
        gate.set()
        assert job.wait(5)
        with pytest.raises(DeadlineExpired):
            job.result(1)
        assert not ran.is_set()  # the pin: never dispatched, never run
        assert sched.counts["expired"] == 1
        sched.close(5)

    def test_deadline_subclass_keeps_overloaded_contract(self):
        # Existing back-off handlers catch Overloaded; DeadlineExpired
        # must ride that path.
        assert issubclass(DeadlineExpired, Overloaded)

    def test_unexpired_job_still_runs(self):
        sched = Scheduler(max_concurrency=1)
        job = sched.submit(lambda: 41 + 1, client="ok", deadline_s=30.0)
        assert job.result(5) == 42
        sched.close(5)


class TestDispatchExpiryFlightDelivery:
    def test_expired_flight_fails_waiters_and_never_leaks(self, tmp_path,
                                                          raw):
        # The review regression: a dispatch-time expiry drops the job
        # without running fn, so the single-flight group must be failed
        # through on_drop — otherwise waiters hang forever and every
        # later identical request coalesces onto the dead flight.
        clock = [0.0]
        tl = Timeline()
        sched = Scheduler(max_concurrency=1, clock=lambda: clock[0],
                          timeline=tl)
        service = ProductService(
            cache=ProductCache(None, ram_bytes=1 << 24, timeline=tl),
            scheduler=sched, timeline=tl)
        gate = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            gate.wait(30)

        sched.submit(blocker, client="blocker")
        assert started.wait(5)
        ticket = service.submit(ProductRequest(raw=raw, nfft=NFFT),
                                deadline_s=5.0, client="late")
        clock[0] = 10.0  # burn the deadline in queue
        gate.set()
        with pytest.raises(DeadlineExpired):
            service.result(ticket, timeout=10)
        deadline = time.monotonic() + 10
        while service.stats()["inflight"]:
            assert time.monotonic() < deadline, "flight leaked"
            time.sleep(0.02)
        # A fresh identical request starts a NEW reduction and succeeds.
        _, data = service.get(ProductRequest(raw=raw, nfft=NFFT),
                              timeout=120)
        assert data.shape[0] > 0
        service.close(5)


class TestSchedulerDrain:
    def test_drain_cancels_queued_and_finishes_running(self):
        sched, gate = _blocked_scheduler(queue_depth=8)
        queued = [sched.submit(lambda: None, client=f"c{i}")
                  for i in range(3)]
        gate.set()
        cancelled = sched.drain(timeout=10)
        assert cancelled == 3
        for j in queued:
            with pytest.raises(Cancelled):
                j.result(1)
        with pytest.raises(RuntimeError):
            sched.submit(lambda: None)


def make_service(tmp_path, max_concurrency=2):
    tl = Timeline()
    return ProductService(
        cache=ProductCache(str(tmp_path / "cache"), ram_bytes=1 << 24,
                           timeline=tl),
        scheduler=Scheduler(max_concurrency=max_concurrency,
                            queue_depth=8, timeline=tl),
        timeline=tl,
    )


class TestServiceDrain:
    def test_drain_releases_stream_capacity_hold(self, tmp_path, raw):
        service = make_service(tmp_path)
        out = str(tmp_path / "live.fil")
        # A live session over a recording that never gets its .done
        # marker: without drain, the FileTailSource tails forever and
        # the capacity hold leaks on interpreter exit.
        ticket = service.submit(
            ProductRequest(raw=raw, kind="stream", out=out, nfft=NFFT),
            client="live")
        deadline = time.monotonic() + 20
        while service.scheduler.held() < 1:
            assert time.monotonic() < deadline, "hold never pinned"
            time.sleep(0.02)
        res = service.drain(timeout=30)
        assert res["stopped"] == 1
        assert service.scheduler.held() == 0  # the hold RELEASED
        hdr, _ = service.result(ticket, timeout=10)
        assert os.path.exists(out)  # the session finished its product
        assert hdr.get("nsamps", 0) > 0
        service.close(5)

    def test_draining_service_refuses_new_submissions(self, tmp_path,
                                                      raw):
        service = make_service(tmp_path)
        service.drain(timeout=10)
        with pytest.raises(Overloaded) as ei:
            service.submit(ProductRequest(raw=raw, nfft=NFFT))
        assert ei.value.retry_after_s > 0
        service.close(5)

    def test_drain_delivers_cancelled_to_queued_flights(self, tmp_path,
                                                        raw):
        service = make_service(tmp_path, max_concurrency=1)
        gate = threading.Event()
        service.scheduler.submit(lambda: gate.wait(30), client="blocker")
        ticket = service.submit(ProductRequest(raw=raw, nfft=NFFT),
                                client="queued")
        gate.set()
        service.drain(timeout=10)
        with pytest.raises(Cancelled):
            service.result(ticket, timeout=5)
        service.close(5)


class TestSignalWiring:
    def test_sigterm_drains_then_exits(self):
        drained = []
        uninstall = install_drain_handler(lambda: drained.append(1))
        try:
            with pytest.raises(SystemExit) as ei:
                os.kill(os.getpid(), signal.SIGTERM)
                # The handler fires between bytecodes; give it one.
                time.sleep(0.5)
            assert ei.value.code == 128 + signal.SIGTERM
            assert drained == [1]
        finally:
            uninstall()

    def test_no_exit_mode_runs_drain_in_place(self):
        drained = []
        uninstall = install_drain_handler(lambda: drained.append(1),
                                          exit_after=False)
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.5)
            assert drained == [1]
        finally:
            uninstall()
