"""A wedged-but-alive fake agent: handshakes, consumes requests, never
replies — the failure mode a hung NFS mount or stuck ssh presents
(VERDICT r3 weak #1).  ``ANSWER_FIRST=1`` serves the first request
properly and wedges from the second on, so the client's reuse-time ping
health check is what trips."""

import os
import sys
import time

from blit.agent import MAGIC, read_msg, write_msg

out = sys.stdout.buffer
out.write(MAGIC)
out.flush()
if os.environ.get("ANSWER_FIRST") == "1":
    read_msg(sys.stdin.buffer)
    write_msg(out, ("ok", "pong"))
# Keep consuming requests without ever answering: alive, framed, wedged.
# (EOF means the client closed the pipe on purpose — exit so pool shutdown
# stays fast; the watchdog path under test kills us, it never sends EOF.)
while True:
    try:
        read_msg(sys.stdin.buffer)
    except (EOFError, OSError):
        sys.exit(0)
    time.sleep(0)  # stay scheduled; never reply
