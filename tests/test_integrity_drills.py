"""Torn- and tampered-manifest drills (ISSUE 13 satellite), parallel to
tests/test_cursor_drills.py: every way a manifest can disagree with its
product — truncated JSON, a digest claiming the wrong window, a
manifest older/newer than the product, corruption inside the claimed
region — must fail CLOSED (fresh start or quarantine), never trust, and
every drill still finishes byte-identical to an uninterrupted run."""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blit import faults, integrity  # noqa: E402
from blit.pipeline import RawReducer  # noqa: E402
from blit.testing import synth_raw  # noqa: E402

NFFT, CF = 32, 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()


@pytest.fixture(autouse=True)
def _isolate_quarantine_watch():
    """The quarantine watch registry is process-wide by design (a serve
    process watches the caches it opened); restore it after each test so
    a drill's leftover quarantine cannot degrade /healthz for unrelated
    test files (test_monitor's clean-process assertions)."""
    with integrity._WATCH_LOCK:
        saved = set(integrity._WATCHED_QUARANTINES)
    yield
    with integrity._WATCH_LOCK:
        integrity._WATCHED_QUARANTINES.clear()
        integrity._WATCHED_QUARANTINES.update(saved)


def _kw():
    return dict(nfft=NFFT, chunk_frames=CF, tune_online=False)


def _bytes(path):
    with open(path, "rb") as f:
        return f.read()


class TestManifestDrills:
    def _interrupted(self, tmp_path):
        """A reference product plus an 'interrupted' resumable twin
        (the test_cursor_drills rig): crash after two durable appends,
        leaving product + cursor + partial manifest behind."""
        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=512,
                  seed=2)
        ref = str(tmp_path / "ref.fil")
        RawReducer(**_kw()).reduce_to_file(raw, ref)
        out = str(tmp_path / "res.fil")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            RawReducer(**_kw()).reduce_resumable(raw, out)
        faults.clear()
        assert os.path.exists(integrity.manifest_path(out))
        return raw, ref, out

    def _full_frames(self, raw):
        return RawReducer(**_kw()).reduce(raw)[1].shape[0]

    def _finish(self, raw, out):
        red = RawReducer(**_kw())
        red.reduce_resumable(raw, out)
        return red

    def test_truncated_manifest_fails_closed(self, tmp_path):
        # Torn JSON (a crash mid-manifest-write on a non-atomic fs):
        # the claim is unverifiable — fresh start, never trust.
        raw, ref, out = self._interrupted(tmp_path)
        mp = integrity.manifest_path(out)
        blob = open(mp).read()
        with open(mp, "w") as f:
            f.write(blob[: len(blob) // 2])
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames == self._full_frames(raw)

    def test_wrong_window_digest_fails_closed(self, tmp_path):
        # A ledger entry whose digest is not the claimed window's (the
        # tampered-sidecar shape): fresh start.
        raw, ref, out = self._interrupted(tmp_path)
        mp = integrity.manifest_path(out)
        doc = json.load(open(mp))
        assert doc["windows"]
        doc["windows"][-1][2] = integrity.hex_crc(
            integrity.parse_crc(doc["windows"][-1][2]) ^ 0xFFFF)
        json.dump(doc, open(mp, "w"))
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames == self._full_frames(raw)

    def test_malformed_ledger_fields_fail_closed_not_raise(self,
                                                           tmp_path):
        # Tampered NON-numeric fields (short entries, string row_bytes)
        # must fail closed like any other tamper — never raise out of
        # the resume probe or the fsck walk.
        raw, ref, out = self._interrupted(tmp_path)
        mp = integrity.manifest_path(out)
        doc = json.load(open(mp))
        doc["windows"] = [[doc["windows"][-1][0]]]  # short entry
        doc["row_bytes"] = "abc"
        json.dump(doc, open(mp, "w"))
        assert integrity.verify_claim(
            out, doc["windows"][0][0], fmt="fil") is False
        _doc2, problems = integrity.verify_product(out)
        assert problems  # fsck flags it instead of crashing the walk
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames == self._full_frames(raw)

    def test_flip_inside_claimed_region_fails_closed(self, tmp_path):
        # The case the old length-only probe could NEVER catch: the
        # file still holds the claimed bytes, but one of them rotted.
        raw, ref, out = self._interrupted(tmp_path)
        with open(out, "r+b") as f:
            f.seek(200)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0x01]))
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames == self._full_frames(raw)

    def test_manifest_for_a_different_product_fails_closed(self,
                                                           tmp_path):
        # Product replaced under a stale cursor+manifest (the
        # manifest-older-than-product shape): a DIFFERENT recording's
        # product lands at out while the sidecars still claim the old
        # one — the claimed-region digest disagrees, fresh start.
        raw, ref, out = self._interrupted(tmp_path)
        other_raw = str(tmp_path / "other.raw")
        synth_raw(other_raw, nblocks=4, obsnchan=2,
                  ntime_per_block=512, seed=9)
        other = str(tmp_path / "other.fil")
        RawReducer(**_kw()).reduce_to_file(other_raw, other)
        data = _bytes(other)
        with open(out, "wb") as f:
            f.write(data)
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames == self._full_frames(raw)

    def test_missing_manifest_keeps_length_only_resume(self, tmp_path):
        # Back-compat: a legacy product (no manifest) still resumes on
        # the length-only probe — the upgrade must not strand cursors
        # written before the integrity plane existed.
        raw, ref, out = self._interrupted(tmp_path)
        os.unlink(integrity.manifest_path(out))
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames < self._full_frames(raw)

    def test_clean_crash_state_still_resumes(self, tmp_path):
        # Control: the legal crash state (manifest consistent with the
        # cursor) must RESUME — fail-closed must not mean fail-always.
        raw, ref, out = self._interrupted(tmp_path)
        red = self._finish(raw, out)
        assert _bytes(out) == _bytes(ref)
        assert red.stats.output_frames < self._full_frames(raw)
        # Completed: cursor gone, manifest flipped to complete + clean.
        assert not os.path.exists(out + ".cursor")
        doc, problems = integrity.verify_product(out)
        assert doc["complete"] and not problems


class TestH5ManifestDrills:
    def _interrupted(self, tmp_path):
        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=512,
                  seed=3)
        ref = str(tmp_path / "ref.h5")
        RawReducer(**_kw()).reduce_to_file(raw, ref)
        out = str(tmp_path / "res.h5")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            RawReducer(**_kw()).reduce_resumable(raw, out)
        faults.clear()
        return raw, ref, out

    def test_flip_inside_claimed_rows_fails_closed(self, tmp_path):
        # Bit rot inside the claimed FBH5 rows: the structural probe
        # (open + decode last row) passes, the logical-row digest does
        # not — fresh start, and the decoded payload still matches.
        from blit.io import read_fbh5_data

        raw, ref, out = self._interrupted(tmp_path)
        import h5py

        with h5py.File(out, "r+") as h5:
            ds = h5["data"]
            row = np.array(ds[0])
            row.flat[0] += 1.0
            ds[0] = row
        red = RawReducer(**_kw())
        red.reduce_resumable(raw, out)
        assert red.stats.output_frames == \
            RawReducer(**_kw()).reduce(raw)[1].shape[0]
        np.testing.assert_array_equal(read_fbh5_data(out),
                                      read_fbh5_data(ref))

    def test_clean_h5_resume_still_resumes(self, tmp_path):
        from blit.io import read_fbh5_data

        raw, ref, out = self._interrupted(tmp_path)
        red = RawReducer(**_kw())
        red.reduce_resumable(raw, out)
        assert red.stats.output_frames < \
            RawReducer(**_kw()).reduce(raw)[1].shape[0]
        np.testing.assert_array_equal(read_fbh5_data(out),
                                      read_fbh5_data(ref))
        doc, problems = integrity.verify_product(out)
        assert doc["complete"] and not problems


class TestHitsManifestDrills:
    def _interrupted(self, tmp_path):
        from blit.search import DedopplerReducer

        raw = str(tmp_path / "r.raw")
        synth_raw(raw, nblocks=4, obsnchan=2, ntime_per_block=512,
                  seed=5, tone_chan=0)
        skw = dict(nfft=NFFT, chunk_frames=8, window_spectra=4,
                   snr_threshold=2.0, top_k=4)
        ref = str(tmp_path / "ref.hits")
        DedopplerReducer(**skw).search_to_file(raw, ref)
        out = str(tmp_path / "res.hits")
        faults.install_spec("sink.write:fail:after=2")
        with pytest.raises(OSError):
            DedopplerReducer(**skw).search_resumable(raw, out)
        faults.clear()
        return raw, ref, out, skw

    def test_tampered_hits_ledger_fails_closed(self, tmp_path):
        from blit.search import DedopplerReducer
        from blit.search.dedoppler import SearchCursor

        raw, ref, out, skw = self._interrupted(tmp_path)
        cur = SearchCursor.load(out)
        assert cur is not None and cur.windows_done > 0
        mp = integrity.manifest_path(out)
        doc = json.load(open(mp))
        assert doc["windows"]
        doc["windows"][-1][2] = "deadbeef"
        json.dump(doc, open(mp, "w"))
        DedopplerReducer(**skw).search_resumable(raw, out)
        assert _bytes(out) == _bytes(ref)

    def test_clean_hits_resume_still_resumes(self, tmp_path):
        from blit.search import DedopplerReducer

        raw, ref, out, skw = self._interrupted(tmp_path)
        DedopplerReducer(**skw).search_resumable(raw, out)
        assert _bytes(out) == _bytes(ref)
        doc, problems = integrity.verify_product(out)
        assert doc["complete"] and not problems


def _flip_byte(path, back=9):
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) - back)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0x20]))


class TestColdTierDrills:
    """Corrupt-cold-entry drills (ISSUE 19 satellite): the cold tier
    shares the hot tier's sidecar convention, so ``blit fsck`` walks it
    with the SAME detection/quarantine rules — and ``--repair``
    re-derives a quarantined cold entry through its recorded recipe."""

    def _cold_tree(self, tmp_path):
        from blit.serve.cache import ProductCache, fingerprint_for
        from blit.serve.service import ProductRequest

        raw = str(tmp_path / "cold-drill.raw")
        synth_raw(raw, nblocks=2, obsnchan=2, ntime_per_block=512,
                  seed=11)
        req = ProductRequest(raw=raw, nfft=NFFT, nint=1)
        reducer = req.reducer()
        fp = fingerprint_for(reducer, raw)
        header, data = reducer.reduce(raw)
        hot = str(tmp_path / "hot")
        cold = str(tmp_path / "cold")
        c = ProductCache(hot, ram_bytes=0, cold_dir=cold)
        c.put(fp, header, data, recipe=req.recipe())
        assert c._demote(fp)
        return hot, cold, c, fp, data

    def test_clean_cold_tier_passes(self, tmp_path):
        _hot, cold, _c, _fp, _data = self._cold_tree(tmp_path)
        rep = integrity.fsck(cold)
        assert rep["clean"] and rep["checked"] == 1 and rep["ok"] == 1

    def test_corrupt_cold_entry_quarantined_and_repaired(self, tmp_path):
        hot, cold, c, fp, data = self._cold_tree(tmp_path)
        _flip_byte(c.cold_data_path(fp))
        rep = integrity.fsck(cold)
        assert not rep["clean"]
        assert f"{fp}.h5" in rep["bad"][0]["path"]
        assert rep["bad"][0]["quarantined"]
        assert not os.path.exists(c.cold_data_path(fp))
        # --repair re-derives the entry from its recorded recipe INTO
        # the cold shard it was quarantined from...
        rep = integrity.fsck(cold, repair=True)
        assert rep["clean"] and rep["repaired"], rep
        rep2 = integrity.fsck(cold)
        assert rep2["clean"] and rep2["checked"] == 1
        # ...and the repaired entry serves byte-identical again.
        c2 = __import__("blit.serve.cache",
                        fromlist=["ProductCache"]).ProductCache(
            hot, ram_bytes=1 << 20, cold_dir=cold)
        got = c2.get(fp)
        assert got is not None and got[2] == "cold"
        np.testing.assert_array_equal(got[1], data)

    def test_cli_walks_both_tiers(self, tmp_path):
        import json as _json

        from blit.__main__ import main

        hot, cold, c, fp, _data = self._cold_tree(tmp_path)
        out = str(tmp_path / "fsck.json")
        assert main(["fsck", hot, "--cold-dir", cold,
                     "--json-out", out]) == 0
        rep = _json.load(open(out))
        assert rep["clean"] and rep["cold_root"] == os.path.abspath(cold)
        _flip_byte(c.cold_data_path(fp))
        assert main(["fsck", hot, "--cold-dir", cold,
                     "--json-out", out]) == 1
        rep = _json.load(open(out))
        assert not rep["clean"]
        assert any(f"{fp}.h5" in b["path"] for b in rep["bad"])
