"""Remote worker backend tests: the real agent subprocess + wire protocol
(no sshd — the transport is `python -m blit.agent` spawned locally, which
exercises everything except the ssh byte pipe itself)."""

import io
import sys

import numpy as np
import pytest

from blit import workers
from blit.agent import read_msg, resolve, serve, write_msg
from blit.parallel.pool import WorkerPool
from blit.parallel.remote import (
    RemoteError,
    RemoteWorker,
    agent_env_with_repo,
    local_agent_command,
    ssh_command,
)
from blit.testing import build_observation_tree, synth_fil


def local_transport(host):
    return local_agent_command()


@pytest.fixture
def remote_pool():
    pool = WorkerPool(
        ["h0", "h1"], backend="remote", transport=local_transport,
        agent_env=agent_env_with_repo(),
    )
    yield pool
    pool.shutdown()


class TestAgentProtocol:
    def test_resolve_allows_blit_only(self):
        assert resolve("blit.ops.fqav.fqav_range") is not None
        with pytest.raises(PermissionError):
            resolve("os.system")
        with pytest.raises(PermissionError):
            resolve("subprocess.run")

    def test_serve_roundtrip_in_memory(self):
        inbuf = io.BytesIO()
        write_msg(inbuf, ("blit.ops.fqav.fqav_range", (1.0, 1.0, 4, 4), {}))
        inbuf.seek(0)
        out = io.BytesIO()
        serve(inbuf, out)
        out.seek(0)
        tag, result = read_msg(out)
        assert tag == "ok" and result == (2.5, 4.0, 1)

    def test_serve_survives_refused_request(self):
        # A refused pickle must produce an ("err", ...) reply — not kill the
        # worker — and the NEXT request on the same stream must still work
        # (the framing survives the refusal).
        import pickle

        class Evil:
            def __reduce__(self):
                import os

                return (os.system, ("true",))

        from blit.agent import _LEN

        inbuf = io.BytesIO()
        body = pickle.dumps((Evil(), (), {}))
        inbuf.write(_LEN.pack(len(body)) + body)
        write_msg(inbuf, ("blit.ops.fqav.fqav_range", (1.0, 1.0, 4, 4), {}))
        inbuf.seek(0)
        out = io.BytesIO()
        serve(inbuf, out)
        out.seek(0)
        tag, etype, msg, _tb = read_msg(out)
        assert tag == "err" and etype == "UnpicklingError" and "refuses" in msg
        tag, result = read_msg(out)
        assert tag == "ok" and result == (2.5, 4.0, 1)

    def test_serve_ships_exceptions(self):
        inbuf = io.BytesIO()
        write_msg(inbuf, ("blit.workers.get_header", ("/nonexistent.fil",), {}))
        inbuf.seek(0)
        out = io.BytesIO()
        serve(inbuf, out)
        out.seek(0)
        tag, etype, msg, tb = read_msg(out)
        assert tag == "err" and "Error" in etype and tb


class TestRemoteWorker:
    def test_subprocess_call_roundtrip(self):
        w = RemoteWorker("local", local_agent_command(),
                         env=agent_env_with_repo())
        try:
            from blit.ops.fqav import fqav_range

            assert w.call(fqav_range, 1.0, 2.0, 8, 4) == (4.0, 8.0, 2)
        finally:
            w.close()

    def test_remote_exception_carries_context(self):
        w = RemoteWorker("local", local_agent_command(),
                         env=agent_env_with_repo())
        try:
            with pytest.raises(RemoteError) as ei:
                w.call(workers.get_header, "/nonexistent.fil")
            assert ei.value.host == "local"
            assert ei.value.remote_traceback
        finally:
            w.close()

    def test_numpy_arrays_cross_the_wire(self, tmp_path):
        p = str(tmp_path / "x.fil")
        _, data = synth_fil(p, nsamps=8, nchans=32)
        w = RemoteWorker("local", local_agent_command(),
                         env=agent_env_with_repo())
        try:
            out = w.call(workers.get_data, p,
                         (slice(2, 6), slice(None), slice(None)))
            np.testing.assert_array_equal(out, data[2:6])
        finally:
            w.close()

    def test_ssh_command_shape(self):
        cmd = ssh_command("blc42", python="python3.12")
        assert cmd[0] == "ssh" and "blc42" in cmd
        assert cmd[-3:] == ["python3.12", "-m", "blit.agent"]


class TestRemotePoolIntegration:
    def test_full_gbt_workflow_over_agents(self, tmp_path, remote_pool):
        from blit import gbt

        build_observation_tree(str(tmp_path), players=((0, 0), (0, 1)))
        invs = gbt.get_inventories(
            pool=remote_pool, root=str(tmp_path)
        )
        assert len(invs) == 2
        # shared fs: both agents see both players' files
        recs = sorted(invs[0], key=lambda r: r.bank)
        assert [r.bank for r in recs] == [0, 1]
        hdrs = gbt.get_headers([1, 2], [recs[0].file, recs[1].file],
                               pool=remote_pool)
        assert hdrs[0]["nchans"] == 64
        data = gbt.get_data([1, 2], [recs[0].file, recs[1].file],
                            fqav_by=4, pool=remote_pool)
        assert data[0].shape[-1] == 16
        kurt = gbt.get_kurtosis([1], [recs[0].file], pool=remote_pool)
        assert kurt[0].shape == (64, 1)

    def test_worker_error_capture_over_agents(self, remote_pool):
        from blit import gbt
        from blit.parallel.pool import WorkerError

        out = gbt.get_headers([1, 2], ["/nope1.fil", "/nope2.fil"],
                              pool=remote_pool, on_error="capture")
        assert all(isinstance(o, WorkerError) for o in out)

    def test_dead_agent_respawns_transparently(self, remote_pool):
        # Kill the agent behind the pool's back; the next call detects the
        # corpse and respawns (SURVEY.md §5: health-checked pool re-spawn —
        # the reference cannot even re-attach, src/gbt.jl:20-22).
        w = remote_pool.workers[0]
        from blit.ops.fqav import fqav_range

        w.remote.call(fqav_range, 1.0, 1.0, 4, 2)  # spawn it
        w.remote._proc.kill()
        w.remote._proc.wait()
        assert w.remote.call(fqav_range, 1.0, 1.0, 4, 2) == (1.5, 2.0, 2)

    def test_midcall_death_raises_agent_died(self):
        # An agent that dies while servicing a request (ssh drop analog)
        # must surface as AgentDied, not hang or corrupt framing.
        w = RemoteWorker(
            "flaky",
            [sys.executable, "-c",
             "import sys; sys.stdout.buffer.write(b'BLITAGENT1\\n'); "
             "sys.stdout.flush(); sys.stdin.buffer.read(8); sys.exit(1)"],
        )
        try:
            from blit.ops.fqav import fqav_range

            with pytest.raises(RemoteError, match="AgentDied"):
                w.call(fqav_range, 1.0, 1.0, 4, 2)
        finally:
            w.close()


class TestHardening:
    def test_malicious_pickle_rejected(self):
        # A __reduce__ payload must be refused by the restricted unpickler,
        # not executed (the allow-list alone runs too late to matter).
        import pickle

        from blit.agent import read_msg, _LEN

        class Evil:
            def __reduce__(self):
                import os

                return (os.system, ("true",))

        body = pickle.dumps(Evil())
        stream = io.BytesIO(_LEN.pack(len(body)) + body)
        with pytest.raises(pickle.UnpicklingError, match="refuses"):
            read_msg(stream)

    def test_safe_payloads_roundtrip(self):
        import re as re_mod

        from blit.agent import read_msg, write_msg
        from blit.inventory import InventoryRecord

        buf = io.BytesIO()
        payload = (
            np.arange(6, dtype=np.float32).reshape(2, 3),
            re_mod.compile(r"0002\.h5$"),
            slice(1, 5, 2),
            InventoryRecord(1, 2, "S", "0001", "A", 0, 1, "h", "f", 1),
        )
        write_msg(buf, payload)
        buf.seek(0)
        back = read_msg(buf)
        np.testing.assert_array_equal(back[0], payload[0])
        assert back[1].pattern == payload[1].pattern
        assert back[2] == slice(1, 5, 2) and back[3] == payload[3]

    def test_admitted_namespace_callables_rejected(self):
        # Module-prefix trust would let REDUCE invoke e.g. numpy.save or a
        # blit worker function with attacker args; the allow-list is exact
        # (module, name) pairs, so these must all refuse.
        import pickle

        from blit.agent import _RestrictedUnpickler

        for module, name in [
            ("numpy", "save"),
            ("numpy", "fromfile"),
            ("numpy.lib.npyio", "save"),
            ("blit.workers", "reduce_raw"),
            ("blit.io.sigproc", "write_fil"),
            ("re", "sub"),
        ]:
            with pytest.raises(pickle.UnpicklingError, match="refuses"):
                _RestrictedUnpickler(io.BytesIO(b"")).find_class(module, name)

    def test_oversized_length_header_rejected_before_allocation(self):
        # A lying u64 header must not trigger a giant allocation: the cap
        # check runs before the body read.  Modestly oversized (within the
        # drain cap) → drained + UnpicklingError, stream stays framed.
        import pickle

        from blit.agent import read_msg, _LEN

        stream = io.BytesIO(_LEN.pack(3 << 10) + b"x" * (3 << 10))
        with pytest.raises(pickle.UnpicklingError, match="exceeds"):
            read_msg(stream, max_bytes=1 << 10)
        assert stream.read() == b""  # body fully drained: framing intact
        # Within an explicit cap: frames normally.
        body = pickle.dumps([1, 2, 3])
        stream = io.BytesIO(_LEN.pack(len(body)) + body)
        assert read_msg(stream, max_bytes=1 << 20) == [1, 2, 3]

    def test_absurd_length_claim_tears_down_stream(self):
        # A claim beyond the drain cap (a u64 can say 2^62) must NOT pin the
        # reader in a discard loop — EOFError ends the connection instead.
        from blit.agent import read_msg, _LEN

        stream = io.BytesIO(_LEN.pack(1 << 62))
        with pytest.raises(EOFError, match="tearing down"):
            read_msg(stream)

    def test_response_allowlist_refuses_compiled_regex(self):
        # Responses must not admit re._compile: a compromised peer's reply
        # could hand the client a pathological (ReDoS) pattern.  Requests
        # keep it (inventory filters legitimately carry regexes).
        import pickle
        import re as re_mod

        from blit.agent import (
            _SAFE_GLOBALS_RESPONSE, read_msg, write_msg,
        )

        buf = io.BytesIO()
        write_msg(buf, re_mod.compile(r"0002\.h5$"))
        buf.seek(0)
        with pytest.raises(pickle.UnpicklingError, match="re._compile"):
            read_msg(buf, safe_globals=_SAFE_GLOBALS_RESPONSE)
        buf.seek(0)
        assert read_msg(buf).pattern == r"0002\.h5$"  # request side: fine

    def test_serve_survives_malformed_body(self):
        # Garbage that fails inside pickle.loads with something OTHER than
        # UnpicklingError (here: truncated pickle → EOF inside loads, and a
        # non-tuple payload → unpack error) must produce err frames, not
        # kill the loop — the stream is still framed after each.
        import pickle

        from blit.agent import _LEN, read_msg, serve, write_msg

        inbuf = io.BytesIO()
        bad = pickle.dumps((1, 2, 3, 4))[:-5]  # truncated mid-stream
        inbuf.write(_LEN.pack(len(bad)) + bad)
        inbuf.write(_LEN.pack(0))  # framed but EMPTY body (loads → EOFError)
        write_msg(inbuf, "not a 3-tuple")
        write_msg(inbuf, ("blit.ops.fqav.fqav_range", (1.0, 1.0, 4, 4), {}))
        inbuf.seek(0)
        out = io.BytesIO()
        serve(inbuf, out)
        out.seek(0)
        assert read_msg(out)[0] == "err"
        assert read_msg(out)[0] == "err"
        assert read_msg(out)[0] == "err"
        tag, result = read_msg(out)
        assert tag == "ok" and result == (2.5, 4.0, 1)

    def test_fqav_reducers_cross_the_wire(self):
        # np.mean / np.sum are the documented fqav_func values; they must
        # survive the exact-symbol allow-list.
        from blit.agent import read_msg, write_msg

        buf = io.BytesIO()
        write_msg(buf, (np.mean, np.sum, np.max))
        buf.seek(0)
        back = read_msg(buf)
        assert back[0] is np.mean and back[1] is np.sum and back[2] is np.max

    def test_banner_noise_skipped(self):
        # An rc file that echoes garbage before the agent starts must not
        # desynchronize the framing.
        cmd = [sys.executable, "-c",
               "import sys, runpy; sys.stdout.write('motd: welcome!\\n'); "
               "sys.stdout.flush(); runpy.run_module('blit.agent', "
               "run_name='__main__')"]
        w = RemoteWorker("noisy", cmd, env=agent_env_with_repo())
        try:
            from blit.ops.fqav import fqav_range

            assert w.call(fqav_range, 1.0, 1.0, 4, 4) == (2.5, 4.0, 1)
        finally:
            w.close()

    def test_invalid_wids_rejected(self, remote_pool):
        from blit import gbt

        with pytest.raises(ValueError, match="invalid worker ids"):
            gbt.get_headers([0], ["x.fil"], pool=remote_pool)
        with pytest.raises(ValueError, match="invalid worker ids"):
            gbt.get_headers([99], ["x.fil"], pool=remote_pool)
