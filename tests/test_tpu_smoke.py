"""Real-hardware smoke: the collective products' per-chip math must execute
on the actual TPU backend (which has no complex-dtype HLOs — DESIGN.md §1).

The suite itself runs on the virtual CPU mesh (conftest.py), so these tests
spawn a subprocess pointed back at the hardware platform the session was
launched with (saved as ``BLIT_HW_PLATFORMS`` before conftest forces CPU).
They guard exactly the round-1 failure mode: beamform/correlator code that
passes on the CPU mesh but dies ``UNIMPLEMENTED`` on the chip.

Skipped when no hardware platform is configured (plain CPU dev boxes) or
when the failure is infrastructure (tunnel hiccups), not semantics: only an
``UNIMPLEMENTED``/complex-dtype error — the regression these tests exist to
catch — fails the suite.
"""

import functools
import os
import subprocess
import sys

import pytest


@functools.lru_cache(maxsize=1)
def hw_platform() -> str:
    """The hardware platform spec for smoke subprocesses, or ''.

    Usually the ``JAX_PLATFORMS`` the session was launched with (saved by
    conftest before it forces CPU).  When that was unset — e.g. a TPU VM
    where JAX auto-detects the chip — probe a clean subprocess for its
    default backend so the smoke still runs.
    """
    hw = os.environ.get("BLIT_HW_PLATFORMS", "")
    if hw:
        return hw
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "BLIT_HW_PLATFORMS")
    }
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            env=env, capture_output=True, text=True, timeout=120,
        )
    except subprocess.TimeoutExpired:
        return ""
    detected = probe.stdout.strip().splitlines()[-1] if probe.stdout.strip() else ""
    return detected if detected in ("tpu", "axon") else ""


def _require_hw() -> str:
    hw = hw_platform()
    if not any(p in hw for p in ("tpu", "axon")):
        pytest.skip("no TPU hardware platform configured or detected")
    return hw

# Runs on the real backend: a 1x1 (band, bank) mesh on the single chip, so
# the full shard_map + psum code path executes — tiny shapes, planar inputs
# (complex device_put does not exist on this backend).
#
# Failures are classified IN the subprocess, where the exception object
# exists, and reported as a tagged sentinel on stdout — the parent never
# greps the combined output (a traceback line quoting a planar docstring
# contains the word "complex" and would misclassify).
#   BLIT-SMOKE-FAIL:SEMANTIC:...  — unsupported-op or wrong-numerics
#                                   regression: fails the suite.
#   BLIT-SMOKE-FAIL:INFRA:...     — import/connection/tunnel trouble: skips.
_SMOKE = r"""
import sys, traceback

def run():
    import numpy as np
    import jax, jax.numpy as jnp
    from blit.ops.channelize import pfb_coeffs
    from blit.parallel import beamform as B
    from blit.parallel import correlator as C
    from blit.parallel import mesh as M

    assert jax.default_backend() in ("tpu", "axon"), jax.default_backend()
    mesh = M.make_mesh(1, 1)
    rng = np.random.default_rng(0)

    # Beamform: planar weights from delays + planar voltages, detect path.
    nant, nbeam, nchan, ntime, npol = 4, 2, 2, 32, 2
    v = (rng.standard_normal((nant, nchan, ntime, npol))
         + 1j * rng.standard_normal((nant, nchan, ntime, npol))).astype(np.complex64)
    wr, wi = B.delay_weights_planar(
        jnp.asarray(rng.uniform(0, 1e-9, (nbeam, nant))),
        jnp.asarray(np.linspace(1e9, 1.1e9, nchan)),
    )
    w = np.asarray(wr) + 1j * np.asarray(wi)
    vp = jax.device_put((v.real.copy(), v.imag.copy()), B.antenna_sharding(mesh))
    wp = jax.device_put((np.asarray(wr), np.asarray(wi)), B.weight_sharding(mesh))
    got = np.asarray(B.beamform(vp, wp, mesh=mesh, nint=8))
    want = B.beamform_np(v, w, nint=8)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    print("beamform: ok")

    # Correlator: planar F-engine (matmul DFT) + planar X-engine + psum.
    nfft, ntap = 32, 4
    cv = (rng.standard_normal((3, 2, 8 * nfft, npol))
          + 1j * rng.standard_normal((3, 2, 8 * nfft, npol))).astype(np.complex64)
    cvp = jax.device_put(
        (cv.real.copy(), cv.imag.copy()), C.correlator_sharding(mesh)
    )
    h = pfb_coeffs(ntap, nfft)
    visr, visi = C.correlate(cvp, jnp.asarray(h), mesh=mesh, nfft=nfft, ntap=ntap)
    want = C.correlate_np(cv, h, nfft=nfft, ntap=ntap)
    np.testing.assert_allclose(np.asarray(visr), want.real, rtol=2e-2, atol=2e-1)
    np.testing.assert_allclose(np.asarray(visi), want.imag, rtol=2e-2, atol=2e-1)
    print("correlator: ok")

    # Round 5: the VMEM-resident packed X-engine compiles NATIVELY and
    # agrees at an MXU-sized baseline count (nap=128 -> pallas path; the
    # CPU suite only reaches it in interpreter mode).
    pn, pc, pfft2, pblk = 64, 1, 8, 8
    pv2 = (rng.standard_normal((pn, pc, pblk * pfft2, npol))
           + 1j * rng.standard_normal((pn, pc, pblk * pfft2, npol))
           ).astype(np.complex64)
    pvp = jax.device_put(
        (pv2.real.copy(), pv2.imag.copy()), C.correlator_sharding(mesh)
    )
    h2 = pfb_coeffs(ntap, pfft2)
    pvis = C.correlate(pvp, jnp.asarray(h2), mesh=mesh, nfft=pfft2,
                       ntap=ntap, vis_layout="packed")
    wantp = C.correlate_np(pv2, h2, nfft=pfft2, ntap=ntap).transpose(
        2, 3, 0, 4, 1, 5)
    np.testing.assert_allclose(np.asarray(pvis[0]), wantp.real,
                               rtol=2e-2, atol=2e-1)
    np.testing.assert_allclose(np.asarray(pvis[1]), wantp.imag,
                               rtol=2e-2, atol=2e-1)
    print("packed xengine: ok")

    # Round 5: the fused beamform+detect kernel compiles NATIVELY and
    # agrees (chip-local antenna axis + eligible tile -> pallas path).
    from blit.ops.pallas_beamform import pack_voltages, pack_weights
    from jax.sharding import NamedSharding, PartitionSpec as P

    bn, bb, bc, bt, bnint = 4, 8, 2, 256, 2  # tile = 2*128 divides 256
    bv = (rng.standard_normal((bn, bc, bt, npol))
          + 1j * rng.standard_normal((bn, bc, bt, npol))
          ).astype(np.complex64)
    bw = (rng.standard_normal((bb, bn, bc))
          + 1j * rng.standard_normal((bb, bn, bc))).astype(np.complex64)
    kv = pack_voltages(jnp.asarray(bv.real), jnp.asarray(bv.imag))
    kw2 = pack_weights(jnp.asarray(bw.real), jnp.asarray(bw.imag))
    kvp = jax.device_put((np.asarray(kv[0]), np.asarray(kv[1])),
                         NamedSharding(mesh, P(None, "bank")))
    kwp = jax.device_put((np.asarray(kw2[0]), np.asarray(kw2[1])),
                         NamedSharding(mesh, P(None, None, "bank")))
    fp = np.asarray(B.beamform(kvp, kwp, mesh=mesh, nint=bnint,
                               layout="chan"))
    if not B.last_beamform_plan().get("fused"):  # survives python -O
        raise AssertionError(
            "chan-layout beamform fell back to einsums on the chip: "
            f"{B.last_beamform_plan()}"
        )
    wantf = B.beamform_np(bv, bw, nint=bnint)
    np.testing.assert_allclose(np.transpose(fp, (1, 0, 3, 2)), wantf,
                               rtol=2e-2, atol=2e-2 * np.abs(wantf).max())
    print("fused beamform: ok")

    # Round 4: the file-fed antenna data plane end-to-end on the real
    # backend — per-antenna RAW files -> planar device shards -> beamform.
    import os as _os
    import tempfile

    from blit.parallel.antenna import load_antennas_mesh
    from blit.testing import synth_raw

    with tempfile.TemporaryDirectory() as td:
        paths, cplx = [], []
        for a in range(nant):
            p = _os.path.join(td, f"ant{a}.raw")
            # synth_raw hands back the written blocks: the golden builds
            # from them directly, independent of the reader under test.
            _, blocks = synth_raw(p, nblocks=2, obsnchan=nchan,
                                  ntime_per_block=64, seed=a)
            stream = np.concatenate(blocks, axis=1)
            cplx.append(stream[..., 0].astype(np.float32)
                        + 1j * stream[..., 1].astype(np.float32))
            paths.append(p)
        hdr, vp2 = load_antennas_mesh(paths, mesh=mesh)
        got2 = np.asarray(B.beamform(vp2, wp, mesh=mesh, nint=8))
        want2 = B.beamform_np(
            np.stack(cplx)[:, :, :hdr["_ntime"]], w, nint=8
        )
    np.testing.assert_allclose(got2, want2, rtol=2e-2, atol=2e-2)
    print("antenna loader: ok")

    # Pallas kernels compile and agree NATIVELY on the chip (the CPU suite
    # only exercises them in interpreter mode): fused dequant+PFB+stage-1
    # and the fused detect+untwist, tiny multi-factor shapes.
    from blit.ops.channelize import channelize, channelize_np

    pfft = 8192  # > DIRECT_DFT_MAX -> multi-level matmul path
    pv = rng.integers(-40, 40, (1, 6 * pfft, 2, 2)).astype(np.int8)
    ph = pfb_coeffs(4, pfft)
    want = channelize_np(pv, ph, nfft=pfft)
    scale = np.abs(want).max()
    for kern, dk in (("fused1", "xla"), ("fused1", "pallas"), ("pallas", "xla")):
        got = np.asarray(channelize(
            jnp.asarray(pv), jnp.asarray(ph), nfft=pfft,
            fft_method="matmul", pfb_kernel=kern, detect_kernel=dk,
        ))
        assert np.abs(got - want).max() / scale < 2e-2, (kern, dk)

    # Fused tail+detect (the production default at 3-factor sizes): the
    # smallest default-factors 3-factor nfft is 2^20 — a fresh multi-minute
    # compile through this rig's tunnel — so smoke the kernel directly at
    # small synthetic factors instead (native mosaic compile + numerics).
    from blit.ops import dft as D
    from blit.ops.pallas_detect import tail2_detect_i

    f1, f2, f3 = 8, 32, 4
    tu_r = rng.standard_normal((2, 2, 3, f1, f2 * f3)).astype(np.float32)
    tu_i = rng.standard_normal((2, 2, 3, f1, f2 * f3)).astype(np.float32)
    got_td = np.asarray(tail2_detect_i(
        jnp.asarray(tu_r), jnp.asarray(tu_i), f2, f3))
    sr_t, si_t = D.dft_tail(jnp.asarray(tu_r), jnp.asarray(tu_i),
                            (f1, f2, f3))
    want_td = np.asarray((sr_t**2 + si_t**2).sum(axis=1)).transpose(1, 0, 2)
    np.testing.assert_allclose(got_td, want_td, rtol=1e-4,
                               atol=1e-3 * np.abs(want_td).max())
    print("pallas kernels: ok")

try:
    run()
except BaseException as e:
    # Semantic = the regressions this smoke exists to catch: wrong numerics
    # (assert_allclose -> AssertionError) or the per-chip math hitting an
    # op the backend can't run (UNIMPLEMENTED / complex-dtype lowering
    # errors).  Classified on the exception itself, not the output.
    # "complex" is matched case-insensitively on the EXCEPTION text only —
    # safe here (unlike grepping combined output, where docstring quotes in
    # tracebacks false-positive) and broad enough to catch any wording of a
    # complex-dtype lowering refusal ("unsupported complex dtype", ...).
    semantic = isinstance(e, AssertionError) or (
        "UNIMPLEMENTED" in str(e) or "complex" in str(e).lower()
    )
    tag = "SEMANTIC" if semantic else "INFRA"
    print(f"BLIT-SMOKE-FAIL:{tag}:{type(e).__name__}", flush=True)
    traceback.print_exc()
    sys.exit(1)
"""


def test_collectives_per_chip_math_runs_on_hardware():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = _require_hw()
    env.pop("BLIT_HW_PLATFORMS", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SMOKE],
            env=env,
            capture_output=True,
            text=True,
            timeout=540,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("hardware smoke timed out (tunnel stall)")
    if proc.returncode != 0:
        blob = proc.stdout + proc.stderr
        if "BLIT-SMOKE-FAIL:SEMANTIC" in proc.stdout:
            pytest.fail(
                "collective per-chip math regressed on the TPU backend "
                "(unsupported op or wrong values):\n" + blob[-3000:]
            )
        # INFRA sentinel, or no sentinel at all (interpreter died before the
        # harness: OOM kill, tunnel reset, import of the script failing):
        # infrastructure, not semantics.
        pytest.skip("hardware smoke infrastructure failure:\n" + blob[-1500:])
    assert "beamform: ok" in proc.stdout
    assert "correlator: ok" in proc.stdout
    assert "packed xengine: ok" in proc.stdout
    assert "fused beamform: ok" in proc.stdout
    assert "antenna loader: ok" in proc.stdout
    assert "pallas kernels: ok" in proc.stdout
