"""Collective science products on the virtual 8-device mesh: coherent
multibeam beamforming (blit/parallel/beamform.py) and the FX correlator
(blit/parallel/correlator.py), golden-tested against NumPy references."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blit.ops.channelize import pfb_coeffs  # noqa: E402
from blit.parallel import beamform as B  # noqa: E402
from blit.parallel import correlator as C  # noqa: E402
from blit.parallel.mesh import make_mesh  # noqa: E402


def make_antenna_voltages(nant=8, nchan=4, ntime=64, npol=2, seed=0):
    rng = np.random.default_rng(seed)
    shape = (nant, nchan, ntime, npol)
    return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex64
    )


class TestDelayWeights:
    def test_phasors(self):
        delays = jnp.asarray([[0.0, 1e-9], [1e-9, 0.0]])  # (2 beams, 2 ants)
        freqs = jnp.asarray([1.0e9, 1.5e9])
        w = B.delay_weights(delays, freqs)
        assert w.shape == (2, 2, 2)
        np.testing.assert_allclose(np.asarray(w[0, 0]), [1, 1], atol=1e-6)
        # exp(-2pi i * 1e9 * 1e-9) = exp(-2pi i) = 1
        np.testing.assert_allclose(np.asarray(w[0, 1, 0]), 1.0, atol=1e-5)
        # exp(-2pi i * 1.5) = -1
        np.testing.assert_allclose(np.asarray(w[0, 1, 1]), -1.0, atol=1e-5)

    def test_amplitude_taper(self):
        w = B.delay_weights(
            jnp.zeros((1, 3)), jnp.ones(2) * 1e9, amplitudes=jnp.asarray([1.0, 0.5, 0.0])
        )
        np.testing.assert_allclose(np.abs(np.asarray(w[0, :, 0])), [1, 0.5, 0])


class TestBeamform:
    @pytest.mark.parametrize("detect,nint", [(True, 4), (True, 1), (False, 1)])
    def test_matches_numpy(self, detect, nint):
        nant, nbeam = 8, 5
        v = make_antenna_voltages(nant=nant)
        rng = np.random.default_rng(1)
        w = (rng.standard_normal((nbeam, nant, 4))
             + 1j * rng.standard_normal((nbeam, nant, 4))).astype(np.complex64)
        m = make_mesh(1, 8)
        vs = jax.device_put(v, B.antenna_sharding(m))
        ws = jax.device_put(w, B.weight_sharding(m))
        got = np.asarray(
            B.beamform(vs, ws, mesh=m, nint=nint, detect=detect)
        )
        want = B.beamform_np(v, w, nint=nint, detect=detect)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)

    def test_steering_recovers_point_source(self):
        # A plane wave delayed per antenna: the matched beam collects nant^2
        # power, a mismatched beam collects ~nant.
        nant, nchan, ntime = 8, 2, 32
        freqs = np.array([1.0e9, 1.1e9])
        delays = np.linspace(0, 3e-9, nant)
        t = np.arange(ntime)
        v = np.zeros((nant, nchan, ntime, 1), np.complex64)
        for a in range(nant):
            for c in range(nchan):
                # source signal with per-antenna geometric phase
                v[a, c, :, 0] = np.exp(2j * np.pi * (0.05 * t + freqs[c] * delays[a]))
        w_match = B.delay_weights(jnp.asarray(delays)[None, :], jnp.asarray(freqs))
        w_zero = B.delay_weights(jnp.zeros((1, nant)), jnp.asarray(freqs))
        m = make_mesh(1, 8)
        vs = jax.device_put(v, B.antenna_sharding(m))
        p_match = np.asarray(B.beamform(
            vs, jax.device_put(w_match, B.weight_sharding(m)), mesh=m,
            nint=ntime)).sum()
        p_zero = np.asarray(B.beamform(
            vs, jax.device_put(w_zero, B.weight_sharding(m)), mesh=m,
            nint=ntime)).sum()
        assert p_match > 5 * p_zero
        np.testing.assert_allclose(
            p_match, nant**2 * nchan * ntime, rtol=1e-3
        )


class TestBeamformBf16:
    def test_bf16_resident_matches_f32(self):
        # bf16-resident planes (load_antennas_mesh(dtype="bfloat16")) run
        # the contraction + psum in bf16 (measured +26% on the chip,
        # DESIGN.md §9 r5).  8-bit voltages are exact in bf16; rounding
        # comes from the weight phasors and the bf16 partial sums —
        # ~1e-2 max rel err on detected power.
        nant, nbeam, nchan, ntime = 8, 5, 4, 64
        rng = np.random.default_rng(7)
        v8 = rng.integers(-40, 41, (2, nant, nchan, ntime, 2)).astype(
            np.float32
        )
        wr, wi = B.delay_weights_planar(
            jnp.asarray(rng.uniform(0, 1e-9, (nbeam, nant))),
            jnp.asarray(np.linspace(1e9, 1.1e9, nchan)),
        )
        m = make_mesh(1, 8)
        wp = jax.device_put((np.asarray(wr), np.asarray(wi)),
                            B.weight_sharding(m))
        vp32 = jax.device_put((v8[0], v8[1]), B.antenna_sharding(m))
        vp16 = jax.device_put(
            (v8[0].astype(jnp.bfloat16), v8[1].astype(jnp.bfloat16)),
            B.antenna_sharding(m),
        )
        p32 = np.asarray(B.beamform(vp32, wp, mesh=m, nint=4))
        p16 = np.asarray(B.beamform(vp16, wp, mesh=m, nint=4))
        assert p16.dtype == np.float32  # detection always comes back f32
        np.testing.assert_allclose(p16, p32, rtol=3e-2,
                                   atol=3e-2 * np.abs(p32).max())

    def test_loader_bf16_residency(self, tmp_path):
        from blit.parallel.antenna import load_antennas_mesh
        from blit.testing import synth_raw

        paths = []
        for a in range(8):
            p = str(tmp_path / f"a{a}.raw")
            synth_raw(p, nblocks=1, obsnchan=2, ntime_per_block=64, seed=a)
            paths.append(p)
        m = make_mesh(1, 8)
        _, (vr, vi) = load_antennas_mesh(paths, mesh=m, dtype="bfloat16")
        assert vr.dtype == jnp.bfloat16 and vi.dtype == jnp.bfloat16
        # Lossless: the bf16 planes decode to the same int8-origin values.
        _, (fr, fi) = load_antennas_mesh(paths, mesh=m)
        np.testing.assert_array_equal(
            np.asarray(vr).astype(np.float32), np.asarray(fr)
        )
        with pytest.raises(ValueError, match="dtype"):
            load_antennas_mesh(paths, mesh=m, dtype="float16")


class TestBeamformPlanar:
    """The TPU-native planar (re, im) input path (complex-free backend)."""

    def test_planar_matches_complex_path(self):
        nant, nbeam = 8, 3
        v = make_antenna_voltages(nant=nant)
        rng = np.random.default_rng(7)
        w = (rng.standard_normal((nbeam, nant, 4))
             + 1j * rng.standard_normal((nbeam, nant, 4))).astype(np.complex64)
        m = make_mesh(1, 8)
        vp = jax.device_put(
            (v.real.copy(), v.imag.copy()), B.antenna_sharding(m)
        )
        wp = jax.device_put(
            (w.real.copy(), w.imag.copy()), B.weight_sharding(m)
        )
        got = np.asarray(B.beamform(vp, wp, mesh=m, nint=4))
        want = B.beamform_np(v, w, nint=4)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)

    def test_planar_voltages_out(self):
        v = make_antenna_voltages(nant=8, seed=9)
        rng = np.random.default_rng(10)
        w = (rng.standard_normal((2, 8, 4))
             + 1j * rng.standard_normal((2, 8, 4))).astype(np.complex64)
        m = make_mesh(1, 8)
        vp = jax.device_put((v.real.copy(), v.imag.copy()), B.antenna_sharding(m))
        wp = jax.device_put((w.real.copy(), w.imag.copy()), B.weight_sharding(m))
        br, bi = B.beamform(vp, wp, mesh=m, detect=False)
        want = B.beamform_np(v, w, detect=False)
        np.testing.assert_allclose(np.asarray(br), want.real, rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(bi), want.imag, rtol=1e-4, atol=1e-3)

    def test_delay_weights_planar_matches_numpy(self):
        delays = np.array([[0.0, 1e-9, 2e-9]])
        freqs = np.array([1.0e9, 1.5e9])
        amp = np.array([1.0, 0.5, 2.0])
        wr, wi = B.delay_weights_planar(
            jnp.asarray(delays), jnp.asarray(freqs), amplitudes=jnp.asarray(amp)
        )
        # Independent reference: the complex phasor computed in NumPy.
        want = np.exp(-2j * np.pi * delays[..., None] * freqs[None, None, :])
        want = want * amp[None, :, None]
        # f32 phase accumulation at multiples of 2pi costs ~1e-6 absolute.
        np.testing.assert_allclose(np.asarray(wr), want.real, atol=1e-5)
        np.testing.assert_allclose(np.asarray(wi), want.imag, atol=1e-5)


class TestCorrelator:
    @pytest.mark.parametrize("nband,nbank", [(1, 8), (2, 4), (4, 2)])
    def test_matches_numpy(self, nband, nbank):
        nfft, ntap = 16, 4
        nant, nchan = 3, 8
        ntime = nband * 8 * nfft  # 8 blocks per band segment
        v = make_antenna_voltages(nant=nant, nchan=nchan, ntime=ntime, seed=3)
        h = pfb_coeffs(ntap, nfft)
        m = make_mesh(nband, nbank)
        vs = jax.device_put(v, C.correlator_sharding(m))
        got = np.asarray(
            C.correlate(vs, jnp.asarray(h), mesh=m, nfft=nfft, ntap=ntap)
        )
        want = C.correlate_np(v, h, nfft=nfft, ntap=ntap, nsegments=nband)
        assert got.shape == (nant, nant, nchan, nfft, 2, 2)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-2)

    def test_hermitian_and_autos_real(self):
        nfft = 8
        v = make_antenna_voltages(nant=2, nchan=8, ntime=8 * nfft, seed=4)
        h = pfb_coeffs(4, nfft)
        m = make_mesh(1, 8)
        vis = np.asarray(C.correlate(
            jax.device_put(v, C.correlator_sharding(m)), jnp.asarray(h),
            mesh=m, nfft=nfft))
        # V[a,b,...,p,q] = conj(V[b,a,...,q,p])
        np.testing.assert_allclose(
            vis, np.conj(np.transpose(vis, (1, 0, 2, 3, 5, 4))), rtol=1e-5,
            atol=1e-4,
        )
        autos = vis[np.arange(2), np.arange(2)][..., [0, 1], [0, 1]]
        assert np.abs(autos.imag).max() < 1e-3
        assert autos.real.min() >= 0

    def test_planar_matches_complex_path(self):
        nfft, ntap = 16, 4
        nant, nchan = 3, 8
        nband, nbank = 2, 4
        ntime = nband * 8 * nfft
        v = make_antenna_voltages(nant=nant, nchan=nchan, ntime=ntime, seed=11)
        h = pfb_coeffs(ntap, nfft)
        m = make_mesh(nband, nbank)
        vp = jax.device_put(
            (v.real.copy(), v.imag.copy()), C.correlator_sharding(m)
        )
        visr, visi = C.correlate(vp, jnp.asarray(h), mesh=m, nfft=nfft, ntap=ntap)
        want = C.correlate_np(v, h, nfft=nfft, ntap=ntap, nsegments=nband)
        np.testing.assert_allclose(np.asarray(visr), want.real, rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(np.asarray(visi), want.imag, rtol=1e-3, atol=1e-2)

    @pytest.mark.parametrize("nband,nbank", [(1, 8), (2, 4)])
    def test_packed_layout_matches_standard(self, nband, nbank):
        # vis_layout="packed" is the TPU-fast layout (pallas X-engine at
        # MXU-sized nap; packed einsums elsewhere — this CPU mesh takes
        # the einsum fallback).  Same numbers, axes (c,f,a,p,b,q).
        nfft, ntap = 16, 4
        nant, nchan = 3, 8
        ntime = nband * 8 * nfft
        v = make_antenna_voltages(nant=nant, nchan=nchan, ntime=ntime,
                                  seed=13)
        h = pfb_coeffs(ntap, nfft)
        m = make_mesh(nband, nbank)
        vs = jax.device_put(v, C.correlator_sharding(m))
        std = np.asarray(
            C.correlate(vs, jnp.asarray(h), mesh=m, nfft=nfft, ntap=ntap)
        )
        packed = np.asarray(C.correlate(
            vs, jnp.asarray(h), mesh=m, nfft=nfft, ntap=ntap,
            vis_layout="packed",
        ))
        assert packed.shape == (nchan, nfft, nant, 2, nant, 2)
        np.testing.assert_allclose(
            packed, std.transpose(2, 3, 0, 4, 1, 5), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.parametrize("vis_layout", ["standard", "packed"])
    def test_bf16_resident_matches_f32(self, vis_layout):
        # bf16-resident voltages run the bf16-staged path (bf16 FIR +
        # bf16 spectra, f32 accumulation — measured +25% at nant=64,
        # DESIGN.md §9 r5).  On this CPU mesh the f32 reference computes
        # exact f32 (no MXU truncation), so the tolerance covers the
        # bf16 rounding the chip applies to BOTH paths anyway.
        nfft, ntap = 16, 4
        nant, nchan = 3, 8
        ntime = 8 * nfft
        rng = np.random.default_rng(23)
        v8 = rng.integers(-40, 41, (2, nant, nchan, ntime, 2)).astype(
            np.float32
        )
        h = pfb_coeffs(ntap, nfft)
        m = make_mesh(1, 8)
        vp32 = jax.device_put((v8[0], v8[1]), C.correlator_sharding(m))
        vp16 = jax.device_put(
            (v8[0].astype(jnp.bfloat16), v8[1].astype(jnp.bfloat16)),
            C.correlator_sharding(m),
        )
        kw = dict(mesh=m, nfft=nfft, ntap=ntap, vis_layout=vis_layout)
        r32, i32 = C.correlate(vp32, jnp.asarray(h), **kw)
        r16, i16 = C.correlate(vp16, jnp.asarray(h), **kw)
        assert r16.dtype == jnp.float32  # visibilities accumulate f32
        scale = float(np.abs(np.asarray(r32)).max())
        np.testing.assert_allclose(np.asarray(r16), np.asarray(r32),
                                   rtol=2e-2, atol=2e-2 * scale)
        np.testing.assert_allclose(np.asarray(i16), np.asarray(i32),
                                   rtol=2e-2, atol=2e-2 * scale)

    def test_loader_bf16_residency(self, tmp_path):
        from blit.parallel.antenna import load_correlator_mesh
        from blit.testing import synth_raw

        paths = []
        for a in range(3):
            p = str(tmp_path / f"c{a}.raw")
            synth_raw(p, nblocks=2, obsnchan=4, ntime_per_block=512, seed=a)
            paths.append(p)
        m = make_mesh(2, 4)
        _, (vr, vi) = load_correlator_mesh(paths, mesh=m, nfft=64,
                                           dtype="bfloat16")
        assert vr.dtype == jnp.bfloat16 and vi.dtype == jnp.bfloat16
        _, (fr, fi) = load_correlator_mesh(paths, mesh=m, nfft=64)
        np.testing.assert_array_equal(
            np.asarray(vr).astype(np.float32), np.asarray(fr)
        )

    def test_bad_vis_layout_rejected(self):
        m = make_mesh(1, 8)
        v = make_antenna_voltages(nant=2, nchan=8, ntime=8 * 16, seed=1)
        with pytest.raises(ValueError, match="vis_layout"):
            C.correlate(
                jax.device_put(v, C.correlator_sharding(m)),
                jnp.asarray(pfb_coeffs(4, 16)), mesh=m, nfft=16,
                vis_layout="fast",
            )

    def test_correlated_signal_shows_fringe(self):
        # Identical signal in two antennas → cross-power == auto-power.
        nfft = 16
        rng = np.random.default_rng(5)
        base = (rng.standard_normal(8 * nfft) +
                1j * rng.standard_normal(8 * nfft)).astype(np.complex64)
        v = np.zeros((2, 8, 8 * nfft, 1), np.complex64)
        v[0, 0, :, 0] = base
        v[1, 0, :, 0] = base
        h = pfb_coeffs(4, nfft)
        m = make_mesh(1, 8)
        vis = np.asarray(C.correlate(
            jax.device_put(v, C.correlator_sharding(m)), jnp.asarray(h),
            mesh=m, nfft=nfft))
        np.testing.assert_allclose(
            np.abs(vis[0, 1, 0, :, 0, 0]), vis[0, 0, 0, :, 0, 0].real, rtol=1e-4
        )
