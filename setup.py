"""Install-time hook that builds blit's native C++ libraries.

All package metadata lives in pyproject.toml; this file exists only to
compile ``blit/native`` (bitshuffle+LZ4 codec, GUPPI block reader) during
``pip install`` / wheel builds.  The build is best-effort by design:
blit degrades to its NumPy fallback paths when the libraries are absent
(blit/io/native.py), so a host without a C++ toolchain still installs —
it just reads bitshuffle files and RAW blocks more slowly.
"""

import os
import subprocess
import sys

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_native(build_py):
    def run(self):
        native = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "blit", "native")
        try:
            subprocess.run(["make", "-C", native], check=True)
        except (OSError, subprocess.CalledProcessError) as e:
            print(
                f"blit: native build skipped ({e}); the installed package "
                "falls back to NumPy codec paths (build later with "
                "`make -C blit/native` inside the installed tree)",
                file=sys.stderr,
            )
        super().run()


setup(cmdclass={"build_py": build_py_with_native})
