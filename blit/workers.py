"""Per-worker data-access functions.

The rebuild of ``GBT.WorkerFunctions`` (src/gbtworkerfunctions.jl) — every
function here runs *on the host that owns the files* (or in-process for the
local backend) and returns reduced results, keeping the reference's key
design lever: reduce worker-side, before the wire (SURVEY.md §3.3).

Index convention: blit arrays are C-order ``(time, pol, chan)`` (see
blit/ops/fqav.py); ``idxs`` is a 3-tuple over those axes, 0-based, ints
sanitized to length-1 slices so results are always 3-D (reference:
``sanitizeidxs``, src/gbtworkerfunctions.jl:167-169).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from blit import faults
from blit.config import nfpc_from_foff
from blit.inventory import get_inventory  # noqa: F401  (re-export: workers run it)
from blit.io import fbh5, sigproc
from blit.ops.fqav import fqav, fqav_range
from blit.ops.stats import kurtosis as _kurtosis

Idxs = Tuple


def sanitize_idxs(idxs: Idxs) -> Idxs:
    """Replace integer indices with length-1 slices so indexing never drops
    a dimension (reference: src/gbtworkerfunctions.jl:167-169)."""
    return tuple(
        slice(i, i + 1) if isinstance(i, (int, np.integer)) else i for i in idxs
    )


def get_fb_header(path: str) -> Dict:
    """Normalized SIGPROC header: on-disk keywords + computed ``nfpc`` (the
    GBT constant 187.5/64 over |foff|), ``nsamps`` and ``data_size``; no
    ``header_size``/``sample_size`` — FBH5 parity (reference:
    src/gbtworkerfunctions.jl:131-139)."""
    hdr, _ = sigproc.read_fil_header(path)
    hdr["nfpc"] = nfpc_from_foff(hdr["foff"])
    hdr["data_size"] = (
        hdr["nsamps"] * hdr.get("nifs", 1) * hdr["nchans"] * hdr.get("nbits", 32) // 8
    )
    return dict(sorted(hdr.items()))


def get_fbh5_header(path: str) -> Dict:
    """Normalized FBH5 header (reference: src/gbtworkerfunctions.jl:141-155,
    with the missing-nfpc crash fixed)."""
    return fbh5.read_fbh5_header(path)


def get_header(path: str) -> Dict:
    """Format dispatch (reference: src/gbtworkerfunctions.jl:157-159)."""
    return get_fbh5_header(path) if fbh5.is_hdf5(path) else get_fb_header(path)


_ALL = (slice(None), slice(None), slice(None))


def get_fb_data(
    path: str,
    idxs: Idxs = _ALL,
    fqav_by: int = 1,
    fqav_func: Optional[Callable] = None,
) -> np.ndarray:
    """Memmap a .fil file, materialize the requested slab, frequency-average
    (reference: src/gbtworkerfunctions.jl:171-177; the explicit finalize is
    unnecessary here — the memmap unmaps on GC)."""
    if len(idxs) != 3:
        raise ValueError("idxs must have exactly three indices")

    def _read():
        # Transient NFS weather retries under faults.io_policy(); the
        # materializing copy happens inside so page-in faults retry too.
        faults.fire("workers.read", key=path)
        _, mm = sigproc.read_fil_data(path, mmap=True)
        data = np.ascontiguousarray(mm[idxs])
        del mm
        return data

    return fqav(faults.retry_io(_read, describe=f"read {path}"),
                fqav_by, f=fqav_func)


def get_fbh5_data(
    path: str,
    idxs: Idxs = _ALL,
    fqav_by: int = 1,
    fqav_func: Optional[Callable] = None,
) -> np.ndarray:
    """Hyperslab-read an FBH5 file then frequency-average — averaging is
    post-read, on the worker (reference: src/gbtworkerfunctions.jl:179-189)."""

    def _read():
        faults.fire("workers.read", key=path)
        return fbh5.read_fbh5_data(path, idxs)

    return fqav(faults.retry_io(_read, describe=f"read {path}"),
                fqav_by, f=fqav_func)


def get_data(
    path: str,
    idxs: Idxs = _ALL,
    fqav_by: int = 1,
    fqav_func: Optional[Callable] = None,
) -> np.ndarray:
    """Sanitize indices, dispatch on format (reference:
    src/gbtworkerfunctions.jl:191-195)."""
    idxs = sanitize_idxs(idxs)
    reader = get_fbh5_data if fbh5.is_hdf5(path) else get_fb_data
    return reader(path, idxs, fqav_by=fqav_by, fqav_func=fqav_func)


@functools.cache
def _kurtosis_jit():
    """The jitted on-device kurtosis kernel (built lazily: importing jax —
    and holding a chip — only when a worker asks for device statistics)."""
    import jax

    return jax.jit(functools.partial(_kurtosis, axis=0))


def get_kurtosis(path: str, idxs: Idxs = _ALL, device: bool = False) -> np.ndarray:
    """Excess kurtosis over time per (chan, pol), full time resolution
    (reference: src/gbtworkerfunctions.jl:197-202).  Returns shape
    ``(nchan, nifs)`` to preserve the reference's ``[chan, if]`` indexing.

    ``device=True`` runs the moment reduction on the accelerator under jit
    (SURVEY.md §2.2 StatsBase → "JAX moment kernels") — the reference's
    "ship the computation, return the reduced statistic" lever (§3.4), with
    only the tiny (nchan, nifs) map crossing back from the chip.
    """
    data = get_data(path, idxs)
    if device:
        import jax.numpy as jnp

        return np.asarray(_kurtosis_jit()(jnp.asarray(data))).T
    return np.asarray(_kurtosis(data, axis=0)).T


def get_freq_axis(header: Dict, fqav_by: int = 1) -> Tuple[float, float, int]:
    """The (fch1, foff, nchans) triple of a file's channel axis after
    optional frequency averaging — the range arithmetic the reference
    exposes as ``fqav(::AbstractRange, n)`` (src/gbtworkerfunctions.jl:27-33)."""
    return fqav_range(header["fch1"], header["foff"], header["nchans"], fqav_by)


def reduce_raw(
    raw_path,
    out_path: Optional[str] = None,
    product: Optional[str] = None,
    nfft: int = 1024,
    nint: int = 1,
    stokes: str = "I",
    resume: bool = False,
    **reducer_kw,
):
    """Reduce a GUPPI RAW recording to a filterbank product on this worker —
    the rawspec-equivalent stage the reference assumes already ran on each
    node (SURVEY.md §0 "File products").  ``raw_path`` may be a single file,
    a ``.NNNN.raw`` sequence stem, or a path list: multi-file scans stream
    as one gap-free reduction (blit/io/guppi.GuppiScan).

    ``product`` selects a standard rawspec preset ("0000"/"0001"/"0002",
    blit/pipeline.py); otherwise ``nfft``/``nint``/``stokes`` configure the
    reduction directly.  With ``out_path`` the product is written
    (``.fil``/``.h5`` by extension) and the output header returned; without
    it, ``(header, data)`` come back over the wire (small products only).
    ``resume=True`` (with a ``.fil`` out_path) restarts an interrupted
    reduction from its cursor sidecar (blit/pipeline.py ReductionCursor).
    """
    from blit.observability import process_timeline
    from blit.pipeline import RawReducer, reducer_for_product

    # Fan-out reductions record on the process-wide timeline by default
    # (ISSUE 5 tentpole #3): this is what ``WorkerPool.harvest_telemetry``
    # pulls back from each worker, so a remote reduction's stage table is
    # visible from the driver.  Callers can still pass their own.
    reducer_kw.setdefault("timeline", process_timeline())
    if product is not None:
        if nfft != 1024 or nint != 1:
            raise ValueError(
                "reduce_raw: pass either product= or explicit nfft/nint, not both"
            )
        red = reducer_for_product(product, stokes=stokes, **reducer_kw)
    else:
        red = RawReducer(nfft=nfft, nint=nint, stokes=stokes, **reducer_kw)
    if out_path is not None:
        if resume:
            return red.reduce_resumable(raw_path, out_path)
        return red.reduce_to_file(raw_path, out_path)
    if resume:
        raise ValueError("reduce_raw: resume=True requires a .fil out_path")
    return red.reduce(raw_path)


def stream_raw(
    raw_path: str,
    out_path: str,
    search: bool = False,
    replay_rate: Optional[float] = None,
    lateness_s: Optional[float] = None,
    idle_timeout_s: Optional[float] = None,
    done_path: Optional[str] = None,
    source: Optional[dict] = None,
    nfft: int = 1024,
    nint: int = 1,
    **reducer_kw,
):
    """LIVE-reduce a recording still being written on this worker
    (ISSUE 7) — the streaming twin of :func:`reduce_raw` /
    :func:`search_raw`: the host that owns the growing file tails it
    locally (``blit.stream.FileTailSource``) and only the finished
    product header crosses the wire, so a pool can fan a whole live
    session across its recorder nodes.

    ``replay_rate`` switches to a paced replay of an at-rest recording
    (``blit.stream.ReplaySource`` — drills and the bench rig);
    ``source`` is a source SPEC dict
    (:func:`blit.stream.session.source_from_spec` — how a session
    orchestrator hands a worker a packet-capture or packet-replay seat
    over the wire, ISSUE 18) and overrides the tail/replay knobs;
    ``search=True`` writes a ``.hits`` drift-search product through
    :func:`blit.stream.stream_search` instead of a filterbank.  The
    watermark knobs left ``None`` resolve from SiteConfig +
    ``BLIT_STREAM_*`` on the WORKER, as deployments expect."""
    from blit.observability import process_timeline
    from blit.stream import (
        FileTailSource,
        ReplaySource,
        source_from_spec,
        stream_reduce,
        stream_search,
    )

    reducer_kw.setdefault("timeline", process_timeline())
    if source is not None:
        spec = dict(source)
        spec.setdefault("raw", raw_path)
        src = source_from_spec(spec, timeline=reducer_kw["timeline"])
    elif replay_rate is not None:
        src = ReplaySource(raw_path, rate=replay_rate)
    else:
        src = FileTailSource(raw_path, idle_timeout_s=idle_timeout_s,
                             done_path=done_path)
    fn = stream_search if search else stream_reduce
    hdr = fn(src, out_path, lateness_s=lateness_s, nfft=nfft,
             nint=nint, **reducer_kw)
    if hasattr(src, "packet_report"):
        hdr = dict(hdr)
        hdr["_packet_report"] = src.packet_report()
    return hdr


def search_raw(
    raw_path,
    out_path: Optional[str] = None,
    nfft: int = 1024,
    nint: int = 1,
    resume: bool = False,
    **search_kw,
):
    """Drift-search a GUPPI RAW recording on this worker (ISSUE 6) — the
    search-plane twin of :func:`reduce_raw`, so pools fan drift searches
    across the hosts that own the files exactly like reductions.

    With ``out_path`` a ``.hits`` product is written (``resume=True``
    restarts from its cursor sidecar) and the search header returned;
    without it, ``(header, hit_records)`` come back over the wire —
    records as plain dicts (:meth:`blit.search.hits.Hit.record`) so the
    restricted agent transport never needs the Hit class.  ``search_kw``
    passes the :class:`~blit.search.dedoppler.DedopplerReducer` knobs
    through (window_spectra / snr_threshold / top_k / max_drift_bins /
    kernel / ...); unset knobs resolve from SiteConfig + ``BLIT_SEARCH_*``
    on the WORKER, as deployments expect."""
    from blit.observability import process_timeline
    from blit.search import DedopplerReducer

    search_kw.setdefault("timeline", process_timeline())
    red = DedopplerReducer(nfft=nfft, nint=nint, **search_kw)
    if out_path is not None:
        if resume:
            return red.search_resumable(raw_path, out_path)
        return red.search_to_file(raw_path, out_path)
    if resume:
        raise ValueError("search_raw: resume=True requires an out_path")
    header, hits = red.search(raw_path)
    return header, [h.record() for h in hits]
