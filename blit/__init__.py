"""blit — TPU-native Breakthrough Listen distributed data-product framework.

A brand-new, TPU-first (JAX/XLA/Pallas/pjit) framework with the capabilities of
the reference package ``BLDistributedDataProducts.jl`` (see ``SURVEY.md``):
distributed discovery, access, and reduction of Breakthrough Listen datasets
recorded across the BL@GBT cluster's ``(band, bank)`` node topology.

Layer map (mirrors SURVEY.md §1, rebuilt TPU-first):

- ``blit.gbt``       — main-process orchestration API (reference: src/gbt.jl).
- ``blit.workers``   — per-worker access functions (reference:
  src/gbtworkerfunctions.jl), host-side Python.
- ``blit.io``        — SIGPROC filterbank / FBH5 / GUPPI RAW codecs (reference:
  Blio.jl + HDF5.jl + H5Zbitshuffle.jl dependency layer).
- ``blit.ops``       — JAX/Pallas compute: fqav, kurtosis, despike, dequant,
  PFB channelizer, large staged FFT, Stokes detect.
- ``blit.parallel``  — the (band, bank) ``jax.sharding.Mesh``, worker pools,
  all_gather band stitching, psum beamforming, FX correlation.
- ``blit.pipeline``  — GUPPI RAW → high-resolution filterbank reduction driver.
- ``blit.faults``    — deterministic fault injection + recovery policy
  (transient-I/O retry, circuit breakers, degradation counters).
- ``blit.outplane``  — the asynchronous output plane: overlapped
  device→host readback (OutputRotation) and write-behind product sinks
  (AsyncSink) behind every streaming driver.
- ``blit.serve``     — the product service layer: priority scheduler with
  admission control, single-flight request coalescing, two-tier
  content-addressed result cache.
- ``blit.search``    — the search plane: on-device Taylor-tree
  drift-rate search (``.hits`` products alongside ``.fil``/``.h5``),
  windowed feeds + device-side threshold/top-k + ragged async hit sink.
- ``blit.stream``    — the streaming ingest plane: chunk sources
  (growing-file tailer / paced replay / queue), watermark-based
  windowing with zero-weight late/missing-chunk masking, and
  ``stream_reduce``/``stream_search`` live entry points byte-identical
  to the batch paths.
- ``blit.observability`` — the telemetry plane: spans/tracer with fan-out
  context propagation, stage timelines + log-bucketed histograms, fleet
  telemetry harvest, and the crash/stall flight recorder.
- ``blit.monitor``  — the live monitoring & SLO plane: the background
  metrics publisher (interval snapshots → spool JSONL + ``/metrics``/
  ``/healthz``/``/snapshot`` HTTP endpoint), multi-window burn-rate SLO
  evaluation with load-shed breach actions, the ``blit top`` terminal
  dashboard, and the ``blit bench-diff`` perf-regression gate.
- ``blit.tune``      — the ingest autotuner: per-rig content-addressed
  tuning profiles (chunk_frames / prefetch_depth / out_depth) converged
  offline (``blit tune``) or online during the first windows of a
  reduction, loaded automatically by every reducer.
- ``blit.hostmem``   — pinned host staging: page-aligned slab allocation
  and the process-wide staging pool behind the chunk rotations and
  readback rings.
"""

from blit.version import __version__

__all__ = [
    "__version__",
    "ProductService",
    "ProductRequest",
    "ProductCache",
    "Scheduler",
    "Overloaded",
    "FleetFrontDoor",
    "DedopplerReducer",
    "Hit",
    "stream_reduce",
    "stream_search",
]

# The serving layer's front-door names re-export from blit.serve (lazily —
# `import blit` must stay light for the worker agents).
_SERVE_EXPORTS = (
    "ProductService",
    "ProductRequest",
    "ProductCache",
    "Scheduler",
    "Overloaded",
    "FleetFrontDoor",
)

# The search plane's front-door names re-export from blit.search (lazily —
# the drift kernels pull jax, which `import blit` must not).
_SEARCH_EXPORTS = (
    "DedopplerReducer",
    "Hit",
)

# The streaming ingest plane's front-door names re-export from
# blit.stream (lazily — the plane pulls the reducers, which pull jax).
_STREAM_EXPORTS = (
    "stream_reduce",
    "stream_search",
)


def __getattr__(name):
    if name in _SERVE_EXPORTS:
        import importlib

        return getattr(importlib.import_module("blit.serve"), name)
    if name in _SEARCH_EXPORTS:
        import importlib

        return getattr(importlib.import_module("blit.search"), name)
    if name in _STREAM_EXPORTS:
        import importlib

        return getattr(importlib.import_module("blit.stream"), name)
    # Lazy submodule access (keeps `import blit` light; JAX-dependent modules
    # only load when touched).
    if name in (
        "gbt",
        "workers",
        "io",
        "ops",
        "parallel",
        "pipeline",
        "inventory",
        "naming",
        "config",
        "testing",
        "faults",
        "outplane",
        "serve",
        "search",
        "stream",
        "observability",
        "monitor",
        "tune",
        "hostmem",
    ):
        import importlib

        try:
            return importlib.import_module(f"blit.{name}")
        except ModuleNotFoundError as e:
            if e.name == f"blit.{name}":
                # PEP 562: absent submodule surfaces as AttributeError (so
                # hasattr() works); genuine dependency failures inside an
                # existing submodule re-raise unmasked.
                raise AttributeError(
                    f"module 'blit' has no attribute {name!r}"
                ) from e
            raise
    raise AttributeError(f"module 'blit' has no attribute {name!r}")
